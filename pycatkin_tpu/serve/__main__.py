"""``python -m pycatkin_tpu.serve`` -- run a sweep server until
drained (SIGINT/SIGTERM trigger the graceful drain path).

Configuration comes from the ``PYCATKIN_SERVE_*`` environment knobs
(docs/index.md registry) and the flags below; the bound port is
printed as a JSON line on stdout so a supervisor can scrape it.

``--router`` runs the FRONT ROUTER instead (serve/router.py): a
JAX-free process that routes to the replica endpoints published in
``--fleet-file`` (or ``PYCATKIN_ROUTER_FLEET_FILE``), optionally
journal-backed via ``--journal-dir`` / ``PYCATKIN_DURABLE_DIR`` so a
supervised router (``FleetConfig(role="router")``) replays its
accepted-but-unanswered backlog after a crash.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys


async def _amain(args) -> int:
    from .protocol import ServeConfig
    from .server import SweepServer

    cfg = ServeConfig(
        host=args.host, port=args.port, runner=args.runner,
        aot_pack=args.aot_pack, work_dir=args.work_dir,
        max_occupancy=args.max_occupancy)
    server = await SweepServer(cfg).start()
    print(json.dumps({"serving": True, "host": cfg.host,
                      "port": server.port}), flush=True)

    loop = asyncio.get_running_loop()
    draining = asyncio.Event()

    def _trigger_drain():
        if not draining.is_set():
            draining.set()
            loop.create_task(server.drain())

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, _trigger_drain)
        except (NotImplementedError, OSError):
            pass
    # Serve until something (a signal, or a client "drain" op) drains
    # the server and its scheduler loop exits.
    while server._scheduler_task is not None:
        await asyncio.sleep(0.1)
    print(json.dumps({"serving": False,
                      "stats": server.stats()}), flush=True)
    return 0


async def _amain_router(args) -> int:
    from .fleet import FLEET_FILE_ENV, FileFleet
    from .router import RouterConfig, SweepRouter

    fleet_file = args.fleet_file or os.environ.get(FLEET_FILE_ENV)
    if not fleet_file:
        print("--router requires --fleet-file (or "
              f"{FLEET_FILE_ENV})", file=sys.stderr)
        return 2
    cfg = RouterConfig(host=args.host or "127.0.0.1",
                       port=args.port or 0,
                       journal_dir=args.journal_dir)
    router = await SweepRouter(FileFleet(fleet_file), cfg).start()
    # The serving line is scraped by a role="router" supervisor, the
    # same way replica lines are; journal replay is already running in
    # the background at this point (progress via the stats op).
    print(json.dumps({"serving": True, "router": True,
                      "host": cfg.host, "port": router.port}),
          flush=True)

    loop = asyncio.get_running_loop()
    draining = asyncio.Event()

    def _trigger_drain():
        if not draining.is_set():
            draining.set()
            loop.create_task(router.drain())

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, _trigger_drain)
        except (NotImplementedError, OSError):
            pass
    while router._tcp_server is not None:
        await asyncio.sleep(0.1)
    print(json.dumps({"serving": False, "router": True,
                      "stats": router.stats()}), flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pycatkin_tpu.serve",
        description="Run the sweep-as-a-service server.")
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None,
                    help="0 binds an ephemeral port (printed)")
    ap.add_argument("--runner", choices=("inproc", "elastic"),
                    default=None)
    ap.add_argument("--aot-pack", default=None,
                    help="AOT cache pack imported before listening")
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--max-occupancy", type=int, default=None)
    ap.add_argument("--router", action="store_true",
                    help="run the front router instead of a replica")
    ap.add_argument("--fleet-file", default=None,
                    help="router mode: endpoints file published by "
                         "the replica supervisor")
    ap.add_argument("--journal-dir", default=None,
                    help="router mode: write-ahead request journal "
                         "directory (enables durable requests)")
    args = ap.parse_args(argv)
    if args.router:
        return asyncio.run(_amain_router(args))
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
