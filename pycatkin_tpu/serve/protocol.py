"""Wire schema and knobs of the serving layer (docs/serving.md).

One JSON object per line, both directions. Requests carry ``op``
("sweep" | "ping" | "stats" | "drain" | "result") and, for sweeps, a
mechanism in the reference input-file schema (utils/io.system_to_dict),
a conditions grid, and a deadline class. Responses echo the request
``id`` and either ``ok: true`` with the result payload or ``ok: false``
with a structured error -- admission control rejects are data, not
dropped connections.

Durable extension (docs/serving.md "Durable requests"): a sweep may
carry an optional client-chosen ``idempotency_key``. Against a
journal-backed router the client then receives an out-of-band
``{"accepted": true, "key": ...}`` ack line once the request is
fsynced to the write-ahead journal, and a ``result`` op
(``{"op": "result", "key": ...}``) fetches the journaled answer for a
key. Keyless requests are byte-identical to the pre-durability
protocol.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

PROTOCOL = "pycatkin-serve/v1"

# Env knobs (PCL006 registry rows in docs/index.md).
HOST_ENV = "PYCATKIN_SERVE_HOST"
PORT_ENV = "PYCATKIN_SERVE_PORT"
MAX_PENDING_ENV = "PYCATKIN_SERVE_MAX_PENDING"
RUNNER_ENV = "PYCATKIN_SERVE_RUNNER"
AOT_PACK_ENV = "PYCATKIN_SERVE_AOT_PACK"
BUDGET_INTERACTIVE_ENV = "PYCATKIN_SERVE_BUDGET_INTERACTIVE"
BUDGET_STANDARD_ENV = "PYCATKIN_SERVE_BUDGET_STANDARD"
BUDGET_BATCH_ENV = "PYCATKIN_SERVE_BUDGET_BATCH"

TIMEOUT_INTERACTIVE_ENV = "PYCATKIN_SERVE_TIMEOUT_INTERACTIVE"
TIMEOUT_STANDARD_ENV = "PYCATKIN_SERVE_TIMEOUT_STANDARD"
TIMEOUT_BATCH_ENV = "PYCATKIN_SERVE_TIMEOUT_BATCH"

# Durable-request knobs (serve/durable.py, docs/serving.md): where the
# router's write-ahead request journal lives, how large a journal
# segment may grow before rotation, and how many journaled requests
# the boot-time replay re-dispatches concurrently.
DURABLE_DIR_ENV = "PYCATKIN_DURABLE_DIR"
DURABLE_SEGMENT_BYTES_ENV = "PYCATKIN_DURABLE_SEGMENT_BYTES"
DURABLE_REPLAY_CONCURRENCY_ENV = "PYCATKIN_DURABLE_REPLAY_CONCURRENCY"

_DEFAULT_BUDGETS = {"interactive": 0.02, "standard": 0.2, "batch": 2.0}
_BUDGET_ENVS = {"interactive": BUDGET_INTERACTIVE_ENV,
                "standard": BUDGET_STANDARD_ENV,
                "batch": BUDGET_BATCH_ENV}
DEADLINE_CLASSES = tuple(_DEFAULT_BUDGETS)

# Per-class END-TO-END request deadlines (seconds from send to
# response), distinct from the coalescing WAIT budgets above: the wait
# budget bounds how long a request may sit collecting co-tenants; the
# request timeout bounds the whole round trip, solve included, and is
# what the TCP client and the front router resolve to a structured
# ``E_TIMEOUT`` instead of hanging on a stalled peer.
_DEFAULT_TIMEOUTS = {"interactive": 30.0, "standard": 120.0,
                     "batch": 600.0}
_TIMEOUT_ENVS = {"interactive": TIMEOUT_INTERACTIVE_ENV,
                 "standard": TIMEOUT_STANDARD_ENV,
                 "batch": TIMEOUT_BATCH_ENV}

# Structured reject/error codes (docs/serving.md).
E_BAD_REQUEST = "bad_request"
E_OVERLOADED = "overloaded"
E_DRAINING = "draining"
E_INTERNAL = "internal"
E_TIMEOUT = "timeout"
# The transport under an in-flight request died (TCP client): the
# error names the peer and whether the request carried an idempotency
# key, so callers know a resubmit is safe.
E_CONN_LOST = "conn_lost"
# A ``result`` fetch named a key the journal has no answer for (never
# accepted, still in flight, or already compacted away).
E_UNKNOWN_KEY = "unknown_key"


class ServeError(Exception):
    """A request failure that maps to a structured error response."""

    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(message)


def deadline_budgets() -> dict:
    """Per-class coalescing wait budgets (seconds a request may sit
    waiting for co-tenants), env-overridable per class."""
    out = {}
    for cls, default in _DEFAULT_BUDGETS.items():
        out[cls] = float(os.environ.get(_BUDGET_ENVS[cls], default))
    return out


@dataclass
class ServeConfig:
    """Everything a :class:`serve.server.SweepServer` needs to boot.
    ``None`` fields resolve from the environment at construction."""

    host: Optional[str] = None
    port: Optional[int] = None
    max_pending: Optional[int] = None
    runner: Optional[str] = None          # "inproc" | "elastic"
    aot_pack: Optional[str] = None        # pack imported before listen
    work_dir: Optional[str] = None        # events + elastic group dirs
    max_occupancy: Optional[int] = None   # coalescer knob passthrough
    max_wait_s: Optional[float] = None
    tick_s: float = 0.005                 # scheduler poll period
    n_workers: int = 2                    # elastic runner width
    budgets: dict = field(default_factory=deadline_budgets)

    def __post_init__(self):
        if self.host is None:
            self.host = os.environ.get(HOST_ENV, "127.0.0.1")
        if self.port is None:
            self.port = int(os.environ.get(PORT_ENV, "0"))
        if self.max_pending is None:
            self.max_pending = int(os.environ.get(MAX_PENDING_ENV,
                                                  "256"))
        if self.runner is None:
            self.runner = os.environ.get(RUNNER_ENV, "inproc")
        if self.runner not in ("inproc", "elastic"):
            raise ValueError(f"runner must be 'inproc' or 'elastic', "
                             f"got {self.runner!r}")
        if self.aot_pack is None:
            self.aot_pack = os.environ.get(AOT_PACK_ENV) or None
        if self.max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, "
                             f"got {self.max_pending}")

    def wait_budget_for(self, deadline_class: str) -> float:
        try:
            return float(self.budgets[deadline_class])
        except KeyError:
            raise ServeError(
                E_BAD_REQUEST,
                f"unknown deadline_class {deadline_class!r}; choose "
                f"one of {sorted(self.budgets)}") from None


def request_timeouts() -> dict:
    """Per-class end-to-end request deadlines in seconds,
    env-overridable per class (``PYCATKIN_SERVE_TIMEOUT_*``)."""
    out = {}
    for cls, default in _DEFAULT_TIMEOUTS.items():
        out[cls] = float(os.environ.get(_TIMEOUT_ENVS[cls], default))
    return out


def request_timeout_for(deadline_class: str) -> float:
    """The end-to-end deadline of one request of this class; unknown
    classes get the ``standard`` deadline (the request itself is
    validated -- and rejected -- elsewhere)."""
    return request_timeouts().get(str(deadline_class),
                                  _DEFAULT_TIMEOUTS["standard"])


def jsonable(obj):
    """Recursively convert a result payload (numpy arrays/scalars,
    nested dicts/sequences) into plain JSON types."""
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return jsonable(obj.tolist())
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        v = float(obj)
        return v if np.isfinite(v) else repr(v)
    if isinstance(obj, float) and not np.isfinite(obj):
        return repr(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "__array__"):  # jax Arrays land here
        return jsonable(np.asarray(obj))
    return repr(obj)


def error_response(req_id, code: str, message: str, **extra) -> dict:
    """Structured error line. ``extra`` keys (e.g. ``peer``,
    ``idempotency_key`` on ``conn_lost``) ride inside the ``error``
    object; legacy callers pass none and the shape is unchanged."""
    err = {"code": code, "message": message}
    if extra:
        err.update(extra)
    return {"protocol": PROTOCOL, "id": req_id, "ok": False,
            "error": err}


def accepted_ack(req_id, key: str) -> dict:
    """The durability ack: written to the socket only AFTER the
    ``accepted`` journal record is fsynced, it promises the keyed
    request will be answered exactly once even across router death."""
    return {"protocol": PROTOCOL, "id": req_id, "accepted": True,
            "key": key}


def canonical_answer(resp: dict) -> str:
    """Canonical form of a sweep answer for bitwise-identity audits:
    the duplicate-suppression audit (hedge losers, failover stragglers,
    serve/router.py), the journaled-answer replay audit
    (serve/durable.py) and the chaos drill all compare THIS string.
    Covers the solver-derived payload; per-request envelope fields
    (``id``, ``timing``, ``pack``) legitimately differ between
    duplicates and are excluded."""
    return json.dumps({"result": resp.get("result"),
                       "quarantine": resp.get("quarantine"),
                       "lanes": resp.get("lanes")}, sort_keys=True)


def parse_sweep_request(payload: dict) -> dict:
    """Validate the sweep-specific fields of a request payload; returns
    ``{mechanism, T(list), p(list), tof_terms, deadline_class,
    wait_budget_s, want}``. Raises :class:`ServeError` (bad_request)
    with the offending field named."""
    mech = payload.get("mechanism")
    if mech is None:
        raise ServeError(E_BAD_REQUEST, "/mechanism: missing (expected "
                         "reference input-file JSON or a built System)")
    conds = payload.get("conditions")
    if not isinstance(conds, dict):
        raise ServeError(E_BAD_REQUEST,
                         "/conditions: expected an object like "
                         '{"T": [500, 550], "p": 1e5}')
    T = conds.get("T")
    if T is None:
        raise ServeError(E_BAD_REQUEST, "/conditions/T: missing")
    T = [float(t) for t in (T if isinstance(T, (list, tuple)) else [T])]
    if not T:
        raise ServeError(E_BAD_REQUEST, "/conditions/T: empty grid")
    p = conds.get("p", 1.0e5)
    p = [float(v) for v in (p if isinstance(p, (list, tuple))
                            else [p] * len(T))]
    if len(p) != len(T):
        raise ServeError(E_BAD_REQUEST,
                         f"/conditions/p: {len(p)} values for "
                         f"{len(T)} temperatures")
    tof_terms = payload.get("tof_terms")
    if tof_terms is not None and not isinstance(tof_terms, (list, tuple)):
        raise ServeError(E_BAD_REQUEST, "/tof_terms: expected a list")
    cls = payload.get("deadline_class", "standard")
    wait = payload.get("wait_budget_s")
    if wait is not None:
        wait = float(wait)
        if wait < 0:
            raise ServeError(E_BAD_REQUEST,
                             "/wait_budget_s: must be >= 0")
    want = payload.get("return", ())
    if not isinstance(want, (list, tuple)):
        raise ServeError(E_BAD_REQUEST, "/return: expected a list of "
                         "result keys (e.g. [\"y\"])")
    key = payload.get("idempotency_key")
    if key is not None:
        if not isinstance(key, str) or not key:
            raise ServeError(E_BAD_REQUEST, "/idempotency_key: "
                             "expected a non-empty string")
        if len(key) > 256:
            raise ServeError(E_BAD_REQUEST, "/idempotency_key: "
                             "longer than 256 characters")
    return {"mechanism": mech, "T": T, "p": p,
            "tof_terms": list(tof_terms) if tof_terms else None,
            "deadline_class": str(cls), "wait_budget_s": wait,
            "want": [str(k) for k in want], "idempotency_key": key}


def parse_transient_request(payload: dict) -> dict:
    """Validate the transient-specific fields of a request payload
    (docs/serving.md, op ``transient``): the sweep fields minus
    ``tof_terms``, plus the required dense-output ``save_ts`` grid --
    at least two non-negative, strictly increasing save times starting
    at 0. Returns ``{mechanism, T(list), p(list), save_ts(list),
    deadline_class, wait_budget_s, want, idempotency_key}``; raises
    :class:`ServeError` (bad_request) with the offending field
    named."""
    parsed = parse_sweep_request({**payload, "tof_terms": None})
    ts = payload.get("save_ts")
    if not isinstance(ts, (list, tuple)) or len(ts) < 2:
        raise ServeError(E_BAD_REQUEST, "/save_ts: expected a list of "
                         "at least 2 save times")
    try:
        ts = [float(t) for t in ts]
    except (TypeError, ValueError):
        raise ServeError(E_BAD_REQUEST,
                         "/save_ts: non-numeric entry") from None
    if ts[0] != 0.0:
        raise ServeError(E_BAD_REQUEST, "/save_ts: must start at 0 "
                         "(the reported trajectory includes y0)")
    if any(b <= a for a, b in zip(ts, ts[1:])):
        raise ServeError(E_BAD_REQUEST,
                         "/save_ts: must be strictly increasing")
    if not all(np.isfinite(ts)):
        raise ServeError(E_BAD_REQUEST, "/save_ts: non-finite entry")
    parsed.pop("tof_terms", None)
    parsed["save_ts"] = ts
    return parsed
