"""Soak harness: stream randomized synthetic mechanisms through a
live :class:`serve.server.SweepServer` and report serving metrics in a
BENCH-style JSON record (``tools/soak.py`` is the CLI; the bench smoke
gate runs a miniature in-process soak).

Phases:

1. **pool** -- seed-deterministic mechanisms per requested ABI bucket
   (:func:`models.synthetic.synthetic_system_for_bucket`), so the soak
   controls pack occupancy bucket by bucket;
2. **warm** -- the server's prewarm (solo zoo + packed executables per
   k_bucket), then one streamed burst per bucket through the real
   serving path; everything after :meth:`SweepServer.mark_warm` counts
   against the zero-compile gate;
3. **measure** -- ``n_requests`` concurrent sweeps, round-robin over
   buckets, random mechanism + temperature grid per request; client-
   side latency per request, response-schema presence audited;
4. **drain burst** -- a final burst is submitted and the server is
   drained WHILE they are pending: graceful drain must complete every
   accepted request (no-loss proof).

The resulting record carries ``serve.p50_s`` / ``serve.p99_s`` /
``serve.zero_compile_rate`` / ``serve.mean_occupancy``, which
``obs/history.py`` tracks with the same median±MAD sentinel as sweep
throughput (tools/perfwatch.py).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Optional

import numpy as np

SCHEMA = "pycatkin-serve-soak/v1"

# Response fields every ok sweep response must carry (acceptance:
# manifest, telemetry and quarantine round-trip on EVERY response).
REQUIRED_RESPONSE_FIELDS = ("result", "manifest", "lane_telemetry",
                            "quarantine", "pack", "timing")

# Largest ABI bucket the soak mixes `transient` requests into. Dense
# transient device time is step-count-bound per save interval, so a
# warm bucket-128 flush runs ~30 s on CPU -- fine for bench.py
# --transient's throughput lane, ruinous for a latency-gated mix where
# it serializes every co-resident sweep flush behind it.
TRANSIENT_MIX_MAX_BUCKET = 32


def _audit_response(resp: dict) -> list:
    """Names of required fields missing from an ok response.
    ``lane_telemetry`` must be present but may be null (a runner that
    produced none); everything else must be a real value."""
    bad = [f for f in REQUIRED_RESPONSE_FIELDS
           if f not in resp
           or (resp[f] is None and f != "lane_telemetry")]
    # Verdict arrays must arrive as real JSON lists, one entry per
    # lane -- a serializer regression that ships reprs instead of
    # values (e.g. an unhandled array type) is a schema violation,
    # not a cosmetic one.
    result = resp.get("result")
    if isinstance(result, dict):
        # Transient responses carry ``save_points`` and a per-lane
        # ``ok`` verdict; sweeps carry per-lane ``success``.
        key = "ok" if "save_points" in resp else "success"
        succ = result.get(key)
        if not (isinstance(succ, list)
                and len(succ) == resp.get("lanes")):
            bad.append(f"result.{key}")
    return bad


def _percentile(values, q) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, dtype=float), q))


async def soak_async(n_requests: int = 1000, buckets=(16, 32, 128),
                     lanes: int = 4, seed: int = 0,
                     transport: str = "inproc",
                     mechs_per_bucket: int = 6,
                     max_occupancy: int = 8,
                     concurrency: int = 16,
                     runner: str = "inproc",
                     aot_pack: Optional[str] = None,
                     deadline_class: str = "standard",
                     t_range=(480.0, 520.0),
                     drain_burst: Optional[int] = None,
                     transient_frac: float = 0.0,
                     verbose: bool = False) -> dict:
    """Run the full soak against a fresh server; returns the BENCH
    record. ``transport`` is ``"inproc"`` (direct handler calls,
    mechanisms passed as built Systems) or ``"tcp"`` (full JSON wire
    round-trip on localhost). ``transient_frac`` > 0 mixes that
    fraction of ``transient`` (dense-output) requests into the
    measured stream on a fixed log-spaced save grid -- warmed,
    coalesced and audited exactly like sweeps. Transients mix only on
    buckets <= TRANSIENT_MIX_MAX_BUCKET: a dense sweep's device time
    is step-count-bound per save interval, so at the big buckets one
    warm flush runs ~30 s on CPU -- a throughput job that belongs in
    ``bench.py --transient``, not in a latency-gated request mix it
    would serialize every co-resident sweep behind."""
    from ..models.synthetic import synthetic_system_for_bucket
    from .client import SweepClient, TcpSweepClient
    from .protocol import ServeConfig
    from .server import SweepServer

    rng = np.random.default_rng(seed)
    t_wall0 = time.monotonic()

    def say(msg):
        if verbose:
            print(f"soak: {msg}", flush=True)

    # -- phase 1: mechanism pool --------------------------------------
    say(f"building pool: {mechs_per_bucket} mechanisms x "
        f"{len(buckets)} buckets")
    pool = {b: [synthetic_system_for_bucket(
                    b, seed=int(rng.integers(0, 2**31)))
                for _ in range(mechs_per_bucket)]
            for b in buckets}

    cfg = ServeConfig(port=0, runner=runner, aot_pack=aot_pack,
                      max_occupancy=max_occupancy)
    server = await SweepServer(cfg).start(listen=(transport == "tcp"))
    tcp = None
    if transport == "tcp":
        tcp = await TcpSweepClient("127.0.0.1", server.port).connect()
        client = tcp
    elif transport == "inproc":
        client = SweepClient(server)
    else:
        raise ValueError(f"transport must be 'inproc' or 'tcp', "
                         f"got {transport!r}")

    def payload_mech(sim):
        # TCP exercises the full wire schema; in-proc skips the JSON
        # round-trip (the production embedding's fast path).
        if transport == "tcp":
            from ..utils.io import system_to_dict
            return system_to_dict(sim)
        return sim

    def random_T():
        return [float(t) for t in rng.uniform(*t_range, size=lanes)]

    # One fixed save grid for the whole soak: every transient request
    # shares it, so same-bucket transients coalesce into packed
    # flushes just like sweeps. Only the small buckets mix transients
    # (see the docstring); with no eligible bucket the mix degrades to
    # a pure sweep soak.
    save_ts = [0.0] + [float(t) for t in np.logspace(-9, 0, 13)]
    transient_buckets = [b for b in buckets
                         if b <= TRANSIENT_MIX_MAX_BUCKET]
    if transient_frac > 0 and not transient_buckets:
        transient_frac = 0.0

    async def one_request(sim, sem, latencies, failures, violations,
                          transient=False):
        async with sem:
            t0 = time.monotonic()
            if transient:
                resp = await client.transient(
                    payload_mech(sim), random_T(), save_ts,
                    deadline_class=deadline_class)
            else:
                resp = await client.sweep(
                    payload_mech(sim), random_T(),
                    deadline_class=deadline_class)
            dt = time.monotonic() - t0
            if resp.get("ok"):
                latencies.append(dt)
                missing = _audit_response(resp)
                if missing:
                    violations.append({"id": resp.get("id"),
                                       "missing": missing})
            else:
                failures.append(resp.get("error", {}))

    try:
        # -- phase 2: warm --------------------------------------------
        say("prewarming (solo zoo + packed executables)")
        k_buckets = sorted({1 << i for i in range(
            max(1, max_occupancy).bit_length())} | {max_occupancy})
        prewarm = await asyncio.to_thread(
            server.warm, [pool[b][0] for b in buckets], lanes,
            tuple(k for k in k_buckets if k > 1))
        if transient_frac > 0:
            # Transient programs only for the buckets that mix them.
            tw = await asyncio.to_thread(
                server.warm, [pool[b][0] for b in transient_buckets],
                lanes, tuple(k for k in k_buckets if k > 1), save_ts)
            prewarm = {k: prewarm[k] + tw[k] for k in prewarm}
        say(f"prewarm: {prewarm}")
        warm_lat, warm_fail, warm_viol = [], [], []
        sem = asyncio.Semaphore(concurrency)
        warm_jobs = []
        for b in buckets:
            # One full burst (packs) plus one straggler (K=1 flush)
            # per bucket, through the real serving path.
            for i in range(max_occupancy):
                warm_jobs.append(one_request(
                    pool[b][i % len(pool[b])], sem, warm_lat,
                    warm_fail, warm_viol))
            if transient_frac > 0 and b in transient_buckets:
                for i in range(max_occupancy):
                    warm_jobs.append(one_request(
                        pool[b][i % len(pool[b])], sem, warm_lat,
                        warm_fail, warm_viol, transient=True))
        await asyncio.gather(*warm_jobs)
        for b in buckets:
            await one_request(pool[b][0], sem, warm_lat, warm_fail,
                              warm_viol)
            if transient_frac > 0 and b in transient_buckets:
                await one_request(pool[b][0], sem, warm_lat,
                                  warm_fail, warm_viol,
                                  transient=True)
        server.mark_warm()
        n_warmup = len(warm_lat) + len(warm_fail)
        say(f"warmup done: {n_warmup} requests "
            f"({len(warm_fail)} failed)")

        # -- phase 3: measured stream ---------------------------------
        latencies, failures, violations = [], [], []
        jobs = []
        n_transient = 0
        for i in range(n_requests):
            b = buckets[i % len(buckets)]
            sim = pool[b][int(rng.integers(0, len(pool[b])))]
            transient = (transient_frac > 0
                         and b in transient_buckets
                         and rng.random() < transient_frac)
            n_transient += int(transient)
            jobs.append(one_request(sim, sem, latencies, failures,
                                    violations, transient=transient))
        say(f"streaming {n_requests} measured requests "
            f"({n_transient} transient, concurrency {concurrency})")
        t_meas0 = time.monotonic()
        await asyncio.gather(*jobs)
        measure_s = time.monotonic() - t_meas0
        say(f"measured phase: {measure_s:.1f}s, "
            f"{len(failures)} failures")

        # -- phase 4: drain burst (no-loss proof) ---------------------
        nb = (len(buckets) * max_occupancy if drain_burst is None
              else drain_burst)
        burst_lat, burst_fail, burst_viol = [], [], []
        burst = [one_request(pool[buckets[i % len(buckets)]][0], sem,
                             burst_lat, burst_fail, burst_viol)
                 for i in range(nb)]
        completed0 = server.stats()["completed_total"]
        burst_tasks = [asyncio.ensure_future(j) for j in burst]
        # Drain only once every burst request is past admission (over
        # TCP that takes a round-trip): the no-loss claim is about
        # ACCEPTED requests, and draining earlier would just reject
        # them at the door.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            done = server.stats()["completed_total"] - completed0
            if done + server.in_service >= nb:
                break
            await asyncio.sleep(0.002)
        drain_task = asyncio.ensure_future(server.drain())
        await asyncio.gather(*burst_tasks)
        await drain_task
        stats = server.stats()
        drain_burst_ok = (len(burst_lat) + len(burst_fail) == nb
                          and not burst_fail and not burst_viol)
        say(f"drain complete; burst ok={drain_burst_ok}")
    finally:
        if tcp is not None:
            await tcp.close()
        await server.stop()

    backend = ((server.boot_manifest.get("backend") or {})
               .get("platform")) or "cpu"
    zero_rate = stats.get("zero_compile_rate_after_warm")
    record = {
        "bench": "serve-soak", "schema": SCHEMA,
        "backend": backend, "transport": transport, "runner": runner,
        "aot_pack": bool(aot_pack),
        "n_requests": n_requests, "n_ok": len(latencies),
        "n_failed": len(failures),
        "n_transient": n_transient,
        "transient_frac": transient_frac,
        "n_warmup": n_warmup, "n_drain_burst": nb,
        "buckets": list(buckets), "lanes": lanes,
        "mechs_per_bucket": mechs_per_bucket,
        "max_occupancy": max_occupancy, "concurrency": concurrency,
        "seed": seed,
        "schema_violations": len(violations) + len(warm_viol),
        "warmup": {"prewarm": prewarm,
                   "requests": n_warmup,
                   "failed": len(warm_fail)},
        "serve": {
            "p50_s": _percentile(latencies, 50),
            "p99_s": _percentile(latencies, 99),
            "mean_s": (float(np.mean(latencies)) if latencies
                       else None),
            "throughput_rps": (len(latencies) / measure_s
                               if measure_s > 0 else None),
            "zero_compile_rate": zero_rate,
            "mean_occupancy": stats.get("mean_occupancy"),
            "flushes": stats.get("flushes"),
            "flushes_after_warm": stats.get("flushes_after_warm"),
            "compiles_after_warm": stats.get("compiles_after_warm"),
            "rejects": stats.get("rejected_total"),
            "drain_burst_ok": bool(drain_burst_ok),
        },
        "server_stats": stats,
        "failures": failures[:10],
        "violations": (violations + warm_viol)[:10],
        "wall_s": time.monotonic() - t_wall0,
        "manifest": server.boot_manifest,
    }
    return record


def run_soak(out_path: Optional[str] = None, **kwargs) -> dict:
    """Synchronous entry: force the ABI gate on (packing is the whole
    point of the service), run :func:`soak_async`, optionally write
    the record to ``out_path``."""
    prev_abi = os.environ.get("PYCATKIN_ABI")
    os.environ["PYCATKIN_ABI"] = "1"
    try:
        record = asyncio.run(soak_async(**kwargs))
    finally:
        if prev_abi is None:
            os.environ.pop("PYCATKIN_ABI", None)
        else:
            os.environ["PYCATKIN_ABI"] = prev_abi
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
    return record


CHAOS_SCHEMA = "pycatkin-serve-chaos/v1"


def _free_port() -> int:
    """Reserve an ephemeral port for the supervised router: it must
    sit on a FIXED address across incarnations so reconnecting clients
    find the rebooted process."""
    import socket
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def chaos_drill_async(n_requests: int = 24, bucket: int = 16,
                            lanes: int = 3, mechs: int = 4,
                            n_replicas: int = 3, kill: int = 2,
                            max_occupancy: int = 4, seed: int = 0,
                            with_pack: bool = True,
                            router_crash: bool = False,
                            work_dir: Optional[str] = None,
                            verbose: bool = False) -> dict:
    """The serve-tier chaos drill (docs/failure_model.md):

    1. **baseline** -- every request of the drill grid is answered by
       an UNDISTURBED in-process server; the canonical answers are the
       bitwise-identity reference. The run warms the AOT cache, which
       (``with_pack``) is exported as the replicas' boot pack.
    2. **fleet** -- ``n_replicas`` pack-booted replicas under a
       :class:`fleet.ReplicaSupervisor`, fronted by a
       :class:`router.SweepRouter`; the same grid streams through the
       router over TCP (every 4th request ``interactive``, so hedged
       dispatch runs too).
    3. **chaos** -- once a third of the stream has completed, a fault
       plan (O_EXCL ticket budgets under ``work_dir``) SIGKILLs
       ``kill`` of the replicas via their ``router:replica:<i>`` sites
       and tears one dispatch line + resets one connection at
       ``router:dispatch:<i>``. In-flight requests must fail over.
    4. **audit** -- zero lost requests, every answer bitwise identical
       to the baseline, the router's duplicate-suppression audit has
       zero mismatches, killed replicas restarted (incarnation >= 2)
       and -- ``with_pack`` -- every replica's flushes compiled
       NOTHING (the pack-boot zero-compile proof), re-verified with
       one direct sweep per restarted replica.

    ``router_crash=True`` additionally kills the FRONT ROUTER
    (docs/serving.md "Durable requests"): the router runs as a
    journal-backed subprocess under a ``FleetConfig(role="router")``
    supervisor on a fixed port, every drill request carries an
    ``idempotency_key``, and the fault plan SIGKILLs the router at the
    ``router:front`` site mid-stream on top of the replica kills. The
    reconnecting client resubmits its unanswered keyed requests; the
    rebooted router replays its journal. The audit then ALSO fetches
    every key's journaled answer (the ``result`` op) and requires it
    bitwise identical to the baseline -- no acknowledged request may
    be lost and no key may ever show two differing answers. The
    dispatch-level faults (conn-reset / torn-line) move into the
    router subprocess via ``PYCATKIN_FAULTS`` with its own ticket
    directory (parent and child budgets must not share spec indices).
    """
    import sys
    import tempfile

    from ..models.synthetic import synthetic_system_for_bucket
    from ..robustness import faults
    from ..utils.io import system_to_dict
    from .client import SweepClient, TcpSweepClient, sweep_payload
    from .fleet import FleetConfig, ReplicaSupervisor
    from .protocol import ServeConfig
    from .router import SweepRouter, _canonical
    from .server import SweepServer

    rng = np.random.default_rng(seed)
    t_wall0 = time.monotonic()

    def say(msg):
        if verbose:
            print(f"chaos-drill: {msg}", flush=True)

    sims = [synthetic_system_for_bucket(
                bucket, seed=int(rng.integers(0, 2**31)))
            for _ in range(mechs)]
    mech_dicts = [system_to_dict(s) for s in sims]
    plan_grid = [(i % mechs,
                  [float(t) for t in rng.uniform(480.0, 520.0,
                                                 size=lanes)],
                  "interactive" if i % 4 == 0 else "standard")
                 for i in range(n_requests)]

    own_td = None
    if work_dir is None:
        own_td = tempfile.TemporaryDirectory(prefix="pycatkin_chaos_")
        work_dir = own_td.name
    pack_path = os.path.join(work_dir, "chaos_pack.tar.gz")
    tickets = os.path.join(work_dir, "fault_tickets")
    endpoints_path = os.path.join(work_dir, "endpoints.json")
    journal_dir = os.path.join(work_dir, "journal")
    router_tickets = os.path.join(work_dir, "fault_tickets_router")
    supervisor = router = router_sup = client = None
    router_events: list = []
    drill_ok = False
    try:
        # -- phase 1: undisturbed baseline + pack ----------------------
        say(f"baseline: {n_requests} requests, in-process")
        base_cfg = ServeConfig(port=0, max_occupancy=max_occupancy)
        base = await SweepServer(base_cfg).start(listen=False)
        k_buckets = sorted({1 << i for i in range(
            max(1, max_occupancy).bit_length())} | {max_occupancy})
        await asyncio.to_thread(base.warm, sims, lanes,
                                tuple(k for k in k_buckets if k > 1))
        bclient = SweepClient(base)
        sem = asyncio.Semaphore(8)

        async def base_one(i):
            mi, T, cls = plan_grid[i]
            async with sem:
                return await bclient.sweep(mech_dicts[mi], T,
                                           deadline_class=cls)
        base_resps = await asyncio.gather(
            *(base_one(i) for i in range(n_requests)))
        bad = [r for r in base_resps if not r.get("ok")]
        if bad:
            raise RuntimeError(f"baseline run failed: {bad[:3]}")
        baseline = [_canonical(r) for r in base_resps]
        backend = ((base.boot_manifest.get("backend") or {})
                   .get("platform")) or "cpu"
        await base.drain()
        if with_pack:
            from ..parallel.compile_pool import export_cache_pack
            stats = await asyncio.to_thread(export_cache_pack,
                                            pack_path)
            say(f"exported boot pack ({stats['entries']} entries)")

        # -- phase 2: fleet + router -----------------------------------
        replica_cache = os.path.join(work_dir, "replica_cache")
        env = {"PYCATKIN_ABI": "1"}
        if with_pack:
            env["PYCATKIN_AOT_CACHE"] = replica_cache
        cmd = [sys.executable, "-m", "pycatkin_tpu.serve",
               "--host", "127.0.0.1", "--port", "0",
               "--max-occupancy", str(max_occupancy)]
        supervisor = ReplicaSupervisor(FleetConfig(
            n_replicas=n_replicas, command=cmd, env=env,
            aot_pack=pack_path if with_pack else None,
            endpoints_file=endpoints_path if router_crash else None))
        say(f"booting {n_replicas} replicas"
            f"{' from pack' if with_pack else ''}")
        await supervisor.start()
        if router_crash:
            # The router is a supervised subprocess on a FIXED port:
            # clients must reconnect to the same address after its
            # SIGKILL. Dispatch-level chaos rides in its environment
            # (separate ticket dir -- spec indices must not collide
            # with the parent plan's), and the journal segment cap is
            # raised so drill answers never compact out of the dedup
            # window mid-audit.
            dispatch_specs = [
                {"site": "router:dispatch:*", "kind": "conn-reset",
                 "times": 1},
                {"site": "router:dispatch:*", "kind": "torn-line",
                 "times": 1}]
            renv = {"PYCATKIN_ABI": "1",
                    faults.ENV_VAR: json.dumps(
                        {"specs": dispatch_specs,
                         "state_dir": router_tickets}),
                    "PYCATKIN_DURABLE_SEGMENT_BYTES": str(1 << 30)}
            router_port = _free_port()
            rcmd = [sys.executable, "-m", "pycatkin_tpu.serve",
                    "--router", "--host", "127.0.0.1",
                    "--port", str(router_port),
                    "--fleet-file", endpoints_path,
                    "--journal-dir", journal_dir]
            router_sup = ReplicaSupervisor(FleetConfig(
                role="router", command=rcmd, env=renv))
            router_sup.add_listener(
                lambda info: router_events.append(
                    (time.monotonic(), dict(info))))
            say(f"booting the journal-backed router subprocess "
                f"on port {router_port}")
            await router_sup.start()
        else:
            router = await SweepRouter(supervisor).start()
            router_port = router.port
        client = await TcpSweepClient("127.0.0.1",
                                      router_port).connect()

        # -- phase 3: stream + mid-soak chaos --------------------------
        results: list = [None] * n_requests
        done_box = {"n": 0}

        async def fleet_one(i):
            mi, T, cls = plan_grid[i]
            async with sem:
                resp = await client.request(sweep_payload(
                    mech_dicts[mi], T, deadline_class=cls,
                    req_id=f"q{i}",
                    idempotency_key=(f"q{i}" if router_crash
                                     else None)))
            results[i] = resp
            done_box["n"] += 1

        say(f"streaming {n_requests} requests through the router")
        drive = asyncio.ensure_future(asyncio.gather(
            *(fleet_one(i) for i in range(n_requests))))
        trigger = max(1, n_requests // 3)
        while done_box["n"] < trigger and not drive.done():
            await asyncio.sleep(0.01)
        specs = [{"site": f"router:replica:{i}",
                  "kind": "replica-crash", "times": 1}
                 for i in range(kill)]
        if router_crash:
            # Dispatch faults live in the router subprocess's own
            # plan; the parent enacts the kills, router included.
            specs += [{"site": "router:front", "kind": "router-crash",
                       "times": 1}]
        else:
            specs += [{"site": "router:dispatch:*",
                       "kind": "conn-reset", "times": 1},
                      {"site": "router:dispatch:*", "kind": "torn-line",
                       "times": 1}]
        chaos = faults.FaultPlan(specs, state_dir=tickets)
        say(f"chaos: SIGKILLing {kill} of {n_replicas} replicas"
            f"{' + the front router' if router_crash else ''} "
            f"mid-soak")
        with faults.fault_scope(chaos):
            await drive
        kills_fired = [e for e in chaos.log
                       if e["kind"] == "replica-crash"]
        router_kills = [e for e in chaos.log
                        if e["kind"] == "router-crash"]

        # -- phase 4: audit --------------------------------------------
        say("waiting for killed replicas to reboot from the pack")
        reboot_deadline = time.monotonic() + 120.0
        killed = [supervisor.replicas[i] for i in range(kill)]
        while time.monotonic() < reboot_deadline and any(
                r.state != "abandoned"
                and (r.incarnation < 2 or not r.routable)
                for r in killed):
            await asyncio.sleep(0.1)

        durable_audit = None
        if router_crash:
            say("waiting for the rebooted router + journal replay")
            rrep = router_sup.replicas[0]
            while time.monotonic() < reboot_deadline \
                    and rrep.state != "abandoned" \
                    and (rrep.incarnation < 2 or not rrep.routable):
                await asyncio.sleep(0.1)
            # Recovery wall: the supervisor's down event (router died)
            # to the next up event (rebooted, registered, routable).
            recovery_s = None
            down_t = None
            for t, ev in router_events:
                if ev["event"] == "down" and down_t is None:
                    down_t = t
                elif ev["event"] == "up" and down_t is not None:
                    recovery_s = t - down_t
                    break
            # Journal replay must have finished before the per-key
            # audit (a key still in flight would fail the fetch).
            replay = {}
            durable = {}
            while time.monotonic() < reboot_deadline:
                st = await client.stats()
                durable = ((st.get("stats") or {}).get("durable")
                           if st.get("ok") else None) or {}
                replay = durable.get("replay") or {}
                if durable and not replay.get("active"):
                    break
                await asyncio.sleep(0.1)
            # Every key's journaled answer, fetched over the wire,
            # must be bitwise identical to the baseline: one key, one
            # answer, forever.
            result_bad = []
            for i in range(n_requests):
                rr = await client.fetch_result(f"q{i}")
                if not rr.get("ok") or _canonical(rr) != baseline[i]:
                    result_bad.append(
                        {"key": f"q{i}",
                         "error": rr.get("error"),
                         "mismatch": bool(rr.get("ok"))})
            durable_audit = {
                "router_kills_fired": len(router_kills),
                "router_incarnations": rrep.incarnation,
                "router_recovery_s": recovery_s,
                "journal_replay_s": replay.get("wall_s"),
                "replay": replay,
                "duplicates_served": durable.get("duplicates_served"),
                "coalesced": durable.get("coalesced"),
                "client_reconnects": client.reconnects,
                "client_acks": client.acks,
                "result_fetch_bad": result_bad,
            }

        n_ok = sum(1 for r in results if r and r.get("ok"))
        mismatches = [i for i, r in enumerate(results)
                      if r and r.get("ok")
                      and _canonical(r) != baseline[i]]
        replica_stats = {}
        reverify_bad = []
        for r in supervisor.replicas:
            if not r.routable:
                continue
            rc = await TcpSweepClient("127.0.0.1",
                                      r.port).connect()
            try:
                if r.incarnation > 1:
                    # One direct sweep through the REBOOTED replica:
                    # its answer must match the baseline bit for bit.
                    mi, T, cls = plan_grid[0]
                    resp = await rc.request(sweep_payload(
                        mech_dicts[mi], T, deadline_class=cls,
                        req_id=f"verify{r.idx}"))
                    if not resp.get("ok") or \
                            _canonical(resp) != baseline[0]:
                        reverify_bad.append(r.idx)
                st = await rc.stats()
                replica_stats[str(r.idx)] = (st.get("stats")
                                             if st.get("ok") else None)
            finally:
                await rc.close()
        zero_compile_bad = []
        if with_pack:
            for idx, st in replica_stats.items():
                if not st or not st.get("flushes") \
                        or st.get("flushes_with_compiles"):
                    zero_compile_bad.append(
                        {"replica": idx,
                         "flushes": st.get("flushes") if st else None,
                         "flushes_with_compiles":
                             st.get("flushes_with_compiles")
                             if st else None})
        if router_crash:
            st = await client.stats()
            rstats = (st.get("stats") or {}) if st.get("ok") else {}
        else:
            rstats = router.stats()
        await client.close()
        if router is not None:
            await router.drain()
        if router_sup is not None:
            await router_sup.stop()
        await supervisor.stop()
        drill_ok = True
    finally:
        if not drill_ok:
            # Best-effort teardown on the failure path so a raising
            # drill never strands replica subprocesses.
            for closer in (client and client.close,
                           router and router.stop,
                           router_sup and router_sup.stop,
                           supervisor and supervisor.stop):
                if closer is None:
                    continue
                try:
                    await closer()
                except Exception:
                    pass
        if own_td is not None:
            own_td.cleanup()

    incarnations = [r.incarnation for r in supervisor.replicas]
    record = {
        "bench": "serve-chaos-drill", "schema": CHAOS_SCHEMA,
        "backend": backend, "with_pack": bool(with_pack),
        "router_crash": bool(router_crash),
        "durable": durable_audit,
        "n_requests": n_requests, "n_ok": n_ok,
        "n_failed": n_requests - n_ok,
        "bucket": bucket, "lanes": lanes, "mechs": mechs,
        "max_occupancy": max_occupancy, "seed": seed,
        "n_replicas": n_replicas, "kill": kill,
        "kills_fired": len(kills_fired),
        "chaos_log": chaos.log,
        "incarnations": incarnations,
        "router": {
            "availability": rstats.get("availability"),
            "failover_p99_s": rstats.get("failover_p99_s"),
            "retries": rstats.get("retries"),
            "hedges": rstats.get("hedges"),
            "failovers": rstats.get("failovers"),
            "duplicates": rstats.get("duplicates"),
            "lost": n_requests - n_ok,
            "bitwise_mismatches": len(mismatches),
            "reverify_failed": reverify_bad,
            "zero_compile_violations": zero_compile_bad,
        },
        "router_stats": rstats,
        "replica_stats": replica_stats,
        "failures": [r.get("error") for r in results
                     if r and not r.get("ok")][:10],
        "wall_s": time.monotonic() - t_wall0,
    }
    return record


def run_chaos_drill(out_path: Optional[str] = None, **kwargs) -> dict:
    """Synchronous entry for the chaos drill (forces the ABI gate on,
    like :func:`run_soak`); optionally writes the record.

    Unless the caller pinned them, the per-class request timeouts are
    widened to the standard budget for the drill's duration: on the
    CPU CI backend a flush takes seconds and a killed replica's
    pack-warmed reboot tens of seconds, so the production
    ``interactive`` SLA would turn backend slowness into fake request
    loss -- the drill gates on LOSS under chaos, not on CPU latency.
    """
    from .protocol import _TIMEOUT_ENVS, request_timeout_for
    saved = {}
    for var in ("PYCATKIN_ABI", *(_TIMEOUT_ENVS.values())):
        saved[var] = os.environ.get(var)
    os.environ["PYCATKIN_ABI"] = "1"
    for cls, var in _TIMEOUT_ENVS.items():
        if saved[var] is None:
            os.environ[var] = str(request_timeout_for("standard"))
    try:
        record = asyncio.run(chaos_drill_async(**kwargs))
    finally:
        for var, val in saved.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
    return record


def check_chaos_record(record: dict) -> list:
    """Gate a chaos-drill record; returns failure strings (empty =
    pass). ``make router-check`` and ``bench.py --smoke`` share it."""
    problems = []
    router = record.get("router") or {}
    if router.get("lost"):
        problems.append(f"{router['lost']} of "
                        f"{record.get('n_requests')} requests lost "
                        f"during the drill: {record.get('failures')}")
    if router.get("bitwise_mismatches"):
        problems.append(f"{router['bitwise_mismatches']} answers "
                        f"differ bitwise from the undisturbed "
                        f"baseline run")
    dups = router.get("duplicates") or {}
    if dups.get("mismatched"):
        problems.append(f"duplicate-suppression audit: "
                        f"{dups['mismatched']} suppressed answers "
                        f"were NOT bit-identical to the delivered one")
    if record.get("kills_fired", 0) < record.get("kill", 0):
        problems.append(f"chaos plan fired only "
                        f"{record.get('kills_fired')} of "
                        f"{record.get('kill')} replica kills")
    incs = record.get("incarnations") or []
    restarted = sum(1 for i in incs[:record.get("kill", 0)] if i >= 2)
    if restarted < record.get("kill", 0):
        problems.append(f"only {restarted} of {record.get('kill')} "
                        f"killed replicas came back "
                        f"(incarnations={incs})")
    if router.get("reverify_failed"):
        problems.append(f"rebooted replicas "
                        f"{router['reverify_failed']} answered the "
                        f"verification sweep wrong")
    if record.get("with_pack") and router.get("zero_compile_violations"):
        problems.append(f"pack-booted replicas compiled during "
                        f"flushes: {router['zero_compile_violations']}")
    if record.get("router_crash"):
        durable = record.get("durable")
        if not durable:
            problems.append("router-crash drill produced no durable "
                            "audit")
            return problems
        if not durable.get("router_kills_fired"):
            problems.append("the router-crash fault never fired")
        if (durable.get("router_incarnations") or 0) < 2:
            problems.append(
                f"the killed router never came back (incarnations="
                f"{durable.get('router_incarnations')})")
        if durable.get("result_fetch_bad"):
            bad = durable["result_fetch_bad"]
            problems.append(
                f"{len(bad)} journaled answers missing or not "
                f"bitwise identical to the baseline: {bad[:3]}")
        replay = durable.get("replay") or {}
        if replay.get("failed"):
            problems.append(f"journal replay failed to re-answer "
                            f"{replay['failed']} accepted requests")
    return problems


DURABLE_SCHEMA = "pycatkin-serve-durable-smoke/v1"


def _write_json_file(path: str, obj) -> None:
    with open(path, "w") as fh:
        json.dump(obj, fh)


def _append_bytes(path: str, data: bytes) -> None:
    with open(path, "ab") as fh:
        fh.write(data)


async def durable_smoke_async(n_keys: int = 6, lanes: int = 2,
                              work_dir: Optional[str] = None,
                              verbose: bool = False) -> dict:
    """The durable-serving smoke (``bench.py --smoke`` ``durable_ok``
    gate): a miniature journal round-trip plus a router-kill replay,
    JAX-free (stub replica) so it runs in seconds.

    1. **journal round-trip** -- a tiny-segment
       :class:`durable.RequestJournal` takes accepted/answered records
       through rotation and compaction, gets its tail torn mid-record
       (a kill mid-append), and must replay losing NOTHING but the
       torn -- never acknowledged -- final line;
    2. **router-kill replay** -- router A (journal-backed, fronting a
       deterministic stub replica through a :class:`fleet.FileFleet`
       endpoints file) answers ``n_keys`` keyed sweeps; two extra keys
       are journaled accepted-but-unanswered, modeling a router killed
       between fsynced ack and dispatch; router A stops WITHOUT
       draining. Router B boots over the same journal: it must
       re-dispatch exactly the pending backlog, serve a resubmitted
       key bitwise from the journal, and answer a ``result`` fetch
       identically.
    """
    import tempfile

    from .client import TcpSweepClient, sweep_payload
    from .durable import RequestJournal
    from .fleet import FileFleet
    from .protocol import PROTOCOL
    from .router import RouterConfig, SweepRouter, _canonical

    t_wall0 = time.monotonic()

    def say(msg):
        if verbose:
            print(f"durable-smoke: {msg}", flush=True)

    own_td = None
    if work_dir is None:
        own_td = tempfile.TemporaryDirectory(
            prefix="pycatkin_durable_")
        work_dir = own_td.name

    # -- phase 1: journal round-trip ----------------------------------
    n_rt = 8
    jdir1 = os.path.join(work_dir, "roundtrip")
    j = await asyncio.to_thread(RequestJournal, jdir1, 128)
    for i in range(n_rt):
        await asyncio.to_thread(j.record_accepted, f"rt{i}",
                                {"op": "sweep", "n": i})
        await asyncio.to_thread(
            j.record_answered, f"rt{i}",
            {"ok": True, "result": {"n": i}, "quarantine": [],
             "lanes": 1, "id": f"rt{i}"})
    await asyncio.to_thread(j.record_accepted, "rt-pending",
                            {"op": "sweep", "n": -1})
    st1 = j.stats()
    # Tear the active segment's tail mid-record, as a SIGKILL between
    # write and fsync would; the torn key was never acknowledged, so
    # replay must drop it and keep everything before it.
    torn_path = os.path.join(
        jdir1, f"requests_{st1['active_segment']:05d}.jsonl")
    await asyncio.to_thread(_append_bytes, torn_path,
                            b'{"kind": "accepted", "key": "torn')
    j2 = await asyncio.to_thread(RequestJournal, jdir1)
    last = f"rt{n_rt - 1}"
    roundtrip = {
        "n": n_rt,
        "rotations": st1["rotations"],
        "compacted_segments": st1["compacted_segments"],
        # Compaction deletes fully-answered sealed segments WITH their
        # answered records -- that is the documented dedup-window
        # bound -- so only answers in segments still on disk replay.
        # The LAST answer always lands in a segment compaction never
        # ran on, so its survival is the deterministic gate.
        "answers_survived": sum(
            1 for i in range(n_rt)
            if (j2.answered_response(f"rt{i}") or {}).get("result")
            == {"n": i}),
        "last_answer_survived": (
            (j2.answered_response(last) or {}).get("result")
            == {"n": n_rt - 1}),
        "pending_survived": [k for k, _ in j2.unanswered()],
        "torn_key_leaked": j2.is_accepted("torn"),
        "replayed_records": j2.stats()["replayed_records"],
    }
    say(f"roundtrip: {roundtrip}")

    # -- phase 2: stub fleet + journal-backed router A ----------------
    async def stub_handler(reader, writer):
        # A wire-compatible replica whose answer is a pure function of
        # the request's conditions: bitwise identity across dispatches
        # and router incarnations is checkable with canonical_answer.
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    req = json.loads(line)
                except ValueError:
                    continue
                if req.get("op") == "ping":
                    resp = {"protocol": PROTOCOL, "id": req.get("id"),
                            "ok": True, "pong": True}
                else:
                    T = list((req.get("conditions") or {})
                             .get("T") or [])
                    resp = {"protocol": PROTOCOL, "id": req.get("id"),
                            "ok": True,
                            "result": {"success": [True] * len(T),
                                       "T": T},
                            "quarantine": [], "lanes": len(T)}
                writer.write((json.dumps(resp) + "\n").encode())
                await writer.drain()
        except (ConnectionError, OSError):
            pass

    stub = await asyncio.start_server(stub_handler, "127.0.0.1", 0)
    stub_port = stub.sockets[0].getsockname()[1]
    endpoints_path = os.path.join(work_dir, "endpoints.json")
    await asyncio.to_thread(_write_json_file, endpoints_path, {
        "endpoints": [{"idx": 0, "incarnation": 1,
                       "host": "127.0.0.1", "port": stub_port}]})
    jdir = os.path.join(work_dir, "journal")
    router_a = router_b = client = None
    try:
        router_a = await SweepRouter(
            FileFleet(endpoints_path),
            RouterConfig(port=0, journal_dir=jdir)).start()
        client = await TcpSweepClient("127.0.0.1",
                                      router_a.port).connect()
        say(f"router A answering {n_keys} keyed sweeps")
        baseline = {}
        for i in range(n_keys):
            resp = await client.request(sweep_payload(
                {"stub": True}, [500.0 + i] * lanes,
                req_id=f"s{i}", idempotency_key=f"k{i}"))
            if not resp.get("ok"):
                raise RuntimeError(f"stub sweep failed: {resp}")
            baseline[f"k{i}"] = _canonical(resp)
        acks_a = client.acks
        await client.close()
        client = None
        # Model a router killed between fsynced ack and dispatch: the
        # journal holds accepted records no answer ever followed.
        for i in range(2):
            await asyncio.to_thread(
                router_a._journal.record_accepted, f"pending{i}",
                {"op": "sweep", "mechanism": {"stub": True},
                 "conditions": {"T": [600.0 + i] * lanes},
                 "deadline_class": "standard",
                 "idempotency_key": f"pending{i}"})
        await router_a.stop()   # no drain: the "kill"
        router_a = None

        # -- phase 3: router B replays the journal --------------------
        say("booting router B over the same journal")
        t0 = time.monotonic()
        router_b = await SweepRouter(
            FileFleet(endpoints_path),
            RouterConfig(port=0, journal_dir=jdir)).start()
        recovery_s = time.monotonic() - t0
        deadline = time.monotonic() + 30.0
        replay = {}
        while time.monotonic() < deadline:
            replay = router_b.stats()["durable"]["replay"]
            if not replay.get("active"):
                break
            await asyncio.sleep(0.01)
        client = await TcpSweepClient("127.0.0.1",
                                      router_b.port).connect()
        # A duplicate of an answered key must come back bitwise from
        # the journal, not from a fresh dispatch.
        dup = await client.request(sweep_payload(
            {"stub": True}, [500.0] * lanes, req_id="dup0",
            idempotency_key="k0"))
        fetch = await client.fetch_result("k1")
        pend = await client.fetch_result("pending0")
        bstats = router_b.stats()["durable"]
        await client.close()
        client = None
        await router_b.drain()
        router_b = None
    finally:
        for closer in (client and client.close,
                       router_a and router_a.stop,
                       router_b and router_b.stop):
            if closer is None:
                continue
            try:
                await closer()
            except Exception:
                pass
        stub.close()
        await stub.wait_closed()
        if own_td is not None:
            own_td.cleanup()

    record = {
        "bench": "serve-durable-smoke", "schema": DURABLE_SCHEMA,
        "n_keys": n_keys, "lanes": lanes,
        "roundtrip": roundtrip,
        "replay": dict(replay, pending_expected=2,
                       router_recovery_s=recovery_s),
        "dup": {
            "served": bstats.get("duplicates_served"),
            "bitwise_ok": bool(dup.get("ok")
                               and _canonical(dup) == baseline["k0"]),
            "result_ok": bool(fetch.get("ok")
                              and _canonical(fetch) == baseline["k1"]),
            "replayed_pending_ok": bool(pend.get("ok")),
            "acks": acks_a,
        },
        "journal": bstats.get("journal"),
        "wall_s": time.monotonic() - t_wall0,
    }
    return record


def run_durable_smoke(out_path: Optional[str] = None,
                      **kwargs) -> dict:
    """Synchronous entry for the durable smoke; optionally writes the
    record (the ``make durable-check`` CI lane does)."""
    record = asyncio.run(durable_smoke_async(**kwargs))
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
    return record


def check_durable_record(record: dict) -> list:
    """Gate a durable-smoke record; returns failure strings (empty =
    pass). ``make durable-check`` and ``bench.py --smoke`` share it."""
    problems = []
    rt = record.get("roundtrip") or {}
    if not rt.get("rotations"):
        problems.append("journal round-trip never rotated a segment")
    if not rt.get("compacted_segments"):
        problems.append("journal round-trip never compacted a "
                        "fully-answered segment")
    if not rt.get("last_answer_survived"):
        problems.append("the newest journaled answer (whose segment "
                        "was never compacted) did not survive replay")
    if rt.get("pending_survived") != ["rt-pending"]:
        problems.append(f"pending keys after replay: "
                        f"{rt.get('pending_survived')} "
                        f"(expected ['rt-pending'])")
    if rt.get("torn_key_leaked"):
        problems.append("a torn (never-acknowledged) final record "
                        "leaked into the replayed journal")
    replay = record.get("replay") or {}
    if (replay.get("total") != replay.get("pending_expected")
            or replay.get("failed")
            or replay.get("done") != replay.get("total")):
        problems.append(f"router-kill replay did not re-answer the "
                        f"journal backlog: {replay}")
    dup = record.get("dup") or {}
    if not dup.get("bitwise_ok"):
        problems.append("a duplicate keyed request was not answered "
                        "bitwise from the journal")
    if not dup.get("result_ok"):
        problems.append("the result op did not return the journaled "
                        "answer bitwise")
    if not dup.get("replayed_pending_ok"):
        problems.append("a replayed accepted-but-unanswered key has "
                        "no fetchable answer")
    if not dup.get("served"):
        problems.append("the duplicates-served counter never moved")
    if not dup.get("acks"):
        problems.append("the client received no durability ack lines")
    return problems


def check_soak_record(record: dict, p99_budget_s: float = 30.0,
                      expect_zero_compiles: bool = True,
                      expect_warm_compiled_zero: bool = False) -> list:
    """Gate a soak record; returns a list of failure strings (empty =
    pass). The serve-check CI lane and ``bench.py --smoke`` both call
    this, so the gate logic cannot drift between them."""
    problems = []
    serve = record.get("serve") or {}
    if record.get("n_failed"):
        problems.append(f"{record['n_failed']} measured requests "
                        f"failed: {record.get('failures')}")
    if record.get("n_ok") != record.get("n_requests"):
        problems.append(f"only {record.get('n_ok')} of "
                        f"{record.get('n_requests')} measured requests "
                        f"returned ok")
    if record.get("schema_violations"):
        problems.append(f"{record['schema_violations']} responses "
                        f"missing manifest/telemetry/quarantine: "
                        f"{record.get('violations')}")
    if expect_zero_compiles and serve.get("zero_compile_rate") != 1.0:
        problems.append(f"zero-compile rate after warmup is "
                        f"{serve.get('zero_compile_rate')} "
                        f"(compiles_after_warm="
                        f"{serve.get('compiles_after_warm')}), not 1.0")
    p99 = serve.get("p99_s")
    if p99 is None or p99 > p99_budget_s:
        problems.append(f"p99 latency {p99}s over budget "
                        f"{p99_budget_s}s")
    if not serve.get("drain_burst_ok"):
        problems.append("graceful drain lost or failed burst requests")
    if (expect_warm_compiled_zero
            and ((record.get("warmup") or {}).get("prewarm") or {})
            .get("compiled") != 0):
        problems.append(
            f"pack-warmed boot still compiled "
            f"{record['warmup']['prewarm'].get('compiled')} programs "
            f"(AOT pack miss)")
    return problems
