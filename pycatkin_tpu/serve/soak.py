"""Soak harness: stream randomized synthetic mechanisms through a
live :class:`serve.server.SweepServer` and report serving metrics in a
BENCH-style JSON record (``tools/soak.py`` is the CLI; the bench smoke
gate runs a miniature in-process soak).

Phases:

1. **pool** -- seed-deterministic mechanisms per requested ABI bucket
   (:func:`models.synthetic.synthetic_system_for_bucket`), so the soak
   controls pack occupancy bucket by bucket;
2. **warm** -- the server's prewarm (solo zoo + packed executables per
   k_bucket), then one streamed burst per bucket through the real
   serving path; everything after :meth:`SweepServer.mark_warm` counts
   against the zero-compile gate;
3. **measure** -- ``n_requests`` concurrent sweeps, round-robin over
   buckets, random mechanism + temperature grid per request; client-
   side latency per request, response-schema presence audited;
4. **drain burst** -- a final burst is submitted and the server is
   drained WHILE they are pending: graceful drain must complete every
   accepted request (no-loss proof).

The resulting record carries ``serve.p50_s`` / ``serve.p99_s`` /
``serve.zero_compile_rate`` / ``serve.mean_occupancy``, which
``obs/history.py`` tracks with the same median±MAD sentinel as sweep
throughput (tools/perfwatch.py).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Optional

import numpy as np

SCHEMA = "pycatkin-serve-soak/v1"

# Response fields every ok sweep response must carry (acceptance:
# manifest, telemetry and quarantine round-trip on EVERY response).
REQUIRED_RESPONSE_FIELDS = ("result", "manifest", "lane_telemetry",
                            "quarantine", "pack", "timing")


def _audit_response(resp: dict) -> list:
    """Names of required fields missing from an ok response.
    ``lane_telemetry`` must be present but may be null (a runner that
    produced none); everything else must be a real value."""
    bad = [f for f in REQUIRED_RESPONSE_FIELDS
           if f not in resp
           or (resp[f] is None and f != "lane_telemetry")]
    # Verdict arrays must arrive as real JSON lists, one entry per
    # lane -- a serializer regression that ships reprs instead of
    # values (e.g. an unhandled array type) is a schema violation,
    # not a cosmetic one.
    result = resp.get("result")
    if isinstance(result, dict):
        succ = result.get("success")
        if not (isinstance(succ, list)
                and len(succ) == resp.get("lanes")):
            bad.append("result.success")
    return bad


def _percentile(values, q) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, dtype=float), q))


async def soak_async(n_requests: int = 1000, buckets=(16, 32, 128),
                     lanes: int = 4, seed: int = 0,
                     transport: str = "inproc",
                     mechs_per_bucket: int = 6,
                     max_occupancy: int = 8,
                     concurrency: int = 16,
                     runner: str = "inproc",
                     aot_pack: Optional[str] = None,
                     deadline_class: str = "standard",
                     t_range=(480.0, 520.0),
                     drain_burst: Optional[int] = None,
                     verbose: bool = False) -> dict:
    """Run the full soak against a fresh server; returns the BENCH
    record. ``transport`` is ``"inproc"`` (direct handler calls,
    mechanisms passed as built Systems) or ``"tcp"`` (full JSON wire
    round-trip on localhost)."""
    from ..models.synthetic import synthetic_system_for_bucket
    from .client import SweepClient, TcpSweepClient
    from .protocol import ServeConfig
    from .server import SweepServer

    rng = np.random.default_rng(seed)
    t_wall0 = time.monotonic()

    def say(msg):
        if verbose:
            print(f"soak: {msg}", flush=True)

    # -- phase 1: mechanism pool --------------------------------------
    say(f"building pool: {mechs_per_bucket} mechanisms x "
        f"{len(buckets)} buckets")
    pool = {b: [synthetic_system_for_bucket(
                    b, seed=int(rng.integers(0, 2**31)))
                for _ in range(mechs_per_bucket)]
            for b in buckets}

    cfg = ServeConfig(port=0, runner=runner, aot_pack=aot_pack,
                      max_occupancy=max_occupancy)
    server = await SweepServer(cfg).start(listen=(transport == "tcp"))
    tcp = None
    if transport == "tcp":
        tcp = await TcpSweepClient("127.0.0.1", server.port).connect()
        client = tcp
    elif transport == "inproc":
        client = SweepClient(server)
    else:
        raise ValueError(f"transport must be 'inproc' or 'tcp', "
                         f"got {transport!r}")

    def payload_mech(sim):
        # TCP exercises the full wire schema; in-proc skips the JSON
        # round-trip (the production embedding's fast path).
        if transport == "tcp":
            from ..utils.io import system_to_dict
            return system_to_dict(sim)
        return sim

    def random_T():
        return [float(t) for t in rng.uniform(*t_range, size=lanes)]

    async def one_request(sim, sem, latencies, failures, violations):
        async with sem:
            t0 = time.monotonic()
            resp = await client.sweep(payload_mech(sim), random_T(),
                                      deadline_class=deadline_class)
            dt = time.monotonic() - t0
            if resp.get("ok"):
                latencies.append(dt)
                missing = _audit_response(resp)
                if missing:
                    violations.append({"id": resp.get("id"),
                                       "missing": missing})
            else:
                failures.append(resp.get("error", {}))

    try:
        # -- phase 2: warm --------------------------------------------
        say("prewarming (solo zoo + packed executables)")
        k_buckets = sorted({1 << i for i in range(
            max(1, max_occupancy).bit_length())} | {max_occupancy})
        prewarm = await asyncio.to_thread(
            server.warm, [pool[b][0] for b in buckets], lanes,
            tuple(k for k in k_buckets if k > 1))
        say(f"prewarm: {prewarm}")
        warm_lat, warm_fail, warm_viol = [], [], []
        sem = asyncio.Semaphore(concurrency)
        warm_jobs = []
        for b in buckets:
            # One full burst (packs) plus one straggler (K=1 flush)
            # per bucket, through the real serving path.
            for i in range(max_occupancy):
                warm_jobs.append(one_request(
                    pool[b][i % len(pool[b])], sem, warm_lat,
                    warm_fail, warm_viol))
        await asyncio.gather(*warm_jobs)
        for b in buckets:
            await one_request(pool[b][0], sem, warm_lat, warm_fail,
                              warm_viol)
        server.mark_warm()
        n_warmup = len(warm_lat) + len(warm_fail)
        say(f"warmup done: {n_warmup} requests "
            f"({len(warm_fail)} failed)")

        # -- phase 3: measured stream ---------------------------------
        latencies, failures, violations = [], [], []
        jobs = []
        for i in range(n_requests):
            b = buckets[i % len(buckets)]
            sim = pool[b][int(rng.integers(0, len(pool[b])))]
            jobs.append(one_request(sim, sem, latencies, failures,
                                    violations))
        say(f"streaming {n_requests} measured requests "
            f"(concurrency {concurrency})")
        t_meas0 = time.monotonic()
        await asyncio.gather(*jobs)
        measure_s = time.monotonic() - t_meas0
        say(f"measured phase: {measure_s:.1f}s, "
            f"{len(failures)} failures")

        # -- phase 4: drain burst (no-loss proof) ---------------------
        nb = (len(buckets) * max_occupancy if drain_burst is None
              else drain_burst)
        burst_lat, burst_fail, burst_viol = [], [], []
        burst = [one_request(pool[buckets[i % len(buckets)]][0], sem,
                             burst_lat, burst_fail, burst_viol)
                 for i in range(nb)]
        completed0 = server.stats()["completed_total"]
        burst_tasks = [asyncio.ensure_future(j) for j in burst]
        # Drain only once every burst request is past admission (over
        # TCP that takes a round-trip): the no-loss claim is about
        # ACCEPTED requests, and draining earlier would just reject
        # them at the door.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            done = server.stats()["completed_total"] - completed0
            if done + server.in_service >= nb:
                break
            await asyncio.sleep(0.002)
        drain_task = asyncio.ensure_future(server.drain())
        await asyncio.gather(*burst_tasks)
        await drain_task
        stats = server.stats()
        drain_burst_ok = (len(burst_lat) + len(burst_fail) == nb
                          and not burst_fail and not burst_viol)
        say(f"drain complete; burst ok={drain_burst_ok}")
    finally:
        if tcp is not None:
            await tcp.close()
        await server.stop()

    backend = ((server.boot_manifest.get("backend") or {})
               .get("platform")) or "cpu"
    zero_rate = stats.get("zero_compile_rate_after_warm")
    record = {
        "bench": "serve-soak", "schema": SCHEMA,
        "backend": backend, "transport": transport, "runner": runner,
        "aot_pack": bool(aot_pack),
        "n_requests": n_requests, "n_ok": len(latencies),
        "n_failed": len(failures),
        "n_warmup": n_warmup, "n_drain_burst": nb,
        "buckets": list(buckets), "lanes": lanes,
        "mechs_per_bucket": mechs_per_bucket,
        "max_occupancy": max_occupancy, "concurrency": concurrency,
        "seed": seed,
        "schema_violations": len(violations) + len(warm_viol),
        "warmup": {"prewarm": prewarm,
                   "requests": n_warmup,
                   "failed": len(warm_fail)},
        "serve": {
            "p50_s": _percentile(latencies, 50),
            "p99_s": _percentile(latencies, 99),
            "mean_s": (float(np.mean(latencies)) if latencies
                       else None),
            "throughput_rps": (len(latencies) / measure_s
                               if measure_s > 0 else None),
            "zero_compile_rate": zero_rate,
            "mean_occupancy": stats.get("mean_occupancy"),
            "flushes": stats.get("flushes"),
            "flushes_after_warm": stats.get("flushes_after_warm"),
            "compiles_after_warm": stats.get("compiles_after_warm"),
            "rejects": stats.get("rejected_total"),
            "drain_burst_ok": bool(drain_burst_ok),
        },
        "server_stats": stats,
        "failures": failures[:10],
        "violations": (violations + warm_viol)[:10],
        "wall_s": time.monotonic() - t_wall0,
        "manifest": server.boot_manifest,
    }
    return record


def run_soak(out_path: Optional[str] = None, **kwargs) -> dict:
    """Synchronous entry: force the ABI gate on (packing is the whole
    point of the service), run :func:`soak_async`, optionally write
    the record to ``out_path``."""
    prev_abi = os.environ.get("PYCATKIN_ABI")
    os.environ["PYCATKIN_ABI"] = "1"
    try:
        record = asyncio.run(soak_async(**kwargs))
    finally:
        if prev_abi is None:
            os.environ.pop("PYCATKIN_ABI", None)
        else:
            os.environ["PYCATKIN_ABI"] = prev_abi
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
    return record


def check_soak_record(record: dict, p99_budget_s: float = 30.0,
                      expect_zero_compiles: bool = True,
                      expect_warm_compiled_zero: bool = False) -> list:
    """Gate a soak record; returns a list of failure strings (empty =
    pass). The serve-check CI lane and ``bench.py --smoke`` both call
    this, so the gate logic cannot drift between them."""
    problems = []
    serve = record.get("serve") or {}
    if record.get("n_failed"):
        problems.append(f"{record['n_failed']} measured requests "
                        f"failed: {record.get('failures')}")
    if record.get("n_ok") != record.get("n_requests"):
        problems.append(f"only {record.get('n_ok')} of "
                        f"{record.get('n_requests')} measured requests "
                        f"returned ok")
    if record.get("schema_violations"):
        problems.append(f"{record['schema_violations']} responses "
                        f"missing manifest/telemetry/quarantine: "
                        f"{record.get('violations')}")
    if expect_zero_compiles and serve.get("zero_compile_rate") != 1.0:
        problems.append(f"zero-compile rate after warmup is "
                        f"{serve.get('zero_compile_rate')} "
                        f"(compiles_after_warm="
                        f"{serve.get('compiles_after_warm')}), not 1.0")
    p99 = serve.get("p99_s")
    if p99 is None or p99 > p99_budget_s:
        problems.append(f"p99 latency {p99}s over budget "
                        f"{p99_budget_s}s")
    if not serve.get("drain_burst_ok"):
        problems.append("graceful drain lost or failed burst requests")
    if (expect_warm_compiled_zero
            and ((record.get("warmup") or {}).get("prewarm") or {})
            .get("compiled") != 0):
        problems.append(
            f"pack-warmed boot still compiled "
            f"{record['warmup']['prewarm'].get('compiled')} programs "
            f"(AOT pack miss)")
    return problems
