"""The front router: one wire endpoint multiplexing a replica fleet.

Speaks the same ``pycatkin-serve/v1`` line protocol as a single
:class:`SweepServer` -- clients cannot tell the difference -- and is
deliberately JAX-free: mechanisms and results pass through verbatim
(only the request ``id`` is rewritten per dispatch), so the router
process never compiles, never touches a device, and its event loop
only ever moves bytes.

Per request (docs/serving.md "Fleet serving"):

- **admission control** -- ``E_DRAINING`` while draining,
  ``E_OVERLOADED`` when the router-wide in-flight cap is hit or every
  replica breaker is open;
- **deadline-class SLA budget** -- the request's end-to-end budget
  (``protocol.request_timeout_for``) bounds everything below; burning
  it yields a structured ``E_TIMEOUT``;
- **per-replica circuit breakers** -- consecutive dispatch failures
  open a breaker (closed -> open); after a cooldown the router probes
  the replica with a ``ping`` (open -> half-open) and closes on
  success, so a recovered replica re-enters rotation without eating
  live traffic first;
- **retries with full-jitter backoff** under the remaining budget
  (``utils/retry.backoff_delay``; the retryable-vs-fatal split is the
  shared taxonomy of ``utils/retry.TRANSIENT_CONNECTION_TYPES``),
  failing over to a different replica when one exists;
- **hedged dispatch** for the ``interactive`` class: a second replica
  is engaged once the primary is slower than the tracked latency
  quantile; the first answer wins and the loser is cancelled;
- **loss-free failover** -- a dead/partitioned replica's in-flight
  dispatches fail over idempotently (same-width sweeps are
  deterministic, so a duplicated dispatch is bit-identical); answers
  from abandoned dispatches that arrive late are suppressed and
  AUDITED: the duplicate must be bitwise identical to the answer the
  client saw, and a mismatch is a hard drill failure.

Chaos: each dispatch polls :func:`robustness.faults.take` at its
``router:dispatch:<i>`` site for the connection-level kinds and enacts
them itself (``conn-reset`` aborts the replica link, ``torn-line``
truncates the dispatch's wire line mid-object).

Durability (docs/serving.md "Durable requests"): with a journal dir
configured (``PYCATKIN_DURABLE_DIR``), sweeps carrying an
``idempotency_key`` are write-ahead journaled (``serve/durable.py``):
the ``accepted`` record is fsynced before the ack line reaches the
socket, the answer is journaled before the client can see it, boot
replays the journal and re-dispatches the accepted-but-unanswered
backlog, and duplicate keys are answered bitwise from the journal.
Keyless requests take the legacy path untouched.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..obs import metrics as _metrics
from ..utils.profiling import record_event
from ..utils.retry import backoff_delay, is_transient_backend_error
from .durable import RequestJournal
from .protocol import (DURABLE_DIR_ENV, DURABLE_REPLAY_CONCURRENCY_ENV,
                       E_BAD_REQUEST, E_DRAINING, E_INTERNAL,
                       E_OVERLOADED, E_TIMEOUT, E_UNKNOWN_KEY, PROTOCOL,
                       ServeError, accepted_ack, canonical_answer,
                       error_response, request_timeout_for)

# Env knobs (PCL006 registry rows in docs/index.md).
MAX_INFLIGHT_ENV = "PYCATKIN_ROUTER_MAX_INFLIGHT"
BREAKER_FAILS_ENV = "PYCATKIN_ROUTER_BREAKER_FAILS"
BREAKER_COOLDOWN_ENV = "PYCATKIN_ROUTER_BREAKER_COOLDOWN_S"
HEDGE_QUANTILE_ENV = "PYCATKIN_ROUTER_HEDGE_QUANTILE"
HEDGE_MIN_ENV = "PYCATKIN_ROUTER_HEDGE_MIN_S"
RETRIES_ENV = "PYCATKIN_ROUTER_RETRIES"

# The serve-tier chaos kinds THIS tier enacts at dispatch sites.
ROUTER_FAULT_KINDS = ("conn-reset", "torn-line")

# Replica error codes that mean "try another replica", not "tell the
# client": a draining or momentarily saturated replica is the fleet's
# problem, the fleet has spares.
_FAILOVER_CODES = frozenset({E_DRAINING, E_OVERLOADED})


@dataclass
class RouterConfig:
    """Knobs of one front router. ``None`` fields resolve from the
    environment at construction."""

    host: str = "127.0.0.1"
    port: int = 0
    max_inflight: Optional[int] = None
    breaker_fails: Optional[int] = None
    breaker_cooldown_s: Optional[float] = None
    hedge_quantile: Optional[float] = None
    hedge_min_s: Optional[float] = None
    retries: Optional[int] = None
    retry_base_delay_s: float = 0.02
    retry_max_delay_s: float = 0.5
    connect_timeout_s: float = 2.0
    probe_timeout_s: float = 2.0
    tick_s: float = 0.02
    # Durability (docs/serving.md "Durable requests"): a journal dir
    # enables the write-ahead request journal; unset (and no
    # PYCATKIN_DURABLE_DIR in the environment) leaves the router
    # memory-only with byte-identical legacy behavior.
    journal_dir: Optional[str] = None
    replay_concurrency: Optional[int] = None

    def __post_init__(self):
        env = os.environ.get
        if self.max_inflight is None:
            self.max_inflight = int(env(MAX_INFLIGHT_ENV, "64"))
        if self.breaker_fails is None:
            self.breaker_fails = int(env(BREAKER_FAILS_ENV, "3"))
        if self.breaker_cooldown_s is None:
            self.breaker_cooldown_s = float(
                env(BREAKER_COOLDOWN_ENV, "1.0"))
        if self.hedge_quantile is None:
            self.hedge_quantile = float(env(HEDGE_QUANTILE_ENV, "0.95"))
        if self.hedge_min_s is None:
            self.hedge_min_s = float(env(HEDGE_MIN_ENV, "0.05"))
        if self.retries is None:
            self.retries = int(env(RETRIES_ENV, "3"))
        if self.journal_dir is None:
            self.journal_dir = env(DURABLE_DIR_ENV) or None
        if self.replay_concurrency is None:
            self.replay_concurrency = int(
                env(DURABLE_REPLAY_CONCURRENCY_ENV, "4"))
        if self.replay_concurrency < 1:
            raise ValueError(f"replay_concurrency must be >= 1, "
                             f"got {self.replay_concurrency}")
        if self.max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, "
                             f"got {self.max_inflight}")


class CircuitBreaker:
    """closed -> (N consecutive failures) -> open -> (cooldown) ->
    half-open ping probe -> closed | open. Success anywhere resets."""

    def __init__(self, fails: int, cooldown_s: float):
        self.threshold = fails
        self.cooldown_s = cooldown_s
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.probing = False

    def _to(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        _metrics.counter(
            "pycatkin_router_breaker_transitions_total",
            "per-replica circuit-breaker state transitions").inc(
                to=state)

    @property
    def routable(self) -> bool:
        return self.state == "closed"

    def record_success(self) -> None:
        self.failures = 0
        self._to("closed")

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == "half-open" or \
                self.failures >= self.threshold:
            self.opened_at = time.monotonic()
            self._to("open")

    def force_open(self) -> None:
        self.failures = max(self.failures, self.threshold)
        self.opened_at = time.monotonic()
        self._to("open")

    def probe_due(self) -> bool:
        return (self.state == "open" and not self.probing
                and time.monotonic() - self.opened_at
                >= self.cooldown_s)

    def begin_probe(self) -> None:
        self.probing = True
        self._to("half-open")

    def probe_result(self, ok: bool) -> None:
        self.probing = False
        if ok:
            self.record_success()
        else:
            self.opened_at = time.monotonic()
            self._to("open")


class _Link:
    """One router->replica connection; dispatches are id-multiplexed
    like :class:`serve.client.TcpSweepClient`, but failures surface as
    EXCEPTIONS (the router's retry taxonomy), and abandoned dispatches
    stay registered as *orphans* so a late answer feeds the
    duplicate-suppression audit instead of vanishing."""

    def __init__(self, idx: int, incarnation: int, host: str,
                 port: int, on_orphan):
        self.idx = idx
        self.incarnation = incarnation
        self.host = host
        self.port = port
        self.closed = False
        self._on_orphan = on_orphan
        self._reader = None
        self._writer = None
        self._task = None
        self._wlock = asyncio.Lock()
        self.pending: dict = {}    # did -> (future, audit state)
        self.orphans: dict = {}    # did -> audit state

    async def open(self, timeout_s: float) -> "_Link":
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), timeout_s)
        self._task = asyncio.get_running_loop().create_task(
            self._read_loop())
        return self

    @property
    def inflight(self) -> int:
        return len(self.pending)

    async def _read_loop(self):
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                try:
                    resp = json.loads(line)
                except ValueError:
                    continue   # torn replica line; deadlines recover
                did = resp.get("id")
                entry = self.pending.pop(did, None)
                if entry is not None:
                    fut, _state = entry
                    if not fut.done():
                        fut.set_result(resp)
                    continue
                state = self.orphans.pop(did, None)
                if state is not None:
                    self._on_orphan(state, resp)
        except (ConnectionError, OSError,
                asyncio.IncompleteReadError):
            pass     # severed link: the finally fails the pending
        finally:
            self.closed = True
            err = ConnectionResetError(
                f"link to replica {self.idx} closed")
            for fut, _state in self.pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self.pending.clear()
            self.orphans.clear()

    def register(self, did: str, state: dict):
        fut = asyncio.get_running_loop().create_future()
        self.pending[did] = (fut, state)
        return fut

    def make_orphan(self, did: str) -> None:
        """Abandon a dispatch (timeout / hedge-loser cancellation)
        while keeping its identity alive for the duplicate audit."""
        entry = self.pending.pop(did, None)
        if entry is None:
            return
        fut, state = entry
        if fut.done() and not fut.cancelled() \
                and fut.exception() is None:
            # The answer raced our abandonment: it is already a
            # suppressed duplicate.
            self._on_orphan(state, fut.result())
        else:
            self.orphans[did] = state

    async def send_line(self, payload: dict, torn: bool = False):
        data = (json.dumps(payload) + "\n").encode()
        if torn:
            # Injected torn-line: half the JSON object, then the
            # terminator -- the replica reads one undecodable line.
            data = data[:max(1, len(data) // 2)] + b"\n"
        async with self._wlock:
            if self.closed or self._writer is None:
                raise ConnectionResetError(
                    f"link to replica {self.idx} is closed")
            self._writer.write(data)
            await self._writer.drain()

    def abort(self) -> None:
        """Hard-sever the connection (chaos conn-reset / fleet 'down'
        event): pending dispatches fail immediately with a transient
        error, which is what makes failover prompt."""
        if self._writer is not None:
            self._writer.transport.abort()

    async def close(self):
        self.closed = True
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class SweepRouter:
    """The asyncio front tier over a :class:`fleet.ReplicaSupervisor`;
    see the module docstring for per-request behavior."""

    def __init__(self, supervisor, config: Optional[RouterConfig] = None,
                 **overrides):
        self.supervisor = supervisor
        self.config = config or RouterConfig(**overrides)
        self.port: Optional[int] = None
        self._tcp_server = None
        self._draining = False
        self._inflight = 0
        self._dseq = itertools.count()
        self._links: dict = {}
        self._retiring: set = set()
        self._breakers: dict = {}
        self._lat_interactive: deque = deque(maxlen=256)
        self._failover_samples: deque = deque(maxlen=4096)
        self._accepted = 0
        self._ok_total = 0
        self._err_total = 0
        self._retries_total = 0
        self._hedges_total = 0
        self._failovers_total = 0
        self._dup_suppressed = 0
        self._dup_identical = 0
        self._dup_mismatched = 0
        # Durable-request state (docs/serving.md "Durable requests").
        # Constructing the journal replays its on-disk segments, so a
        # rebooted router knows its accepted-but-unanswered backlog
        # before it serves a single request.
        self._journal = (RequestJournal(self.config.journal_dir)
                         if self.config.journal_dir else None)
        self._keyed_inflight: dict = {}   # key -> future -> response
        self._dup_served = 0
        self._dup_coalesced = 0
        self._replay_task = None
        self._replay_stats = {"total": 0, "done": 0, "failed": 0,
                              "active": False, "wall_s": None}
        supervisor.add_listener(self._on_fleet_event)

    # -- lifecycle -----------------------------------------------------

    async def start(self, listen: bool = True) -> "SweepRouter":
        if listen:
            self._tcp_server = await asyncio.start_server(
                self._on_connection, self.config.host,
                self.config.port)
            self.port = self._tcp_server.sockets[0].getsockname()[1]
            record_event("router", action="listen",
                         host=self.config.host, port=self.port)
        if self._journal is not None:
            pending = self._journal.unanswered()
            self._replay_stats["total"] = len(pending)
            if pending:
                self._replay_stats["active"] = True
                self._replay_task = asyncio.get_running_loop() \
                    .create_task(self._replay_pending(pending))
        return self

    async def drain(self) -> None:
        """Stop admitting; every ACCEPTED request still resolves (the
        retry/failover machinery keeps working while we wait), then
        the listener and links come down."""
        if self._draining:
            await self.wait_stopped()
            return
        self._draining = True
        record_event("router", action="drain-begin",
                     inflight=self._inflight)
        while self._inflight:
            await asyncio.sleep(self.config.tick_s)
        record_event("router", action="drain-complete",
                     answered=self._ok_total + self._err_total)
        await self.stop()

    async def stop(self) -> None:
        self._draining = True
        if self._replay_task is not None:
            self._replay_task.cancel()
            try:
                await self._replay_task
            except asyncio.CancelledError:
                pass
            self._replay_task = None
        if self._tcp_server is not None:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()
            self._tcp_server = None
        for link in list(self._links.values()):
            await link.close()
        self._links.clear()
        if self._retiring:
            await asyncio.gather(*list(self._retiring),
                                 return_exceptions=True)

    async def wait_stopped(self) -> None:
        while self._tcp_server is not None or self._inflight:
            await asyncio.sleep(self.config.tick_s)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- boot-time journal replay --------------------------------------

    async def _replay_pending(self, pending: list) -> None:
        """Recovery after router death: every journaled
        accepted-but-unanswered request is re-dispatched to the fleet
        (bounded concurrency) through the ordinary keyed sweep path,
        so its answer lands in the journal and duplicate resubmissions
        from reconnecting clients coalesce onto the same dispatch."""
        t0 = time.monotonic()
        record_event("durable", action="replay-begin",
                     pending=len(pending))

        sem = asyncio.Semaphore(self.config.replay_concurrency)

        async def one(n: int, key: str, payload) -> None:
            async with sem:
                if self._journal.answered_response(key) is not None:
                    self._replay_stats["done"] += 1
                    return   # a client resubmission beat us to it
                req = dict(payload) if isinstance(payload, dict) else {}
                req["idempotency_key"] = key
                req.setdefault("op", "sweep")
                req["id"] = f"replay-{n}"
                resp = None
                # A replica breaker may still be warming right after
                # boot; overload rejects here would silently park the
                # request until the NEXT boot, so back off and retry.
                for attempt in range(8):
                    resp = await self.handle(req)
                    code = ((resp.get("error") or {}).get("code")
                            if not resp.get("ok") else None)
                    if code != E_OVERLOADED:
                        break
                    await asyncio.sleep(backoff_delay(attempt, 0.1,
                                                      2.0))
                if resp is not None and resp.get("ok"):
                    self._replay_stats["done"] += 1
                else:
                    self._replay_stats["failed"] += 1

        try:
            await asyncio.gather(
                *(one(n, key, payload)
                  for n, (key, payload) in enumerate(pending)),
                return_exceptions=True)
        finally:
            self._replay_stats["active"] = False
            self._replay_stats["wall_s"] = time.monotonic() - t0
            record_event("durable", action="replay-complete",
                         done=self._replay_stats["done"],
                         failed=self._replay_stats["failed"],
                         wall_s=self._replay_stats["wall_s"])

    # -- fleet events --------------------------------------------------

    def _breaker(self, idx: int) -> CircuitBreaker:
        br = self._breakers.get(idx)
        if br is None:
            br = self._breakers[idx] = CircuitBreaker(
                self.config.breaker_fails,
                self.config.breaker_cooldown_s)
        return br

    def _on_fleet_event(self, info: dict) -> None:
        idx = info["idx"]
        br = self._breaker(idx)
        if info["event"] == "up":
            # A freshly registered incarnation already won a ping.
            br.record_success()
            return
        br.force_open()
        link = self._links.pop(idx, None)
        if link is not None:
            # Sever now so in-flight dispatches fail over immediately
            # instead of waiting out their attempt timeout; the read
            # task is then reaped in the background (an aborted link
            # must not outlive the router).
            link.abort()
            task = asyncio.get_running_loop().create_task(link.close())
            self._retiring.add(task)
            task.add_done_callback(self._retiring.discard)

    # -- replica selection ---------------------------------------------

    async def _link_for(self, ep: dict) -> _Link:
        idx = ep["idx"]
        link = self._links.get(idx)
        if link is not None and not link.closed \
                and link.incarnation == ep["incarnation"]:
            return link
        if link is not None:
            link.abort()
            await link.close()
        link = _Link(idx, ep["incarnation"], ep["host"], ep["port"],
                     self._suppress_duplicate)
        await link.open(self.config.connect_timeout_s)
        cur = self._links.get(idx)
        if cur is not None and not cur.closed \
                and cur.incarnation == ep["incarnation"]:
            # Lost an open race against a concurrent dispatch: keep
            # the established link, reap ours (its read task must not
            # be orphaned).
            await link.close()
            return cur
        self._links[idx] = link
        return link

    def _kick_probes(self) -> None:
        """Schedule half-open probes for every cooled-down open
        breaker. Called from BOTH the candidate scan and the
        all-breakers-open admission reject: if only the dispatch path
        probed, a router rejecting everything would never discover
        that its replicas recovered."""
        for ep in self.supervisor.endpoints():
            br = self._breaker(ep["idx"])
            if br.probe_due():
                asyncio.get_running_loop().create_task(
                    self._probe_breaker(ep, br))

    def _candidates(self, tried=frozenset()) -> list:
        self._kick_probes()
        eps = []
        for ep in self.supervisor.endpoints():
            br = self._breaker(ep["idx"])
            if br.routable:
                eps.append(ep)
        if not eps:
            return []
        fresh = [e for e in eps if e["idx"] not in tried]
        pool = fresh or eps
        pool.sort(key=lambda e: (
            self._links[e["idx"]].inflight
            if e["idx"] in self._links else 0))
        return pool

    def _any_breaker_routable(self) -> bool:
        return any(self._breaker(ep["idx"]).routable
                   for ep in self.supervisor.endpoints())

    async def _probe_breaker(self, ep: dict, br: CircuitBreaker):
        """half-open ping probe over a fresh connection; closes the
        breaker on success without risking live traffic."""
        br.begin_probe()
        writer = None
        ok = False
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(ep["host"], ep["port"]),
                self.config.probe_timeout_s)
            writer.write(b'{"op": "ping", "id": "breaker-probe"}\n')
            await writer.drain()
            line = await asyncio.wait_for(
                reader.readline(), self.config.probe_timeout_s)
            resp = json.loads(line) if line.strip() else None
            ok = bool(isinstance(resp, dict) and resp.get("ok"))
        except (OSError, ValueError, asyncio.TimeoutError):
            ok = False
        finally:
            if writer is not None:
                writer.close()
        br.probe_result(ok)
        record_event("router", action="breaker-probe",
                     replica=ep["idx"], ok=ok)

    # -- duplicate-suppression audit -----------------------------------

    def _suppress_duplicate(self, state: dict, resp: dict) -> None:
        """A dispatch the router abandoned answered anyway. The client
        never sees it; the audit proves it WOULD have been the same
        answer (same-width sweeps are deterministic, so anything else
        is a real bug, not noise)."""
        if not resp.get("ok"):
            return                       # errors carry no answer
        self._dup_suppressed += 1
        chosen = state.get("canonical")
        if chosen is None:
            state.setdefault("dups", []).append(_canonical(resp))
            _metrics.counter(
                "pycatkin_router_duplicates_suppressed_total",
                "late/hedge-loser answers suppressed by the "
                "router").inc(identical="pending")
            return
        identical = _canonical(resp) == chosen
        self._dup_identical += int(identical)
        self._dup_mismatched += int(not identical)
        _metrics.counter(
            "pycatkin_router_duplicates_suppressed_total",
            "late/hedge-loser answers suppressed by the router").inc(
                identical=str(identical).lower())
        if not identical:
            record_event("router", action="duplicate-mismatch",
                         req_id=state.get("req_id"))

    def _finalize_audit(self, state: dict, resp: dict) -> None:
        if not resp.get("ok"):
            return
        state["canonical"] = _canonical(resp)
        for dup in state.pop("dups", []):
            identical = dup == state["canonical"]
            self._dup_identical += int(identical)
            self._dup_mismatched += int(not identical)
            if not identical:
                record_event("router", action="duplicate-mismatch",
                             req_id=state.get("req_id"))

    # -- request handling ----------------------------------------------

    async def handle(self, payload, ack=None) -> dict:
        req_id = payload.get("id") if isinstance(payload, dict) else None
        try:
            if not isinstance(payload, dict):
                raise ServeError(E_BAD_REQUEST,
                                 "expected a JSON object per line")
            op = payload.get("op", "sweep")
            _metrics.counter("pycatkin_router_requests_total",
                             "requests seen by the front router").inc(
                                 op=str(op))
            if op == "ping":
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "pong": True, "draining": self._draining,
                        "replicas_up": len(self.supervisor.endpoints())}
            if op == "stats":
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "stats": self.stats()}
            if op == "drain":
                asyncio.get_running_loop().create_task(self.drain())
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "draining": True}
            if op == "result":
                return self._fetch_result(payload, req_id)
            if op == "sweep":
                return await self._route_sweep(payload, req_id, ack)
            raise ServeError(E_BAD_REQUEST, f"unknown op {op!r}")
        except ServeError as exc:
            return error_response(req_id, exc.code, str(exc))
        except Exception as exc:  # noqa: BLE001 - wire boundary
            return error_response(req_id, E_INTERNAL,
                                  f"{type(exc).__name__}: {exc}")

    def _fetch_result(self, payload: dict, req_id) -> dict:
        """``{"op": "result", "key": ...}``: fetch the journaled answer
        for an idempotency key -- how a reconnecting client retrieves
        an answer it may have missed, without re-running anything."""
        if self._journal is None:
            raise ServeError(E_BAD_REQUEST,
                             "durability is not enabled on this router "
                             "(no journal dir)")
        key = payload.get("key")
        if not isinstance(key, str) or not key:
            raise ServeError(E_BAD_REQUEST,
                             "/key: expected a non-empty string")
        stored = self._journal.answered_response(key)
        if stored is not None:
            return dict(stored, id=req_id)
        if key in self._keyed_inflight or self._journal.is_accepted(key):
            raise ServeError(E_UNKNOWN_KEY,
                             f"key {key!r} is accepted but not yet "
                             "answered; retry shortly")
        raise ServeError(E_UNKNOWN_KEY,
                         f"no journaled answer for key {key!r} (never "
                         "accepted, or compacted out of the window)")

    async def _route_sweep(self, payload: dict, req_id,
                           ack=None) -> dict:
        cls = str(payload.get("deadline_class", "standard"))
        key = payload.get("idempotency_key")
        key = (str(key) if key is not None and self._journal is not None
               else None)
        if key is not None:
            # Duplicate of an answered key: serve the journaled answer
            # bitwise (only the id is rewritten) -- even while
            # draining, a replayed answer is a read, not new work.
            stored = self._journal.answered_response(key)
            if stored is not None:
                self._dup_served += 1
                _metrics.counter(
                    "pycatkin_durable_duplicates_served_total",
                    "keyed duplicates answered from the journal").inc()
                record_event("durable", action="dup-served", key=key)
                return dict(stored, id=req_id)
            inflight_fut = self._keyed_inflight.get(key)
            if inflight_fut is not None:
                # Same key already being dispatched (client
                # resubmission racing the original or the boot-time
                # replay): coalesce onto one dispatch.
                self._dup_coalesced += 1
                try:
                    resp = await asyncio.wait_for(
                        asyncio.shield(inflight_fut),
                        request_timeout_for(cls))
                except asyncio.TimeoutError:
                    raise ServeError(
                        E_TIMEOUT,
                        f"coalesced dispatch for key {key!r} burned "
                        "the SLA budget") from None
                return dict(resp, id=req_id)
        if self._draining:
            raise ServeError(E_DRAINING,
                             "router is draining; no new sweeps")
        if self._inflight >= self.config.max_inflight:
            raise ServeError(
                E_OVERLOADED,
                f"router in-flight cap reached ({self._inflight} >= "
                f"{self.config.max_inflight}); retry with backoff")
        if not self._any_breaker_routable():
            self._kick_probes()
            raise ServeError(E_OVERLOADED,
                             "every replica breaker is open; "
                             "retry with backoff")
        keyed_fut = None
        if key is not None:
            keyed_fut = asyncio.get_running_loop().create_future()
            self._keyed_inflight[key] = keyed_fut
            try:
                # Durability contract: the accepted record is FSYNCED
                # (append_json_line) before the ack line may reach the
                # socket -- a key the client saw acknowledged survives
                # router death.
                await asyncio.to_thread(self._journal.record_accepted,
                                        key,
                                        {k: v for k, v in payload.items()
                                         if k != "id"})
            except BaseException:
                self._keyed_inflight.pop(key, None)
                keyed_fut.cancel()
                raise
            if ack is not None:
                await ack(accepted_ack(req_id, key))
        self._accepted += 1
        self._inflight += 1
        _metrics.gauge("pycatkin_router_inflight",
                       "sweeps in flight through the router").set(
                           float(self._inflight))
        t0 = time.monotonic()
        state = {"req_id": req_id, "canonical": None}
        try:
            resp = await self._dispatch_with_retries(payload, cls,
                                                     state, t0)
        except ServeError as exc:
            self._err_total += 1
            if keyed_fut is not None:
                self._resolve_key(key, keyed_fut,
                                  error_response(req_id, exc.code,
                                                 str(exc)))
            raise
        except BaseException:
            if keyed_fut is not None:
                self._resolve_key(key, keyed_fut,
                                  error_response(req_id, E_INTERNAL,
                                                 "dispatch aborted"))
            raise
        finally:
            self._inflight -= 1
            _metrics.gauge("pycatkin_router_inflight",
                           "sweeps in flight through the router").set(
                               float(self._inflight))
        total_s = time.monotonic() - t0
        _metrics.histogram(
            "pycatkin_router_request_seconds",
            "routed sweep wall time, admission to answer").observe(
                total_s, deadline_class=cls)
        if cls == "interactive":
            self._lat_interactive.append(total_s)
        if resp.get("ok"):
            self._ok_total += 1
        else:
            self._err_total += 1
        self._finalize_audit(state, resp)
        resp = dict(resp, id=req_id)
        if keyed_fut is not None:
            if resp.get("ok"):
                # Answered BEFORE the client can see the response; a
                # prior record means a replay/resubmission race, and
                # the two answers are audited like hedge losers.
                prior = await asyncio.to_thread(
                    self._journal.record_answered, key, resp)
                if prior is not None:
                    identical = (canonical_answer(prior)
                                 == canonical_answer(resp))
                    self._dup_identical += int(identical)
                    self._dup_mismatched += int(not identical)
                    if not identical:
                        record_event("router",
                                     action="duplicate-mismatch",
                                     req_id=req_id)
                    resp = dict(prior, id=req_id)
            self._resolve_key(key, keyed_fut, resp)
        return resp

    def _resolve_key(self, key: str, fut, resp: dict) -> None:
        self._keyed_inflight.pop(key, None)
        if not fut.done():
            fut.set_result(resp)

    async def _dispatch_with_retries(self, payload: dict, cls: str,
                                     state: dict, t0: float) -> dict:
        cfg = self.config
        budget = request_timeout_for(cls)
        deadline = t0 + budget
        failures = 0
        first_failure_at = None
        last_err = "no replica available"
        tried: set = set()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ServeError(
                    E_TIMEOUT,
                    f"SLA budget burned ({budget:.3f} s, class "
                    f"{cls!r}) after {failures} failed dispatch "
                    f"attempt(s); last error: {last_err}")
            cands = self._candidates(tried)
            if not cands:
                # Nothing routable RIGHT NOW; the supervisor may be
                # rebooting a replica. Wait it out under the budget.
                await asyncio.sleep(min(cfg.tick_s, remaining))
                continue
            attempt_timeout = min(
                remaining, max(budget / (cfg.retries + 1),
                               cfg.hedge_min_s))
            try:
                if cls == "interactive" and len(cands) > 1:
                    resp = await self._hedged_dispatch(
                        cands, payload, state, attempt_timeout)
                else:
                    resp = await self._dispatch_once(
                        cands[0], payload, state, attempt_timeout)
            except Exception as exc:  # noqa: BLE001 - classified below
                if not is_transient_backend_error(exc) \
                        and not isinstance(exc, OSError):
                    raise ServeError(
                        E_INTERNAL,
                        f"dispatch failed: {type(exc).__name__}: "
                        f"{exc}") from exc
                failures += 1
                last_err = f"{type(exc).__name__}: {exc}"
                if first_failure_at is None:
                    first_failure_at = time.monotonic()
                self._retries_total += 1
                _metrics.counter(
                    "pycatkin_router_retries_total",
                    "dispatch attempts retried by the router").inc()
                tried.update(getattr(exc, "_replica_idx", ()) or ())
                if failures > cfg.retries:
                    raise ServeError(
                        E_INTERNAL,
                        f"{failures} dispatch failures (retry budget "
                        f"{cfg.retries}); last error: {last_err}") \
                        from exc
                delay = backoff_delay(failures - 1,
                                      cfg.retry_base_delay_s,
                                      cfg.retry_max_delay_s)
                await asyncio.sleep(
                    min(delay, max(0.0,
                                   deadline - time.monotonic())))
                continue
            if not resp.get("ok") and \
                    (resp.get("error") or {}).get("code") \
                    in _FAILOVER_CODES:
                # The replica said "not me" -- the fleet has spares.
                failures += 1
                last_err = f"replica said {resp['error']['code']}"
                if first_failure_at is None:
                    first_failure_at = time.monotonic()
                tried.add(resp.pop("_replica_idx", None))
                if failures > cfg.retries:
                    return resp
                await asyncio.sleep(min(cfg.tick_s, remaining))
                continue
            if failures and resp.get("ok"):
                self._failovers_total += 1
                _metrics.counter(
                    "pycatkin_router_failovers_total",
                    "requests answered after losing a replica "
                    "mid-flight").inc()
                self._failover_samples.append(
                    time.monotonic() - first_failure_at)
            resp.pop("_replica_idx", None)
            return resp

    async def _dispatch_once(self, ep: dict, payload: dict,
                             state: dict, timeout_s: float) -> dict:
        from ..robustness import faults
        idx = ep["idx"]
        br = self._breaker(idx)
        try:
            link = await self._link_for(ep)
        except (OSError, asyncio.TimeoutError) as exc:
            br.record_failure()
            exc._replica_idx = (idx,)
            raise
        did = f"d{next(self._dseq)}"
        site = f"router:dispatch:{did}"
        torn = False
        for spec in faults.take(site, kinds=ROUTER_FAULT_KINDS):
            record_event("router", action="chaos-enact", replica=idx,
                         label=site, fault_kind=spec.kind)
            if spec.kind == "conn-reset":
                link.abort()
                br.record_failure()
                err = ConnectionResetError(
                    f"injected conn-reset at {site}")
                err._replica_idx = (idx,)
                raise err
            torn = True
        fut = link.register(did, state)
        try:
            await link.send_line(dict(payload, id=did), torn=torn)
            resp = await asyncio.wait_for(asyncio.shield(fut),
                                          timeout_s)
        except asyncio.TimeoutError as exc:
            link.make_orphan(did)
            br.record_failure()
            err = TimeoutError(
                f"replica {idx} gave no answer for {did} within "
                f"{timeout_s:.3f} s")
            err._replica_idx = (idx,)
            raise err from exc
        except asyncio.CancelledError:
            link.make_orphan(did)
            raise
        except Exception as exc:      # noqa: BLE001 - tagged, re-raised
            link.make_orphan(did)
            br.record_failure()
            exc._replica_idx = (idx,)
            raise
        br.record_success()
        resp = dict(resp, _replica_idx=idx)
        return resp

    def _hedge_delay_s(self) -> float:
        lat = self._lat_interactive
        if len(lat) >= 8:
            s = sorted(lat)
            q = s[min(len(s) - 1,
                      int(self.config.hedge_quantile * len(s)))]
            return max(q, self.config.hedge_min_s)
        return self.config.hedge_min_s

    async def _hedged_dispatch(self, cands: list, payload: dict,
                               state: dict, timeout_s: float) -> dict:
        """interactive-class dispatch: engage a second replica at the
        latency quantile; first answer wins, the loser is cancelled
        (its late answer, if any, feeds the duplicate audit)."""
        loop = asyncio.get_running_loop()
        t1 = loop.create_task(self._dispatch_once(
            cands[0], payload, state, timeout_s))
        try:
            return await asyncio.wait_for(asyncio.shield(t1),
                                          self._hedge_delay_s())
        except asyncio.TimeoutError:
            pass
        self._hedges_total += 1
        _metrics.counter(
            "pycatkin_router_hedges_total",
            "interactive dispatches hedged to a second replica").inc()
        record_event("router", action="hedge", primary=cands[0]["idx"],
                     secondary=cands[1]["idx"])
        t2 = loop.create_task(self._dispatch_once(
            cands[1], payload, state, timeout_s))
        tasks = {t1, t2}
        winner = None
        first_exc = None
        while tasks and winner is None:
            done, tasks = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_COMPLETED)
            for d in done:
                try:
                    r = await d
                except asyncio.CancelledError:
                    continue
                except Exception as exc:  # noqa: BLE001 - kept, rethrown
                    if first_exc is None:
                        first_exc = exc
                    continue
                if winner is None:
                    winner = r
                else:
                    self._suppress_duplicate(state, r)
        for t in tasks:
            t.cancel()   # loser: its dispatch orphans itself
        if winner is not None:
            return winner
        if first_exc is None:      # both legs cancelled under us
            first_exc = ConnectionResetError(
                "hedged dispatch lost both replicas")
        raise first_exc

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        answered = self._ok_total + self._err_total
        samples = sorted(self._failover_samples)
        p99 = (samples[min(len(samples) - 1,
                           int(0.99 * len(samples)))]
               if samples else None)
        return {
            "protocol": PROTOCOL,
            "draining": self._draining,
            "port": self.port,
            "inflight": self._inflight,
            "accepted": self._accepted,
            "ok_total": self._ok_total,
            "err_total": self._err_total,
            "availability": (self._ok_total / answered
                             if answered else None),
            "retries": self._retries_total,
            "hedges": self._hedges_total,
            "failovers": self._failovers_total,
            "failover_p99_s": p99,
            "duplicates": {"suppressed": self._dup_suppressed,
                           "identical": self._dup_identical,
                           "mismatched": self._dup_mismatched},
            "breakers": {str(i): br.state
                         for i, br in sorted(self._breakers.items())},
            "fleet": self.supervisor.stats(),
            "durable": (None if self._journal is None else {
                "journal": self._journal.stats(),
                "replay": dict(self._replay_stats),
                "duplicates_served": self._dup_served,
                "coalesced": self._dup_coalesced,
                "keyed_inflight": len(self._keyed_inflight),
            }),
        }

    # -- TCP framing ---------------------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        wlock = asyncio.Lock()
        tasks = set()

        async def ack_line(obj: dict):
            # The durability ack: _route_sweep only calls this after
            # the accepted record is fsynced (fsync-before-ack). A
            # dead client is not an error -- it will reconnect and
            # resubmit by key.
            data = (json.dumps(obj) + "\n").encode()
            try:
                async with wlock:
                    writer.write(data)
                    await writer.drain()
            except (ConnectionError, OSError):
                pass

        async def one_line(line: bytes):
            try:
                try:
                    payload = json.loads(line)
                except ValueError as exc:
                    resp = error_response(None, E_BAD_REQUEST,
                                          f"invalid JSON: {exc}")
                else:
                    resp = await self.handle(payload, ack=ack_line)
                data = (json.dumps(resp) + "\n").encode()
                async with wlock:
                    writer.write(data)
                    await writer.drain()
            except (ConnectionError, asyncio.CancelledError):
                pass

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                if not line.strip():
                    continue
                t = asyncio.get_running_loop().create_task(
                    one_line(line))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass


# The canonicalizer moved to serve/protocol.py (canonical_answer) so
# the request journal can record it without importing the router; the
# old name stays importable for the soak harness and tests.
_canonical = canonical_answer
