"""Replica fleet supervision for the serving layer (docs/serving.md).

One :class:`SweepServer` process is one failure domain; the fleet
tier's job is to keep N of them alive and honest so the front router
(serve/router.py) always has somewhere to send traffic. A
:class:`ReplicaSupervisor` spawns N ``python -m pycatkin_tpu.serve``
subprocesses and, per replica:

- **pack-warmed boot before registration** -- the AOT cache pack is
  handed to the replica via ``PYCATKIN_SERVE_AOT_PACK``; the server
  imports it inside ``start()`` BEFORE printing its ``{"serving":
  true, "port": ...}`` line, and the supervisor registers a replica
  only after scraping that line AND winning a first ``ping``, so a
  replica is never routable until its executables are loaded;
- **exit classification** via ``utils/retry.classify_worker_exit``:
  signal deaths are preemption-shaped and restart on the shared
  full-jitter backoff curve (``utils/retry.backoff_delay``), nonzero
  exits are program-shaped and restart on the slow lane (the full
  restart cap, no jitter) so a crash-looping replica cannot hot-spin;
- **bounded restarts** -- ``max_restarts`` exceeded abandons the
  replica (the router routes around it; the drill gates on
  availability, not on immortality);
- **liveness probes** -- periodic ``ping`` over a fresh connection;
  ``ping_misses`` consecutive misses demote the replica (unroutable,
  announced to listeners), twice that kills it outright, which is how
  a SIGSTOP-stalled replica (the ``replica-stall`` chaos kind) comes
  back: stall -> missed pings -> demote -> kill -> classified signal
  death -> backoff -> pack-warmed reboot.

Chaos: each monitor tick polls :func:`robustness.faults.take` at its
``router:replica:<i>`` site for the externally-enacted serve-tier
kinds and enacts what fires (``replica-crash`` = SIGKILL,
``replica-stall`` = SIGSTOP). ``times=N`` budgets hold fleet-wide
through the plan's O_EXCL ticket files.

Router supervision (``FleetConfig(role="router")``): the same
machinery keeps the FRONT ROUTER alive -- one supervised
``python -m pycatkin_tpu.serve --router`` subprocess under the same
backoff/abandon/registration policy, polled at the ``router:front``
chaos site for the ``router-crash`` kind (SIGKILL). The parent
publishes its replica endpoints to an atomically-written JSON file
(``FleetConfig.endpoints_file``, tmp + ``os.replace``) that the router
subprocess consumes through :class:`FileFleet`; a rebooted router
re-reads the file, replays its request journal (serve/durable.py) and
rebinds the SAME fixed port so clients reconnect.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..obs import metrics as _metrics
from ..utils.profiling import record_event
from ..utils.retry import backoff_delay, classify_worker_exit
from .protocol import AOT_PACK_ENV

# Env knobs (PCL006 registry rows in docs/index.md).
REPLICAS_ENV = "PYCATKIN_ROUTER_REPLICAS"
MAX_RESTARTS_ENV = "PYCATKIN_ROUTER_MAX_RESTARTS"
PING_PERIOD_ENV = "PYCATKIN_ROUTER_PING_PERIOD_S"
PING_MISSES_ENV = "PYCATKIN_ROUTER_PING_MISSES"
FLEET_FILE_ENV = "PYCATKIN_ROUTER_FLEET_FILE"

# The serve-tier chaos kinds THIS tier enacts (the router enacts the
# connection-level ones at its dispatch sites). A role="router"
# supervisor polls for the router-death kind instead.
SUPERVISOR_FAULT_KINDS = ("replica-crash", "replica-stall")
ROUTER_SUPERVISOR_FAULT_KINDS = ("router-crash",)

_STDERR_TAIL_LINES = 40


@dataclass
class FleetConfig:
    """Knobs of one supervised replica fleet. ``None`` fields resolve
    from the environment at construction."""

    n_replicas: Optional[int] = None
    command: Optional[list] = None     # argv override (test stubs)
    env: dict = field(default_factory=dict)
    aot_pack: Optional[str] = None     # pack-warmed boot source
    max_restarts: Optional[int] = None
    restart_base_delay_s: float = 0.05
    restart_max_delay_s: float = 2.0
    ping_period_s: Optional[float] = None
    ping_misses: Optional[int] = None
    ping_timeout_s: float = 2.0
    boot_timeout_s: float = 120.0
    stop_grace_s: float = 30.0
    tick_s: float = 0.02
    host: str = "127.0.0.1"
    # "replica" supervises SweepServer subprocesses; "router"
    # supervises one front-router subprocess (router-crash drills).
    role: str = "replica"
    # Atomic endpoints-file publication for an out-of-process router
    # (consumed via FileFleet); None disables.
    endpoints_file: Optional[str] = None

    def __post_init__(self):
        if self.role not in ("replica", "router"):
            raise ValueError(f"role must be 'replica' or 'router', "
                             f"got {self.role!r}")
        if self.n_replicas is None:
            if self.role == "router":
                # One front router per fleet: a second would race for
                # the same fixed port.
                self.n_replicas = 1
            else:
                self.n_replicas = int(os.environ.get(REPLICAS_ENV,
                                                     "3"))
        if self.max_restarts is None:
            self.max_restarts = int(os.environ.get(MAX_RESTARTS_ENV,
                                                   "5"))
        if self.ping_period_s is None:
            self.ping_period_s = float(os.environ.get(PING_PERIOD_ENV,
                                                      "0.5"))
        if self.ping_misses is None:
            self.ping_misses = int(os.environ.get(PING_MISSES_ENV, "3"))
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, "
                             f"got {self.n_replicas}")


class Replica:
    """One supervised server slot: the slot index is stable identity,
    the incarnation counts boots (each restart is a new subprocess on
    a new port)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.incarnation = 0
        self.proc = None
        self.port: Optional[int] = None
        self.state = "dead"     # booting | up | demoted | dead | abandoned
        self.restarts = 0
        self.missed_pings = 0
        self.next_ping = 0.0
        self.stderr_tail: deque = deque(maxlen=_STDERR_TAIL_LINES)
        self._stderr_task = None

    @property
    def routable(self) -> bool:
        return self.state == "up"

    def summary(self) -> dict:
        return {"idx": self.idx, "state": self.state,
                "incarnation": self.incarnation, "port": self.port,
                "restarts": self.restarts,
                "missed_pings": self.missed_pings}


class ReplicaSupervisor:
    """Spawn, probe, demote, restart and retire N sweep-server
    replicas; see the module docstring for the lifecycle."""

    def __init__(self, config: Optional[FleetConfig] = None,
                 **overrides):
        self.config = config or FleetConfig(**overrides)
        self.replicas = [Replica(i)
                         for i in range(self.config.n_replicas)]
        self._listeners: list = []
        self._tasks: list = []
        self._stopping = False

    # -- lifecycle -----------------------------------------------------

    async def start(self) -> "ReplicaSupervisor":
        """Launch one monitor task per replica and wait until every
        replica registered (or was abandoned); raises if NONE came up."""
        for r in self.replicas:
            self._tasks.append(asyncio.get_running_loop().create_task(
                self._monitor(r)))
        await self.wait_ready()
        return self

    async def wait_ready(self, timeout_s: Optional[float] = None):
        deadline = time.monotonic() + (timeout_s if timeout_s
                                       is not None
                                       else self.config.boot_timeout_s)
        while any(r.state in ("dead", "booting") for r in self.replicas):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet boot timed out: "
                    f"{[r.summary() for r in self.replicas]}")
            await asyncio.sleep(self.config.tick_s)
        if not any(r.routable for r in self.replicas):
            raise RuntimeError(
                f"no replica came up: "
                f"{[r.summary() for r in self.replicas]}")

    async def stop(self):
        """SIGTERM every replica (graceful drain path), escalate to
        SIGKILL after the grace window, and retire the monitors."""
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
        procs = [(r, r.proc) for r in self.replicas
                 if r.proc is not None and r.proc.returncode is None]
        for r, proc in procs:
            try:
                proc.terminate()
            except ProcessLookupError:
                pass
        for r, proc in procs:
            try:
                await asyncio.wait_for(proc.wait(),
                                       self.config.stop_grace_s)
            except asyncio.TimeoutError:
                try:
                    proc.kill()
                except ProcessLookupError:
                    pass
                await proc.wait()
            r.state = "dead"
        for r in self.replicas:
            if r._stderr_task is not None:
                r._stderr_task.cancel()
                try:
                    await r._stderr_task
                except asyncio.CancelledError:
                    pass
                r._stderr_task = None
        self._set_up_gauge()

    # -- listeners (the router subscribes) -----------------------------

    def add_listener(self, fn) -> None:
        """``fn(event_dict)`` is called on every routability change:
        ``{"event": "up" | "down" | "abandoned", "idx", "incarnation",
        "host", "port"}``. Callbacks run on the event loop and must
        not block."""
        self._listeners.append(fn)

    def _notify(self, event: str, r: Replica) -> None:
        self._set_up_gauge()
        self._publish_endpoints()
        info = {"event": event, "idx": r.idx,
                "incarnation": r.incarnation, "host": self.config.host,
                "port": r.port}
        for fn in list(self._listeners):
            fn(dict(info))

    def _publish_endpoints(self) -> None:
        """Republish the routable set to ``endpoints_file`` (tmp +
        ``os.replace``, so an out-of-process FileFleet reader never
        sees a half-written file)."""
        path = self.config.endpoints_file
        if not path:
            return
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump({"endpoints": self.endpoints()}, fh)
        os.replace(tmp, path)

    def _set_up_gauge(self) -> None:
        _metrics.gauge("pycatkin_router_replicas_up",
                       "routable replicas in the supervised fleet").set(
                           float(sum(r.routable
                                     for r in self.replicas)))

    def endpoints(self) -> list:
        """Routable ``(idx, incarnation, host, port)`` snapshots."""
        return [{"idx": r.idx, "incarnation": r.incarnation,
                 "host": self.config.host, "port": r.port}
                for r in self.replicas if r.routable]

    def stats(self) -> dict:
        return {"n_replicas": self.config.n_replicas,
                "up": sum(r.routable for r in self.replicas),
                "replicas": [r.summary() for r in self.replicas]}

    # -- monitor loop --------------------------------------------------

    async def _monitor(self, r: Replica):
        from ..robustness import faults
        cfg = self.config
        while not self._stopping:
            if r.state == "abandoned":
                return
            if r.proc is None:
                if r.restarts > cfg.max_restarts:
                    r.state = "abandoned"
                    record_event("router", action="replica-abandoned",
                                 replica=r.idx, restarts=r.restarts)
                    self._notify("abandoned", r)
                    return
                await self._spawn(r)
                continue
            if self.config.role == "router":
                site = "router:front"
                kinds = ROUTER_SUPERVISOR_FAULT_KINDS
            else:
                site = f"router:replica:{r.idx}"
                kinds = SUPERVISOR_FAULT_KINDS
            for spec in faults.take(site, kinds=kinds):
                self._enact(r, spec.kind, site)
            if r.proc.returncode is not None:
                await self._handle_exit(r)
                continue
            now = time.monotonic()
            if now >= r.next_ping and r.state in ("up", "demoted"):
                r.next_ping = now + cfg.ping_period_s
                await self._probe(r)
            await asyncio.sleep(cfg.tick_s)

    def _enact(self, r: Replica, kind: str, site: str) -> None:
        """Enact one externally-enacted chaos kind on a live child."""
        if r.proc is None or r.proc.returncode is not None:
            return
        record_event("router", action="chaos-enact", replica=r.idx,
                     label=site, fault_kind=kind)
        try:
            if kind in ("replica-crash", "router-crash"):
                r.proc.kill()                       # SIGKILL, no drain
            elif kind == "replica-stall":
                r.proc.send_signal(signal.SIGSTOP)  # alive, silent
        except ProcessLookupError:
            pass

    # -- spawn + registration ------------------------------------------

    def _command(self) -> list:
        if self.config.command:
            return list(self.config.command)
        if self.config.role == "router":
            # A supervised router must sit on a FIXED port so clients
            # reconnect to the same address across incarnations; pass
            # an explicit command (or env PYCATKIN_SERVE_PORT) rather
            # than relying on this ephemeral-port default.
            return [sys.executable, "-m", "pycatkin_tpu.serve",
                    "--router", "--host", self.config.host,
                    "--port", "0"]
        return [sys.executable, "-m", "pycatkin_tpu.serve",
                "--host", self.config.host, "--port", "0"]

    async def _spawn(self, r: Replica):
        cfg = self.config
        if r.restarts > 0:
            we_kind = getattr(r, "last_exit_kind", "signal-death")
            if we_kind == "nonzero-exit":
                # Program-shaped exit: slow lane, no jitter -- a
                # deterministic crash loop must not hot-spin.
                delay = cfg.restart_max_delay_s
            else:
                delay = backoff_delay(r.restarts - 1,
                                      cfg.restart_base_delay_s,
                                      cfg.restart_max_delay_s)
            await asyncio.sleep(delay)
        r.incarnation += 1
        r.state = "booting"
        r.missed_pings = 0
        r.stderr_tail = deque(maxlen=_STDERR_TAIL_LINES)
        env = dict(os.environ)
        env.update(cfg.env)
        if cfg.aot_pack:
            env[AOT_PACK_ENV] = str(cfg.aot_pack)
        try:
            r.proc = await asyncio.create_subprocess_exec(
                *self._command(), env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE)
        except OSError as exc:
            record_event("router", action="replica-spawn-failed",
                         replica=r.idx, detail=str(exc))
            r.proc = None
            r.restarts += 1
            r.state = "dead"
            return
        r._stderr_task = asyncio.get_running_loop().create_task(
            self._drain_stderr(r, r.proc.stderr))
        ok = await self._register(r)
        if ok:
            r.state = "up"
            r.next_ping = time.monotonic() + cfg.ping_period_s
            record_event("router", action="replica-up", replica=r.idx,
                         incarnation=r.incarnation, port=r.port)
            self._notify("up", r)
        elif r.proc is not None and r.proc.returncode is None:
            # Booted wrong (no serving line / failed first ping):
            # treat as a failed incarnation.
            try:
                r.proc.kill()
            except ProcessLookupError:
                pass
            await self._handle_exit(r)

    async def _register(self, r: Replica) -> bool:
        """Scrape the replica's ``serving`` line (printed only after
        its AOT pack import + listen) and win one first ping."""
        try:
            async def scrape():
                while True:
                    line = await r.proc.stdout.readline()
                    if not line:
                        return None
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(msg, dict) and msg.get("serving"):
                        return int(msg["port"])
            port = await asyncio.wait_for(scrape(),
                                          self.config.boot_timeout_s)
        except (asyncio.TimeoutError, OSError, ValueError, KeyError):
            return False
        if port is None:
            return False
        r.port = port
        return await self._ping_once(r)

    async def _drain_stderr(self, r: Replica, stream):
        try:
            while True:
                line = await stream.readline()
                if not line:
                    return
                r.stderr_tail.append(
                    line.decode("utf-8", "replace").rstrip())
        except (asyncio.CancelledError, OSError):
            raise

    # -- probes + exits ------------------------------------------------

    async def _ping_once(self, r: Replica) -> bool:
        cfg = self.config
        writer = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(cfg.host, r.port),
                cfg.ping_timeout_s)
            writer.write(b'{"op": "ping", "id": "probe"}\n')
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(),
                                          cfg.ping_timeout_s)
            resp = json.loads(line) if line.strip() else None
            return bool(isinstance(resp, dict) and resp.get("ok"))
        except (OSError, ValueError, asyncio.TimeoutError):
            return False
        finally:
            if writer is not None:
                writer.close()

    async def _probe(self, r: Replica):
        ok = await self._ping_once(r)
        if ok:
            r.missed_pings = 0
            if r.state == "demoted":
                r.state = "up"
                record_event("router", action="replica-promoted",
                             replica=r.idx)
                self._notify("up", r)
            return
        r.missed_pings += 1
        if r.state == "up" and \
                r.missed_pings >= self.config.ping_misses:
            r.state = "demoted"
            record_event("router", action="replica-demoted",
                         replica=r.idx, missed=r.missed_pings)
            self._notify("down", r)
        if r.missed_pings >= 2 * self.config.ping_misses:
            # Stalled beyond recovery (e.g. SIGSTOP): kill it so the
            # exit branch reboots a fresh incarnation.
            try:
                r.proc.kill()
            except (ProcessLookupError, AttributeError):
                pass

    async def _handle_exit(self, r: Replica):
        await r.proc.wait()
        we = classify_worker_exit(r.proc.returncode)
        r.last_exit_kind = we.kind
        was_routable = r.routable
        tail = list(r.stderr_tail)[-5:]
        record_event("router", action="replica-exit", replica=r.idx,
                     incarnation=r.incarnation, exit_kind=we.kind,
                     transient=we.transient, detail=we.detail,
                     stderr_tail=tail)
        _metrics.counter(
            "pycatkin_router_replica_restarts_total",
            "replica exits observed by the fleet supervisor").inc(
                kind=we.kind)
        if r._stderr_task is not None:
            r._stderr_task.cancel()
            try:
                await r._stderr_task
            except asyncio.CancelledError:
                pass
            r._stderr_task = None
        r.proc = None
        r.port = None
        r.restarts += 1
        r.state = "dead"
        if was_routable:
            self._notify("down", r)


class FileFleet:
    """The supervisor surface a :class:`serve.router.SweepRouter`
    consumes (``endpoints()`` / ``stats()`` / ``add_listener``),
    backed by the endpoints file a ReplicaSupervisor in ANOTHER
    process publishes (``FleetConfig.endpoints_file``). This is how a
    supervised router subprocess routes to replicas owned by its
    parent: the parent republishes atomically on every routability
    change, and incarnation bumps in the file retire stale links in
    the router's ``_link_for``. Listeners never fire -- cross-process
    routability changes surface through the file (and through link
    failures), not callbacks."""

    def __init__(self, path: str):
        self.path = str(path)
        self._sig = None
        self._cache: list = []

    def add_listener(self, fn) -> None:
        pass   # see the class docstring: the file IS the event stream

    def endpoints(self) -> list:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        sig = (st.st_mtime_ns, st.st_size)
        if sig != self._sig:
            try:
                with open(self.path) as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                # Mid-replace race or unreadable file: keep the last
                # good snapshot; the next call re-reads.
                return [dict(ep) for ep in self._cache]
            self._cache = list(data.get("endpoints", []))
            self._sig = sig
        return [dict(ep) for ep in self._cache]

    def stats(self) -> dict:
        eps = self.endpoints()
        return {"n_replicas": len(eps), "up": len(eps),
                "replicas": [], "endpoints_file": self.path}
