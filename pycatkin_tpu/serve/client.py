"""Clients for the sweep service: in-process and JSON-lines-over-TCP.

:class:`SweepClient` talks straight to a :class:`serve.server.
SweepServer` object in the same process -- no serialization, and the
mechanism may be a built ``System`` (the soak harness's fast path).
:class:`TcpSweepClient` speaks the wire protocol; it multiplexes any
number of in-flight requests over one connection by matching response
``id`` to request ``id``, which is what lets K co-tenants of a packed
group be pending simultaneously from a single client.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import Optional

from .protocol import (E_INTERNAL, E_TIMEOUT, error_response,
                       request_timeout_for)

# A connection delivering this many CONSECUTIVE undecodable lines is
# torn, not merely glitched: fail every pending request fast instead of
# letting callers sit on futures that will never resolve. One bad line
# (a torn final write from a dying server) only bumps the metric; the
# streak resets on the next good line.
TORN_LINE_LIMIT = 8

_UNSET = object()


def sweep_payload(mechanism, T, p=1.0e5, tof_terms=None,
                  deadline_class: str = "standard",
                  wait_budget_s: Optional[float] = None,
                  want=(), req_id=None) -> dict:
    """Assemble one sweep request object (docs/serving.md schema)."""
    payload = {
        "op": "sweep", "id": req_id, "mechanism": mechanism,
        "conditions": {
            "T": list(T) if isinstance(T, (list, tuple)) else [T],
            "p": list(p) if isinstance(p, (list, tuple)) else p},
        "deadline_class": deadline_class,
    }
    if tof_terms:
        payload["tof_terms"] = list(tof_terms)
    if wait_budget_s is not None:
        payload["wait_budget_s"] = float(wait_budget_s)
    if want:
        payload["return"] = list(want)
    return payload


class SweepClient:
    """In-process client: calls the server's request handler directly.
    The ``mechanism`` may be a built ``System`` (skipping the JSON
    round-trip) or a reference-schema dict."""

    def __init__(self, server):
        self._server = server
        self._seq = itertools.count()

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        req_id = kwargs.pop("req_id", None) or f"c{next(self._seq)}"
        return await self._server.handle(
            sweep_payload(mechanism, T, p=p, req_id=req_id, **kwargs))

    async def ping(self) -> dict:
        return await self._server.handle({"op": "ping"})

    async def stats(self) -> dict:
        return await self._server.handle({"op": "stats"})

    async def drain(self) -> dict:
        return await self._server.handle({"op": "drain"})


class TcpSweepClient:
    """JSON-lines TCP client with id-multiplexed in-flight requests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._reader = None
        self._writer = None
        self._pending: dict = {}
        self._seq = itertools.count()
        self._read_task = None
        self._wlock = asyncio.Lock()
        self.torn_lines = 0

    async def connect(self) -> "TcpSweepClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._read_task = asyncio.get_running_loop().create_task(
            self._read_loop())
        return self

    async def _read_loop(self):
        from ..obs import metrics
        torn = metrics.counter(
            "pycatkin_serve_torn_lines_total",
            "undecodable JSON lines received by serve TCP clients")
        why = "connection closed"
        streak = 0
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                try:
                    resp = json.loads(line)
                except ValueError:
                    # A torn line (partial write from a dying peer) is
                    # accounted, never silently dropped; the sender
                    # retries by id, so the lost response is recovered
                    # upstream.
                    self.torn_lines += 1
                    torn.inc()
                    streak += 1
                    if streak >= TORN_LINE_LIMIT:
                        why = (f"{streak} consecutive undecodable "
                               f"lines: stream torn")
                        break
                    continue
                streak = 0
                fut = self._pending.pop(resp.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except (ConnectionError, OSError,
                asyncio.IncompleteReadError) as exc:
            why = f"connection lost: {exc}"
        finally:
            # Connection gone: fail whatever is still waiting rather
            # than hanging the caller forever.
            err = error_response(None, E_INTERNAL, why)
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_result(dict(err))
            self._pending.clear()

    async def request(self, payload: dict, timeout=_UNSET) -> dict:
        """Send one request object; resolves when ITS response (by
        ``id``) arrives, regardless of interleaving.

        Every request carries a deadline: ``timeout`` defaults to the
        payload's deadline-class request timeout
        (:func:`protocol.request_timeout_for`), so a stalled -- not
        closed -- server resolves to a structured ``E_TIMEOUT`` error
        instead of hanging the caller forever. Pass ``timeout=None``
        to wait indefinitely, or a float to override."""
        if payload.get("id") is None:
            payload = dict(payload, id=f"t{next(self._seq)}")
        if timeout is _UNSET:
            timeout = request_timeout_for(
                payload.get("deadline_class", "standard"))
        req_id = payload["id"]
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        data = (json.dumps(payload) + "\n").encode()
        async with self._wlock:
            self._writer.write(data)
            await self._writer.drain()
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            if fut.done():         # answer raced the deadline: keep it
                return fut.result()  # pclint: disable=PCL010 -- asyncio future already done; returns instantly
            fut.cancel()
            return error_response(
                req_id, E_TIMEOUT,
                f"no response within {timeout:.3f} s "
                f"(deadline_class "
                f"{payload.get('deadline_class', 'standard')!r})")

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        return await self.request(
            sweep_payload(mechanism, T, p=p, **kwargs))

    async def ping(self) -> dict:
        return await self.request({"op": "ping"})

    async def stats(self) -> dict:
        return await self.request({"op": "stats"})

    async def drain(self) -> dict:
        return await self.request({"op": "drain"})

    async def close(self):
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except asyncio.CancelledError:
                pass
            self._read_task = None
