"""Clients for the sweep service: in-process and JSON-lines-over-TCP.

:class:`SweepClient` talks straight to a :class:`serve.server.
SweepServer` object in the same process -- no serialization, and the
mechanism may be a built ``System`` (the soak harness's fast path).
:class:`TcpSweepClient` speaks the wire protocol; it multiplexes any
number of in-flight requests over one connection by matching response
``id`` to request ``id``, which is what lets K co-tenants of a packed
group be pending simultaneously from a single client.

Severed connections (docs/serving.md "Durable requests"): pending
requests WITHOUT an idempotency key fail immediately with a structured
``E_CONN_LOST`` naming the peer (resubmitting them is not known to be
safe). Requests WITH a key survive: the client reconnects under a
bounded-backoff window (counted in
``pycatkin_serve_reconnects_total``) and resubmits them verbatim --
the router's write-ahead journal dedups, so the caller sees exactly
one answer.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time
from typing import Optional

from ..utils.retry import backoff_delay
from .protocol import (E_CONN_LOST, E_TIMEOUT, error_response,
                       request_timeout_for)

# A connection delivering this many CONSECUTIVE undecodable lines is
# torn, not merely glitched: fail every pending request fast instead of
# letting callers sit on futures that will never resolve. One bad line
# (a torn final write from a dying server) only bumps the metric; the
# streak resets on the next good line.
TORN_LINE_LIMIT = 8

_UNSET = object()


def sweep_payload(mechanism, T, p=1.0e5, tof_terms=None,
                  deadline_class: str = "standard",
                  wait_budget_s: Optional[float] = None,
                  want=(), req_id=None,
                  idempotency_key: Optional[str] = None) -> dict:
    """Assemble one sweep request object (docs/serving.md schema)."""
    payload = {
        "op": "sweep", "id": req_id, "mechanism": mechanism,
        "conditions": {
            "T": list(T) if isinstance(T, (list, tuple)) else [T],
            "p": list(p) if isinstance(p, (list, tuple)) else p},
        "deadline_class": deadline_class,
    }
    if tof_terms:
        payload["tof_terms"] = list(tof_terms)
    if wait_budget_s is not None:
        payload["wait_budget_s"] = float(wait_budget_s)
    if want:
        payload["return"] = list(want)
    if idempotency_key is not None:
        payload["idempotency_key"] = str(idempotency_key)
    return payload


def transient_payload(mechanism, T, save_ts, p=1.0e5,
                      deadline_class: str = "standard",
                      wait_budget_s: Optional[float] = None,
                      want=(), req_id=None,
                      idempotency_key: Optional[str] = None) -> dict:
    """Assemble one transient request object (docs/serving.md
    schema): a sweep-shaped conditions grid plus the dense-output
    save-time grid (``save_ts[0]`` must be 0, strictly increasing)."""
    payload = {
        "op": "transient", "id": req_id, "mechanism": mechanism,
        "conditions": {
            "T": list(T) if isinstance(T, (list, tuple)) else [T],
            "p": list(p) if isinstance(p, (list, tuple)) else p},
        "save_ts": [float(t) for t in save_ts],
        "deadline_class": deadline_class,
    }
    if wait_budget_s is not None:
        payload["wait_budget_s"] = float(wait_budget_s)
    if want:
        payload["return"] = list(want)
    if idempotency_key is not None:
        payload["idempotency_key"] = str(idempotency_key)
    return payload


class SweepClient:
    """In-process client: calls the server's request handler directly.
    The ``mechanism`` may be a built ``System`` (skipping the JSON
    round-trip) or a reference-schema dict."""

    def __init__(self, server):
        self._server = server
        self._seq = itertools.count()

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        req_id = kwargs.pop("req_id", None) or f"c{next(self._seq)}"
        return await self._server.handle(
            sweep_payload(mechanism, T, p=p, req_id=req_id, **kwargs))

    async def transient(self, mechanism, T, save_ts, p=1.0e5,
                        **kwargs) -> dict:
        req_id = kwargs.pop("req_id", None) or f"c{next(self._seq)}"
        return await self._server.handle(
            transient_payload(mechanism, T, save_ts, p=p,
                              req_id=req_id, **kwargs))

    async def ping(self) -> dict:
        return await self._server.handle({"op": "ping"})

    async def stats(self) -> dict:
        return await self._server.handle({"op": "stats"})

    async def drain(self) -> dict:
        return await self._server.handle({"op": "drain"})


class TcpSweepClient:
    """JSON-lines TCP client with id-multiplexed in-flight requests
    and (by default) auto-reconnect; see the module docstring for the
    severed-connection contract."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 reconnect: bool = True,
                 reconnect_window_s: float = 60.0,
                 reconnect_base_delay_s: float = 0.05,
                 reconnect_max_delay_s: float = 2.0):
        self.host = host
        self.port = port
        self.reconnect = reconnect
        self.reconnect_window_s = float(reconnect_window_s)
        self.reconnect_base_delay_s = float(reconnect_base_delay_s)
        self.reconnect_max_delay_s = float(reconnect_max_delay_s)
        self._reader = None
        self._writer = None
        self._pending: dict = {}     # id -> future
        self._payloads: dict = {}    # id -> request payload (resubmit)
        self._seq = itertools.count()
        self._read_task = None
        self._reconnect_task = None
        self._wlock = asyncio.Lock()
        self._closing = False
        self.torn_lines = 0
        self.reconnects = 0
        self.acks = 0                # durability ack lines received

    async def connect(self) -> "TcpSweepClient":
        await self._open()
        return self

    async def _open(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._read_task = asyncio.get_running_loop().create_task(
            self._read_loop())

    def _connected(self) -> bool:
        return (self._writer is not None
                and self._read_task is not None
                and not self._read_task.done())

    @property
    def _peer(self) -> str:
        return f"{self.host}:{self.port}"

    async def _read_loop(self):
        from ..obs import metrics
        torn = metrics.counter(
            "pycatkin_serve_torn_lines_total",
            "undecodable JSON lines received by serve TCP clients")
        why = "connection closed"
        streak = 0
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                try:
                    resp = json.loads(line)
                except ValueError:
                    # A torn line (partial write from a dying peer) is
                    # accounted, never silently dropped; the sender
                    # retries by id, so the lost response is recovered
                    # upstream.
                    self.torn_lines += 1
                    torn.inc()
                    streak += 1
                    if streak >= TORN_LINE_LIMIT:
                        why = (f"{streak} consecutive undecodable "
                               f"lines: stream torn")
                        break
                    continue
                streak = 0
                if resp.get("accepted") is True and "ok" not in resp:
                    # Durability ack (protocol.accepted_ack): the
                    # request is journaled router-side; its real
                    # answer follows under the same id.
                    self.acks += 1
                    continue
                rid = resp.get("id")
                fut = self._pending.pop(rid, None)
                self._payloads.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        except (ConnectionError, OSError,
                asyncio.IncompleteReadError) as exc:
            why = f"connection lost: {exc}"
        finally:
            self._on_conn_lost(why)

    def _conn_lost_error(self, rid, why: str, has_key: bool) -> dict:
        if has_key:
            hint = "an idempotency key, so resubmitting is safe"
        else:
            hint = ("no idempotency key, so resubmitting is NOT "
                    "known to be safe")
        return error_response(
            rid, E_CONN_LOST,
            f"connection to {self._peer} lost ({why}); "
            f"request had {hint}",
            peer=self._peer, idempotency_key=has_key)

    def _on_conn_lost(self, why: str) -> None:
        """The connection died under ``self._pending``: fail keyless
        requests with a structured ``E_CONN_LOST``; keep keyed ones
        pending and reconnect to resubmit them."""
        if self._writer is not None:
            try:
                self._writer.transport.abort()
            except (ConnectionError, OSError, RuntimeError):
                pass
        survivors = 0
        for rid, fut in list(self._pending.items()):
            payload = self._payloads.get(rid)
            has_key = bool(isinstance(payload, dict)
                           and payload.get("idempotency_key"))
            if has_key and self.reconnect and not self._closing:
                survivors += 1
                continue
            self._pending.pop(rid, None)
            self._payloads.pop(rid, None)
            if not fut.done():
                fut.set_result(self._conn_lost_error(rid, why, has_key))
        if survivors and not self._closing:
            self._ensure_reconnect()

    def _ensure_reconnect(self) -> None:
        if not self.reconnect or self._closing:
            return
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.get_running_loop() \
                .create_task(self._reconnect())

    async def _reconnect(self) -> None:
        from ..obs import metrics
        deadline = time.monotonic() + self.reconnect_window_s
        attempt = 0
        while not self._closing and not self._connected():
            try:
                await self._open()
            except (ConnectionError, OSError,
                    asyncio.TimeoutError) as exc:
                attempt += 1
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._fail_pending(
                        f"reconnect window "
                        f"({self.reconnect_window_s:.0f} s) exhausted; "
                        f"last error: {exc}")
                    return
                await asyncio.sleep(min(
                    backoff_delay(attempt - 1,
                                  self.reconnect_base_delay_s,
                                  self.reconnect_max_delay_s),
                    remaining))
                continue
            self.reconnects += 1
            metrics.counter(
                "pycatkin_serve_reconnects_total",
                "serve TCP client reconnects after a severed "
                "connection").inc()
        # Resubmit everything still unanswered, verbatim (same ids;
        # keyed requests dedup in the router's journal, so the caller
        # can never see two answers for one key).
        if self._connected():
            for rid, payload in list(self._payloads.items()):
                if rid not in self._pending:
                    continue
                try:
                    await self._send(payload)
                except (ConnectionError, OSError):
                    return   # the read loop reports the loss again

    def _fail_pending(self, why: str) -> None:
        for rid, fut in list(self._pending.items()):
            payload = self._payloads.get(rid)
            has_key = bool(isinstance(payload, dict)
                           and payload.get("idempotency_key"))
            if not fut.done():
                fut.set_result(self._conn_lost_error(rid, why, has_key))
        self._pending.clear()
        self._payloads.clear()

    async def _send(self, payload: dict) -> None:
        data = (json.dumps(payload) + "\n").encode()
        async with self._wlock:
            if self._writer is None:
                raise ConnectionResetError(
                    f"not connected to {self._peer}")
            self._writer.write(data)
            await self._writer.drain()

    async def _send_after_reconnect(self, payload: dict) -> bool:
        """The initial send hit a dead connection: wait out one
        reconnect cycle, then send again (a duplicate line is safe --
        responses are matched by id and keyed requests dedup
        router-side). Returns False when the request cannot be
        delivered."""
        if not self.reconnect or self._closing:
            return False
        self._ensure_reconnect()
        task = self._reconnect_task
        if task is not None:
            try:
                await asyncio.wait_for(asyncio.shield(task),
                                       self.reconnect_window_s + 5.0)
            except (asyncio.TimeoutError, ConnectionError, OSError):
                pass
        if not self._connected():
            return False
        try:
            await self._send(payload)
        except (ConnectionError, OSError):
            return False
        return True

    async def request(self, payload: dict, timeout=_UNSET) -> dict:
        """Send one request object; resolves when ITS response (by
        ``id``) arrives, regardless of interleaving.

        Every request carries a deadline: ``timeout`` defaults to the
        payload's deadline-class request timeout
        (:func:`protocol.request_timeout_for`), so a stalled -- not
        closed -- server resolves to a structured ``E_TIMEOUT`` error
        instead of hanging the caller forever. Pass ``timeout=None``
        to wait indefinitely, or a float to override."""
        if payload.get("id") is None:
            payload = dict(payload, id=f"t{next(self._seq)}")
        if timeout is _UNSET:
            timeout = request_timeout_for(
                payload.get("deadline_class", "standard"))
        req_id = payload["id"]
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        self._payloads[req_id] = payload
        try:
            try:
                await self._send(payload)
            except (ConnectionError, OSError) as exc:
                if not await self._send_after_reconnect(payload):
                    self._pending.pop(req_id, None)
                    has_key = bool(payload.get("idempotency_key"))
                    if not fut.done():
                        fut.set_result(self._conn_lost_error(
                            req_id, f"send failed: {exc}", has_key))
                    return await fut
            try:
                return await asyncio.wait_for(asyncio.shield(fut),
                                              timeout)
            except asyncio.TimeoutError:
                self._pending.pop(req_id, None)
                if fut.done():     # answer raced the deadline: keep it
                    return fut.result()  # pclint: disable=PCL010 -- asyncio future already done; returns instantly
                fut.cancel()
                return error_response(
                    req_id, E_TIMEOUT,
                    f"no response within {timeout:.3f} s "
                    f"(deadline_class "
                    f"{payload.get('deadline_class', 'standard')!r})")
        finally:
            self._payloads.pop(req_id, None)
            self._pending.pop(req_id, None)

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        return await self.request(
            sweep_payload(mechanism, T, p=p, **kwargs))

    async def transient(self, mechanism, T, save_ts, p=1.0e5,
                        **kwargs) -> dict:
        return await self.request(
            transient_payload(mechanism, T, save_ts, p=p, **kwargs))

    async def ping(self) -> dict:
        return await self.request({"op": "ping"})

    async def stats(self) -> dict:
        return await self.request({"op": "stats"})

    async def drain(self) -> dict:
        return await self.request({"op": "drain"})

    async def fetch_result(self, key: str) -> dict:
        """Fetch the journaled answer for an idempotency key (the
        ``result`` op; journal-backed routers only)."""
        return await self.request({"op": "result", "key": str(key)})

    async def close(self):
        self._closing = True
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
            try:
                await self._reconnect_task
            except asyncio.CancelledError:
                pass
            self._reconnect_task = None
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except asyncio.CancelledError:
                pass
            self._read_task = None
        self._fail_pending("client closed")
