"""Clients for the sweep service: in-process and JSON-lines-over-TCP.

:class:`SweepClient` talks straight to a :class:`serve.server.
SweepServer` object in the same process -- no serialization, and the
mechanism may be a built ``System`` (the soak harness's fast path).
:class:`TcpSweepClient` speaks the wire protocol; it multiplexes any
number of in-flight requests over one connection by matching response
``id`` to request ``id``, which is what lets K co-tenants of a packed
group be pending simultaneously from a single client.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import Optional

from .protocol import E_INTERNAL, error_response


def sweep_payload(mechanism, T, p=1.0e5, tof_terms=None,
                  deadline_class: str = "standard",
                  wait_budget_s: Optional[float] = None,
                  want=(), req_id=None) -> dict:
    """Assemble one sweep request object (docs/serving.md schema)."""
    payload = {
        "op": "sweep", "id": req_id, "mechanism": mechanism,
        "conditions": {
            "T": list(T) if isinstance(T, (list, tuple)) else [T],
            "p": list(p) if isinstance(p, (list, tuple)) else p},
        "deadline_class": deadline_class,
    }
    if tof_terms:
        payload["tof_terms"] = list(tof_terms)
    if wait_budget_s is not None:
        payload["wait_budget_s"] = float(wait_budget_s)
    if want:
        payload["return"] = list(want)
    return payload


class SweepClient:
    """In-process client: calls the server's request handler directly.
    The ``mechanism`` may be a built ``System`` (skipping the JSON
    round-trip) or a reference-schema dict."""

    def __init__(self, server):
        self._server = server
        self._seq = itertools.count()

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        req_id = kwargs.pop("req_id", None) or f"c{next(self._seq)}"
        return await self._server.handle(
            sweep_payload(mechanism, T, p=p, req_id=req_id, **kwargs))

    async def ping(self) -> dict:
        return await self._server.handle({"op": "ping"})

    async def stats(self) -> dict:
        return await self._server.handle({"op": "stats"})

    async def drain(self) -> dict:
        return await self._server.handle({"op": "drain"})


class TcpSweepClient:
    """JSON-lines TCP client with id-multiplexed in-flight requests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._reader = None
        self._writer = None
        self._pending: dict = {}
        self._seq = itertools.count()
        self._read_task = None
        self._wlock = asyncio.Lock()

    async def connect(self) -> "TcpSweepClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._read_task = asyncio.get_running_loop().create_task(
            self._read_loop())
        return self

    async def _read_loop(self):
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                try:
                    resp = json.loads(line)
                except ValueError:
                    continue
                fut = self._pending.pop(resp.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(resp)
        finally:
            # Connection gone: fail whatever is still waiting rather
            # than hanging the caller forever.
            err = error_response(None, E_INTERNAL, "connection closed")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_result(dict(err))
            self._pending.clear()

    async def request(self, payload: dict) -> dict:
        """Send one request object; resolves when ITS response (by
        ``id``) arrives, regardless of interleaving."""
        if payload.get("id") is None:
            payload = dict(payload, id=f"t{next(self._seq)}")
        fut = asyncio.get_running_loop().create_future()
        self._pending[payload["id"]] = fut
        data = (json.dumps(payload) + "\n").encode()
        async with self._wlock:
            self._writer.write(data)
            await self._writer.drain()
        return await fut

    async def sweep(self, mechanism, T, p=1.0e5, **kwargs) -> dict:
        return await self.request(
            sweep_payload(mechanism, T, p=p, **kwargs))

    async def ping(self) -> dict:
        return await self.request({"op": "ping"})

    async def stats(self) -> dict:
        return await self.request({"op": "stats"})

    async def drain(self) -> dict:
        return await self.request({"op": "drain"})

    async def close(self):
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except asyncio.CancelledError:
                pass
            self._read_task = None
