"""The sweep server: one asyncio process owning queue, cache and mesh.

Request lifecycle (docs/serving.md):

  accept -> lower -> coalesce -> flush -> respond

``accept`` is admission control: a bounded pending queue with
structured rejects (overloaded / draining), and the ``serve:accept``
fault-injection site. ``lower`` builds the System from the wire
mechanism and lane-stacks the conditions grid. ``coalesce`` submits to
a :class:`parallel.dispatch.SweepCoalescer` in ``autoflush=False``
mode with the request's deadline-class wait budget -- the SLA hook: a
group flushes when full OR when its most impatient member's budget
burns. ``flush`` is the scheduler loop: due groups are taken on the
event loop (dict-only, race-free) and executed serially on a worker
thread, so compile attribution per flush is exact. ``respond`` ships
the per-tenant result with its run manifest, lane telemetry and
quarantine report.

The solver never runs on the event loop and the event loop never
blocks on the solver; backpressure is the bounded queue, not TCP.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Optional

import numpy as np

from ..obs import metrics as _metrics
from ..utils.profiling import record_event
from .protocol import (E_BAD_REQUEST, E_DRAINING, E_INTERNAL,
                       E_OVERLOADED, PROTOCOL, ServeConfig, ServeError,
                       error_response, jsonable, parse_sweep_request,
                       parse_transient_request)

# Lane-shaped result keys returned by default; the full solution
# vector ``y`` rides only on request (``"return": ["y"]``) -- at
# bucket 512 it is the whole response payload.
SUMMARY_KEYS = ("success", "residual", "attempts", "quarantined",
                "stable", "tof", "activity")


def _compile_count() -> float:
    """Total of the ``pycatkin_compile_total`` counter across label
    sets -- the marginal-compile probe the flush loop differences."""
    vals = _metrics.counter("pycatkin_compile_total").values()
    return float(sum(vals.values()))


def _key_label(key) -> str:
    """Group-key display label: the ABI fingerprint for packable
    groups, ``"solo"`` for the unfittable."""
    return str(key[0]) if isinstance(key, tuple) and key else str(key)


class SweepServer:
    """A live sweep service; see module docstring for the lifecycle.

    Construct with a :class:`serve.protocol.ServeConfig` (or field
    overrides), ``await start()``, submit through
    :class:`serve.client.SweepClient` / TCP, ``await drain()`` to
    finish every accepted request and shut down."""

    def __init__(self, config: Optional[ServeConfig] = None, **overrides):
        self.config = config or ServeConfig(**overrides)
        self._coalescer = None
        self._futures: dict = {}
        self._taken = 0
        self._admitted = 0
        self._inflight = 0
        self._draining = False
        self._stopping = False
        self._wake: Optional[asyncio.Event] = None
        self._scheduler_task = None
        self._tcp_server = None
        self._own_work_dir = None
        self.port: Optional[int] = None
        self.boot_manifest: dict = {}
        self.flushes = 0
        self.flushes_with_compiles = 0
        self.compiles_total = 0.0
        self._occupancy_sum = 0.0
        self._requests_total = 0
        self._rejected_total = 0
        self._completed_total = 0
        self._warm_marked = False
        self.flushes_after_warm = 0
        self.flushes_with_compiles_after_warm = 0
        self.compiles_after_warm = 0.0

    # -- boot ----------------------------------------------------------

    def _make_coalescer(self):
        from ..parallel.dispatch import SweepCoalescer
        cfg = self.config
        work_dir = cfg.work_dir
        if cfg.runner == "elastic" and work_dir is None:
            import tempfile
            self._own_work_dir = tempfile.mkdtemp(
                prefix="pycatkin_serve_")
            work_dir = self._own_work_dir
        runner = None
        if cfg.runner == "elastic":
            from ..robustness.scheduler import packed_group_runner
            runner = packed_group_runner(work_dir=work_dir,
                                         n_workers=cfg.n_workers)
        return SweepCoalescer(runner=runner, autoflush=False,
                              work_dir=work_dir,
                              max_occupancy=cfg.max_occupancy,
                              max_wait_s=cfg.max_wait_s)

    async def start(self, listen: bool = True) -> "SweepServer":
        """Import the AOT pack (if configured), compute the boot
        manifest, start the scheduler loop and (optionally) the TCP
        listener. Cold-start work happens HERE, before the first
        request can arrive."""
        from .. import san as _san
        if _san.enabled():
            # Arm the sanitizer layer on the serve loop: slow-callback
            # detection (stall sanitizer) plus the passive sync/
            # recompile recorders. mark_warm() later arms the
            # recompile TRIPWIRE on top of the recorder.
            from ..san import stall as _san_stall
            _san.install()
            await _san_stall.arm()
        self._coalescer = self._make_coalescer()
        self._wake = asyncio.Event()
        if self.config.aot_pack:
            from ..parallel.compile_pool import import_cache_pack
            stats = await asyncio.to_thread(import_cache_pack,
                                            self.config.aot_pack)
            record_event("serve", action="aot-pack-import",
                         label=str(self.config.aot_pack),
                         entries=stats.get("entries"))
        from ..obs.manifest import run_manifest
        self.boot_manifest = await asyncio.to_thread(run_manifest)
        self._scheduler_task = asyncio.create_task(
            self._scheduler_loop())
        if listen:
            self._tcp_server = await asyncio.start_server(
                self._on_connection, self.config.host, self.config.port)
            self.port = self._tcp_server.sockets[0].getsockname()[1]
            record_event("serve", action="listen",
                         host=self.config.host, port=self.port)
        return self

    def warm(self, sims, lanes: int, k_buckets=(2, 4, 8),
             transient_save_ts=None) -> dict:
        """Load-or-compile every program the serve path can dispatch
        for these representative mechanisms at this lane count: the
        solo zoo (K=1 flushes) plus the packed executables for each
        ``k_bucket``. Pass ``transient_save_ts`` (a save-time grid) to
        also warm the fused + packed transient programs the
        ``transient`` op dispatches. Blocking -- call before serving
        traffic (or via ``asyncio.to_thread``). Booted from a warm AOT
        pack this is deserialization only and the returned
        ``compiled`` is 0."""
        from ..parallel.batch import (broadcast_conditions,
                                      prewarm_packed_sweep_programs,
                                      prewarm_sweep_programs,
                                      prewarm_transient_programs)
        compiled = loaded = 0
        for sim in sims:
            spec = getattr(sim, "spec", sim)
            conds = broadcast_conditions(sim.conditions(), lanes)
            st = prewarm_sweep_programs(spec, conds, buckets=(),
                                        check_stability=False)
            compiled += st.compiled
            loaded += st.loaded
            if transient_save_ts is not None:
                st = prewarm_transient_programs(
                    spec, conds, transient_save_ts,
                    k_buckets=k_buckets)
                compiled += st.compiled
                loaded += st.loaded
            for k in k_buckets:
                if k < 2:
                    continue
                st = prewarm_packed_sweep_programs([spec] * k,
                                                   [conds] * k)
                compiled += st.compiled
                loaded += st.loaded
        record_event("serve", action="warm", compiled=compiled,
                     loaded=loaded, lanes=lanes)
        return {"compiled": compiled, "loaded": loaded}

    def mark_warm(self) -> None:
        """Declare warmup over: flush/compile counters accumulated
        after this call feed the zero-compile-rate gate. Under
        ``PYCATKIN_SAN=1`` this also arms the recompile sanitizer's
        tripwire: from here on a fresh compile (or a never-seen
        program key at the dispatch seam) RAISES instead of just
        moving the rate."""
        self._warm_marked = True
        self.flushes_after_warm = 0
        self.flushes_with_compiles_after_warm = 0
        self.compiles_after_warm = 0.0
        from .. import san as _san
        if _san.enabled():
            from ..san import recompile as _san_recompile
            _san_recompile.mark_warm()

    # -- shutdown ------------------------------------------------------

    async def drain(self) -> None:
        """Stop admitting, finish every accepted request, then stop.
        The no-loss path: rejects are structured responses, accepted
        requests always resolve."""
        if self._draining:
            await self.wait_stopped()
            return
        self._draining = True
        record_event("serve", action="drain-begin",
                     pending=self._coalescer.pending)
        # ``_admitted`` covers the window between admission and the
        # coalescer submit (mechanism/conditions still building on a
        # worker thread): such requests are accepted but not yet
        # visible in any queue, and drain must wait for them too.
        while (self._coalescer.pending or self._taken
               or self._futures or self._admitted or self._inflight):
            self._wake.set()
            await asyncio.sleep(self.config.tick_s)
        record_event("serve", action="drain-complete",
                     completed=self._completed_total)
        await self.stop()

    async def stop(self) -> None:
        """Tear down listener and scheduler. Pending requests (if any)
        are failed; prefer :meth:`drain` for a graceful exit."""
        self._stopping = True
        if self._wake is not None:
            self._wake.set()
        if self._scheduler_task is not None:
            try:
                await self._scheduler_task
            finally:
                self._scheduler_task = None
        if self._tcp_server is not None:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()
            self._tcp_server = None
        for req, fut in list(self._futures.items()):
            if not fut.done():
                fut.set_exception(ServeError(
                    E_INTERNAL, "server stopped before flush"))
            self._futures.pop(req, None)
        if self._own_work_dir:
            import shutil
            shutil.rmtree(self._own_work_dir, ignore_errors=True)
            self._own_work_dir = None

    async def wait_stopped(self) -> None:
        while self._scheduler_task is not None or self._tcp_server:
            await asyncio.sleep(self.config.tick_s)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- request handling ---------------------------------------------

    async def handle(self, payload) -> dict:
        """Process one request object; returns the response object.
        Shared by the TCP framing and the in-process client -- every
        failure maps to a structured error response here."""
        req_id = payload.get("id") if isinstance(payload, dict) else None
        try:
            if not isinstance(payload, dict):
                raise ServeError(E_BAD_REQUEST,
                                 "expected a JSON object per line")
            op = payload.get("op", "sweep")
            if op == "ping":
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "pong": True, "draining": self._draining}
            if op == "stats":
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "stats": self.stats()}
            if op == "drain":
                asyncio.get_running_loop().create_task(self.drain())
                return {"protocol": PROTOCOL, "id": req_id, "ok": True,
                        "draining": True}
            if op == "sweep":
                return await self._handle_sweep(payload, req_id)
            if op == "transient":
                return await self._handle_transient(payload, req_id)
            raise ServeError(E_BAD_REQUEST, f"unknown op {op!r}")
        except ServeError as exc:
            self._rejected_total += 1
            _metrics.counter("pycatkin_serve_rejects_total",
                             "serve requests rejected").inc(
                                 code=exc.code)
            record_event("serve", action="reject", label=str(exc.code),
                         detail=str(exc))
            return error_response(req_id, exc.code, str(exc))
        except Exception as exc:  # noqa: BLE001 - wire boundary
            self._rejected_total += 1
            _metrics.counter("pycatkin_serve_rejects_total",
                             "serve requests rejected").inc(
                                 code=E_INTERNAL)
            return error_response(req_id, E_INTERNAL,
                                  f"{type(exc).__name__}: {exc}")

    async def _handle_sweep(self, payload: dict, req_id) -> dict:
        from ..robustness import faults
        t0 = time.monotonic()
        self._requests_total += 1
        _metrics.counter("pycatkin_serve_requests_total",
                         "sweep requests admitted or rejected").inc()
        parsed = parse_sweep_request(payload)
        if self._draining:
            raise ServeError(E_DRAINING,
                             "server is draining; no new sweeps")
        if self.pending >= self.config.max_pending:
            raise ServeError(
                E_OVERLOADED,
                f"pending queue is full ({self.pending} >= "
                f"{self.config.max_pending}); retry with backoff")
        faults.inject("serve:accept")
        self._admitted += 1
        try:
            sim = await asyncio.to_thread(self._build_system,
                                          parsed["mechanism"])
            conds = await asyncio.to_thread(self._build_conds, sim,
                                            parsed["T"], parsed["p"])
            mask = None
            if parsed["tof_terms"]:
                from .. import engine
                mask = await asyncio.to_thread(engine.tof_mask_for,
                                               sim.spec,
                                               parsed["tof_terms"])
            wait = parsed["wait_budget_s"]
            if wait is None:
                wait = self.config.wait_budget_for(
                    parsed["deadline_class"])
            fut = asyncio.get_running_loop().create_future()
            req = self._coalescer.submit(sim, conds, tof_mask=mask,
                                         wait_budget_s=wait)
            self._futures[req] = fut
            if self._stopping:
                # The scheduler is gone; nothing will ever flush this.
                self._futures.pop(req, None)
                raise ServeError(E_DRAINING,
                                 "server stopped during admission")
            _metrics.gauge("pycatkin_serve_queue_depth",
                           "sweep requests queued, unflushed").set(
                               float(self._coalescer.pending))
            self._wake.set()
            out, pack = await fut
        finally:
            self._admitted -= 1
        total_s = time.monotonic() - t0
        _metrics.histogram("pycatkin_serve_request_seconds",
                           "accepted sweep request wall time").observe(
                               total_s,
                               deadline_class=parsed["deadline_class"])
        self._completed_total += 1
        return self._sweep_response(req_id, sim, out, pack, parsed,
                                    total_s)

    def _sweep_response(self, req_id, sim, out: dict, pack: dict,
                        parsed: dict, total_s: float) -> dict:
        result = {k: out[k] for k in SUMMARY_KEYS if k in out}
        for key in parsed["want"]:
            if key in out:
                result[key] = out[key]
        q = np.asarray(out.get("quarantined", ()), dtype=bool)
        manifest = dict(self.boot_manifest)
        manifest["abi"] = {
            "fingerprint": (pack.get("abi_fingerprint")),
            "packed": pack.get("tenants", 1) > 1}
        solve_s = pack.get("solve_s", 0.0)
        return {
            "protocol": PROTOCOL, "id": req_id, "ok": True,
            "lanes": len(parsed["T"]),
            "result": jsonable(result),
            "quarantine": {"count": int(q.sum()),
                           "lanes": np.nonzero(q)[0].tolist()},
            "lane_telemetry": jsonable(out.get("lane_telemetry")),
            "manifest": jsonable(manifest),
            "pack": jsonable({k: v for k, v in pack.items()
                              if k != "solve_s"}),
            "timing": {"total_s": total_s, "solve_s": solve_s,
                       "queue_s": max(0.0, total_s - solve_s)},
        }

    async def _handle_transient(self, payload: dict, req_id) -> dict:
        from ..robustness import faults
        t0 = time.monotonic()
        self._requests_total += 1
        _metrics.counter("pycatkin_serve_requests_total",
                         "sweep requests admitted or rejected").inc()
        parsed = parse_transient_request(payload)
        if self._draining:
            raise ServeError(E_DRAINING,
                             "server is draining; no new sweeps")
        if self.pending >= self.config.max_pending:
            raise ServeError(
                E_OVERLOADED,
                f"pending queue is full ({self.pending} >= "
                f"{self.config.max_pending}); retry with backoff")
        faults.inject("serve:accept")
        self._admitted += 1
        try:
            sim = await asyncio.to_thread(self._build_system,
                                          parsed["mechanism"])
            conds = await asyncio.to_thread(self._build_conds, sim,
                                            parsed["T"], parsed["p"])
            wait = parsed["wait_budget_s"]
            if wait is None:
                wait = self.config.wait_budget_for(
                    parsed["deadline_class"])
            fut = asyncio.get_running_loop().create_future()
            req = self._coalescer.submit(sim, conds,
                                         wait_budget_s=wait,
                                         save_ts=parsed["save_ts"])
            self._futures[req] = fut
            if self._stopping:
                # The scheduler is gone; nothing will ever flush this.
                self._futures.pop(req, None)
                raise ServeError(E_DRAINING,
                                 "server stopped during admission")
            _metrics.gauge("pycatkin_serve_queue_depth",
                           "sweep requests queued, unflushed").set(
                               float(self._coalescer.pending))
            self._wake.set()
            out, pack = await fut
        finally:
            self._admitted -= 1
        total_s = time.monotonic() - t0
        _metrics.histogram("pycatkin_serve_request_seconds",
                           "accepted sweep request wall time").observe(
                               total_s,
                               deadline_class=parsed["deadline_class"])
        self._completed_total += 1
        return self._transient_response(req_id, out, pack, parsed,
                                        total_s)

    def _transient_response(self, req_id, out: dict, pack: dict,
                            parsed: dict, total_s: float) -> dict:
        ys = np.asarray(out["ys"])
        result = {"ok": np.asarray(out["ok"]),
                  "endpoint": ys[:, -1, :]}
        if "ys" in parsed["want"]:
            result["ys"] = ys
        for key in parsed["want"]:
            if key != "ys" and key in out:
                result[key] = out[key]
        q = np.asarray(out.get("quarantined", ()), dtype=bool)
        manifest = dict(self.boot_manifest)
        manifest["abi"] = {
            "fingerprint": (pack.get("abi_fingerprint")),
            "packed": pack.get("tenants", 1) > 1}
        solve_s = pack.get("solve_s", 0.0)
        return {
            "protocol": PROTOCOL, "id": req_id, "ok": True,
            "lanes": len(parsed["T"]),
            "save_points": len(parsed["save_ts"]),
            "result": jsonable(result),
            "quarantine": {"count": int(q.sum()),
                           "lanes": np.nonzero(q)[0].tolist()},
            "lane_telemetry": jsonable(out.get("lane_telemetry")),
            "manifest": jsonable(manifest),
            "pack": jsonable({k: v for k, v in pack.items()
                              if k != "solve_s"}),
            "timing": {"total_s": total_s, "solve_s": solve_s,
                       "queue_s": max(0.0, total_s - solve_s)},
        }

    def _build_system(self, mech):
        if hasattr(mech, "conditions") and hasattr(mech, "spec"):
            return mech  # in-process client handed a built System
        if not isinstance(mech, dict):
            raise ServeError(E_BAD_REQUEST,
                             "/mechanism: expected reference-schema "
                             "JSON object (or a built System in-proc)")
        import tempfile
        from ..frontend.loader import read_from_input_file
        with tempfile.TemporaryDirectory(
                prefix="pycatkin_serve_mech_") as td:
            path = os.path.join(td, "mechanism.json")
            with open(path, "w") as fh:
                json.dump(mech, fh)
            try:
                return read_from_input_file(path)
            except ServeError:
                raise
            except Exception as exc:  # noqa: BLE001 - schema boundary
                raise ServeError(E_BAD_REQUEST,
                                 f"/mechanism: {exc}") from None

    def _build_conds(self, sim, T, p):
        from ..parallel.batch import stack_conditions
        return stack_conditions([sim.conditions(T=t, p=pv)
                                 for t, pv in zip(T, p)])

    # -- scheduler loop ------------------------------------------------

    @property
    def pending(self) -> int:
        """Admitted-but-unresolved request count (queued + in flush)."""
        return (self._coalescer.pending if self._coalescer else 0) \
            + self._taken

    @property
    def in_service(self) -> int:
        """Sweeps past admission whose response has not been built yet
        (building, queued, solving, or resolving)."""
        return self._admitted

    async def _scheduler_loop(self):
        co = self._coalescer
        while True:
            if self._stopping:
                return
            due = (list(co._groups) if self._draining
                   else co.due_keys())
            for key in due:
                reqs = co.take_group(key, limit=co.max_occupancy)
                if reqs:
                    await self._run_group(key, reqs)
            _metrics.gauge("pycatkin_serve_queue_depth",
                           "sweep requests queued, unflushed").set(
                               float(co.pending))
            if self._stopping:
                return
            try:
                await asyncio.wait_for(self._wake.wait(),
                                       timeout=self.config.tick_s)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    async def _run_group(self, key, reqs):
        label = _key_label(key)
        self._taken += len(reqs)
        c0 = _compile_count()
        t0 = time.monotonic()
        try:
            outs = await asyncio.to_thread(self._execute_group, label,
                                           key, reqs)
        except Exception as exc:  # noqa: BLE001 - reported per request
            record_event("serve", action="flush-failed", label=label,
                         detail=f"{type(exc).__name__}: {exc}")
            err = ServeError(E_INTERNAL,
                             f"group flush failed: {exc}")
            for r in reqs:
                fut = self._futures.pop(r, None)
                if fut is not None and not fut.done():
                    fut.set_exception(err)
            return
        finally:
            self._taken -= len(reqs)
        solve_s = time.monotonic() - t0
        compiles = _compile_count() - c0
        k = len(reqs)
        kb = 1 << max(0, (k - 1).bit_length())
        self.flushes += 1
        self.compiles_total += compiles
        self._occupancy_sum += k / kb
        if compiles:
            self.flushes_with_compiles += 1
        if self._warm_marked:
            self.flushes_after_warm += 1
            self.compiles_after_warm += compiles
            if compiles:
                self.flushes_with_compiles_after_warm += 1
        solo = isinstance(key, tuple) and key and key[0] == "solo"
        _metrics.counter("pycatkin_serve_flush_groups_total",
                         "coalesced groups flushed by the server").inc(
                             kind="solo" if solo else "packed")
        if compiles:
            _metrics.counter(
                "pycatkin_serve_flush_compiles_total",
                "XLA compiles charged to serve flushes").inc(compiles)
        pack = {"tenants": k, "k_bucket": kb, "occupancy": k / kb,
                "abi_fingerprint": None if solo else label,
                "compiles": compiles, "flush_seq": self.flushes,
                "solve_s": solve_s}
        for r, o in zip(reqs, outs):
            fut = self._futures.pop(r, None)
            if fut is not None and not fut.done():
                fut.set_result((o, pack))

    def _execute_group(self, label: str, key, reqs):
        from ..robustness import faults
        faults.inject(f"serve:flush:{label}")
        return self._coalescer.run_requests(key, reqs)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        co = self._coalescer
        return {
            "protocol": PROTOCOL,
            "draining": self._draining,
            "port": self.port,
            "pending": self.pending,
            "queued": co.pending if co else 0,
            "requests_total": self._requests_total,
            "completed_total": self._completed_total,
            "rejected_total": self._rejected_total,
            "flushes": self.flushes,
            "flushes_with_compiles": self.flushes_with_compiles,
            "compiles_total": self.compiles_total,
            "mean_occupancy": (self._occupancy_sum / self.flushes
                               if self.flushes else None),
            "flushes_after_warm": self.flushes_after_warm,
            "flushes_with_compiles_after_warm":
                self.flushes_with_compiles_after_warm,
            "compiles_after_warm": self.compiles_after_warm,
            "zero_compile_rate_after_warm": (
                1.0 - (self.flushes_with_compiles_after_warm
                       / self.flushes_after_warm)
                if self.flushes_after_warm else None),
        }

    # -- TCP framing ---------------------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        wlock = asyncio.Lock()
        tasks = set()

        async def one_line(line: bytes):
            self._inflight += 1
            try:
                try:
                    payload = json.loads(line)
                except ValueError as exc:
                    resp = error_response(None, E_BAD_REQUEST,
                                          f"invalid JSON: {exc}")
                else:
                    resp = await self.handle(payload)
                data = (json.dumps(resp) + "\n").encode()
                async with wlock:
                    writer.write(data)
                    await writer.drain()
            except (ConnectionError, asyncio.CancelledError):
                pass
            finally:
                self._inflight -= 1

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                if not line.strip():
                    continue
                t = asyncio.get_running_loop().create_task(
                    one_line(line))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
