"""Sweep-as-a-service: the long-lived process that owns the warm
cache, the request queue and the mesh.

Eleven PRs built every organ of a serving system in batch form --
ABI-bucketed lowering (frontend/abi.py), multi-tenant packing
(parallel/batch.py), the request coalescer (parallel/dispatch.py), the
elastic lease queue (robustness/scheduler.py), AOT cache packs
(parallel/compile_pool.py, tools/aot_pack.py) and the observability
stack (obs/) -- but nothing *stayed alive* between requests. This
package is that process: a single-process asyncio server speaking
JSON-lines over TCP (plus an in-process :class:`SweepClient`) that
admits mechanism + conditions-grid requests, coalesces same-bucket
tenants into packed dispatches with SLA-aware flushing, and answers
every request with its run manifest, per-lane telemetry and quarantine
report. Schema and semantics: docs/serving.md.

Above the single server sits the fleet tier (PR 16): a
:class:`ReplicaSupervisor` (serve/fleet.py) keeping N pack-warmed
server replicas alive, and a :class:`SweepRouter` (serve/router.py)
multiplexing clients across them with circuit breakers, SLA-budgeted
retries, hedged interactive dispatch and loss-free failover.

PR 17 makes the front tier durable: a :class:`RequestJournal`
(serve/durable.py) write-ahead-logs every keyed request before it is
acknowledged, so a SIGKILLed router -- restarted by a
``FleetConfig(role="router")`` supervisor and re-reading the replica
endpoints through :class:`FileFleet` -- replays its
accepted-but-unanswered backlog and serves bitwise-identical journaled
answers for duplicate idempotency keys.
"""

from .client import SweepClient, TcpSweepClient
from .durable import RequestJournal
from .fleet import FileFleet, FleetConfig, ReplicaSupervisor
from .protocol import (DEADLINE_CLASSES, ServeConfig, ServeError,
                       error_response)
from .router import RouterConfig, SweepRouter
from .server import SweepServer

__all__ = ["SweepServer", "SweepClient", "TcpSweepClient",
           "ServeConfig", "ServeError", "DEADLINE_CLASSES",
           "error_response", "ReplicaSupervisor", "FleetConfig",
           "SweepRouter", "RouterConfig", "RequestJournal",
           "FileFleet"]
