"""Write-ahead request journal for the serve tier (docs/serving.md,
"Durable requests").

The router's in-memory inflight table is a single failure domain: a
router crash forfeits every accepted-but-unanswered request. This
module lifts the chunk-journal discipline (robustness/journal.py) into
serving: an ``accepted`` record is fsynced (``utils/io.
append_json_line``) BEFORE the ack reaches the client's socket, an
``answered`` record carries the full response plus its canonical form
(``serve/protocol.canonical_answer`` -- the same canonicalizer the
duplicate-suppression audit uses), and replay tolerates a torn final
line (``read_json_lines(tolerate_torn_tail=True)``) because a kill
mid-append can tear at most the one record that was never
acknowledged.

Journals are directories of size-bounded segments
(``requests_00000.jsonl``, ``requests_00001.jsonl``, ...). Appends go
to the highest-numbered (active) segment; once it exceeds
``segment_bytes`` the next append rotates to a fresh segment, and any
sealed segment whose every ``accepted`` key has an ``answered`` record
is deleted (compaction). Compaction never loses accepted-but-
unanswered work -- a segment holding an unanswered key is never
deleted -- but it does bound the duplicate-serving window: once a
fully-answered segment is compacted, a duplicate of one of its keys
arriving after the NEXT router boot is treated as a fresh request
(safe, because same-width sweeps are bitwise deterministic; see the
packed-vs-solo identity in docs/serving.md).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..obs import metrics as _metrics
from ..utils.io import append_json_line, read_json_lines
from ..utils.profiling import record_event
from .protocol import DURABLE_SEGMENT_BYTES_ENV, canonical_answer

_SEGMENT_PREFIX = "requests_"
_SEGMENT_SUFFIX = ".jsonl"
_DEFAULT_SEGMENT_BYTES = 1 << 20


def _segment_name(seq: int) -> str:
    return f"{_SEGMENT_PREFIX}{seq:05d}{_SEGMENT_SUFFIX}"


class RequestJournal:
    """Crash-durable accepted/answered ledger for keyed sweep requests.

    All methods are thread-safe and synchronous (they fsync); the
    router calls them through ``asyncio.to_thread`` so the event loop
    never blocks on disk. Constructing the journal replays every
    segment on disk, so ``unanswered()`` / ``answered_response()`` are
    immediately authoritative after a crash.
    """

    def __init__(self, path: str,
                 segment_bytes: Optional[int] = None):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        if segment_bytes is None:
            segment_bytes = int(os.environ.get(
                DURABLE_SEGMENT_BYTES_ENV, _DEFAULT_SEGMENT_BYTES))
        self.segment_bytes = max(1, int(segment_bytes))
        self._lock = threading.Lock()
        # All fields below are guarded by self._lock (PCL011).
        self._accepted = {}       # key -> wire payload, unanswered only
        self._answers = {}        # key -> stored response (id stripped)
        self._segment_keys = {}   # seq -> accepted keys in that segment
        self._active_seq = 0
        self._appends = 0
        self._rotations = 0
        self._compacted = 0
        self._replayed_records = 0
        self._replay()

    # -- replay ---------------------------------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.path, _segment_name(seq))

    def _segments_on_disk(self) -> list:
        seqs = []
        for name in os.listdir(self.path):
            if (name.startswith(_SEGMENT_PREFIX)
                    and name.endswith(_SEGMENT_SUFFIX)):
                stem = name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
                try:
                    seqs.append(int(stem))
                except ValueError:
                    continue
        return sorted(seqs)

    def _replay(self) -> None:
        with self._lock:
            seqs = self._segments_on_disk()
            for seq in seqs:
                records = read_json_lines(self._segment_path(seq),
                                          tolerate_torn_tail=True)
                keys = set()
                for rec in records:
                    kind = rec.get("kind")
                    key = rec.get("key")
                    if key is None:
                        continue
                    if kind == "accepted":
                        keys.add(key)
                        if (key not in self._answers
                                and key not in self._accepted):
                            self._accepted[key] = rec.get("payload")
                    elif kind == "answered":
                        self._answers[key] = rec.get("response")
                        self._accepted.pop(key, None)
                    self._replayed_records += 1
                self._segment_keys[seq] = keys
            self._active_seq = seqs[-1] if seqs else 0
        record_event("durable", action="replay", path=self.path,
                     segments=len(seqs),
                     records=self._replayed_records,
                     pending=len(self._accepted),
                     answered=len(self._answers))

    # -- writes ---------------------------------------------------------

    def record_accepted(self, key: str, payload: dict) -> bool:
        """Fsync an ``accepted`` record for ``key``. Idempotent: a key
        already journaled (accepted or answered) writes nothing and
        returns False. The caller MUST NOT ack the client before this
        returns -- the fsync-before-ack ordering is the durability
        contract."""
        key = str(key)
        with self._lock:
            if key in self._accepted or key in self._answers:
                return False
            self._maybe_rotate_locked()
            append_json_line(self._segment_path(self._active_seq),
                             {"kind": "accepted", "key": key,
                              "payload": payload})
            self._accepted[key] = payload
            self._segment_keys.setdefault(self._active_seq,
                                          set()).add(key)
            self._appends += 1
        _metrics.counter("pycatkin_durable_accepted_total",
                         "keyed requests journaled as accepted").inc()
        return True

    def record_answered(self, key: str, response: dict):
        """Fsync an ``answered`` record carrying the response and its
        canonical form. Returns the PRIOR stored response when the key
        was already answered (replay racing a client resubmission) so
        the caller can audit bitwise identity; returns None when this
        call stored the answer."""
        key = str(key)
        stored = {k: v for k, v in response.items() if k != "id"}
        with self._lock:
            prior = self._answers.get(key)
            if prior is not None:
                return prior
            self._maybe_rotate_locked()
            append_json_line(self._segment_path(self._active_seq),
                             {"kind": "answered", "key": key,
                              "response": stored,
                              "canonical": canonical_answer(response)})
            self._answers[key] = stored
            self._accepted.pop(key, None)
            self._appends += 1
            self._compact_locked()
        _metrics.counter("pycatkin_durable_answered_total",
                         "keyed requests journaled as answered").inc()
        return None

    def _maybe_rotate_locked(self) -> None:
        try:
            size = os.path.getsize(self._segment_path(self._active_seq))
        except OSError:
            size = 0
        if size >= self.segment_bytes:
            self._active_seq += 1
            self._rotations += 1
            record_event("durable", action="rotate",
                         seq=self._active_seq)

    def _compact_locked(self) -> None:
        # A sealed segment is deletable once every key accepted in it
        # is answered (a segment with no accepted keys -- answers only
        # -- is vacuously done). Unanswered work pins its segment.
        for seq in sorted(self._segment_keys):
            if seq == self._active_seq:
                continue
            keys = self._segment_keys[seq]
            if any(k not in self._answers for k in keys):
                continue
            try:
                os.unlink(self._segment_path(seq))
            except OSError:
                continue
            del self._segment_keys[seq]
            self._compacted += 1
            record_event("durable", action="compact", seq=seq,
                         keys=len(keys))
            _metrics.counter(
                "pycatkin_durable_compacted_segments_total",
                "fully-answered journal segments deleted").inc()

    # -- reads ----------------------------------------------------------

    def answered_response(self, key: str):
        """The journaled answer for ``key`` (without an ``id``; the
        caller stamps the duplicate request's own id) or None."""
        with self._lock:
            stored = self._answers.get(str(key))
            return dict(stored) if stored is not None else None

    def is_accepted(self, key: str) -> bool:
        with self._lock:
            k = str(key)
            return k in self._accepted or k in self._answers

    def unanswered(self) -> list:
        """``(key, payload)`` pairs accepted but never answered, in
        acceptance order -- the boot-time replay worklist."""
        with self._lock:
            return [(k, dict(p) if isinstance(p, dict) else p)
                    for k, p in self._accepted.items()]

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path,
                    "segments": max(1, len(self._segment_keys)),
                    "active_segment": self._active_seq,
                    "segment_bytes": self.segment_bytes,
                    "pending": len(self._accepted),
                    "answered": len(self._answers),
                    "appends": self._appends,
                    "rotations": self._rotations,
                    "compacted_segments": self._compacted,
                    "replayed_records": self._replayed_records}
