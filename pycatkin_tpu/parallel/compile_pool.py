"""Concurrent program compilation + on-disk AOT executable cache.

Two independent costs dominate a fresh process's time-to-first-sweep:

1. XLA *compilation* of every program shape the sweep can touch.
   :func:`parallel.batch.prewarm_sweep_programs` warms the program zoo
   -- dieted from 32 programs / 136.6 s sequential (BENCH_r05) down to
   ``parallel.batch.PREWARM_PROGRAM_BUDGET`` (<= 10) now that the fused
   sweep program subsumes the standalone fast-pass/screen/TOF programs.
   Compiles are GIL-releasing C++ work, so a bounded thread pool
   (:func:`map_compile`) overlaps them nearly perfectly (and with the
   fast pass itself, via :func:`submit_compile`).
2. Re-compilation on every *restart*. ``jax.jit``'s in-memory caches
   die with the process and the persistent XLA cache is disabled on
   CPU (utils/cache.py). :class:`AOTCache` serializes compiled
   executables (``jax.experimental.serialize_executable``) under a
   directory next to ``.jax_cache``; a restarted process deserializes
   the executable and skips trace+compile entirely. The cache is also
   *shippable*: :func:`export_cache_pack` archives a warm cache
   directory (entries + verified manifest) and
   :func:`import_cache_pack` unpacks it on another machine/checkout of
   the same toolchain, so a fleet pays the compile wall once
   (``tools/aot_pack.py`` is the CLI; target prewarm-from-pack < 30 s).

Loaded/compiled executables are published in a process-wide *registry*
keyed on (spec, program kind, argument shapes); the sweep hot path
(parallel/batch.py) consults the registry before falling back to the
ordinary jitted program, so an AOT-loaded executable is actually what a
sweep runs -- ``f.lower().compile()`` alone would NOT populate the jit
dispatch cache, and the "warm" prewarm would be a lie.

Environment switches:

- ``PYCATKIN_COMPILE_WORKERS``: compile-pool width (default
  ``min(8, os.cpu_count())``; ``1`` restores sequential compiles).
- ``PYCATKIN_AOT_CACHE``: cache directory (default
  ``<repo>/.jax_aot_cache``); ``0``/``off``/``none`` disables the
  on-disk layer (the pool still runs).

Every cache entry records the full :func:`spec_fingerprint` of the
mechanism it was compiled for; loading an entry against a different
fingerprint raises :class:`CacheMismatch` (callers that can recompile
catch it and overwrite). Entries from a different jax version, backend
or device kind are silently treated as misses -- serialized executables
are only valid on the toolchain that produced them.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs import costs as _costs
from ..obs import metrics as _metrics

_DEFAULT_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_aot_cache")

_DISABLED = ("0", "off", "none", "disabled")


class CacheMismatch(RuntimeError):
    """An AOT cache entry exists but was written for a different model
    spec fingerprint: executing it would silently compute the wrong
    mechanism's physics. Callers that own a compiler recompile and
    overwrite; everyone else must treat the entry as poison."""


def compile_workers() -> int:
    """Bounded width of the compile pool (``PYCATKIN_COMPILE_WORKERS``,
    default ``min(8, cpu_count)``, floor 1)."""
    env = os.environ.get("PYCATKIN_COMPILE_WORKERS", "")
    if env.strip():
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(8, os.cpu_count() or 1))


def tenant_tag(k: int) -> str:
    """Program-key / fingerprint suffix for the tenant-count pow2
    sub-bucket of a packed multi-tenant program (``:tK``). ``k <= 1``
    returns the empty string so every pre-packing program key, AOT
    cache entry and exported pack stays byte-identical -- exactly the
    :func:`precision.tier_tag` compatibility contract, and composed in
    that order (tier tag first, tenant tag last) by the batch layer's
    kind strings."""
    k = int(k)
    if k <= 1:
        return ""
    if k & (k - 1):
        raise ValueError(
            f"tenant sub-buckets are powers of two, got {k} (pad the "
            f"pack with ghost tenants -- frontend.abi.PackedLowered)")
    return f":t{k}"


# THE declared kind-string tag grammar (PCL015 key-tag-discipline).
# A program kind is ``<base><tier><kernel><sharding><tenant>`` with the
# tag segments appended in exactly this order by exactly these helpers;
# every tag maps to the empty string in its default configuration so
# legacy keys stay byte-identical. The lint rule parses this tuple out
# of the module AST (it must stay a pure literal -- no computed
# values), checks every literal tag construction and tag-helper body
# against it, and ``strip_kind_tags`` below is its only inverse:
# never strip or match tag substrings by hand elsewhere.
KIND_TAG_GRAMMAR = (
    {"name": "tier", "literal": ":p32", "strip": ":p32$",
     "owner": "pycatkin_tpu/precision.py", "helper": "tier_tag"},
    {"name": "kernel", "literal": ":kpl", "strip": ":kpl$",
     "owner": "pycatkin_tpu/precision.py", "helper": "kernel_tag"},
    {"name": "sharding", "literal": "@mesh[", "strip": "@mesh\\[.*$",
     "owner": "pycatkin_tpu/parallel/batch.py", "helper": "_sharding_tag"},
    {"name": "tenant", "literal": ":t", "strip": ":t\\d+$",
     "owner": "pycatkin_tpu/parallel/compile_pool.py",
     "helper": "tenant_tag"},
)


def strip_kind_tags(kind: str) -> str:
    """Strip every grammar tag off a kind string, innermost-last: the
    knob-free base kind. Two distinct keys whose stripped bases match
    differ only in knob tags -- the trace-ident sanitizer uses this to
    classify identical-jaxpr duplicates as knob-induced zoo bloat."""
    for entry in reversed(KIND_TAG_GRAMMAR):
        kind = re.sub(entry["strip"], "", kind)
    return kind


def spec_fingerprint(spec) -> str:
    """Content hash of a ModelSpec (field name + dtype/shape/bytes of
    every array field, repr of the rest) -- the identity a cached
    executable is bound to. ModelSpec itself hashes by object identity
    (it keys jit caches), so this is the cross-process stand-in.

    ABI specs (frontend/abi.py AbiProgramSpec / AbiLowered) are the
    exception: their cache identity is deliberately the *bucket*, not
    the mechanism -- ``abi-v<version>:s<S>:r<R>:d<D>:...`` -- so one
    cache entry (and one exported AOT pack) serves every mechanism that
    lowers into the bucket."""
    import dataclasses

    abi_fp = getattr(spec, "abi_fingerprint", None)
    if abi_fp is not None:
        return str(abi_fp)

    h = hashlib.sha256()
    if dataclasses.is_dataclass(spec):
        items = [(f.name, getattr(spec, f.name))
                 for f in dataclasses.fields(spec)]
    elif hasattr(spec, "_asdict"):
        items = list(spec._asdict().items())
    else:                                   # duck-typed test doubles
        items = sorted((k, v) for k, v in vars(spec).items()
                       if not k.startswith("_"))
    for name, v in items:
        h.update(name.encode())
        if isinstance(v, np.ndarray):
            h.update(str(v.dtype).encode())
            h.update(str(v.shape).encode())
            h.update(np.ascontiguousarray(v).tobytes())
        else:
            h.update(repr(v).encode())
    return h.hexdigest()


# Bump to invalidate every previously-written AOT cache entry as a
# plain (silent) miss. v2: program keys carry the per-argument sharding
# fingerprint, so executables compiled for a sharded mesh layout can be
# cached and looked up without ever colliding with the single-device
# entries of the same shapes. v3: the solver result grew a per-lane
# chord-count field and the fused sweep program a packed lane-telemetry
# output -- executables serialized before that return the OLD output
# structure, which would unpack wrong with success=True.
_KEY_VERSION = "aot-key-v3"


def _leaf_sharding_tag(leaf) -> str:
    """Sharding fingerprint of one argument leaf: non-empty only for a
    leaf placed (or abstractly declared, via ``jax.ShapeDtypeStruct``'s
    ``sharding=``) under a multi-device ``NamedSharding``. Host numpy
    arrays and single-device jax arrays contribute the empty string, so
    unsharded program keys are unaffected by this dimension."""
    sh = getattr(leaf, "sharding", None)
    spec = getattr(sh, "spec", None)
    if sh is None or spec is None:
        return ""
    try:
        sizes = tuple(sh.mesh.shape.items())
    except Exception:
        return ""
    if all(s <= 1 for _, s in sizes):
        return ""
    axes = ";".join(f"{n}={s}" for n, s in sizes)
    return f"@[{axes}]{spec}"


def args_sharding_fingerprint(args) -> str:
    """Joined sharding tags of every argument leaf ('' when fully
    unsharded) -- recorded in AOT cache entries so a sharded executable
    is never deserialized into a process with a different device
    population."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    tags = [_leaf_sharding_tag(leaf) for leaf in leaves]
    return "|".join(tags) if any(tags) else ""


def _shape_signature(args) -> str:  # pclint: disable=PCL013 -- key hashing only; asarray wraps non-array leaves (scalars), never pulls a device array
    """Deterministic (treedef, dtype, shape, sharding) signature of a
    concrete argument tuple -- what a compiled executable is
    specialized on. ``None`` subtrees are part of the treedef, so
    seeded (x0 array) and unseeded (x0=None) variants of the same
    program get distinct keys; sharded leaves carry their mesh/spec
    fingerprint so mesh and single-device programs never collide."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = [repr(treedef)]
    for leaf in leaves:
        a = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        parts.append(f"{a.dtype}{tuple(a.shape)}"
                     f"{_leaf_sharding_tag(leaf)}")
    return "|".join(parts)


def program_key(kind: str, args) -> str:
    """Stable cache/registry key for one compiled program: the program
    *kind* (strategy + solver-options repr, from the caller), the
    argument shape+sharding signature, and the executing toolchain
    (backend, device kind, jax version, key-format version)."""
    import jax

    dev = jax.devices()[0]
    mat = "\x1f".join([_KEY_VERSION, kind, _shape_signature(args),
                       dev.platform, dev.device_kind, jax.__version__])
    return hashlib.sha256(mat.encode()).hexdigest()[:32]


# ---------------------------------------------------------------------
# Process-wide executable registry: (spec, key) -> loaded executable.
# Holding the spec object itself (identity-hashed) pins its lifetime
# exactly like the jit program lru_caches in parallel/batch.py;
# clear_program_caches() clears both together.
_REGISTRY: dict = {}
_REGISTRY_LOCK = threading.Lock()


def register(spec, key: str, exe) -> None:
    """Publish a compiled/loaded executable for the sweep hot path."""
    with _REGISTRY_LOCK:
        _REGISTRY[(spec, key)] = exe


def lookup(spec, key: str):
    """The registered executable for (spec, key), or None."""
    return _REGISTRY.get((spec, key))


def unregister(spec, key: str) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.pop((spec, key), None)


def clear_registry() -> None:
    """Drop every registered executable (and the spec references they
    pin). Called by parallel.batch.clear_program_caches."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()


def registry_size() -> int:
    return len(_REGISTRY)


# ---------------------------------------------------------------------
# On-disk AOT executable cache.
class AOTCache:
    """Serialize/deserialize compiled executables under one directory.

    ``root``: cache directory (None reads ``PYCATKIN_AOT_CACHE``, then
    the default next to ``.jax_cache``; the disable sentinels yield a
    cache whose ``enabled`` is False and whose load/save are no-ops).
    ``fingerprint``: the :func:`spec_fingerprint` entries are bound to.

    Writes are atomic (temp file + rename) so a killed process can
    never publish a torn entry; any unreadable/stale entry loads as a
    miss, and only a *fingerprint* disagreement -- a readable entry for
    the wrong mechanism -- raises :class:`CacheMismatch`.
    """

    def __init__(self, root: str | None = None, fingerprint: str = ""):
        if root is None:
            env = os.environ.get("PYCATKIN_AOT_CACHE", "").strip()
            if env.lower() in _DISABLED:
                root = ""
            else:
                root = env or _DEFAULT_ROOT
        elif str(root).strip().lower() in _DISABLED:
            root = ""
        self.root = str(root) if root else ""
        self.fingerprint = str(fingerprint)
        self.hits = 0
        self.misses = 0
        self.writes = 0
        self.mismatches = 0

    @property
    def enabled(self) -> bool:
        return bool(self.root)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.aot")

    def _tick(self, which: str):
        """One cache outcome: the per-instance counter (``stats()``)
        plus the process-wide metrics registry."""
        setattr(self, which, getattr(self, which) + 1)
        _metrics.counter(f"pycatkin_aot_cache_{which}_total",
                         f"AOT executable cache {which}").inc()

    def load(self, key: str):
        """Deserialize the executable cached under ``key``.

        Returns the loaded executable (callable with the original
        arguments) or None on miss/stale entry; raises
        :class:`CacheMismatch` when the entry's recorded spec
        fingerprint differs from this cache's."""
        if not self.enabled:
            return None
        import jax
        from jax.experimental import serialize_executable as se

        path = self._path(key)
        try:
            with open(path, "rb") as fh:
                entry = pickle.load(fh)
        except (OSError, pickle.UnpicklingError, EOFError, ValueError,
                AttributeError, ImportError):
            self._tick("misses")
            return None
        dev = jax.devices()[0]
        if (entry.get("jax") != jax.__version__
                or entry.get("backend") != dev.platform
                or entry.get("device_kind") != dev.device_kind):
            self._tick("misses")            # stale toolchain: plain miss
            return None
        # A sharded executable bakes in its mesh's device assignment;
        # deserializing it into a process with a different device
        # population would fail (or worse, misplace shards) at call
        # time. Different population = plain miss, like a toolchain
        # change -- only the spec fingerprint is a hard error.
        if entry.get("sharding") and \
                entry.get("devices") != jax.device_count():
            self._tick("misses")
            return None
        if entry.get("fingerprint") != self.fingerprint:
            self._tick("mismatches")
            raise CacheMismatch(
                f"AOT cache entry {os.path.basename(path)} was compiled "
                f"for spec fingerprint "
                f"{str(entry.get('fingerprint'))[:12]}..., expected "
                f"{self.fingerprint[:12]}... -- refusing to execute "
                f"another mechanism's program (recompile to overwrite)")
        try:
            exe = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception:               # corrupt payload: plain miss
            self._tick("misses")
            return None
        # Replay the compile-time cost analyses recorded at save time:
        # a deserialized executable cannot recompute them on every
        # backend, so the entry is the only place they survive.
        _costs.record(key, cost=entry.get("cost"), source="cache")
        self._tick("hits")
        return exe

    def save(self, key: str, compiled, sharding: str = "") -> bool:
        """Serialize ``compiled`` (a jax ``Compiled``) under ``key``.
        ``sharding``: the :func:`args_sharding_fingerprint` of the
        arguments the program was compiled for ('' for single-device
        programs); sharded entries additionally record the device
        population they are valid on. Returns True on success;
        serialization failures (unsupported backend, unpicklable
        treedefs, full disk) degrade to False -- the in-process
        registry still carries the executable."""
        if not self.enabled:
            return False
        import jax
        from jax.experimental import serialize_executable as se

        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            dev = jax.devices()[0]
            entry = {"fingerprint": self.fingerprint,
                     "jax": jax.__version__,
                     "backend": dev.platform,
                     "device_kind": dev.device_kind,
                     "sharding": str(sharding),
                     "devices": jax.device_count(),
                     "payload": payload,
                     "in_tree": in_tree,
                     "out_tree": out_tree}
            # Bucket-keyed (ABI) entries additionally record their
            # abi_version + bucket shape: the entry serves EVERY
            # mechanism in the bucket, and pack consumers audit that
            # claim from the manifest without parsing fingerprints.
            entry.update(abi_entry_fields(self.fingerprint))
            # Jaxpr fingerprint of the program this executable was
            # compiled from (trace-ident sanitizer, when armed): rides
            # into pack manifests so imported packs are audited against
            # locally-traced programs. Empty when the sanitizer never
            # saw the key -- entries stay legal either way.
            from ..san import trace_ident as _trace_ident
            entry.update(_trace_ident.entry_fields(key))
            # Compile-time device-cost truth rides in the entry (and on
            # into pack manifests via _entry_meta): load() replays it
            # into the cost ledger, so cache-warmed processes still
            # know what their programs cost.
            cost = _costs.harvest_cost(compiled)
            if cost:
                entry["cost"] = cost
            _costs.record(key, cost=cost, source="compiled")
            blob = pickle.dumps(entry)
            os.makedirs(self.root, exist_ok=True)
            tmp = self._path(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, self._path(key))
        except Exception:
            return False
        self._tick("writes")
        return True

    def stats(self) -> dict:
        return {"root": self.root or None, "hits": self.hits,
                "misses": self.misses, "writes": self.writes,
                "mismatches": self.mismatches}


class PendingCompiles:
    """Handle for an in-flight :func:`submit_compile` batch. ``wait()``
    blocks until every task finished, shuts the pool down, and returns
    the results in submission order (re-raising the first failure, like
    :func:`map_compile`). Width 1 degenerates to running the tasks
    serially inside ``wait()`` -- submission then costs nothing and no
    compile overlaps the caller's work, which is exactly the
    ``PYCATKIN_COMPILE_WORKERS=1`` sequential contract."""

    def __init__(self, tasks, workers: int):
        self._tasks = list(tasks)
        self._executor = None
        self._futures = []
        if workers > 1 and len(self._tasks) > 1:
            self._executor = ThreadPoolExecutor(
                max_workers=min(workers, len(self._tasks)))
            self._futures = [self._executor.submit(t)
                             for t in self._tasks]

    def wait(self):
        if self._executor is None:
            return [t() for t in self._tasks]
        results = [None] * len(self._futures)
        errors: list[BaseException] = []
        try:
            for i, fut in enumerate(self._futures):
                try:
                    results[i] = fut.result()
                except BaseException as e:  # noqa: BLE001 - re-raised
                    errors.append(e)
        finally:
            self._executor.shutdown(wait=True)
            self._executor = None
        if errors:
            raise errors[0]
        return results


def submit_compile(tasks, workers: int | None = None) -> PendingCompiles:
    """Non-blocking :func:`map_compile`: start ``tasks`` on the pool and
    return immediately with a :class:`PendingCompiles` handle. XLA
    compiles release the GIL, so the caller can execute device programs
    (e.g. the sweep's first fast pass) while the tail programs compile
    concurrently."""
    return PendingCompiles(tasks, workers or compile_workers())


def map_compile(tasks, workers: int | None = None):
    """Run ``tasks`` (zero-arg callables, each returning a compiled
    executable or raising) on a bounded thread pool and return their
    results in order; exceptions propagate to the caller after all
    tasks have been collected (re-raising the FIRST failure, so one
    flaky compile does not orphan the others mid-flight).

    XLA compilation releases the GIL (it is C++ work), so wall-clock
    scales nearly linearly with pool width up to the machine's cores.
    """
    tasks = list(tasks)
    if not tasks:
        return []
    workers = workers or compile_workers()
    if workers <= 1 or len(tasks) == 1:
        return [t() for t in tasks]
    results = [None] * len(tasks)
    errors: list[tuple[int, BaseException]] = []
    with ThreadPoolExecutor(max_workers=min(workers, len(tasks))) as ex:
        futs = {ex.submit(t): i for i, t in enumerate(tasks)}
        for fut, i in futs.items():
            try:
                results[i] = fut.result()
            except BaseException as e:      # noqa: BLE001 - re-raised
                errors.append((i, e))
    if errors:
        raise errors[0][1]
    return results


# ---------------------------------------------------------------------
# Shippable AOT cache packs. A warm cache directory is just a bag of
# content-keyed `<key>.aot` entries; the pack format is a tar.gz of
# those entries plus a manifest.json recording, per key, the metadata
# a consumer needs to decide validity WITHOUT unpickling payloads
# (key version, spec fingerprint, jax version, backend, device kind,
# sharding fingerprint, device count, size). tools/aot_pack.py is the
# CLI; bench.py measures prewarm-from-pack with it.
PACK_MANIFEST = "manifest.json"


def abi_entry_fields(fingerprint: str) -> dict:
    """ABI provenance recorded on cache entries whose spec fingerprint
    is a bucket identity (``abi-v<ver>:s<S>:r<R>:d<D>:...``, see
    frontend/abi.py): the abi_version and the bucket shape, split out
    so pack consumers can audit cross-mechanism compatibility without
    parsing the fingerprint. Empty for legacy per-mechanism entries."""
    fp = str(fingerprint)
    if not fp.startswith("abi-v"):
        return {}
    head, _, bucket = fp.partition(":")
    try:
        version = int(head[len("abi-v"):])
    except ValueError:
        return {}
    fields = {"abi_version": version, "abi_bucket": bucket}
    # Packed multi-tenant fingerprints carry the tenant-count pow2
    # sub-bucket as a trailing ``:tK`` (frontend.abi.PackedLowered);
    # split it out so pack audits can tell a 4-tenant executable from
    # the solo one without string surgery.
    m = re.search(r":t(\d+)$", bucket)
    if m:
        fields["abi_bucket"] = bucket[:m.start()]
        fields["abi_tenants"] = int(m.group(1))
    return fields


def _entry_meta(path: str) -> dict:
    """Validity metadata of one on-disk cache entry (unpickles the
    entry dict but never deserializes the executable payload)."""
    with open(path, "rb") as fh:
        entry = pickle.load(fh)
    meta = {"fingerprint": entry.get("fingerprint"),
            "jax": entry.get("jax"),
            "backend": entry.get("backend"),
            "device_kind": entry.get("device_kind"),
            "sharding": entry.get("sharding", ""),
            "devices": entry.get("devices"),
            "size": os.path.getsize(path)}
    for k in ("abi_version", "abi_bucket", "cost", "trace_ident",
              "kind"):
        if k in entry:
            meta[k] = entry[k]
    return meta


def export_cache_pack(pack_path: str, cache_root: str | None = None) -> dict:
    """Archive a warm AOT cache directory into a shippable pack
    (tar.gz: every ``<key>.aot`` entry + a manifest). Unreadable
    entries are skipped (counted). Returns a stats dict
    ``{path, entries, skipped, bytes}``. Raises FileNotFoundError when
    the cache directory does not exist or holds no entries -- shipping
    an empty pack is always a caller bug."""
    import json
    import tarfile

    root = cache_root or AOTCache().root
    if not root or not os.path.isdir(root):
        raise FileNotFoundError(
            f"export_cache_pack: no AOT cache directory at {root!r} "
            "(run a prewarm first, or pass cache_root)")
    names = sorted(f for f in os.listdir(root) if f.endswith(".aot"))
    manifest: dict = {"format": "pycatkin-aot-pack-v1",
                      "key_version": _KEY_VERSION, "entries": {}}
    skipped = 0
    total = 0
    for name in names:
        path = os.path.join(root, name)
        try:
            meta = _entry_meta(path)
        except Exception:
            skipped += 1                 # torn/foreign file: not shipped
            continue
        manifest["entries"][name[:-len(".aot")]] = meta
        total += meta["size"]
    if not manifest["entries"]:
        raise FileNotFoundError(
            f"export_cache_pack: no readable .aot entries under {root!r}")
    os.makedirs(os.path.dirname(os.path.abspath(pack_path)) or ".",
                exist_ok=True)
    tmp = f"{pack_path}.tmp.{os.getpid()}"
    try:
        with tarfile.open(tmp, "w:gz") as tar:
            for key in manifest["entries"]:
                tar.add(os.path.join(root, f"{key}.aot"),
                        arcname=f"{key}.aot")
            import io as _io
            blob = json.dumps(manifest, indent=2).encode()
            info = tarfile.TarInfo(PACK_MANIFEST)
            info.size = len(blob)
            tar.addfile(info, _io.BytesIO(blob))
        os.replace(tmp, pack_path)       # atomic publish
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return {"path": pack_path, "entries": len(manifest["entries"]),
            "skipped": skipped, "bytes": total}


def import_cache_pack(pack_path: str, cache_root: str | None = None,
                      verify: bool = True) -> dict:
    """Unpack an exported AOT pack into a cache directory.

    Extraction is defensive: only flat ``<key>.aot`` members named in
    the manifest are written (no paths, no links -- a hostile archive
    cannot traverse out of ``cache_root``), each via a temp file +
    atomic rename so a killed import never publishes a torn entry.
    With ``verify`` (default) every entry is unpickled and checked
    against the manifest: key-format version, spec fingerprint and the
    filename<->manifest agreement are hard errors (ValueError --
    executing a mismatched entry would run the wrong program);
    toolchain drift (jax version / backend / device kind / device
    count vs THIS process) is counted under ``foreign_toolchain`` but
    still imported -- AOTCache.load treats those as silent misses, and
    the pack may legitimately serve several platforms. Existing
    entries are overwritten. Returns
    ``{root, imported, foreign_toolchain, bytes}``."""
    import json
    import tarfile

    import jax

    root = cache_root or AOTCache().root
    if not root:
        raise ValueError("import_cache_pack: the AOT cache is disabled "
                         "(PYCATKIN_AOT_CACHE) and no cache_root given")
    with tarfile.open(pack_path, "r:gz") as tar:
        fh = tar.extractfile(PACK_MANIFEST)
        if fh is None:
            raise ValueError(
                f"import_cache_pack: {pack_path} has no {PACK_MANIFEST}")
        manifest = json.load(fh)
        if manifest.get("key_version") != _KEY_VERSION:
            raise ValueError(
                "import_cache_pack: pack was written with key format "
                f"{manifest.get('key_version')!r}, this build uses "
                f"{_KEY_VERSION!r} -- its keys can never be looked up")
        os.makedirs(root, exist_ok=True)
        dev = jax.devices()[0]
        imported = 0
        foreign = 0
        total = 0
        for key, meta in manifest.get("entries", {}).items():
            name = f"{key}.aot"
            member = tar.getmember(name)   # KeyError: truncated pack
            if not member.isfile() or "/" in key or "\\" in key \
                    or key in (".", ".."):
                raise ValueError(
                    f"import_cache_pack: refusing member {name!r}")
            blob = tar.extractfile(member).read()
            if verify:
                entry = pickle.loads(blob)
                if entry.get("fingerprint") != meta.get("fingerprint"):
                    raise ValueError(
                        f"import_cache_pack: entry {key} fingerprint "
                        "disagrees with the pack manifest (tampered or "
                        "torn pack)")
                if (entry.get("jax") != jax.__version__
                        or entry.get("backend") != dev.platform
                        or entry.get("device_kind") != dev.device_kind
                        or (entry.get("sharding")
                            and entry.get("devices")
                            != jax.device_count())):
                    foreign += 1
            tmp = os.path.join(root, f"{name}.tmp.{os.getpid()}")
            with open(tmp, "wb") as out:
                out.write(blob)
            os.replace(tmp, os.path.join(root, name))
            # Pack-shipped cost rows land in the ledger immediately --
            # a worker booted from a pack may never call load() before
            # its first manifest/bench snapshot.
            if isinstance(meta.get("cost"), dict):
                _costs.record(key, cost=meta["cost"], source="pack")
            # Replay manifest jaxpr fingerprints through the trace-ident
            # sanitizer (no-op unless armed): an imported pack whose
            # fingerprint contradicts a locally-traced program under the
            # same key raises right here, not at first wrong dispatch.
            if meta.get("trace_ident"):
                from ..san import trace_ident as _trace_ident
                _trace_ident.note_jaxpr(meta.get("kind", "?"), key,
                                        fp=meta["trace_ident"])
            imported += 1
            total += len(blob)
    _metrics.counter("pycatkin_aot_pack_imports_total",
                     "cache-pack import operations").inc()
    _metrics.counter("pycatkin_aot_pack_entries_imported_total",
                     "cache entries landed by pack imports").inc(imported)
    return {"root": root, "imported": imported,
            "foreign_toolchain": foreign, "bytes": total}
