"""DCN-tier sweep dispatcher: split a lane grid across processes.

SURVEY.md §5.8's outer parallelism tier: descriptor/condition lanes are
physically independent, so beyond one mesh (vmap + shard_map over ICI)
the next axis is *embarrassingly parallel dispatch* of disjoint lane
blocks to independent workers -- separate processes on one host, or
separate hosts/slices connected only by DCN. No collective runs between
blocks; the only "communication" is the result merge, exactly the
structure the reference's serial sweep loops imply (grid points couple
nowhere in the math -- the one neighbor coupling, grid-repair
averaging, is post-hoc host-side).

Protocol (all host-side, no JAX in the parent):
  1. the mechanism is serialized once (utils.io.save_system_json --
     the reference-schema JSON round-trip);
  2. the lane-batched Conditions pytree is split into contiguous
     blocks, one .npz per worker;
  3. each worker is a fresh ``python -m pycatkin_tpu.parallel.dispatch``
     process: loads the JSON, rebuilds the spec, runs
     ``sweep_steady_state`` on its block, writes results to .npz;
  4. the parent waits and concatenates blocks in lane order.

Workers inherit the parent environment by default; pass ``worker_env``
overrides to pin devices (e.g. one TPU slice per worker via
``JAX_PLATFORMS`` / topology env vars, or ``JAX_PLATFORMS=cpu`` for
host-only workers).
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

def save_conditions(path: str, conds) -> None:
    """Write a (lane-batched) Conditions pytree to .npz (the namedtuple's
    own field list, so a schema change round-trips automatically)."""
    np.savez_compressed(
        path, **{f: np.asarray(getattr(conds, f)) for f in conds._fields})


def load_conditions(path: str):
    """Read a Conditions pytree written by :func:`save_conditions`."""
    from ..frontend.spec import Conditions
    with np.load(path) as z:
        return Conditions(**{f: z[f] for f in Conditions._fields})


# Request-coalescer policy knobs (documented in the PCL006 env
# registry, docs/index.md; semantics in docs/perf_packed_batching.md).
PACKED_MAX_OCCUPANCY_ENV = "PYCATKIN_PACKED_MAX_OCCUPANCY"
PACKED_MAX_WAIT_ENV = "PYCATKIN_PACKED_MAX_WAIT_S"
_PACKED_MAX_OCCUPANCY_DEFAULT = 8
_PACKED_MAX_WAIT_DEFAULT = 0.05


class PackedRequest:
    """One tenant's pending sweep inside a :class:`SweepCoalescer`
    group. ``result()`` blocks nothing: if the group has not flushed
    yet it flushes NOW (the submitting caller asking for its answer is
    the strongest possible "stop waiting for co-tenants" signal).

    ``submitted_at`` / ``wait_budget_s`` record when the request
    arrived and how long it agreed to wait for co-tenants (None =
    the coalescer's ``max_wait_s``); the serving layer's SLA-aware
    flushing derives group deadlines from them.

    A non-None ``save_ts`` marks a TRANSIENT request (docs/
    perf_transient.md): the group key then carries the save grid, so
    only same-grid transients co-pack, and the group executes through
    the coalescer's ``transient_runner`` instead of ``runner``."""

    __slots__ = ("sim", "spec", "conds", "tof_mask", "x0", "save_ts",
                 "group_key", "submitted_at", "wait_budget_s",
                 "_coalescer", "_result", "done")

    def __init__(self, coalescer, sim, spec, conds, tof_mask, x0,
                 group_key, submitted_at=None, wait_budget_s=None,
                 save_ts=None):
        self.sim = sim
        self.spec = spec
        self.conds = conds
        self.tof_mask = tof_mask
        self.x0 = x0
        self.save_ts = save_ts
        self.group_key = group_key
        self.submitted_at = submitted_at
        self.wait_budget_s = wait_budget_s
        self._coalescer = coalescer
        self._result = None
        self.done = False

    def result(self) -> dict:
        if not self.done:
            self._coalescer.flush_group(self.group_key)
        if not self.done:
            raise RuntimeError("packed request did not resolve after "
                               "its group flushed (coalescer bug)")
        return self._result


def _default_packed_runner(sims, conds_list, masks, x0s, *,
                           check_stability, opts, pos_jac_tol):
    """Coalescer runner seam default: the in-process packed sweep.
    :func:`robustness.scheduler.packed_group_runner` builds the
    scheduler-integrated alternative."""
    from ..solvers.newton import SolverOptions
    from .batch import packed_sweep_steady_state
    return packed_sweep_steady_state(
        [getattr(s, "spec", s) for s in sims], conds_list,
        tof_mask=masks, x0=x0s,
        opts=SolverOptions() if opts is None else opts,
        check_stability=check_stability, pos_jac_tol=pos_jac_tol)


def _default_transient_runner(sims, conds_list, save_ts, *, opts=None):
    """Transient-group runner seam default: the in-process packed
    transient (:func:`parallel.batch.packed_batch_transient`). Returns
    per-tenant dicts ``{ys, ok, quarantined}`` -- ``quarantined`` marks
    lanes with a non-finite endpoint, the transient analogue of the
    sweep quarantine the flush event reports."""
    from ..solvers.ode import ODEOptions
    from .batch import packed_batch_transient
    outs = []
    for ys, ok in packed_batch_transient(
            [getattr(s, "spec", s) for s in sims], conds_list, save_ts,
            opts=ODEOptions() if opts is None else opts):
        ys, ok = np.asarray(ys), np.asarray(ok)
        finite = np.isfinite(ys[:, -1, :]).all(axis=-1)
        outs.append({"ys": ys, "ok": ok, "quarantined": ~finite})
    return outs


class SweepCoalescer:
    """Continuous-batching front door for sweep-as-a-service: pending
    sweep requests are grouped by ``(abi_fingerprint, lane count,
    TOF-ness, x0-ness)`` -- the exact compatibility predicate of
    :func:`frontend.abi.pack_lowered` plus the packed program's traced
    shapes -- and each group is flushed as ONE packed multi-tenant
    dispatch (:func:`parallel.batch.packed_sweep_steady_state`) when it
    reaches ``max_occupancy`` tenants or its oldest request has waited
    ``max_wait_s`` seconds (checked by :meth:`poll`), whichever comes
    first.

    Requests whose mechanism does not lower into an ABI bucket get an
    id-unique group key, so they never co-pack and degrade to solo
    sweeps through the K=1 path.

    ``runner`` is the group-execution seam: any callable
    ``runner(sims, conds_list, masks, x0s, *, check_stability, opts,
    pos_jac_tol) -> list[dict]``. The default runs in-process;
    :func:`robustness.scheduler.packed_group_runner` routes singleton
    groups through the elastic scheduler and shares its events file.

    When ``work_dir`` is given, every flush appends a ``pack-flush``
    worker event (tenants, occupancy, lanes, per-tenant quarantine
    counts) to ``work_dir/events.jsonl`` -- the same file the elastic
    scheduler and ``tools/obsview.py --workers`` read.

    ``autoflush=False`` turns the coalescer into a pure queue for an
    external scheduler (the serving layer, ``pycatkin_tpu/serve``):
    ``submit`` never runs the solver inline; the owner polls
    :meth:`due_keys`, pops ripe groups with :meth:`take_group`
    (thread-safe: queue state lives behind the coalescer's own lock)
    and executes them with :meth:`run_requests` wherever it likes (a
    worker thread, the elastic queue). ``submit(..., wait_budget_s=...)`` tightens the
    group's flush deadline below ``max_wait_s`` per request -- the
    SLA-aware hook: a group's deadline is the EARLIEST budget of its
    members, so one latency-sensitive tenant flushes the whole pack
    early instead of burning its budget waiting for stragglers."""

    def __init__(self, runner=None, max_occupancy: Optional[int] = None,
                 max_wait_s: Optional[float] = None,
                 work_dir: Optional[str] = None,
                 check_stability: bool = False, opts=None,
                 pos_jac_tol: float = 1e-2, autoflush: bool = True,
                 transient_runner=None, ode_opts=None):
        if max_occupancy is None:
            max_occupancy = int(os.environ.get(
                PACKED_MAX_OCCUPANCY_ENV, _PACKED_MAX_OCCUPANCY_DEFAULT))
        if max_wait_s is None:
            max_wait_s = float(os.environ.get(
                PACKED_MAX_WAIT_ENV, _PACKED_MAX_WAIT_DEFAULT))
        if max_occupancy < 1:
            raise ValueError(f"max_occupancy must be >= 1, "
                             f"got {max_occupancy}")
        self.runner = _default_packed_runner if runner is None else runner
        self.transient_runner = (_default_transient_runner
                                 if transient_runner is None
                                 else transient_runner)
        self.ode_opts = ode_opts
        self.max_occupancy = int(max_occupancy)
        self.max_wait_s = float(max_wait_s)
        self.work_dir = work_dir
        self.check_stability = bool(check_stability)
        self.opts = opts
        self.pos_jac_tol = float(pos_jac_tol)
        self.autoflush = bool(autoflush)
        # The queue dicts are shared between the serving loop's submit
        # path and its executor threads (serve/server.py runs
        # take_group/poll off-loop); the lock covers QUEUE STATE only
        # -- no holder ever runs the solver or another locking method,
        # so there is no nesting and flushes happen outside it. The
        # '# guarded-by' contracts are enforced by pclint PCL011.
        self._lock = threading.Lock()
        self._groups: dict = {}      # guarded-by: _lock
        self._deadlines: dict = {}   # guarded-by: _lock
        # Monotonic solo-group sequence: ``id(sim)`` is reusable after
        # GC, so two distinct unfittable sims submitted over a server's
        # lifetime could alias one key and silently co-flush.
        self._solo_seq = itertools.count()
        self.flushes = 0

    def _group_key(self, sim, spec, conds, tof_mask, x0, save_ts=None):
        n = len(np.asarray(conds.T))
        fp = None
        try:
            from ..frontend import abi as _abi
            low = (spec if isinstance(spec, _abi.AbiLowered)
                   else _abi.maybe_lower(spec))
            if low is not None:
                fp = low.abi_fingerprint
        except Exception:
            fp = None
        if fp is None:
            # Unpackable mechanism: unique key -> always a solo group.
            return ("solo", next(self._solo_seq), n)
        if save_ts is not None:
            # Transient groups carry the exact save grid: the packed
            # transient program scans ONE shared grid, so only
            # same-grid requests may co-pack (and they never mix with
            # steady sweeps).
            return (fp, n, "transient",
                    tuple(float(t) for t in save_ts))
        return (fp, n, tof_mask is not None, x0 is not None)

    def _deadline_for(self, reqs) -> float:
        """The group flush deadline its members imply: the earliest
        ``submitted_at + wait_budget_s`` (``max_wait_s`` for members
        without a budget)."""
        return min(r.submitted_at
                   + (self.max_wait_s if r.wait_budget_s is None
                      else float(r.wait_budget_s))
                   for r in reqs)

    def submit(self, sim, conds, tof_mask=None, x0=None,
               wait_budget_s: Optional[float] = None,
               save_ts=None) -> PackedRequest:
        """Queue one sweep; returns its :class:`PackedRequest` handle.
        With ``autoflush`` (the default) the group flushes inline when
        it reaches ``max_occupancy``. ``wait_budget_s`` caps how long
        THIS request may sit waiting for co-tenants (tightening the
        group deadline below ``max_wait_s``) -- the serving layer
        derives it from the request's deadline class. A non-None
        ``save_ts`` queues a TRANSIENT request instead: grouped by
        (fingerprint, lanes, grid), executed through
        ``transient_runner``."""
        import time as _time
        spec = getattr(sim, "spec", sim)
        key = self._group_key(sim, spec, conds, tof_mask, x0, save_ts)
        req = PackedRequest(self, sim, spec, conds, tof_mask, x0, key,
                            submitted_at=_time.monotonic(),
                            wait_budget_s=wait_budget_s,
                            save_ts=save_ts)
        with self._lock:
            group = self._groups.setdefault(key, [])
            group.append(req)
            self._deadlines[key] = min(
                self._deadlines.get(key, float("inf")),
                self._deadline_for([req]))
            should_flush = (self.autoflush
                            and len(group) >= self.max_occupancy)
        # Flush OUTSIDE the lock: flush_group -> take_group re-acquires
        # it, and the runner must never execute under queue state.
        if should_flush:
            self.flush_group(key)
        return req

    @property
    def pending(self) -> int:
        with self._lock:
            return sum(len(g) for g in self._groups.values())

    def due_keys(self, now: Optional[float] = None) -> list:
        """Keys of every group ripe for flushing: at/over
        ``max_occupancy``, or past its deadline (``max_wait_s`` or the
        tightest submitted ``wait_budget_s``, whichever came first). A
        caller-supplied ``now`` earlier than every deadline -- a clock
        that moved backwards -- simply reports nothing due."""
        import time as _time
        now = _time.monotonic() if now is None else now
        with self._lock:
            due = [k for k, g in self._groups.items()
                   if len(g) >= self.max_occupancy]
            for key, d in self._deadlines.items():
                if now >= d and key not in due and key in self._groups:
                    due.append(key)
        return due

    def poll(self, now: Optional[float] = None) -> int:
        """Flush every group whose oldest request exceeded its wait
        budget; returns how many groups flushed. A serving loop calls
        this on its idle tick."""
        import time as _time
        now = _time.monotonic() if now is None else now
        with self._lock:
            due = [k for k, d in self._deadlines.items()
                   if now >= d and self._groups.get(k)]
        for key in due:
            self.flush_group(key)
        return len(due)

    def flush_all(self) -> int:
        """Flush every pending group regardless of age/occupancy."""
        flushed = 0
        with self._lock:
            keys = list(self._groups)
        for key in keys:
            reqs = self.take_group(key)
            if reqs:
                self.run_requests(key, reqs)
                flushed += 1
        return flushed

    def take_group(self, key, limit: Optional[int] = None) -> list:
        """Pop up to ``limit`` (all, if None) requests of one group,
        leaving any remainder queued with a recomputed deadline.
        Mutates only the queue dicts under the coalescer's own lock --
        never runs the solver -- so an external scheduler may call it
        from any thread and execute the returned requests elsewhere.
        Returns ``[]`` for a key already taken (the benign half of a
        flush race)."""
        with self._lock:
            reqs = self._groups.get(key)
            if not reqs:
                self._groups.pop(key, None)
                self._deadlines.pop(key, None)
                return []
            if limit is None or len(reqs) <= limit:
                self._groups.pop(key, None)
                self._deadlines.pop(key, None)
                return reqs
            taken, rest = reqs[:limit], reqs[limit:]
            self._groups[key] = rest
            self._deadlines[key] = self._deadline_for(rest)
            return taken

    def run_requests(self, key, reqs) -> list:
        """Execute one taken group through ``runner`` NOW, resolve its
        requests and emit the pack-flush event; returns the per-tenant
        result dicts in request order."""
        if reqs and reqs[0].save_ts is not None:
            # Transient group (all members share the grid: it is part
            # of the group key).
            outs = self.transient_runner(
                [r.sim for r in reqs], [r.conds for r in reqs],
                reqs[0].save_ts, opts=self.ode_opts)
        else:
            masks = [r.tof_mask for r in reqs]
            x0s = [r.x0 for r in reqs]
            outs = self.runner(
                [r.sim for r in reqs], [r.conds for r in reqs], masks,
                x0s, check_stability=self.check_stability,
                opts=self.opts, pos_jac_tol=self.pos_jac_tol)
        if len(outs) != len(reqs):
            raise RuntimeError(
                f"coalescer runner returned {len(outs)} results for "
                f"{len(reqs)} tenants")
        for r, o in zip(reqs, outs):
            r._result = o
            r.done = True
        self.flushes += 1
        self._emit_flush(key, reqs, outs)
        return outs

    def flush_group(self, key) -> None:
        reqs = self.take_group(key)
        if reqs:
            self.run_requests(key, reqs)

    def _emit_flush(self, key, reqs, outs) -> None:
        from ..utils.profiling import record_event
        k = len(reqs)
        kb = 1 << max(0, (k - 1).bit_length())
        n = len(np.asarray(reqs[0].conds.T))
        tq = [int(np.asarray(o.get("quarantined", ())).sum())
              for o in outs]
        fields = {"tenants": k, "k_bucket": kb,
                  "pack_occupancy": k / kb, "lanes": n,
                  "tenant_quarantined": tq}
        label = key[0] if isinstance(key, tuple) else str(key)
        record_event("worker", action="pack-flush", label=str(label),
                     **fields)
        if self.work_dir:
            import time as _time
            from ..robustness.scheduler import EVENTS
            from ..utils.io import append_json_line
            os.makedirs(self.work_dir, exist_ok=True)
            append_json_line(
                os.path.join(self.work_dir, EVENTS),
                {"kind": "worker", "action": "pack-flush",
                 "label": str(label), "t": _time.time(), **fields})


def _split_slices(n: int, k: int):
    """k contiguous, near-equal [start, stop) blocks covering range(n)."""
    bounds = np.linspace(0, n, k + 1).astype(int)
    return [(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
            if b > a]


def dispatch_sweep(sim, conds, n_workers: int = 2,
                   work_dir: Optional[str] = None,
                   tof_terms=None, check_stability: bool = False,
                   worker_env: Optional[dict] = None,
                   timeout: Optional[float] = None,
                   on_failure: str = "raise",
                   aot_cache: Optional[str] = None,
                   mode: str = "static", **elastic_opts) -> dict:
    """Run ``sweep_steady_state`` over ``conds`` split across
    ``n_workers`` independent processes; returns the merged result dict
    (same keys as the in-process sweep, lane order preserved).

    ``sim``: a built System (serialized to JSON for the workers).
    ``conds``: lane-batched Conditions.

    ``on_failure`` is the degradation policy for failed/timed-out
    worker blocks (the DCN tier's rung of the robustness ladder,
    robustness/ladder.py):

    - ``"raise"``  (default): fail fast, inputs + partial results left
      in ``work_dir`` for debugging -- the historical behavior.
    - ``"salvage"``: re-run each failed block IN-PROCESS (the parent
      becomes the host-fallback worker; this imports JAX into the
      otherwise JAX-free parent), recording a degradation event per
      block; only if the in-process re-solve also fails does the
      error propagate.

    ``aot_cache``: directory of the shared AOT executable cache
    (parallel/compile_pool.py) threaded to every worker via
    ``PYCATKIN_AOT_CACHE``; each worker then registers any cached
    executables matching its block's programs before solving
    (:func:`parallel.batch.warm_from_aot_cache` -- deserialization
    only, a miss costs nothing), so N workers don't each recompile
    programs some earlier run already built. None inherits the
    parent's environment unchanged.

    ``mode="elastic"`` swaps the static split-and-wait protocol for
    the lease-based elastic scheduler
    (:func:`robustness.scheduler.run_elastic`): the grid becomes a
    shared work queue, dead/stalled workers are restarted and their
    leases stolen, and poison chunks are bisected down to quarantine
    instead of failing the sweep. Extra keyword arguments
    (``chunk``, ``ttl_s``, ``max_kills``, ...) pass through;
    ``on_failure`` does not apply (degradation is per-span, built in).

    ``mode="packed"`` is the multi-tenant front door: ``sim`` and
    ``conds`` become per-tenant SEQUENCES (a single value is shared),
    requests are coalesced by :class:`SweepCoalescer` into same-bucket
    packs and each pack runs as ONE device dispatch
    (:func:`parallel.batch.packed_sweep_steady_state`); returns a LIST
    of per-tenant result dicts, each bit-identical to that tenant's
    solo sweep. Extra keyword arguments (``max_occupancy``,
    ``max_wait_s``, ``runner``, ``opts``, ``pos_jac_tol``) configure
    the coalescer; ``n_workers``/``timeout``/``on_failure`` do not
    apply (packed runs in-process unless ``runner`` says otherwise).
    """
    import tempfile

    from ..utils.io import save_system_json

    if mode not in ("static", "elastic", "packed"):
        raise ValueError(f"mode must be 'static', 'elastic' or "
                         f"'packed', got {mode!r}")
    if mode == "packed":
        sims = list(sim) if isinstance(sim, (list, tuple)) else [sim]
        conds_list = (list(conds) if isinstance(conds, (list, tuple))
                      else [conds] * len(sims))
        if len(conds_list) != len(sims):
            raise ValueError(f"packed mode: {len(conds_list)} conds "
                             f"for {len(sims)} sims")
        masks = [None] * len(sims)
        if tof_terms:
            from .. import engine
            masks = [engine.tof_mask_for(getattr(s, "spec", s),
                                         list(tof_terms))
                     for s in sims]
        co = SweepCoalescer(work_dir=work_dir,
                            check_stability=check_stability,
                            **elastic_opts)
        if worker_env:
            raise TypeError("packed mode runs in-process; worker_env "
                            "does not apply")
        if aot_cache is not None:
            os.environ.setdefault("PYCATKIN_AOT_CACHE", str(aot_cache))
        reqs = [co.submit(s, c, tof_mask=m)
                for s, c, m in zip(sims, conds_list, masks)]
        co.flush_all()
        return [r.result() for r in reqs]
    if mode == "elastic":
        from ..robustness.scheduler import run_elastic
        out, _report = run_elastic(
            sim, conds, n_workers=n_workers, work_dir=work_dir,
            tof_terms=tof_terms, check_stability=check_stability,
            worker_env=worker_env, aot_cache=aot_cache,
            timeout=timeout, **elastic_opts)
        return out
    if elastic_opts:
        raise TypeError(f"unexpected keyword argument(s) for static "
                        f"mode: {sorted(elastic_opts)}")
    if on_failure not in ("raise", "salvage"):
        raise ValueError(f"on_failure must be 'raise' or 'salvage', "
                         f"got {on_failure!r}")

    own_dir = work_dir is None
    if own_dir:
        work_dir = tempfile.mkdtemp(prefix="pycatkin_dispatch_")
    os.makedirs(work_dir, exist_ok=True)

    model_path = os.path.join(work_dir, "model.json")
    save_system_json(sim, model_path)

    n = len(np.asarray(conds.T))
    blocks = _split_slices(n, n_workers)
    procs = []
    for i, (a, b) in enumerate(blocks):
        block = type(conds)(**{
            f: np.asarray(getattr(conds, f))[a:b] for f in conds._fields})
        in_path = os.path.join(work_dir, f"block_{i}.npz")
        out_path = os.path.join(work_dir, f"result_{i}.npz")
        save_conditions(in_path, block)
        cfg = {"model": model_path, "conds": in_path, "out": out_path,
               "block": i,
               "tof_terms": list(tof_terms) if tof_terms else None,
               "check_stability": bool(check_stability)}
        cfg_path = os.path.join(work_dir, f"job_{i}.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        env = dict(os.environ)
        if aot_cache is not None:
            env["PYCATKIN_AOT_CACHE"] = str(aot_cache)
        if worker_env:
            env.update({k: str(v) for k, v in worker_env.items()})
        # Workers write stderr to per-block log files so a failure can
        # surface the actual traceback, not a bare returncode (and a
        # retry storm in one worker doesn't interleave with another's).
        stderr_path = os.path.join(work_dir, f"worker_{i}.stderr.log")
        with open(stderr_path, "wb") as errf:
            procs.append((i, out_path, subprocess.Popen(
                [sys.executable, "-m", "pycatkin_tpu.parallel.dispatch",
                 cfg_path],
                env=env, cwd=os.getcwd(), stderr=errf)))

    failed = []
    # ``timeout`` is a SHARED deadline for the whole sweep, not a
    # per-worker budget (a sequential per-worker wait would bound the
    # call at ~n_workers * timeout).
    import time as _time
    deadline = (_time.monotonic() + timeout) if timeout else None
    try:
        for i, out_path, p in procs:
            try:
                remaining = (max(0.0, deadline - _time.monotonic())
                             if deadline is not None else None)
                rc = p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                failed.append((i, None, True))
                continue
            if rc != 0 or not os.path.exists(out_path):
                failed.append((i, rc, False))
    finally:
        # Never orphan workers: on timeout/failure/interrupt, terminate
        # whatever is still running before propagating.
        for _, _, p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if failed and on_failure == "salvage":
        # Host-fallback rung of the ladder at the DCN tier: the block
        # inputs are still on disk, so re-solve them here in-process
        # (CPU/host devices of the parent) rather than losing the whole
        # sweep to one dead worker.
        from ..obs import metrics as _metrics
        from ..utils.profiling import record_event
        still_failed = []
        for i, rc, timed_out in failed:
            cfg_path = os.path.join(work_dir, f"job_{i}.json")
            record_event("degradation", label=f"dispatch:block:{i}",
                         rung="host-fallback",
                         detail="worker process failed/timed out; "
                                "re-solving block in-process")
            _metrics.counter("pycatkin_dispatch_salvaged_blocks_total",
                             "worker blocks re-solved in-process").inc()
            try:
                _worker(cfg_path, inject_faults=False)
            except Exception as exc:  # noqa: BLE001 - reported below
                record_event("degradation", label=f"dispatch:block:{i}",
                             rung="abandoned",
                             detail=f"in-process re-solve failed: {exc}")
                _metrics.counter(
                    "pycatkin_dispatch_abandoned_blocks_total",
                    "worker blocks abandoned after salvage failed").inc()
                still_failed.append((i, rc, timed_out))
        failed = still_failed
    if failed:
        # Classify each failure into the retry taxonomy and quote the
        # worker's dying words -- "block 2 failed" with no cause costs
        # a debugging round-trip into the work_dir every time.
        from ..robustness.scheduler import stderr_tail
        from ..utils.retry import classify_worker_exit
        details = []
        for i, rc, timed_out in failed:
            info = classify_worker_exit(rc, timed_out=timed_out)
            line = f"block {i}: {info.kind} ({info.detail})"
            tail = stderr_tail(
                os.path.join(work_dir, f"worker_{i}.stderr.log"))
            if tail:
                line += "; last stderr: " + " | ".join(tail[-3:])
            details.append(line)
        raise RuntimeError(
            "dispatch_sweep: worker block(s) failed or timed out -- "
            + "; ".join(details)
            + f"; inputs and any partial results are in {work_dir}")

    from ..utils.profiling import span
    merged: dict = {}
    with span("dispatch merge", n_blocks=len(procs)):
        for i, out_path, _ in procs:
            with np.load(out_path) as z:
                for key in z.files:
                    merged.setdefault(key, []).append(z[key])
        out = {k: np.concatenate(v, axis=0) for k, v in merged.items()}
    if own_dir:
        # Self-created scratch only; caller-supplied work_dirs (and any
        # failure, which raises above) are left in place for debugging.
        import shutil
        shutil.rmtree(work_dir, ignore_errors=True)
    return out


def _worker(cfg_path: str, inject_faults: bool = True) -> None:
    with open(cfg_path) as f:
        cfg = json.load(f)

    import pycatkin_tpu as pk
    from .. import engine
    from ..robustness import faults
    from .batch import sweep_steady_state

    # Deterministic fault-injection site at the dispatch boundary:
    # workers inherit PYCATKIN_FAULTS via the environment, so a plan
    # targeting "dispatch:block:<i>" fires inside the subprocess (the
    # resulting nonzero exit is what the parent's salvage path handles).
    # The parent's in-process salvage re-run passes inject_faults=False:
    # an injected fault models the remote worker/device, and the host
    # fallback is by construction a different device.
    if inject_faults:
        faults.inject(f"dispatch:block:{cfg.get('block', 0)}")

    sim = pk.read_from_input_file(cfg["model"])
    conds = load_conditions(cfg["conds"])
    mask = (engine.tof_mask_for(sim.spec, cfg["tof_terms"])
            if cfg.get("tof_terms") else None)
    # Deserialize (never compile/execute) any AOT-cached executables
    # matching this block's programs -- free on miss, and it spares a
    # worker fleet from redundantly recompiling what one run already
    # built (the cache dir arrives via PYCATKIN_AOT_CACHE).
    from .batch import warm_from_aot_cache
    from ..utils.profiling import span
    block = cfg.get("block", 0)
    with span("worker aot warm", block=block):
        warm_from_aot_cache(sim.spec, conds, tof_mask=mask,
                            check_stability=cfg.get("check_stability",
                                                    False))
    with span("worker sweep", block=block):
        out = sweep_steady_state(sim.spec, conds, tof_mask=mask,
                                 check_stability=cfg.get(
                                     "check_stability", False))
    np.savez_compressed(cfg["out"],
                        **{k: np.asarray(v) for k, v in out.items()})


if __name__ == "__main__":
    _worker(sys.argv[1])
