"""DCN-tier sweep dispatcher: split a lane grid across processes.

SURVEY.md §5.8's outer parallelism tier: descriptor/condition lanes are
physically independent, so beyond one mesh (vmap + shard_map over ICI)
the next axis is *embarrassingly parallel dispatch* of disjoint lane
blocks to independent workers -- separate processes on one host, or
separate hosts/slices connected only by DCN. No collective runs between
blocks; the only "communication" is the result merge, exactly the
structure the reference's serial sweep loops imply (grid points couple
nowhere in the math -- the one neighbor coupling, grid-repair
averaging, is post-hoc host-side).

Protocol (all host-side, no JAX in the parent):
  1. the mechanism is serialized once (utils.io.save_system_json --
     the reference-schema JSON round-trip);
  2. the lane-batched Conditions pytree is split into contiguous
     blocks, one .npz per worker;
  3. each worker is a fresh ``python -m pycatkin_tpu.parallel.dispatch``
     process: loads the JSON, rebuilds the spec, runs
     ``sweep_steady_state`` on its block, writes results to .npz;
  4. the parent waits and concatenates blocks in lane order.

Workers inherit the parent environment by default; pass ``worker_env``
overrides to pin devices (e.g. one TPU slice per worker via
``JAX_PLATFORMS`` / topology env vars, or ``JAX_PLATFORMS=cpu`` for
host-only workers).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

import numpy as np

def save_conditions(path: str, conds) -> None:
    """Write a (lane-batched) Conditions pytree to .npz (the namedtuple's
    own field list, so a schema change round-trips automatically)."""
    np.savez_compressed(
        path, **{f: np.asarray(getattr(conds, f)) for f in conds._fields})


def load_conditions(path: str):
    """Read a Conditions pytree written by :func:`save_conditions`."""
    from ..frontend.spec import Conditions
    with np.load(path) as z:
        return Conditions(**{f: z[f] for f in Conditions._fields})


def _split_slices(n: int, k: int):
    """k contiguous, near-equal [start, stop) blocks covering range(n)."""
    bounds = np.linspace(0, n, k + 1).astype(int)
    return [(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
            if b > a]


def dispatch_sweep(sim, conds, n_workers: int = 2,
                   work_dir: Optional[str] = None,
                   tof_terms=None, check_stability: bool = False,
                   worker_env: Optional[dict] = None,
                   timeout: Optional[float] = None,
                   on_failure: str = "raise",
                   aot_cache: Optional[str] = None,
                   mode: str = "static", **elastic_opts) -> dict:
    """Run ``sweep_steady_state`` over ``conds`` split across
    ``n_workers`` independent processes; returns the merged result dict
    (same keys as the in-process sweep, lane order preserved).

    ``sim``: a built System (serialized to JSON for the workers).
    ``conds``: lane-batched Conditions.

    ``on_failure`` is the degradation policy for failed/timed-out
    worker blocks (the DCN tier's rung of the robustness ladder,
    robustness/ladder.py):

    - ``"raise"``  (default): fail fast, inputs + partial results left
      in ``work_dir`` for debugging -- the historical behavior.
    - ``"salvage"``: re-run each failed block IN-PROCESS (the parent
      becomes the host-fallback worker; this imports JAX into the
      otherwise JAX-free parent), recording a degradation event per
      block; only if the in-process re-solve also fails does the
      error propagate.

    ``aot_cache``: directory of the shared AOT executable cache
    (parallel/compile_pool.py) threaded to every worker via
    ``PYCATKIN_AOT_CACHE``; each worker then registers any cached
    executables matching its block's programs before solving
    (:func:`parallel.batch.warm_from_aot_cache` -- deserialization
    only, a miss costs nothing), so N workers don't each recompile
    programs some earlier run already built. None inherits the
    parent's environment unchanged.

    ``mode="elastic"`` swaps the static split-and-wait protocol for
    the lease-based elastic scheduler
    (:func:`robustness.scheduler.run_elastic`): the grid becomes a
    shared work queue, dead/stalled workers are restarted and their
    leases stolen, and poison chunks are bisected down to quarantine
    instead of failing the sweep. Extra keyword arguments
    (``chunk``, ``ttl_s``, ``max_kills``, ...) pass through;
    ``on_failure`` does not apply (degradation is per-span, built in).
    """
    import tempfile

    from ..utils.io import save_system_json

    if mode not in ("static", "elastic"):
        raise ValueError(f"mode must be 'static' or 'elastic', "
                         f"got {mode!r}")
    if mode == "elastic":
        from ..robustness.scheduler import run_elastic
        out, _report = run_elastic(
            sim, conds, n_workers=n_workers, work_dir=work_dir,
            tof_terms=tof_terms, check_stability=check_stability,
            worker_env=worker_env, aot_cache=aot_cache,
            timeout=timeout, **elastic_opts)
        return out
    if elastic_opts:
        raise TypeError(f"unexpected keyword argument(s) for static "
                        f"mode: {sorted(elastic_opts)}")
    if on_failure not in ("raise", "salvage"):
        raise ValueError(f"on_failure must be 'raise' or 'salvage', "
                         f"got {on_failure!r}")

    own_dir = work_dir is None
    if own_dir:
        work_dir = tempfile.mkdtemp(prefix="pycatkin_dispatch_")
    os.makedirs(work_dir, exist_ok=True)

    model_path = os.path.join(work_dir, "model.json")
    save_system_json(sim, model_path)

    n = len(np.asarray(conds.T))
    blocks = _split_slices(n, n_workers)
    procs = []
    for i, (a, b) in enumerate(blocks):
        block = type(conds)(**{
            f: np.asarray(getattr(conds, f))[a:b] for f in conds._fields})
        in_path = os.path.join(work_dir, f"block_{i}.npz")
        out_path = os.path.join(work_dir, f"result_{i}.npz")
        save_conditions(in_path, block)
        cfg = {"model": model_path, "conds": in_path, "out": out_path,
               "block": i,
               "tof_terms": list(tof_terms) if tof_terms else None,
               "check_stability": bool(check_stability)}
        cfg_path = os.path.join(work_dir, f"job_{i}.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        env = dict(os.environ)
        if aot_cache is not None:
            env["PYCATKIN_AOT_CACHE"] = str(aot_cache)
        if worker_env:
            env.update({k: str(v) for k, v in worker_env.items()})
        # Workers write stderr to per-block log files so a failure can
        # surface the actual traceback, not a bare returncode (and a
        # retry storm in one worker doesn't interleave with another's).
        stderr_path = os.path.join(work_dir, f"worker_{i}.stderr.log")
        with open(stderr_path, "wb") as errf:
            procs.append((i, out_path, subprocess.Popen(
                [sys.executable, "-m", "pycatkin_tpu.parallel.dispatch",
                 cfg_path],
                env=env, cwd=os.getcwd(), stderr=errf)))

    failed = []
    # ``timeout`` is a SHARED deadline for the whole sweep, not a
    # per-worker budget (a sequential per-worker wait would bound the
    # call at ~n_workers * timeout).
    import time as _time
    deadline = (_time.monotonic() + timeout) if timeout else None
    try:
        for i, out_path, p in procs:
            try:
                remaining = (max(0.0, deadline - _time.monotonic())
                             if deadline is not None else None)
                rc = p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                failed.append((i, None, True))
                continue
            if rc != 0 or not os.path.exists(out_path):
                failed.append((i, rc, False))
    finally:
        # Never orphan workers: on timeout/failure/interrupt, terminate
        # whatever is still running before propagating.
        for _, _, p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if failed and on_failure == "salvage":
        # Host-fallback rung of the ladder at the DCN tier: the block
        # inputs are still on disk, so re-solve them here in-process
        # (CPU/host devices of the parent) rather than losing the whole
        # sweep to one dead worker.
        from ..obs import metrics as _metrics
        from ..utils.profiling import record_event
        still_failed = []
        for i, rc, timed_out in failed:
            cfg_path = os.path.join(work_dir, f"job_{i}.json")
            record_event("degradation", label=f"dispatch:block:{i}",
                         rung="host-fallback",
                         detail="worker process failed/timed out; "
                                "re-solving block in-process")
            _metrics.counter("pycatkin_dispatch_salvaged_blocks_total",
                             "worker blocks re-solved in-process").inc()
            try:
                _worker(cfg_path, inject_faults=False)
            except Exception as exc:  # noqa: BLE001 - reported below
                record_event("degradation", label=f"dispatch:block:{i}",
                             rung="abandoned",
                             detail=f"in-process re-solve failed: {exc}")
                _metrics.counter(
                    "pycatkin_dispatch_abandoned_blocks_total",
                    "worker blocks abandoned after salvage failed").inc()
                still_failed.append((i, rc, timed_out))
        failed = still_failed
    if failed:
        # Classify each failure into the retry taxonomy and quote the
        # worker's dying words -- "block 2 failed" with no cause costs
        # a debugging round-trip into the work_dir every time.
        from ..robustness.scheduler import stderr_tail
        from ..utils.retry import classify_worker_exit
        details = []
        for i, rc, timed_out in failed:
            info = classify_worker_exit(rc, timed_out=timed_out)
            line = f"block {i}: {info.kind} ({info.detail})"
            tail = stderr_tail(
                os.path.join(work_dir, f"worker_{i}.stderr.log"))
            if tail:
                line += "; last stderr: " + " | ".join(tail[-3:])
            details.append(line)
        raise RuntimeError(
            "dispatch_sweep: worker block(s) failed or timed out -- "
            + "; ".join(details)
            + f"; inputs and any partial results are in {work_dir}")

    from ..utils.profiling import span
    merged: dict = {}
    with span("dispatch merge", n_blocks=len(procs)):
        for i, out_path, _ in procs:
            with np.load(out_path) as z:
                for key in z.files:
                    merged.setdefault(key, []).append(z[key])
        out = {k: np.concatenate(v, axis=0) for k, v in merged.items()}
    if own_dir:
        # Self-created scratch only; caller-supplied work_dirs (and any
        # failure, which raises above) are left in place for debugging.
        import shutil
        shutil.rmtree(work_dir, ignore_errors=True)
    return out


def _worker(cfg_path: str, inject_faults: bool = True) -> None:
    with open(cfg_path) as f:
        cfg = json.load(f)

    import pycatkin_tpu as pk
    from .. import engine
    from ..robustness import faults
    from .batch import sweep_steady_state

    # Deterministic fault-injection site at the dispatch boundary:
    # workers inherit PYCATKIN_FAULTS via the environment, so a plan
    # targeting "dispatch:block:<i>" fires inside the subprocess (the
    # resulting nonzero exit is what the parent's salvage path handles).
    # The parent's in-process salvage re-run passes inject_faults=False:
    # an injected fault models the remote worker/device, and the host
    # fallback is by construction a different device.
    if inject_faults:
        faults.inject(f"dispatch:block:{cfg.get('block', 0)}")

    sim = pk.read_from_input_file(cfg["model"])
    conds = load_conditions(cfg["conds"])
    mask = (engine.tof_mask_for(sim.spec, cfg["tof_terms"])
            if cfg.get("tof_terms") else None)
    # Deserialize (never compile/execute) any AOT-cached executables
    # matching this block's programs -- free on miss, and it spares a
    # worker fleet from redundantly recompiling what one run already
    # built (the cache dir arrives via PYCATKIN_AOT_CACHE).
    from .batch import warm_from_aot_cache
    from ..utils.profiling import span
    block = cfg.get("block", 0)
    with span("worker aot warm", block=block):
        warm_from_aot_cache(sim.spec, conds, tof_mask=mask,
                            check_stability=cfg.get("check_stability",
                                                    False))
    with span("worker sweep", block=block):
        out = sweep_steady_state(sim.spec, conds, tof_mask=mask,
                                 check_stability=cfg.get(
                                     "check_stability", False))
    np.savez_compressed(cfg["out"],
                        **{k: np.asarray(v) for k, v in out.items()})


if __name__ == "__main__":
    _worker(sys.argv[1])
