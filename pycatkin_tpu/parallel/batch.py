"""Batched / sharded execution of the engine over condition grids.

The reference runs every sweep as a serial Python loop (temperature sweep
presets.py:43-64, 2-D volcano grid cooxvolcano.py:22-49, UQ samples
uncertainty.py:109-112, DRC perturbations old_system.py:503-513). Here a
sweep is data: a :class:`Conditions` pytree with a leading lane axis.
One ``vmap`` turns the whole solve into a single XLA program; ``shard_map``
over a ``jax.sharding.Mesh`` spreads lanes across chips with collectives
riding ICI. Grid points are physically independent (SURVEY.md §5.7), so
the only cross-device communication is the result gather.

Per-lane convergence heterogeneity is handled inside the solver
(bounded retry while_loops with per-lane masks); lanes that finish early
simply stop improving, which is the price of SIMD execution.
"""

from __future__ import annotations

import os
import time as _time_mod
from functools import lru_cache, partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import engine
from .. import precision as _precision
from ..frontend import abi as _abi
from ..frontend.spec import Conditions, ModelSpec
from ..lint.hotpath import hotpath
from ..obs import costs as _costs
from ..san import recompile as _san_recompile
from ..san import trace_ident as _san_trace_ident
from ..obs import metrics as _metrics
from ..solvers.newton import STRATEGY_CODES, SolverOptions
from ..solvers.ode import ODEOptions
from ..utils.profiling import host_sync, record_event, span
from ..utils.retry import call_with_backend_retry
from . import compile_pool

# Program-zoo budget: the number of distinct programs a full production
# prewarm (bench.py bucket layout) may touch. The r05 zoo held 32
# (4 strategy-specific rescue programs per solve bucket); r06's
# consolidated rescue program brought it to 14, and the fused sweep
# program (solve + quarantine + tier-0 screen + TOF/activity + packed
# diagnostics in ONE dispatch, :func:`_fused_sweep_program`) subsumes
# the standalone fast-pass/screen/TOF programs, bringing the full
# layout under 10. bench.py --smoke asserts the ceiling.
PREWARM_PROGRAM_BUDGET = 10

# Floor (pow2) for the stability tier-2 Jacobian subset shape: ambiguous
# counts drift trial to trial, and every distinct pow2 shape below the
# floor used to be its own compiled program (4 warmed shapes at 64..512
# in r05). One 512 floor collapses them to a single program; the pad
# lanes are sliced off ON DEVICE before the host transfer, so only the
# device flops (cheap) grow, never the tunnel payload.
TIER2_MIN_BUCKET = 512


# ---------------------------------------------------------------------
# Cached jitted programs. jax.jit caches on function identity, so the
# vmapped solver closures must be built ONCE per (spec, opts, sharding)
# -- rebuilding them per call would recompile the whole batched solve
# every time (tens of seconds at volcano-grid scale). ModelSpec hashes
# by identity (frozen, eq=False) precisely to key these caches.
#
# Identity keys mean entries for dead specs can never be re-hit, and each
# pins its spec + compiled executable; the size is kept small and
# :func:`clear_program_caches` lets long-running sessions (one System per
# UQ copy, loops over mechanisms) release device memory explicitly.
def clear_program_caches():
    """Drop all cached jitted programs (and their spec references),
    including the engine-level transient chunk/finish programs and the
    AOT executable registry (compile_pool)."""
    _steady_program.cache_clear()
    _fused_sweep_program.cache_clear()
    _packed_fused_sweep_program.cache_clear()
    _rescue_program.cache_clear()
    _transient_chunk_program.cache_clear()
    _transient_finish_program.cache_clear()
    _fused_transient_program.cache_clear()
    _packed_transient_program.cache_clear()
    _tof_program.cache_clear()
    _jacobian_program.cache_clear()
    _stability_screen_program.cache_clear()
    engine._transient_chunk_program.cache_clear()
    engine._transient_finish_program.cache_clear()
    compile_pool.clear_registry()
    _abi.clear_lowering_cache()


# ---------------------------------------------------------------------
# AOT executable registry bridge. prewarm_sweep_programs publishes
# compiled (or disk-loaded) executables in compile_pool's registry; the
# hot path consults it before the ordinary jitted program. This is what
# makes a warm-disk prewarm real: ``f.lower().compile()`` does NOT
# populate jit's dispatch cache, so without the registry an AOT-loaded
# executable would never actually run and the first in-band hit would
# silently re-trace + re-compile.
def _sharding_tag(sharding) -> str:
    """Kind-string suffix for a program compiled with explicit
    ``out_shardings``. Trivial (None / one-device) shardings map to the
    empty string, so a mesh of 1 produces byte-identical kinds -- and
    therefore registry hits -- against the unsharded prewarm."""
    if sharding is None:
        return ""
    try:
        sizes = tuple(sharding.mesh.shape.items())
    except Exception:
        return ""
    if all(s <= 1 for _, s in sizes):
        return ""
    axes = ";".join(f"{k}={v}" for k, v in sizes)
    return f"@mesh[{axes}]{sharding.spec}"


def _steady_kind(opts: SolverOptions, strategy: str,
                 sharding=None, tier: str = "f64") -> str:
    """Registry/cache kind string for a steady-solve program variant;
    prewarm and the hot path MUST derive it identically (shapes ride in
    the key separately). ``tier`` tags non-default precision tiers so
    f32-bulk and f64 programs never share a registry/AOT entry; the
    f64 tag is empty, keeping every pre-tier key byte-identical. The
    direction-kernel tag (``:kpl``, resolved from
    PYCATKIN_LINALG_KERNEL at call time) rides after the tier tag for
    the same reason: Pallas-kernel and XLA programs never share an
    entry, and the xla tag is empty."""
    return (f"steady:{strategy}:{opts!r}{_precision.tier_tag(tier)}"
            f"{_precision.kernel_tag()}{_sharding_tag(sharding)}")


def _pacing_key(opts: SolverOptions) -> SolverOptions:
    """Options with the four TRACED pacing knobs of the consolidated
    rescue program replaced by sentinels: every ladder rung that
    differs only in pacing (polish vs full PTC vs the unseeded demote
    re-solve) normalizes to the same value, hence the same compiled
    program. The verdict tolerances (and the STATIC chord_steps) stay
    in the key -- they are compile-time constants of the program."""
    return opts._replace(dt0=-1.0, dt_grow_min=-1.0, max_steps=-1,
                         max_attempts=-1)


def _rescue_kind(opts: SolverOptions, sharding=None) -> str:
    # Rescue always runs f64 (no tier tag), but its Newton ladder
    # embeds direction solves, so the kernel tag applies.
    return (f"rescue:{_pacing_key(opts)!r}{_precision.kernel_tag()}"
            f"{_sharding_tag(sharding)}")


def _screen_kind(pos_tol: float, backend: str) -> str:
    return f"screen:{pos_tol!r}:{backend}"


def _fused_kind(opts: SolverOptions, pos_tol: float, backend: str,
                has_tof: bool, check_stability: bool,
                sharding=None, tier: str = "f64") -> str:
    """Registry/cache kind string for the fused sweep program (solve +
    quarantine + tier-0 certificate + TOF/activity + packed diagnostics
    in ONE dispatch). prewarm, warm_from_aot_cache and the hot path
    MUST derive it identically; ``opts`` must be the fast-pass options
    (:func:`_fast_pass_opts`). ``tier`` tags the precision tier the
    bulk solve runs in (empty for f64: pre-tier keys stay
    byte-identical; the cost ledger keys its roofline on this tag)."""
    return (f"fused:{opts!r}:{pos_tol!r}:{backend}"
            f":s{int(check_stability)}t{int(has_tof)}"
            f"{_precision.tier_tag(tier)}{_precision.kernel_tag()}"
            f"{_sharding_tag(sharding)}")


def _fused_enabled() -> bool:
    """Whether sweep_steady_state may take the fused one-dispatch tail.

    ON by default; OFF when (a) the caller disabled it
    (``PYCATKIN_FUSED_SWEEP=0``) or (b) a fault-injection plan is
    active: ``nan``-kind fault transforms poison the OUTPUT of a
    retried dispatch, and the fused program computes its quarantine
    verdicts INSIDE the dispatch -- poison applied after the fact would
    bypass them, silently voiding the per-lane containment the fault
    tests certify. The legacy split pipeline (solve dispatch, then
    tail programs) keeps every fault site meaningful, exactly like
    robustness/chunked.py dropping double-buffering under an active
    plan."""
    from ..robustness.faults import active_plan
    if active_plan() is not None:
        return False
    return os.environ.get("PYCATKIN_FUSED_SWEEP", "1").strip().lower() \
        not in ("0", "off", "none", "disabled", "false")


def _prog_spec(spec):
    """The identity a program builder / the executable registry keys on:
    the interned bucket object for an ABI-lowered spec (shared by every
    mechanism in the bucket -- the whole point), the ModelSpec itself
    otherwise."""
    if isinstance(spec, (_abi.AbiLowered, _abi.PackedLowered)):
        return spec.program_spec
    return spec


def _prog_args(spec, args):
    """Argument tuple a program is actually dispatched with: ABI
    programs take the mechanism operand pytree as their leading traced
    argument (a :class:`frontend.abi.PackedLowered` prepends the
    tenant-stacked pytree the same way). Prewarm's direct
    program_key()/lower() paths and the in-band dispatch MUST both go
    through this, or their keys drift."""
    if isinstance(spec, (_abi.AbiLowered, _abi.PackedLowered)):
        return (spec.operands(),) + tuple(args)
    return tuple(args)


def _registered_call(spec: ModelSpec, kind: str, prog, args):
    """Run ``prog(*args)`` through a registered AOT executable when one
    matches (kind + argument shapes), else through the jitted program.
    A registered executable that refuses the arguments (shape/sharding
    drift vs what prewarm saw) is evicted and the call falls back --
    correctness never depends on the registry.

    ``args`` is always the LEGACY argument tuple; the ABI operand
    prepend (and the bucket registry identity) is applied here, in one
    place, so no call site can desynchronize key and dispatch."""
    args = _prog_args(spec, args)
    spec = _prog_spec(spec)
    key = compile_pool.program_key(kind, args)
    # pcsan seam: records (cold) / verifies (warm) the program key --
    # a never-seen key after mark_warm() is an in-band recompile about
    # to happen. One bool check when the sanitizer is off.
    _san_recompile.note_program(kind, key, args)
    # pcsan trace-ident seam: fingerprint the jaxpr on the key's first
    # sighting; a later distinct jaxpr under the same key raises.
    _san_trace_ident.note_jaxpr(kind, key, prog, args)
    exe = compile_pool.lookup(spec, key)
    if exe is not None:
        t0 = _time_mod.perf_counter()
        try:
            out = exe(*args)
        except Exception as e:
            compile_pool.unregister(spec, key)
            record_event("degradation", label="aot:fallback",
                         error=f"{type(e).__name__}: {e}"[:200])
            _metrics.counter(
                "pycatkin_aot_fallback_total",
                "registered AOT executables evicted to the jit "
                "fallback").inc()
        else:
            # Dispatch wall into the cost ledger. On the async backend
            # this is enqueue time only; the hot paths that own the
            # matching materialization fold its blocked wall onto the
            # same key (count=0), so MFU denominators stay honest.
            _costs.note_dispatch(key, _time_mod.perf_counter() - t0)
            return out
    # Registry miss: the jitted fallback traces + compiles SYNCHRONOUSLY
    # on its first call at this shape, which is exactly the in-band
    # recompile the variance forensics hunt for -- the span carries the
    # wall so a slow trial can be attributed to a named program.
    with span(f"inband:{kind.split(':', 1)[0]}", key=key[:8]):
        t0 = _time_mod.perf_counter()
        out = prog(*args)
        _costs.note_dispatch(key, _time_mod.perf_counter() - t0)
        return out


def _donate_argnums(argnums):
    """Buffer donation for the solve programs, gated OFF on CPU where
    XLA ignores donation with a warning per call (and the aliasing buys
    nothing -- host RAM is not the scarce resource). Callers that
    donate MUST rebuild the donated arguments inside their retried
    closures: a retry after a transient flake would otherwise re-feed
    already-consumed buffers."""
    return () if jax.default_backend() == "cpu" else tuple(argnums)


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _steady_program(spec: ModelSpec, opts: SolverOptions,
                    out_sharding=None, strategy: str = "ptc",
                    tier: str = "f64", kernel: str = "xla"):
    # ``tier`` is an explicit cache-key parameter (never read from the
    # environment inside the builder): flipping PYCATKIN_PRECISION_TIER
    # at runtime must select a DIFFERENT cached program, not mutate a
    # stale one. ``kernel`` plays the same cache-key role for
    # PYCATKIN_LINALG_KERNEL (filled by the kernel_keyed wrapper; the
    # trace bakes select_solver's choice in).
    if isinstance(spec, _abi.AbiProgramSpec):
        # ABI form: the mechanism rides in as the leading traced operand
        # pytree instead of being constant-folded, so every mechanism in
        # the bucket shares this one executable. Operands are never
        # donated -- the same buffers back every dispatch.
        def program(ops, conds, keys, x0):
            tspec = spec.bind(ops)

            def solve_one(cond, key, x0):
                return engine.steady_state(tspec, cond, x0=x0, key=key,
                                           opts=opts, strategy=strategy,
                                           tier=tier)
            return jax.vmap(solve_one)(conds, keys, x0)
        kw = {"donate_argnums": _donate_argnums((2,))}
        if out_sharding is not None:
            kw["out_shardings"] = out_sharding
        return jax.jit(program, **kw)

    def solve_one(cond, key, x0):
        return engine.steady_state(spec, cond, x0=x0, key=key, opts=opts,
                                   strategy=strategy, tier=tier)
    fn = jax.vmap(solve_one)
    # Only the PRNG keys are donated: x0 may be caller-owned (sweep
    # seeds, continuation stage solutions) and conds are reused by
    # every downstream tail program.
    kw = {"donate_argnums": _donate_argnums((1,))}
    if out_sharding is not None:
        kw["out_shardings"] = out_sharding
    return jax.jit(fn, **kw)


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _rescue_program(spec: ModelSpec, pacing: SolverOptions,
                    out_sharding=None, kernel: str = "xla"):
    """ONE strategy-parameterized rescue program per (spec, verdict
    tolerances, bucket shape): the r05 zoo compiled four separate
    programs per bucket (polish / full PTC / LM / unseeded PTC). Here

    - PTC vs LM is a static branch PAIR under a scalar ``lax.cond``
      (the predicate is unbatched, so XLA keeps it a true conditional:
      only the selected solver executes);
    - seeded vs unseeded is a traced per-program select
      (``engine.steady_state(use_x0=...)`` -- x0 is always a concrete
      array, never a treedef-changing None);
    - the pacing knobs (dt0, dt_grow_min, max_steps, max_attempts) ride
      in as traced scalars, so the polish rung and the full ladder are
      the same executable called with different numbers.

    ``pacing`` must be pre-normalized via :func:`_pacing_key` (the
    lru_cache would otherwise split per pacing value and resurrect the
    zoo this program exists to collapse)."""
    def make(strategy, sp):
        def solve_one(cond, key, x0, seeded, dt0, grow, max_steps,
                      max_attempts):
            o = pacing._replace(dt0=dt0, dt_grow_min=grow,
                                max_steps=max_steps,
                                max_attempts=max_attempts)
            return engine.steady_state(sp, cond, x0=x0, key=key,
                                       opts=o, strategy=strategy,
                                       use_x0=seeded)
        return jax.vmap(solve_one,
                        in_axes=(0, 0, 0) + (None,) * 5)

    if isinstance(spec, _abi.AbiProgramSpec):
        def program(ops, conds, keys, x0, strat, seeded, dt0, grow,
                    max_steps, max_attempts):
            # bind() once; the traced operands are closure-captured into
            # both lax.cond branches (hoisted as implicit cond operands).
            tspec = spec.bind(ops)
            args = (conds, keys, x0, seeded, dt0, grow, max_steps,
                    max_attempts)
            return jax.lax.cond(strat == 1,
                                lambda a: make("lm", tspec)(*a),
                                lambda a: make("ptc", tspec)(*a), args)
        kw = {"donate_argnums": _donate_argnums((2, 3))}
        if out_sharding is not None:
            kw["out_shardings"] = out_sharding
        return jax.jit(program, **kw)

    run_ptc, run_lm = make("ptc", spec), make("lm", spec)

    def program(conds, keys, x0, strat, seeded, dt0, grow, max_steps,
                max_attempts):
        args = (conds, keys, x0, seeded, dt0, grow, max_steps,
                max_attempts)
        return jax.lax.cond(strat == 1,
                            lambda a: run_lm(*a),
                            lambda a: run_ptc(*a), args)

    kw = {"donate_argnums": _donate_argnums((1, 2))}
    if out_sharding is not None:
        kw["out_shardings"] = out_sharding
    return jax.jit(program, **kw)


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _transient_chunk_program(spec: ModelSpec, opts: ODEOptions,
                             kernel: str = "xla"):
    # ``kernel`` is a cache key only (kernel_keyed): the implicit ODE
    # stages embed make_msolve direction solves.
    if isinstance(spec, _abi.AbiProgramSpec):
        def program(ops, conds, state, part):
            tspec = spec.bind(ops)

            def run_one(cond, st, p):
                return engine.transient_state(tspec, cond, st, p, opts)
            return jax.vmap(run_one, in_axes=(0, 0, None))(conds, state,
                                                           part)
        return jax.jit(program)

    def run_one(cond, state, part):
        return engine.transient_state(spec, cond, state, part, opts)
    return jax.jit(jax.vmap(run_one, in_axes=(0, 0, None)))


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _transient_finish_program(spec: ModelSpec, sopts: SolverOptions,
                              kernel: str = "xla"):
    if isinstance(spec, _abi.AbiProgramSpec):
        def program(ops, conds, y_last, ok):
            tspec = spec.bind(ops)

            def fin_one(cond, y, o):
                return engine.transient_finish(tspec, cond, y, o,
                                               sopts=sopts)
            return jax.vmap(fin_one)(conds, y_last, ok)
        return jax.jit(program)

    def fin_one(cond, y_last, ok):
        return engine.transient_finish(spec, cond, y_last, ok, sopts=sopts)
    return jax.jit(jax.vmap(fin_one))


# ---------------------------------------------------------------------
# Fused transient sweeps (docs/perf_transient.md): the whole save grid
# scanned inside ONE traced program (solvers/ode.integrate's lax.scan
# assembles the dense-output buffer on device, XLA aliases the scan
# carry), Newton finish and per-lane verdict packing fused in, so a
# clean transient sweep costs ONE dispatch and ONE counted host sync
# instead of the chunked drive's one-per-chunk. The host-driven chunk
# loop survives behind PYCATKIN_FUSED_TRANSIENT=0 and under active
# fault plans (engine.fused_transient_enabled), bit-identical.
def _ftrans_kind(opts: ODEOptions, backend: str, sharding=None) -> str:
    """Registry/cache kind string for the fused transient program
    (scan-chunked integration + Newton finish + packed diagnostics in
    ONE dispatch). Transients always run the full-f64 path (no tier
    tag -- the chunk/finish programs never resolve the tier either,
    PCL014), but the implicit ODE stages embed make_msolve direction
    solves, so the kernel tag applies."""
    return (f"ftrans:{opts!r}:{backend}"
            f"{_precision.kernel_tag()}{_sharding_tag(sharding)}")


def _packed_ftrans_kind(opts: ODEOptions, backend: str,
                        k_bucket: int) -> str:
    """Packed multi-tenant transient kind: the solo fused-transient
    kind plus the tenant-count pow2 sub-bucket tag, composed LAST so a
    ``k_bucket`` of 1 reproduces the solo kind byte-for-byte."""
    return (_ftrans_kind(opts, backend, None)
            + compile_pool.tenant_tag(k_bucket))


def _abi_transient_body(spec, opts: ODEOptions):
    """Module-level fused transient body over ONE tenant's inputs:
    ``program(ops, conds, save_ts) -> (ys, ok, bundle)``. Shared
    verbatim by the solo fused program and the packed multi-tenant
    program (which vmaps it over the tenant axis), so the per-tenant
    math is the SAME trace either way -- the packed bit-identity
    contract, exactly like :func:`_abi_fused_body`."""
    from ..solvers.newton import packed_sweep_diagnostics

    def program(ops, conds, save_ts):
        tspec = spec.bind(ops)

        def run_one(cond):
            return engine.transient(tspec, cond, save_ts, opts)

        ys, ok = jax.vmap(run_one)(conds)
        # Lanes whose endpoint is non-finite (NaN-poisoned inputs,
        # genuinely diverged integrations) are counted as quarantined
        # in the bundle; isolation is structural -- vmap lanes (and
        # stacked tenants) never mix values.
        finite = jnp.all(jnp.isfinite(ys[:, -1, :]), axis=-1)
        return ys, ok, packed_sweep_diagnostics(ok & finite, ~finite)

    return program


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _fused_transient_program(spec: ModelSpec, opts: ODEOptions,
                             kernel: str = "xla"):
    # ``kernel`` is a cache key only (kernel_keyed), exactly like the
    # chunk/finish programs: no tier knob reaches the transient trace.
    from ..solvers.newton import packed_sweep_diagnostics
    if isinstance(spec, _abi.AbiProgramSpec):
        return jax.jit(_abi_transient_body(spec, opts))

    def program(conds, save_ts):
        def run_one(cond):
            return engine.transient(spec, cond, save_ts, opts)
        ys, ok = jax.vmap(run_one)(conds)
        finite = jnp.all(jnp.isfinite(ys[:, -1, :]), axis=-1)
        return ys, ok, packed_sweep_diagnostics(ok & finite, ~finite)

    return jax.jit(program)


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _packed_transient_program(spec, opts: ODEOptions,
                              kernel: str = "xla"):
    """K tenants' fused transient bodies under ONE ``jax.vmap`` over
    the stacked operand/condition pytrees (the save grid is shared:
    the request coalescer groups transient requests by grid). The body
    is the module-level :func:`_abi_transient_body` -- the same trace
    as the solo program, which is what makes per-tenant results
    bitwise equal to solo runs. The REAL tenant count is not a cache
    key (vmap adapts to the leading axis length), so every k in a pow2
    sub-bucket shares one program."""
    return jax.jit(jax.vmap(_abi_transient_body(spec, opts),
                            in_axes=(0, 0, None)))


@hotpath
def _fused_batch_transient(spec: ModelSpec, conds: Conditions, save_ts,
                           opts: ODEOptions):
    """The fused-dispatch transient sweep: one device program scans the
    whole save grid (dense output assembled on device), applies the
    Newton finish and packs the per-lane verdicts; ONE counted host
    sync pulls (ys, ok, bundle) as a single batched transfer. Returns
    (ys [lanes, t, n_s], ok [lanes])."""
    n_lanes = jax.tree_util.tree_leaves(conds)[0].shape[0]
    backend = _resolve_backend()
    prog = _fused_transient_program(_prog_spec(spec), opts)
    kind = _ftrans_kind(opts, backend)
    ts = jnp.asarray(save_ts, dtype=jnp.float64)

    def run_fused():
        args = (conds, ts)
        fkey = compile_pool.program_key(kind, _prog_args(spec, args))
        out = _registered_call(spec, kind, prog, args)
        t0 = _time_mod.perf_counter()
        # The ONE materialization: dense output + ok + diagnostics as
        # a single batched device_get, inside the retried unit; its
        # blocked wall folds onto the fused program's ledger row
        # (count=0: _registered_call already counted the dispatch).
        ys, ok, bundle = host_sync(out, "fused transient bundle")
        _costs.note_dispatch(fkey, _time_mod.perf_counter() - t0,
                             count=0)
        return ys, ok, bundle

    with span("fused transient sweep", lanes=n_lanes,
              save_pts=len(save_ts)):
        ys, ok, bundle = call_with_backend_retry(
            run_fused, label="batched transient sweep")
    engine._transient_materialized(1)
    n_quar = int(bundle[1])
    if n_quar:
        record_event("degradation", label="transient:nonfinite",
                     detail="transient lanes with non-finite "
                            "endpoints", lanes=n_quar)
    return jnp.asarray(ys), jnp.asarray(ok)


def _warn_negative_tof(neg):
    neg = int(neg)
    if neg:
        import warnings
        # stacklevel=3: _warn_negative_tof <- sweep_steady_state <- user.
        warnings.warn(
            f"sweep_steady_state: net TOF is negative on {neg} lane(s) "
            "(selected steps run in reverse); 'activity' reports the "
            "|TOF| activity for those lanes. Inspect out['tof'] for "
            "signs.", stacklevel=3)


@lru_cache(maxsize=16)
def _tof_program(spec: ModelSpec):
    """One jitted program for (tof, activity, n_negative): everything
    derived from the solved states in a single dispatch (eager
    activity_from_tof on [lanes] cost ~1 s of per-op dispatch on the
    tunneled backend).

    ``ok`` is the per-lane good-lane mask (converged AND finite): the
    cross-lane reduction counts negatives only over good lanes, so one
    quarantined/unconverged lane cannot poison (NaN) or inflate the
    aggregate while every per-lane output stays untouched."""
    if isinstance(spec, _abi.AbiProgramSpec):
        def batched(ops, conds, ys, mask, ok):
            tspec = spec.bind(ops)
            tofs = jax.vmap(lambda c, y: engine.tof(tspec, c, y,
                                                    mask))(conds, ys)
            act = engine.activity_from_tof(
                tofs, jax.tree_util.tree_leaves(conds.T)[0])
            lane_ok = ok & jnp.isfinite(tofs)
            return tofs, act, jnp.sum(lane_ok & (tofs < 0.0))
        return jax.jit(batched)

    def batched(conds, ys, mask, ok):
        tofs = jax.vmap(lambda c, y: engine.tof(spec, c, y, mask))(conds,
                                                                   ys)
        act = engine.activity_from_tof(
            tofs, jax.tree_util.tree_leaves(conds.T)[0])
        lane_ok = ok & jnp.isfinite(tofs)
        return tofs, act, jnp.sum(lane_ok & (tofs < 0.0))
    return jax.jit(batched)


def stack_conditions(conds: list[Conditions]) -> Conditions:
    """Stack per-point Conditions into one lane-batched pytree."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *conds)


def broadcast_conditions(cond: Conditions, n: int) -> Conditions:
    """Repeat one condition n times along a new lane axis."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x),
                                   (n,) + jnp.asarray(x).shape), cond)


def make_mesh(n_devices: Optional[int] = None, axis: str = "lanes") -> Mesh:
    """1-D device mesh over the lane axis. Descriptor/condition lanes are
    the large, embarrassingly parallel axis of this domain (SURVEY.md
    §5.7-5.8) -- the honest TPU counterpart of data parallelism."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _pad_lanes(conds: Conditions, multiple: int):
    """Pad the lane axis to a device-count multiple (lanes are padded with
    copies of lane 0; callers slice the result back)."""
    n = jax.tree_util.tree_leaves(conds)[0].shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return conds, n
    def pad(x):
        return jnp.concatenate([x, jnp.repeat(x[:1], rem, axis=0)], axis=0)
    return jax.tree_util.tree_map(pad, conds), n


@hotpath
def batch_steady_state(spec: ModelSpec, conds: Conditions,
                       x0: Optional[jnp.ndarray] = None,
                       opts: SolverOptions = SolverOptions(),
                       mesh: Optional[Mesh] = None):
    """Solve the steady state of every lane in one device program.

    conds: lane-batched Conditions; x0: optional [lanes, n_dyn] initial
    guesses. With a mesh, lanes are sharded across devices.
    Returns a lane-batched SteadyStateResults.
    """
    low = _abi.maybe_lower(spec)
    if low is not None:
        out = batch_steady_state(low, low.pad_conditions(conds),
                                 x0=low.pad_x0(x0), opts=opts, mesh=mesh)
        return out._replace(x=low.unpad_y(jnp.asarray(out.x)))

    n_lanes = jax.tree_util.tree_leaves(conds)[0].shape[0]
    # Precision tier resolved at CALL time (like _resolve_backend) and
    # passed as an explicit cache-key parameter, never read inside a
    # cached builder. The f32-bulk pipeline only engages for
    # single-attempt pacing (the fast pass); other opts run f64 math
    # under a tier-tagged key.
    tier = _precision.active_tier()

    # Retry covers BOTH failure windows: the dispatch (this is the
    # LARGEST lazy compile of the sweep surface, so a dropped
    # remote-compile connection here costs the most to lose) and the
    # execution, which on the async backend only surfaces at a
    # materialization -- hence the one-scalar sync inside the retried
    # unit (~0.1 s round trip; downstream consumers materialize a
    # scalar off this result immediately anyway). The PRNG keys are
    # rebuilt inside the retried closures: the solve program donates
    # its key buffer, so a retry must never re-feed a consumed array.
    if mesh is None:
        prog = _steady_program(_prog_spec(spec), opts, tier=tier)
        kind = _steady_kind(opts, "ptc", tier=tier)

        def run_solve():
            keys = jax.random.split(jax.random.PRNGKey(0), n_lanes)
            out = _registered_call(spec, kind, prog, (conds, keys, x0))
            host_sync(jnp.sum(out.residual), "solve fence")
            return out

        with span("solve dispatch"):
            return call_with_backend_retry(run_solve,
                                           label="batched steady solve")

    n_dev = mesh.devices.size
    conds_p, n = _pad_lanes(conds, n_dev)
    x0_p = None
    if x0 is not None:
        x0_p, _ = _pad_lanes(x0, n_dev)
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    conds_p = jax.device_put(conds_p, sharding)
    if x0_p is not None:
        x0_p = jax.device_put(x0_p, sharding)
    prog_sh = _steady_program(_prog_spec(spec), opts, sharding,
                              tier=tier)
    # The mesh path consults the registry like every other dispatch:
    # program keys carry the per-argument sharding fingerprint
    # (compile_pool._shape_signature), so a serialized executable is
    # only matched by calls with the very mesh layout it baked in --
    # prewarm(mesh=...) publishes those, and single-device entries can
    # never be confused for them.
    kind_sh = _steady_kind(opts, "ptc", sharding, tier=tier)

    def run_solve_sharded():
        keys = jax.random.split(jax.random.PRNGKey(0), n_lanes)
        keys_p, _ = _pad_lanes(keys, n_dev)
        keys_p = jax.device_put(keys_p, sharding)
        out = _registered_call(spec, kind_sh, prog_sh,
                               (conds_p, keys_p, x0_p))
        host_sync(jnp.sum(out.residual), "solve fence (sharded)")
        return out

    with span("solve dispatch"):
        out = call_with_backend_retry(
            run_solve_sharded, label="batched steady solve (sharded)")
    if n == jax.tree_util.tree_leaves(conds_p)[0].shape[0]:
        return out
    return jax.tree_util.tree_map(lambda x: x[:n], out)


def batch_transient(spec: ModelSpec, conds: Conditions, save_ts,
                    opts: ODEOptions = ODEOptions(),
                    mesh: Optional[Mesh] = None, chunk: int = 8):
    """Integrate every lane's transient, the save grid chunked into
    bounded device calls driven from the host (one compiled program per
    chunk shape; a single monolithic kernel integrating hundreds of
    intervals for the slowest lane can run for minutes and trip
    execution watchdogs on shared TPU runtimes).
    Returns (ys [lanes, t, n_s], ok [lanes])."""
    low = _abi.maybe_lower(spec)
    if low is not None:
        ys, ok = batch_transient(low, low.pad_conditions(conds), save_ts,
                                 opts=opts, mesh=mesh, chunk=chunk)
        return low.unpad_y(ys), ok

    if mesh is None and engine.fused_transient_enabled():
        # Fused one-dispatch path (docs/perf_transient.md): the scan
        # over the save grid runs inside one traced program instead of
        # the host chunk loop below -- bit-identical output, one
        # counted sync. Disabled by PYCATKIN_FUSED_TRANSIENT=0 and
        # under active fault plans (the fault sites live on the
        # chunked path).
        return _fused_batch_transient(spec, conds, save_ts, opts)

    n = None
    if mesh is not None:
        n_dev = mesh.devices.size
        conds, n = _pad_lanes(conds, n_dev)
        axis = mesh.axis_names[0]
        conds = jax.device_put(conds, NamedSharding(mesh, P(axis)))

    cprog = _transient_chunk_program(_prog_spec(spec), opts)
    fprog = _transient_finish_program(_prog_spec(spec),
                                      engine.finish_options(opts))
    if isinstance(spec, _abi.AbiLowered):
        # The chunk driver calls the programs with legacy signatures;
        # bake the operand pytree in as the leading argument here.
        ops = spec.operands()
        cprog, fprog = partial(cprog, ops), partial(fprog, ops)

    ys, ok = engine.chunked_transient_drive(
        cprog, fprog,
        conds, jnp.asarray(conds.y0, dtype=jnp.float64), save_ts, opts,
        chunk, batched=True)
    if n is not None:
        return ys[:n], ok[:n]
    return ys, ok


@hotpath
def packed_batch_transient(specs, conds, save_ts,
                           opts: ODEOptions = ODEOptions(),
                           chunk: int = 8) -> list:
    """Multi-tenant :func:`batch_transient`: K mechanisms that lower
    into ONE ABI bucket integrate as one packed device dispatch (one
    host sync, one AOT executable, zero marginal compiles in a warm
    bucket) and return a LIST of per-tenant ``(ys, ok)`` pairs, each
    bitwise identical to that mechanism's solo ``batch_transient``
    call. The save grid is shared across tenants (the request
    coalescer groups transient requests by grid, so its packs satisfy
    this by construction).

    Degradations mirror :func:`packed_sweep_steady_state`: a single
    tenant, the ABI gate off / no bucket fit, or the fused transient
    disabled (``PYCATKIN_FUSED_TRANSIENT=0``, active fault plan) fall
    back to per-tenant solo runs with a ``degradation`` event;
    cross-bucket tenants raise :class:`frontend.abi.AbiBucketError`."""
    specs = list(specs)
    k = len(specs)
    if k == 0:
        return []

    def _per_tenant(v, name):
        vs = (list(v) if isinstance(v, (list, tuple)) else [v] * k)
        if len(vs) != k:
            raise ValueError(f"{name}: {len(vs)} entries for {k} "
                             f"tenants")
        return vs

    conds_list = _per_tenant(conds, "conds")

    def _solo():
        return [batch_transient(s, c, save_ts, opts=opts, chunk=chunk)
                for s, c in zip(specs, conds_list)]

    if k == 1:
        # Degenerate pack: the solo path, so program keys/caches stay
        # byte-identical to the solo world (:tK contract).
        return _solo()
    lows = [s if isinstance(s, _abi.AbiLowered) else _abi.maybe_lower(s)
            for s in specs]
    if any(low is None for low in lows) or \
            not engine.fused_transient_enabled():
        record_event("degradation", label="packed:solo-fallback",
                     detail="ABI lowering or the fused transient is "
                            "unavailable; running tenants as solo "
                            "transients", tenants=k)
        _metrics.counter(
            "pycatkin_packed_solo_fallbacks_total",
            "packed sweep requests degraded to per-tenant solo "
            "sweeps").inc()
        return _solo()
    pack = _abi.pack_lowered(lows)
    lanes = [jax.tree_util.tree_leaves(c)[0].shape[0]
             for c in conds_list]
    if len(set(lanes)) != 1:
        raise ValueError(f"packed tenants must share a lane count, "
                         f"got {lanes}")
    n_lanes = lanes[0]
    kb = pack.k_bucket
    backend = _resolve_backend()
    _metrics.counter(
        "pycatkin_packed_transient_sweeps_total",
        "packed multi-tenant transient dispatches per tenant "
        "sub-bucket").inc(bucket=pack.abi_fingerprint)
    conds_st = pack.stack_tenants(
        [low.pad_conditions(c) for low, c in zip(lows, conds_list)])
    prog = _packed_transient_program(pack.program_spec, opts)
    kind = _packed_ftrans_kind(opts, backend, kb)
    ts = jnp.asarray(save_ts, dtype=jnp.float64)

    def run_packed():
        args = (conds_st, ts)
        fkey = compile_pool.program_key(kind, _prog_args(pack, args))
        _costs.record(fkey, kind=kind,
                      label=f"packed transient @{n_lanes}"
                            f" x{pack.k}/{kb}")
        out = _registered_call(pack, kind, prog, args)
        t0 = _time_mod.perf_counter()
        ys, ok, bundle = host_sync(out, "packed transient bundle")
        _costs.note_dispatch(fkey, _time_mod.perf_counter() - t0,
                             count=0)
        return ys, ok, bundle

    with span("packed transient sweep", tenants=pack.k, k_bucket=kb,
              lanes=n_lanes):
        ys, ok, bundle = call_with_backend_retry(
            run_packed, label="packed batched transient")
    engine._transient_materialized(1)
    n_quar = int(np.sum(bundle[:pack.k, 1]))
    if n_quar:
        record_event("degradation", label="transient:nonfinite",
                     detail="transient lanes with non-finite "
                            "endpoints", lanes=n_quar)
    return [(low.unpad_y(jnp.asarray(ys[i])), jnp.asarray(ok[i]))
            for i, low in enumerate(pack.tenants)]


def prewarm_transient_programs(spec, conds, save_ts,
                               opts: ODEOptions = ODEOptions(),
                               k_buckets=(), cache=None):
    """Load-or-compile the fused transient executables a
    :func:`batch_transient` / :func:`packed_batch_transient` call over
    these inputs would dispatch (registry + AOT cache, no execution):
    the solo fused program, plus one packed program per tenant bucket
    in ``k_buckets``. Transient programs key on the save-grid LENGTH
    (shape), not its values, so warming with any same-length grid
    covers every request on that grid size. No-op (empty stats) when
    the fused transient is disabled -- the chunked fallback's
    chunk/finish programs compile lazily per chunk shape and are not
    AOT-managed. Returns :class:`PrewarmStats`."""
    stats = PrewarmStats(0)
    stats.compiled = stats.loaded = stats.executed = 0
    stats.cache_writes = 0
    stats.cache = {}
    if not engine.fused_transient_enabled():
        return stats
    low = (spec if isinstance(spec, _abi.AbiLowered)
           else _abi.maybe_lower(spec))
    spec_l = low if low is not None else spec
    conds_l = low.pad_conditions(conds) if low is not None else conds
    if cache is None:
        cache = compile_pool.AOTCache(
            fingerprint=compile_pool.spec_fingerprint(
                _prog_spec(spec_l)))
    elif cache is False:
        cache = compile_pool.AOTCache(root="off")
    backend = _resolve_backend()
    ts = jnp.asarray(save_ts, dtype=jnp.float64)
    n_lanes = jax.tree_util.tree_leaves(conds_l)[0].shape[0]

    jobs = [(spec_l, _prog_spec(spec_l), _ftrans_kind(opts, backend),
             _fused_transient_program(_prog_spec(spec_l), opts),
             (conds_l, ts), f"fused transient @{n_lanes}")]
    if low is not None:
        for kraw in sorted({int(x) for x in k_buckets if int(x) > 1}):
            pk = _abi.pack_lowered([low] * kraw)
            jobs.append(
                (pk, pk.program_spec,
                 _packed_ftrans_kind(opts, backend, pk.k_bucket),
                 _packed_transient_program(pk.program_spec, opts),
                 (pk.stack_tenants([conds_l] * kraw), ts),
                 f"packed transient @{n_lanes} x{pk.k_bucket}"))

    for holder, pspec, kind, prog, argt, label in jobs:
        args = _prog_args(holder, argt)
        key = compile_pool.program_key(kind, args)
        # Per-program transient row in the cost ledger, stamped at
        # prewarm like the sweep programs.
        _costs.record(key, kind=kind, label=label)
        if compile_pool.lookup(pspec, key) is not None:
            stats.loaded += 1
            continue
        exe = None
        try:
            exe = cache.load(key)
        except compile_pool.CacheMismatch:
            exe = None
        if exe is not None:
            compile_pool.register(pspec, key, exe)
            stats.loaded += 1
            continue
        _san_recompile.note_compile(label)
        _san_trace_ident.note_jaxpr(kind, key, prog, args, force=True)
        exe = call_with_backend_retry(
            lambda prog=prog, args=args: prog.lower(*args).compile(),
            label=f"compile:{label}")
        _metrics.counter("pycatkin_compile_total",
                         "fresh XLA compiles through the compile "
                         "pool").inc()
        cache.save(key, exe,
                   sharding=compile_pool.args_sharding_fingerprint(
                       args))
        _costs.record(key, kind=kind, cost=_costs.harvest_cost(exe),
                      source="compiled")
        compile_pool.register(pspec, key, exe)
        stats.compiled += 1
    out = PrewarmStats(len(jobs))
    out.compiled, out.loaded = stats.compiled, stats.loaded
    out.executed = 0
    out.cache_writes = cache.writes
    out.cache = cache.stats()
    return out


@lru_cache(maxsize=16)
def _jacobian_program(spec: ModelSpec):
    if isinstance(spec, _abi.AbiProgramSpec):
        def program(ops, conds, ys):
            tspec = spec.bind(ops)
            dyn = tspec.dynamic_indices

            def jac_one(cond, y):
                return engine.steady_jacobian(tspec, cond, y[dyn])
            return jax.vmap(jac_one)(conds, ys)
        return jax.jit(program)

    dyn = jnp.asarray(spec.dynamic_indices)

    def jac_one(cond, y):
        return engine.steady_jacobian(spec, cond, y[dyn])

    return jax.jit(jax.vmap(jac_one))


def _resolve_backend(backend=None, mesh: Optional[Mesh] = None) -> str:
    """Concrete backend/platform string for certificate-margin
    selection: an explicit ``backend`` wins, else the mesh's devices'
    platform, else ``jax.default_backend()`` read NOW (call time --
    never baked into a cached program at trace time, ADVICE r5)."""
    if backend is not None:
        return str(backend)
    if mesh is not None:
        return mesh.devices.flat[0].platform
    return jax.default_backend()


@lru_cache(maxsize=16)
def _stability_screen_program(spec: ModelSpec, pos_tol: float,
                              backend: str = "cpu"):
    """Device-side Gershgorin stability certificate + verdict assembly.

    For any (real or complex) eigenvalue of J, Re(lambda) is bounded by
    the Gershgorin row bound max_i(J_ii + sum_{j!=i}|J_ij|), and -- via
    J^T having the same spectrum -- by the column bound. The per-lane
    bound, the scale-aware threshold
    (solvers.newton.stability_tolerance_from_scale on max|J|) and the
    certified/ambiguous combination ALL live in this one jitted program
    (eager per-op dispatch is expensive on the tunneled backend), so
    one call returns (certified [lanes], ambiguous [lanes],
    n_ambiguous scalar).

    Both certificates are SOUND one-way: passing proves stability;
    failing proves nothing. Two device tiers run in the same program:

    - Gershgorin (row AND column discs): free, but hopeless for stiff
      kinetics Jacobians -- the conservation-null eigenvalue sits at
      ~0 inside a disc of radius ~||J||; measured on the 256x256 COOx
      volcano it clears ~0.1 % of lanes.
    - Deflated Lyapunov witness
      (:func:`solvers.newton.lyapunov_certified_stable`): deflates the
      exact conservation nullspace, then constructs and CHECKS a
      Lyapunov certificate per lane (an m^2 x m^2 solve, m = deflated
      dimension -- 3 for the volcano). Clears ~99 % of volcano lanes
      (Higham-margin residual bound); skipped when m >
      LYAPUNOV_MAX_DIM.

    Only the remaining ambiguous lanes pay a host nonsymmetric-eig
    solve (XLA has none on TPU).

    ``backend`` is part of the cache key: the Lyapunov certificate's
    error margin tracks the EXECUTING backend's unit roundoff, so the
    caller that owns the mesh/devices resolves it
    (:func:`_resolve_backend`) before the cache lookup -- a cached
    program can never bake in a stale ``jax.default_backend()``
    choice."""
    from ..solvers.newton import (LYAPUNOV_MAX_DIM,
                                  deflation_basis_for_spec,
                                  effective_unit_roundoff,
                                  lyapunov_certified_stable,
                                  stability_tolerance_from_scale)

    eps_eff = effective_unit_roundoff(jnp.float64, backend)

    if isinstance(spec, _abi.AbiProgramSpec):
        # ABI form: the deflation basis is the traced lyap_q operand
        # ([D, LYAP_PAD], real basis embedded + exact unit columns on
        # pad slots), so the certificate shape is bucket-static. When a
        # mechanism's deflated dimension cannot be represented (m == 0,
        # m > LYAP_PAD, or too few pad slots) its lyap_ok operand is 0
        # and the Lyapunov tier soundly abstains for every lane --
        # those lanes fall through to tier 2 exactly like a
        # Gershgorin-only legacy program.
        def batched(ops, conds, ys, ok):
            tspec = spec.bind(ops)
            dyn = tspec.dynamic_indices
            Q = tspec.lyap_q
            lyap_ok = tspec.lyap_ok > 0

            def screen_one(cond, y):
                J = engine.steady_jacobian(tspec, cond, y[dyn])
                absJ = jnp.abs(J)
                diag = jnp.diag(J)
                offrow = jnp.sum(absJ, axis=1) - jnp.abs(diag)
                offcol = jnp.sum(absJ, axis=0) - jnp.abs(diag)
                bound = jnp.minimum(jnp.max(diag + offrow),
                                    jnp.max(diag + offcol))
                scale = jnp.max(absJ)
                finite = jnp.all(jnp.isfinite(J))
                tol = stability_tolerance_from_scale(scale, pos_tol)
                cert = finite & (bound <= tol)
                cert = cert | (finite & lyap_ok & lyapunov_certified_stable(
                    J, Q, tol, eps_eff=eps_eff))
                return cert, finite

            cert, finite = jax.vmap(screen_one)(conds, ys)
            good = finite & ok
            certified = good & cert
            ambiguous = good & ~certified
            return certified, ambiguous, jnp.sum(ambiguous)

        return jax.jit(batched)

    dyn = jnp.asarray(spec.dynamic_indices)
    Q = deflation_basis_for_spec(spec)       # static per spec
    # m == 0 (all-conservation spectrum) has nothing to certify and
    # would crash the kernel's empty reductions at trace time.
    use_lyap = 0 < Q.shape[1] <= LYAPUNOV_MAX_DIM

    def screen_one(cond, y):
        J = engine.steady_jacobian(spec, cond, y[dyn])
        absJ = jnp.abs(J)
        diag = jnp.diag(J)
        offrow = jnp.sum(absJ, axis=1) - jnp.abs(diag)
        offcol = jnp.sum(absJ, axis=0) - jnp.abs(diag)
        bound = jnp.minimum(jnp.max(diag + offrow), jnp.max(diag + offcol))
        scale = jnp.max(absJ)
        finite = jnp.all(jnp.isfinite(J))
        tol = stability_tolerance_from_scale(scale, pos_tol)
        cert = finite & (bound <= tol)
        if use_lyap:
            cert = cert | (finite & lyapunov_certified_stable(
                J, Q, tol, eps_eff=eps_eff))
        return cert, finite

    def batched(conds, ys, ok):
        cert, finite = jax.vmap(screen_one)(conds, ys)
        good = finite & ok
        certified = good & cert
        ambiguous = good & ~certified
        return certified, ambiguous, jnp.sum(ambiguous)

    return jax.jit(batched)


def _abi_fused_body(spec: "_abi.AbiProgramSpec", opts: SolverOptions,
                    pos_tol: float, backend: str, has_tof: bool,
                    check_stability: bool, tier: str):
    """The traceable body of the ABI fused sweep program --
    ``program(ops, conds, keys, x0, *tail)`` over one mechanism's
    operand pytree and one ``[lanes]`` batch. Shared VERBATIM by the
    solo jit (:func:`_fused_sweep_program`'s ABI branch) and the
    tenant-vmapped packed jit (:func:`_packed_fused_sweep_program`), so
    a packed tenant runs the exact same trace as its solo sweep -- the
    bit-identity contract of tests/test_packed_batching.py hangs on
    this function having exactly one definition."""
    tier_code = _precision.TIER_CODES[tier]
    from ..solvers.newton import (effective_unit_roundoff,
                                  lane_finite_mask,
                                  lyapunov_certified_stable,
                                  packed_lane_telemetry,
                                  packed_sweep_diagnostics,
                                  stability_tolerance_from_scale)
    eps_eff = (effective_unit_roundoff(jnp.float64, backend)
               if check_stability else None)

    def program(ops, conds, keys, x0, *tail_args):
        tspec = spec.bind(ops)
        dyn = tspec.dynamic_indices

        def solve_one(cond, key, x0):
            return engine.steady_state(tspec, cond, x0=x0, key=key,
                                       opts=opts, strategy="ptc",
                                       tier=tier)

        res = jax.vmap(solve_one)(conds, keys, x0)
        finite_l = lane_finite_mask(res.x, res.residual)
        succ_raw = jnp.asarray(res.success)
        quar = succ_raw & ~finite_l
        succ0 = succ_raw & finite_l
        res = res._replace(success=succ0)
        outs = [res, quar]
        amb = demoted = None
        ok_spec = succ0
        if check_stability:
            Q = tspec.lyap_q
            lyap_ok = tspec.lyap_ok > 0

            def screen_one(cond, y):
                J = engine.steady_jacobian(tspec, cond, y[dyn])
                absJ = jnp.abs(J)
                diag = jnp.diag(J)
                offrow = jnp.sum(absJ, axis=1) - jnp.abs(diag)
                offcol = jnp.sum(absJ, axis=0) - jnp.abs(diag)
                bound = jnp.minimum(jnp.max(diag + offrow),
                                    jnp.max(diag + offcol))
                scale = jnp.max(absJ)
                finite = jnp.all(jnp.isfinite(J))
                tol = stability_tolerance_from_scale(scale, pos_tol)
                cert = finite & (bound <= tol)
                cert = cert | (finite & lyap_ok
                               & lyapunov_certified_stable(
                                   J, Q, tol, eps_eff=eps_eff))
                return cert, finite

            cert_raw, finite = jax.vmap(screen_one)(conds, res.x)
            good = finite & succ0
            cert = good & cert_raw
            amb = good & ~cert
            demoted = succ0 & ~cert
            ok_spec = succ0 & cert
            outs += [cert, amb]
        n_neg = None
        if has_tof:
            mask = tail_args[0]
            tofs = jax.vmap(
                lambda c, y: engine.tof(tspec, c, y, mask))(conds,
                                                            res.x)
            act = engine.activity_from_tof(
                tofs, jax.tree_util.tree_leaves(conds.T)[0])
            neg = jnp.isfinite(tofs) & (tofs < 0.0)
            lane_ok = ok_spec & jnp.isfinite(tofs)
            n_neg = jnp.sum(lane_ok & (tofs < 0.0))
            outs += [tofs, act, neg]
        # Packed per-lane telemetry (iterations/chords/residual
        # decade/strategy/tier) rides as the second-to-last output,
        # so the clean tail syncs it in the SAME batched device_get
        # as the diagnostics bundle -- sync count unchanged. The
        # tier column stamps lanes the first pass ACCEPTED (the
        # rescue ladder that rewrites the rest is always f64).
        outs.append(packed_lane_telemetry(
            res.iterations, res.chords, res.residual,
            tier=jnp.where(succ0, jnp.int32(tier_code),
                           jnp.int32(0))))
        outs.append(packed_sweep_diagnostics(succ0, quar, amb,
                                             demoted, n_neg))
        return tuple(outs)

    return program


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _packed_fused_sweep_program(spec: "_abi.AbiProgramSpec",
                                opts: SolverOptions, pos_tol: float,
                                backend: str, has_tof: bool,
                                check_stability: bool,
                                tier: str = "f64",
                                kernel: str = "xla"):
    """The multi-tenant fused sweep: :func:`_abi_fused_body` vmapped
    over a new leading *tenant* axis, so K same-bucket mechanisms'
    sweeps are ONE device dispatch producing the solo output tuple with
    every element stacked ``[k_bucket, ...]`` (the diagnostics bundle
    becomes ``[k_bucket, 5]`` -- per-tenant escalation verdicts from
    one sync).

    The tenant count is deliberately NOT a cache key here: one jitted
    callable serves every occupancy, and XLA specializes per stacked
    shape exactly as it does per lane count. Registry/AOT keys still
    separate occupancies through the ``:tK`` kind tag + the argument
    shape signature (:func:`compile_pool.tenant_tag`). Only the PRNG
    keys are donated, mirroring the solo program."""
    body = _abi_fused_body(spec, opts, pos_tol, backend, has_tof,
                           check_stability, tier)
    return jax.jit(jax.vmap(body),
                   donate_argnums=_donate_argnums((2,)))


@_precision.kernel_keyed
@lru_cache(maxsize=16)
def _fused_sweep_program(spec: ModelSpec, opts: SolverOptions,
                         pos_tol: float, backend: str, has_tof: bool,
                         check_stability: bool, out_sharding=None,
                         tier: str = "f64", kernel: str = "xla"):
    """The whole clean sweep as ONE device program: batched steady
    solve, per-lane NaN quarantine, tier-0 stability certificate
    (Gershgorin + deflated-Lyapunov -- byte-identical math to
    :func:`_stability_screen_program`), TOF/activity, and the packed
    int32 diagnostics bundle. A clean 65,536-lane stability-screened
    volcano sweep is one dispatch + one host sync (the bundle);
    anything ambiguous escalates OUTSIDE this program
    (:func:`_fused_sweep`).

    Output tuple, in order: ``res`` (SteadyStateResults, success
    already quarantine-demoted), ``quar`` [lanes]; with
    ``check_stability``: ``cert`` [lanes] (certified stable),
    ``amb`` [lanes] (converged+finite but uncertified); with
    ``has_tof``: ``tofs`` [lanes], ``act`` [lanes], ``neg`` [lanes]
    (finite-and-negative TOF -- per-lane so the escalation path can
    recount negatives host-side without a second TOF dispatch); always
    last: the packed diagnostics bundle
    (:func:`solvers.newton.packed_sweep_diagnostics`).

    ``opts`` must be the fast-pass options and ``backend`` the
    resolved executing platform (see :func:`_stability_screen_program`
    on why backend is a cache key). Only the PRNG keys are donated
    (conds/x0 are caller-owned).

    ``tier`` (explicit cache key, mirroring :func:`_steady_program`)
    selects the precision tier the bulk Newton/PTC march runs in
    (engine.steady_state's f32-bulk + f64-polish pipeline under
    ``f32-polish``); the quarantine demotion, the tier-0 stability
    certificate and every verdict threshold below stay f64 REGARDLESS
    of tier -- that is the acceptance contract
    (docs/perf_precision_tiers.md). The lane-telemetry pack's 5th
    column records the tier that produced each accepted iterate."""
    tier_code = _precision.TIER_CODES[tier]
    from ..solvers.newton import (LYAPUNOV_MAX_DIM,
                                  deflation_basis_for_spec,
                                  effective_unit_roundoff,
                                  lane_finite_mask,
                                  lyapunov_certified_stable,
                                  packed_lane_telemetry,
                                  packed_sweep_diagnostics,
                                  stability_tolerance_from_scale)

    if isinstance(spec, _abi.AbiProgramSpec):
        # ABI form: one fused executable per bucket; the mechanism is
        # the leading traced operand pytree. Same output tuple, same
        # tier-0 math -- the screen's deflation basis comes from the
        # traced lyap_q/lyap_ok operands (see
        # _stability_screen_program's ABI branch for the abstention
        # semantics).
        program = _abi_fused_body(spec, opts, pos_tol, backend, has_tof,
                                  check_stability, tier)
        kw = {"donate_argnums": _donate_argnums((2,))}
        if out_sharding is not None:
            # 3 = res + quar + the [lanes, 5] telemetry pack.
            n_lane_outs = 3 + (2 if check_stability else 0) \
                + (3 if has_tof else 0)
            repl = NamedSharding(out_sharding.mesh, P())
            kw["out_shardings"] = (out_sharding,) * n_lane_outs + (repl,)
        return jax.jit(program, **kw)

    dyn = jnp.asarray(spec.dynamic_indices)

    def solve_one(cond, key, x0):
        return engine.steady_state(spec, cond, x0=x0, key=key, opts=opts,
                                   strategy="ptc", tier=tier)

    if check_stability:
        eps_eff = effective_unit_roundoff(jnp.float64, backend)
        Q = deflation_basis_for_spec(spec)       # static per spec
        use_lyap = 0 < Q.shape[1] <= LYAPUNOV_MAX_DIM

        def screen_one(cond, y):
            # EXACTLY _stability_screen_program's tier-0 body: the
            # equivalence corpus (tests/test_tiered_screen.py) pins
            # the fused verdicts bitwise against the standalone
            # screen's, so any drift here is a test failure.
            J = engine.steady_jacobian(spec, cond, y[dyn])
            absJ = jnp.abs(J)
            diag = jnp.diag(J)
            offrow = jnp.sum(absJ, axis=1) - jnp.abs(diag)
            offcol = jnp.sum(absJ, axis=0) - jnp.abs(diag)
            bound = jnp.minimum(jnp.max(diag + offrow),
                                jnp.max(diag + offcol))
            scale = jnp.max(absJ)
            finite = jnp.all(jnp.isfinite(J))
            tol = stability_tolerance_from_scale(scale, pos_tol)
            cert = finite & (bound <= tol)
            if use_lyap:
                cert = cert | (finite & lyapunov_certified_stable(
                    J, Q, tol, eps_eff=eps_eff))
            return cert, finite

    def program(conds, keys, x0, *tail_args):
        res = jax.vmap(solve_one)(conds, keys, x0)
        # Quarantine demotion IN-PROGRAM (same math as
        # _quarantine_mask): flagged-converged lanes whose stored
        # solution/residual is non-finite are poisoned results.
        finite_l = lane_finite_mask(res.x, res.residual)
        succ_raw = jnp.asarray(res.success)
        quar = succ_raw & ~finite_l
        succ0 = succ_raw & finite_l
        res = res._replace(success=succ0)
        outs = [res, quar]
        amb = demoted = None
        ok_spec = succ0
        if check_stability:
            cert_raw, finite = jax.vmap(screen_one)(conds, res.x)
            good = finite & succ0
            cert = good & cert_raw
            amb = good & ~cert
            demoted = succ0 & ~cert
            ok_spec = succ0 & cert
            outs += [cert, amb]
        n_neg = None
        if has_tof:
            mask = tail_args[0]
            tofs = jax.vmap(lambda c, y: engine.tof(spec, c, y, mask))(
                conds, res.x)
            act = engine.activity_from_tof(
                tofs, jax.tree_util.tree_leaves(conds.T)[0])
            neg = jnp.isfinite(tofs) & (tofs < 0.0)
            lane_ok = ok_spec & jnp.isfinite(tofs)
            n_neg = jnp.sum(lane_ok & (tofs < 0.0))
            outs += [tofs, act, neg]
        # Same second-to-last telemetry slot as the ABI branch (the
        # clean tail's single batched sync depends on the ordering).
        outs.append(packed_lane_telemetry(
            res.iterations, res.chords, res.residual,
            tier=jnp.where(succ0, jnp.int32(tier_code), jnp.int32(0))))
        outs.append(packed_sweep_diagnostics(succ0, quar, amb, demoted,
                                             n_neg))
        return tuple(outs)

    kw = {"donate_argnums": _donate_argnums((1,))}
    if out_sharding is not None:
        # out_shardings is a pytree PREFIX over the output tuple: one
        # sharding per top-level element (the SteadyStateResults
        # subtree takes the lane sharding wholesale; the scalar bundle
        # is replicated). 3 = res + quar + the [lanes, 5] telemetry.
        n_lane_outs = 3 + (2 if check_stability else 0) \
            + (3 if has_tof else 0)
        repl = NamedSharding(out_sharding.mesh, P())
        kw["out_shardings"] = (out_sharding,) * n_lane_outs + (repl,)
    return jax.jit(program, **kw)


def _padded_subset(conds: Conditions, idx: np.ndarray, arrays=(),
                   bucket: int = 64):
    """Gather lanes ``idx`` of a Conditions pytree (plus companion
    arrays), padded with repeats of idx[0] to the next POWER OF TWO at
    or above ``bucket``: vmapped programs compile per subset SHAPE, and
    variable counts would otherwise pay a fresh multi-second XLA
    compile each time (shared by the rescue passes and the stability
    tier 2). Powers of two bound the universe of shapes to ~10 for any
    grid, so trials/retries with drifting counts reuse warm programs
    (a plain multiple-of-64 padding recompiled on nearly every count
    change -- measured as ~8 s per timed volcano trial)."""
    target = max(bucket, 1 << (max(len(idx), 1) - 1).bit_length())
    n_pad = target - len(idx)
    idx_p = np.concatenate([idx, np.repeat(idx[:1], n_pad)])
    sub = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[idx_p], conds)
    return (sub, idx_p) + tuple(jnp.asarray(a)[idx_p] for a in arrays)


def _subset_sharding(mesh: Optional[Mesh], n_sub: int):
    """Lane sharding for a gathered subset when the mesh divides it
    evenly, else None (single-device placement)."""
    if mesh is None or n_sub % mesh.devices.size != 0:
        return None
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def _place_subset(mesh: Optional[Mesh], n_sub: int, *trees):
    """Deterministic device placement for gathered subset pytrees.
    Gathering from a SHARDED parent leaves the output layout -- hence
    the program-key sharding fingerprint -- to the compiler's whim;
    pinning it makes the hot path hit the very executables prewarm
    registered. Lane-shard across the mesh when the subset divides it,
    else commit to one device (fingerprints as unsharded). With no
    mesh the inputs pass through untouched -- the unsharded path stays
    byte-identical to its pre-mesh behavior."""
    if mesh is None:
        return trees if len(trees) > 1 else trees[0]
    sh = _subset_sharding(mesh, n_sub)
    tgt = sh if sh is not None else jax.devices()[0]
    placed = tuple(jax.device_put(t, tgt) for t in trees)
    return placed if len(placed) > 1 else placed[0]


@hotpath
def stability_mask(spec: ModelSpec, conds: Conditions, ys,
                   pos_tol: float = 1e-2, ok=None,
                   backend: Optional[str] = None,
                   precomputed=None,
                   mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """[lanes] Jacobian-eigenvalue stability verdict (reference
    solver.py:102-106) for batched steady solutions, two-tier:

    1. On-device certificates (one program): Gershgorin discs (cheap,
       but nearly useless for stiff kinetics -- measured ~0.1 % of
       volcano lanes) plus the deflated-Lyapunov witness
       (:func:`solvers.newton.lyapunov_certified_stable`, ~99 % of
       volcano lanes). Certified lanes are stable, full stop; the only
       mandatory host traffic is ONE scalar (the ambiguous count).
    2. Host ``numpy.linalg.eigvals`` on the AMBIGUOUS subset only (the
       certificates are one-sided; XLA ships no nonsymmetric eig on
       TPU).

    Both tiers use the :func:`solvers.newton.stability_tolerance_from_scale`
    formula, so the verdict matches the all-host implementation exactly
    on lanes where the certificates abstain, and can only differ by
    declaring a lane stable that the host eig ALSO declares stable
    (both certificates are sound one-way proofs).

    ``ok``: optional [lanes] convergence mask -- non-converged or
    non-finite lanes are reported unstable without entering the
    eigenvalue solve. ``backend``: platform of the devices the screen
    actually runs on (certificate margins are backend-dependent; the
    caller that owns the mesh passes it -- None reads the default
    backend at call time). ``precomputed``: an already-dispatched
    ``(certified, ambiguous, n_ambiguous)`` triple from the SAME screen
    program on the SAME ``ys``/``ok`` (the fused sweep tail's
    speculative screen) -- skips re-running tier 1. ``mesh``: lane mesh
    of a sharded sweep -- the tier-2 Jacobian subset is re-placed on it
    (lane-sharded) so the prewarmed sharded jac program is hit instead
    of compiling an unsharded twin in-band. Returns a DEVICE bool
    array.
    """
    ys = jnp.asarray(ys)
    n = ys.shape[0]
    ok_dev = (jnp.asarray(ok).astype(bool) if ok is not None
              else jnp.ones(n, dtype=bool))
    backend = _resolve_backend(backend, mesh)
    if precomputed is not None:
        certified, ambiguous, n_amb = precomputed
        n_amb = int(n_amb)
    else:
        def run_screen():
            # Dispatch AND the scalar materialization inside one
            # retried unit: on the async backend an execution-time
            # transport flake surfaces at the materialization, so
            # retrying only the dispatch would not re-run the program.
            cert, amb, n_amb_dev = _registered_call(
                spec, _screen_kind(pos_tol, backend),
                _stability_screen_program(_prog_spec(spec), pos_tol,
                                          backend),
                (conds, ys, ok_dev))
            # scalar round trip
            return cert, amb, int(host_sync(n_amb_dev,
                                            "stability screen"))

        with span("stability screen"):
            certified, ambiguous, n_amb = call_with_backend_retry(
                run_screen, label="stability screen")
    if n_amb:
        idx = np.flatnonzero(np.asarray(ambiguous))  # sync-ok: tier-2 failure path
        out = _stability_tier2(spec, conds, ys, idx,
                               np.array(certified),  # sync-ok: tier-2 failure path, writable host copy
                               pos_tol, mesh=mesh)
        return jnp.asarray(out)
    return certified


@hotpath
def _stability_tier2(spec: ModelSpec, conds: Conditions, ys,
                     idx: np.ndarray, certified_host: np.ndarray,
                     pos_tol: float,
                     mesh: Optional[Mesh] = None) -> np.ndarray:
    """Tier-2 host eigensolve over the ambiguous lanes ``idx``: batch
    the subset Jacobians on device (padded to the TIER2_MIN_BUCKET
    floor so drifting ambiguous counts share ONE compiled shape),
    ``numpy.linalg.eigvals`` on the host, and merge the verdicts into
    the writable ``certified_host`` copy. Shared by
    :func:`stability_mask` (the legacy two-tier path) and the fused
    sweep's escalation branch (:func:`_fused_sweep`) so their verdicts
    cannot drift. Returns the merged host bool array [lanes]."""
    from ..solvers.newton import stability_tolerance
    ys = jnp.asarray(ys)
    # Ambiguous counts drift trial to trial; the TIER2_MIN_BUCKET
    # floor collapses every sub-512 count onto ONE compiled shape
    # (pads are sliced off on device before the host transfer).
    sub, idx_p, ys_p = _padded_subset(conds, idx, (ys,),
                                      bucket=TIER2_MIN_BUCKET)
    sub, ys_p = _place_subset(mesh, len(idx_p), sub, ys_p)

    # Slice the pad off ON DEVICE: the padded lanes' Jacobians must
    # never cross the ~11 MB/s tunnel (pow2 padding can nearly
    # double the payload).
    def run_jac():
        return host_sync(
            _registered_call(spec, "jac",
                             _jacobian_program(_prog_spec(spec)),
                             (sub, ys_p))[:len(idx)],
            "tier-2 jacobian")

    with span("tier-2 jacobian"):
        Js = call_with_backend_retry(
            run_jac, label="stability tier-2 jacobian")
    eig = np.linalg.eigvals(Js)
    tol_sub = stability_tolerance(Js, pos_tol)
    host_ok = np.all(eig.real <= tol_sub[..., None], axis=-1)
    certified_host[idx] = host_ok
    return certified_host


@hotpath
def _neighbor_seed_lanes(conds: Conditions, success: np.ndarray):
    """For each failed lane, the index of the nearest CONVERGED lane in
    (z-scored) condition space, or None when unavailable.

    Failed lanes cluster along physical boundaries (phase transitions,
    bistable regions); their own final iterates are the worst possible
    restart points (measured on the 256x256 volcano's 269 such lanes:
    up to 6 ladder attempts / 1091 accumulated iterations), while the
    converged solution ONE grid step away is a near-root seed the very
    same rescue program polishes in <=2 attempts / 216 iterations (the
    reference's own sweep-continuation pattern, presets.py
    run_temperatures).
    Distance uses every condition leaf that varies across lanes
    (descriptor energies, T, p, eps, ...), z-scored per feature; the
    kd-tree query on the host costs milliseconds at volcano scale.
    """
    n = len(success)
    fail_idx = np.flatnonzero(~success)
    ok_idx = np.flatnonzero(success)
    if len(ok_idx) == 0 or len(fail_idx) == 0:
        return None
    # ONE batched device->host transfer for the whole pytree (a
    # per-leaf np.asarray loop would pay a tunnel round trip per leaf
    # -- the very cost class this rescue path is optimized against).
    host_conds = call_with_backend_retry(jax.device_get, conds,
                                         label="neighbor-seed transfer")
    feats = []
    for a in jax.tree_util.tree_leaves(host_conds):
        a = np.asarray(a)  # sync-ok: host leaf of the batched transfer above
        if a.ndim >= 1 and a.shape[0] == n:
            f = a.reshape(n, -1).astype(np.float64)
            std = f.std(axis=0)
            varying = std > 0
            if varying.any():
                f = f[:, varying]
                feats.append((f - f.mean(axis=0)) / std[varying])
    if not feats:
        return None
    X = np.concatenate(feats, axis=1)
    try:
        from scipy.spatial import cKDTree
        _, nn = cKDTree(X[ok_idx]).query(X[fail_idx])
    except ImportError:       # minimal installs: scipy is an extra
        nn = _chunked_nearest(X[fail_idx], X[ok_idx])
    out = np.arange(n)
    out[fail_idx] = ok_idx[nn]
    return out


def _chunked_nearest(Xf: np.ndarray, Xo: np.ndarray,
                     chunk: int = 128) -> np.ndarray:
    """argmin_j |Xf_i - Xo_j| per row, via chunked
    |a-b|^2 = |a|^2 + |b|^2 - 2ab -- memory stays O(chunk x n_ok)
    instead of a dense 3-D difference tensor (a 512x512 grid's
    failed-vs-converged difference tensor would be multiple GB)."""
    o2 = (Xo * Xo).sum(axis=1)
    nn = np.empty(len(Xf), dtype=np.int64)
    for s in range(0, len(Xf), chunk):
        f = Xf[s:s + chunk]
        d = (f * f).sum(axis=1)[:, None] + o2[None, :] - 2.0 * (f @ Xo.T)
        nn[s:s + chunk] = np.argmin(d, axis=1)
    return nn


@hotpath
def _rescue(spec: ModelSpec, conds: Conditions, res,
            opts: SolverOptions, strategy: str, pad_to: int = 64,
            seed: int = 1, use_x0: bool = True,
            neighbor_seed: bool = False, n_failed: int | None = None,
            mesh: Optional[Mesh] = None,
            codes: Optional[np.ndarray] = None, code: int = 0):
    """Host-side second pass over FAILED lanes only: re-solve the failed
    subset with the given strategy/options from the best iterates of the
    first pass. Padded to a multiple of ``pad_to`` so recompiles stay
    rare. The hot batched path never pays for stragglers: a handful of
    hard lanes otherwise force every lane through the full retry ladder
    (SIMD executes the union of all lanes' work).

    ``use_x0=False`` restarts from the base state + PRNG random guesses
    instead of each lane's best iterate -- required when the iterate
    itself is the problem (a converged-but-UNSTABLE root: re-seeding on
    it would reconverge with zero residual immediately).

    ``neighbor_seed=True`` seeds each failed lane from the nearest
    CONVERGED lane's solution instead of its own failed iterate (see
    :func:`_neighbor_seed_lanes`); the retry ladder's later attempts
    (renormalize, random restarts) still back the seed up, so a bad
    neighbor costs nothing vs the old behavior.

    ``n_failed``: the caller's already-materialized failed-lane count
    (skips this function's scalar pre-check round trip -- each
    materialization call costs ~0.1-1 s on the tunneled backend).
    ``codes``/``code``: optional host int32 [lanes] strategy-code array
    (telemetry column 3, :data:`solvers.newton.STRATEGY_CODES`) --
    every lane THIS pass recovers is stamped with ``code`` in place.
    ``mesh``: the sweep's lane mesh -- the failed subset is re-placed
    on it so the prewarmed SHARDED rescue executable is hit, and the
    merged result is re-sharded so downstream tail programs keep their
    sharded program keys.
    Returns ``(res, n_remaining)`` with the post-rescue failed count,
    so chained rescue passes never re-materialize it.

    Every rung of the ladder dispatches the ONE consolidated rescue
    program (:func:`_rescue_program`): strategy / seededness / pacing
    ride in as traced scalars, so polish, full PTC, LM and the unseeded
    demote re-solve share a single compiled executable per bucket
    shape."""
    # Scalar pre-check (only when the caller didn't already know): the
    # full mask crosses to the host only when lanes actually failed
    # (the common volcano case is zero failures -> one cheap scalar).
    if n_failed is None:
        n_failed = int(host_sync(jnp.sum(~jnp.asarray(res.success)),
                                 "rescue pre-check"))
    if n_failed == 0:
        return res, 0
    success = np.asarray(res.success)  # sync-ok: failure path, full mask needed
    idx = np.flatnonzero(~success)
    sub, idx_p = _padded_subset(conds, idx, bucket=pad_to)
    seed_lane = idx_p
    if use_x0 and neighbor_seed:
        nn = _neighbor_seed_lanes(conds, success)
        if nn is not None:
            seed_lane = nn[idx_p]
    dyn = jnp.asarray(spec.dynamic_indices)
    x_dtype = jnp.asarray(res.x).dtype
    sub = _place_subset(mesh, len(idx_p), sub)
    bsh = _subset_sharding(mesh, len(idx_p))
    prog = _rescue_program(_prog_spec(spec), _pacing_key(opts), bsh)
    kind = _rescue_kind(opts, bsh)
    # The pacing/strategy scalars are ()-shaped TRACED arguments --
    # their VALUES never enter the program key, so every ladder rung
    # at this bucket shape resolves to the same registered executable.
    scal = (np.int32(1 if strategy == "lm" else 0), np.bool_(use_x0),
            np.float64(opts.dt0), np.float64(opts.dt_grow_min),
            np.int64(opts.max_steps), np.int64(opts.max_attempts))

    # Retry on transient compile-service/transport flakes: the rescue
    # program compiles lazily at the failed subset's bucket shape, and
    # one dropped remote-compile connection otherwise kills the whole
    # sweep (the round-4 driver bench died exactly here). The success
    # materialization rides inside the retried unit so execution-time
    # flakes re-dispatch too. keys and x0 are rebuilt INSIDE the
    # retried closure: the rescue program donates both buffers, so a
    # retry must never re-feed consumed arrays.
    def run_rescue():
        keys = jax.random.split(jax.random.PRNGKey(seed), len(idx_p))
        # x0 is always a CONCRETE array (never a treedef-changing
        # None): the seeded/unseeded choice is the traced `use_x0`
        # select inside the program, so both variants share one
        # executable. The unseeded values are dead (the select takes
        # the base state) -- zeros keep the dispatch cheap.
        if use_x0:
            x0 = jnp.asarray(res.x)[seed_lane][:, dyn]
        else:
            x0 = jnp.zeros((len(idx_p), dyn.size), dtype=x_dtype)
        if mesh is not None:
            keys, x0 = _place_subset(mesh, len(idx_p), keys, x0)
        o = _registered_call(spec, kind, prog, (sub, keys, x0) + scal)
        return o, host_sync(o.success,
                            f"rescue[{strategy}]")[:len(idx)]

    with span(f"rescue[{strategy}]"):
        out, got = call_with_backend_retry(run_rescue,
                                           label=f"rescue[{strategy}]")
    n_remaining = int(n_failed - got.sum())
    # Structured evidence of every rescue-pass invocation (bench.py
    # folds the per-trial counts into its report; no sync -- a host
    # list append on already-materialized ints).
    record_event("rescue", label=f"rescue[{strategy}]",
                 n_failed=int(n_failed), n_remaining=n_remaining)
    _metrics.counter("pycatkin_rescue_lanes_total",
                     "failed lanes entering each rescue strategy").inc(
                         int(n_failed), strategy=str(strategy))
    _metrics.counter("pycatkin_rescued_lanes_total",
                     "lanes recovered per rescue strategy").inc(
                         int(n_failed) - n_remaining,
                         strategy=str(strategy))
    if not got.any():
        return res, n_remaining
    x = np.array(res.x)  # sync-ok: failure path, writable host merge copies
    succ = np.array(res.success)
    resid = np.array(res.residual)
    iters = np.array(res.iterations)
    atts = np.array(res.attempts)
    x[idx[got]] = np.asarray(out.x)[:len(idx)][got]  # sync-ok: failure path
    succ[idx[got]] = True
    resid[idx[got]] = np.asarray(out.residual)[:len(idx)][got]  # sync-ok: failure path
    # Diagnostics accumulate across passes: the hardest lanes must
    # report their true total cost, not the capped fast-pass numbers.
    iters[idx] += np.asarray(out.iterations)[:len(idx)]  # sync-ok: failure path
    atts[idx] += np.asarray(out.attempts)[:len(idx)]  # sync-ok: failure path
    if codes is not None:
        codes[idx[got]] = np.int32(code)
    # Forensic fields follow the iterate actually stored: recovered
    # lanes take the rescue attempt's diagnostics; still-failed lanes
    # keep the ones describing the res.x they still carry.
    extra = {}
    for name in ("rate_ok", "pos_ok", "sums_ok", "dt_exit"):
        cur = getattr(res, name)
        new = getattr(out, name)
        if cur is None or new is None:
            continue
        arr = np.array(cur)
        arr[idx[got]] = np.asarray(new)[:len(idx)][got]  # sync-ok: failure path
        extra[name] = jnp.asarray(arr)
    # Chord counts accumulate like iterations (total cost, every pass),
    # not follow-the-iterate like the verdict fields above.
    cur_ch = getattr(res, "chords", None)
    new_ch = getattr(out, "chords", None)
    if cur_ch is not None and new_ch is not None:
        ch = np.array(cur_ch)  # sync-ok: failure path
        ch[idx] += np.asarray(new_ch)[:len(idx)]  # sync-ok: failure path
        extra["chords"] = jnp.asarray(ch)
    merged = res._replace(x=jnp.asarray(x), success=jnp.asarray(succ),
                          residual=jnp.asarray(resid),
                          iterations=jnp.asarray(iters),
                          attempts=jnp.asarray(atts), **extra)
    if mesh is not None:
        # The host-side merge produced unsharded arrays; re-shard so
        # the downstream tail (screen/TOF) keeps hitting the SHARDED
        # program keys its prewarmed executables were registered under.
        n_lanes = len(success)
        sh = _subset_sharding(mesh, n_lanes)
        if sh is not None:
            merged = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), merged)
    return merged, n_remaining


@hotpath
def sweep_steady_state(spec: ModelSpec, conds: Conditions, tof_mask=None,
                       x0=None, opts: SolverOptions = SolverOptions(),
                       mesh: Optional[Mesh] = None,
                       check_stability: bool = False,
                       pos_jac_tol: float = 1e-2):
    """Steady state + optional TOF for every lane; the one-call volcano.

    Returns dict with y [lanes, n_s], success [lanes], residual [lanes],
    and (if tof_mask given) tof [lanes] and activity [lanes]. With
    check_stability, converged-but-unstable lanes (Jacobian eigenvalue
    verdict) are demoted to success=False and reported under 'stable' --
    grid triage then treats them like any other failed lane.

    Negative net TOF lanes (selected steps running in reverse): the
    'activity' column uses |TOF| (see engine.activity_from_tof); a
    warning always fires host-side on the materialized TOF vector, and
    out['tof'] carries the signs.
    """
    # ABI gate: lower the mechanism into its shape bucket and run the
    # WHOLE sweep (fused or legacy tail, sharded or not) on the padded
    # system -- every downstream program then keys on the bucket, not
    # the mechanism. Only the public 'y' needs unpadding; the per-lane
    # masks/diagnostics are lane-shaped and pass through unchanged.
    low = _abi.maybe_lower(spec)
    if low is not None:
        _metrics.counter(
            "pycatkin_abi_bucket_sweeps_total",
            "sweeps dispatched per ABI shape bucket").inc(
                bucket=low.abi_fingerprint)
        out = sweep_steady_state(low, low.pad_conditions(conds),
                                 tof_mask=low.pad_tof_mask(tof_mask),
                                 x0=low.pad_x0(x0), opts=opts, mesh=mesh,
                                 check_stability=check_stability,
                                 pos_jac_tol=pos_jac_tol)
        out["y"] = low.unpad_y(jnp.asarray(out["y"]))
        return out

    # Sweep-level throughput instruments: lane count is a host-side
    # shape read, the wall a perf_counter pair -- nothing device-
    # visible is added (the sync budget and dispatch count are pinned
    # by tests/test_sync_budget.py).
    _metrics.counter("pycatkin_lanes_solved_total",
                     "lanes entering sweep_steady_state").inc(
                         jax.tree_util.tree_leaves(conds)[0].shape[0])
    _t_sweep = _time_mod.perf_counter()
    try:
        return _sweep_steady_state_tail(spec, conds, tof_mask, x0, opts,
                                        mesh, check_stability,
                                        pos_jac_tol)
    finally:
        _metrics.histogram(
            "pycatkin_sweep_wall_seconds",
            "sweep_steady_state wall time").observe(
                _time_mod.perf_counter() - _t_sweep)


def _sweep_steady_state_tail(spec, conds, tof_mask, x0, opts, mesh,
                             check_stability, pos_jac_tol):
    """Post-ABI-gate body of :func:`sweep_steady_state` (split out so
    the metrics wrapper above stays flat)."""

    # Two-phase solve: a capped single-attempt first pass (sized for the
    # ~p99 lane), then host-side rescue of the failed subset with the
    # full retry ladder, then the LM strategy fallback. Stragglers no
    # longer drag every lane through the whole retry ladder.
    #
    # With a mesh, the ENTIRE tail is mesh-aware: conds are lane-
    # sharded up front (so the screen/TOF program keys carry the
    # sharding fingerprint prewarm registered) and the mesh threads
    # through the rescue ladder, the stability tiers and the TOF
    # re-run. Lane counts the mesh cannot divide fall back to the
    # padded solve + unsharded tail (correct, just not prewarmed).
    tail_mesh = None
    if mesh is not None:
        n = jax.tree_util.tree_leaves(conds)[0].shape[0]
        if n % mesh.devices.size == 0:
            conds = shard_conditions(conds, mesh)
            tail_mesh = mesh
    if _fused_enabled() and (mesh is None or tail_mesh is not None):
        # The common case: ONE fused dispatch covers solve +
        # quarantine + tier-0 certificate + TOF + diagnostics, and a
        # clean sweep exits on one counted host sync. Failures and
        # uncertified lanes escalate from inside _fused_sweep; a lane
        # count the mesh cannot divide keeps the legacy padded path.
        return _fused_sweep(spec, conds, tof_mask, x0, opts,
                            check_stability, pos_jac_tol,
                            mesh=tail_mesh)
    res = batch_steady_state(spec, conds, x0=x0, opts=_fast_pass_opts(opts),
                             mesh=mesh)
    return _finish_sweep(spec, conds, res, opts, tof_mask,
                         check_stability, pos_jac_tol,
                         backend=_resolve_backend(mesh=mesh),
                         mesh=tail_mesh, tier=_precision.active_tier())


@hotpath
def _assemble_clean(res, quar, stable, tofs, act,
                    check_stability: bool, has_tof: bool, n_neg: int,
                    lane_tel=None):
    """Sweep result dict from already-computed device arrays -- no
    materialization happens here (the caller already has every count it
    needs). Mirrors _finish_sweep's clean-branch assembly exactly so
    the fused path's output is field-for-field identical.
    ``lane_tel``: the already-materialized [lanes, 5] packed telemetry
    that rode the bundle sync."""
    out = {"y": res.x, "success": res.success,
           "residual": res.residual, "iterations": res.iterations,
           "attempts": res.attempts, "quarantined": quar}
    for name in ("rate_ok", "pos_ok", "sums_ok", "dt_exit", "chords"):
        v = getattr(res, name, None)
        if v is not None:
            out[name] = v
    if lane_tel is not None:
        out["lane_telemetry"] = lane_tel
    if check_stability:
        out["stable"] = stable
        out["success"] = jnp.logical_and(jnp.asarray(res.success),
                                         jnp.asarray(stable))
    if has_tof:
        out["tof"] = tofs
        out["activity"] = act
        _warn_negative_tof(n_neg)
    return out


@hotpath
def _fused_sweep(spec: ModelSpec, conds: Conditions, tof_mask, x0,
                 opts: SolverOptions, check_stability: bool,
                 pos_jac_tol: float, mesh: Optional[Mesh] = None):
    """The fused-dispatch sweep: one device program
    (:func:`_fused_sweep_program`) computes the solve, the quarantine
    demotion, the tier-0 stability certificate, TOF/activity and the
    packed diagnostics bundle; ONE counted host sync (the bundle)
    decides the outcome tier:

    - CLEAN (no failures; every converged lane certified): assemble
      the result from the already-computed device arrays. 1 counted
      sync total.
    - TIER-2 ESCALATION (no failures, but some converged lanes only
      AMBIGUOUS -- the one-sided certificates abstained): pull the
      verdict masks in one batched sync, run the existing host
      eigensolve on the ambiguous subset (:func:`_stability_tier2`,
      gather-compacted to the TIER2_MIN_BUCKET floor), and -- when the
      eigensolve confirms every lane -- finish with the fused TOF
      arrays (they do not depend on the verdict masks). 3 counted
      syncs, no extra full-shape dispatch.
    - Anything else (failed/quarantined lanes, host-eig demotions):
      reconstruct the raw fast-pass result and hand it to the exact
      legacy tail (:func:`_finish_sweep` -- rescue ladder, demote
      loop, final TOF), bit-for-bit.
    """
    n_lanes = jax.tree_util.tree_leaves(conds)[0].shape[0]
    backend = _resolve_backend(mesh=mesh)
    tier = _precision.active_tier()
    fast = _fast_pass_opts(opts)
    has_tof = tof_mask is not None
    sh = _subset_sharding(mesh, n_lanes)
    prog = _fused_sweep_program(_prog_spec(spec), fast, pos_jac_tol,
                                backend, has_tof, check_stability, sh,
                                tier=tier)
    kind = _fused_kind(fast, pos_jac_tol, backend, has_tof,
                       check_stability, sh, tier=tier)
    mask_arr = jnp.asarray(tof_mask) if has_tof else None
    tail = (mask_arr,) if has_tof else ()

    def run_fused():
        # Keys are rebuilt per retry (the program donates them); the
        # ONE materialization (the telemetry pack + packed bundle, a
        # single batched device_get) rides inside the retried unit so
        # an execution-time transport flake re-runs the whole (pure)
        # program.
        keys = jax.random.split(jax.random.PRNGKey(0), n_lanes)
        if sh is not None:
            keys = jax.device_put(keys, sh)
        fkey = compile_pool.program_key(
            kind, _prog_args(spec, (conds, keys, x0) + tail))
        out = _registered_call(spec, kind, prog,
                               (conds, keys, x0) + tail)
        t0 = _time_mod.perf_counter()
        tel, bundle = host_sync((out[-2], out[-1]),
                                "fused tail bundle")
        # The bundle materialization IS this dispatch's blocked wall;
        # fold it onto the fused program's ledger row (count=0: the
        # dispatch itself was already counted by _registered_call).
        _costs.note_dispatch(fkey, _time_mod.perf_counter() - t0,
                             count=0)
        return out[:-2] + (tel, bundle)

    with span("fused sweep"):
        out = call_with_backend_retry(run_fused,
                                      label="batched steady solve")
    parts = _split_fused_out(out, check_stability, has_tof)
    return _fused_decide(spec, conds, tof_mask, opts, check_stability,
                         pos_jac_tol, mesh, tier, backend, parts)


@hotpath
def _split_fused_out(out, check_stability: bool, has_tof: bool):
    """Name the fused program's positional output tuple (after the tail
    bundle sync replaced the last two slots with host arrays):
    ``(res, quar, cert, amb, tofs, act, neg, lane_tel, bundle)`` with
    ``None`` for absent optional slots."""
    res, quar = out[0], out[1]
    pos = 2
    cert = amb = None
    if check_stability:
        cert, amb = out[pos], out[pos + 1]
        pos += 2
    tofs = act = neg = None
    if has_tof:
        tofs, act, neg = out[pos], out[pos + 1], out[pos + 2]
        pos += 3
    return (res, quar, cert, amb, tofs, act, neg, out[pos],
            out[pos + 1])


@hotpath
def _fused_decide(spec: ModelSpec, conds: Conditions, tof_mask,
                  opts: SolverOptions, check_stability: bool,
                  pos_jac_tol: float, mesh: Optional[Mesh], tier: str,
                  backend: str, parts):
    """The fused sweep's post-bundle outcome triage (see
    :func:`_fused_sweep`'s tier docstring): clean assembly, the
    tier-2-only escalation, or the exact legacy tail. Factored out of
    :func:`_fused_sweep` so the packed multi-tenant path runs the SAME
    decision per tenant over its slice of the stacked outputs -- a
    poisoned tenant escalates alone, bit-for-bit like its solo run,
    while clean co-tenants assemble with zero further syncs."""
    res, quar, cert, amb, tofs, act, neg, lane_tel, bundle = parts
    has_tof = tof_mask is not None
    nf, nq, n_amb, n_dem, n_neg = (int(c) for c in bundle)

    # Escalation instrument from the already-materialized bundle
    # counts: host ints only, no extra syncs or dispatches on any tier.
    # (Quarantined lanes are counted by ladder.record_quarantine -- any
    # nq > 0 run reaches it through the legacy tail.)
    if check_stability and n_amb > 0:
        _metrics.counter(
            "pycatkin_tier2_escalations_total",
            "tier-0 certificate abstentions escalated to the tier-2 "
            "eigensolve").inc(n_amb)

    if nf == 0 and (not check_stability
                    or (n_amb == 0 and n_dem == 0)):
        # Clean sweep: everything already computed; no further syncs.
        _note_lane_telemetry(lane_tel, spec)
        return _assemble_clean(res, quar, cert, tofs, act,
                               check_stability, has_tof, n_neg,
                               lane_tel=lane_tel)

    if nf == 0 and check_stability and n_amb > 0 and n_dem == n_amb:
        # Tier-2-only escalation: every demoted lane is merely
        # AMBIGUOUS (certificates abstained; nothing failed, nothing
        # screen-non-finite). One batched mask pull, then the host
        # eigensolve over the compacted subset.
        pull = (amb, cert) + ((neg,) if has_tof else ())
        got = host_sync(pull, "tier-0 escalation masks")
        idx = np.flatnonzero(got[0])
        stable_h = _stability_tier2(spec, conds, res.x, idx,
                                    np.array(got[1]), pos_jac_tol,
                                    mesh=mesh)
        if stable_h[idx].all():
            # Host eig confirmed every escalated lane: verdicts are
            # final and nothing is demoted, so the fused TOF/activity
            # arrays stand as-is (they never depended on the verdict
            # masks -- only the n_neg aggregate did, recounted here
            # from the per-lane negatives with every lane now ok).
            n_neg2 = int(np.sum(got[2])) if has_tof else 0
            # res.x never changed, so the fused telemetry pack is
            # still the truth (strategy stays 0 -- no rescue ran).
            _note_lane_telemetry(lane_tel, spec)
            return _assemble_clean(res, quar, jnp.asarray(stable_h),
                                   tofs, act, check_stability, has_tof,
                                   n_neg2, lane_tel=lane_tel)
        # Host eig DEMOTED lanes: they need the unseeded re-solve +
        # re-judge loop -- exact legacy territory (below).

    # Failure path: reconstruct the raw (pre-quarantine) fast-pass
    # result and run the exact legacy tail. _finish_sweep re-derives
    # quarantine/screen/TOF itself, so the fused outputs are dropped
    # wholesale -- the speculative dispatch is the acceptable waste on
    # this rare path, bit-identity is not negotiable.
    res_raw = res._replace(success=jnp.asarray(res.success)
                           | jnp.asarray(quar))
    return _finish_sweep(spec, conds, res_raw, opts, tof_mask,
                         check_stability, pos_jac_tol, backend=backend,
                         mesh=mesh, tier=tier)


def _packed_kind(opts: SolverOptions, pos_tol: float, backend: str,
                 has_tof: bool, check_stability: bool, tier: str,
                 k_bucket: int) -> str:
    """Registry/cache kind string for the packed multi-tenant fused
    sweep: the solo fused kind plus the tenant-count pow2 sub-bucket
    tag, composed LAST (after the tier tag) so a ``k_bucket`` of 1
    reproduces the solo kind byte-for-byte."""
    return (_fused_kind(opts, pos_tol, backend, has_tof,
                        check_stability, None, tier=tier)
            + compile_pool.tenant_tag(k_bucket))


@hotpath
def _packed_fused_sweep(pack, conds_list, mask_list, x0_list,
                        opts: SolverOptions, check_stability: bool,
                        pos_jac_tol: float):
    """One packed dispatch for K same-bucket tenants, then per-tenant
    outcome triage. ``conds_list``/``mask_list``/``x0_list`` are the
    per-REAL-tenant *padded* inputs (exactly what each tenant's solo
    ABI sweep would dispatch); ghost-tenant replication happens in
    :meth:`PackedLowered.stack_tenants`.

    The clean path spends exactly ONE counted host sync regardless of
    K: the stacked telemetry pack + ``[k_bucket, 5]`` diagnostics
    bundle ride a single batched ``host_sync``, and each clean tenant's
    :func:`_fused_decide` assembles from device slices without another
    pull. A dirty tenant escalates through its own solo-identical
    decision (tier-2 masks / legacy tail) without touching its
    co-tenants' results."""
    kb = pack.k_bucket
    backend = _resolve_backend()
    tier = _precision.active_tier()
    fast = _fast_pass_opts(opts)
    has_tof = mask_list is not None
    n_lanes = jax.tree_util.tree_leaves(conds_list[0])[0].shape[0]
    conds_st = pack.stack_tenants(conds_list)
    x0_st = pack.stack_tenants(x0_list) if x0_list is not None else None
    tail = ((pack.stack_tenants([jnp.asarray(m) for m in mask_list]),)
            if has_tof else ())
    prog = _packed_fused_sweep_program(pack.program_spec, fast,
                                       pos_jac_tol, backend, has_tof,
                                       check_stability, tier=tier)
    kind = _packed_kind(fast, pos_jac_tol, backend, has_tof,
                        check_stability, tier, kb)

    def run_packed():
        # Every tenant gets the SAME per-lane key array its solo sweep
        # would build (bit-identity); rebuilt per retry because the
        # program donates the keys.
        keys = jnp.broadcast_to(
            jax.random.split(jax.random.PRNGKey(0), n_lanes),
            (kb, n_lanes, 2))
        args = (conds_st, keys, x0_st) + tail
        fkey = compile_pool.program_key(kind, _prog_args(pack, args))
        _costs.record(fkey, kind=kind,
                      label=f"packed fused sweep @{n_lanes}"
                            f" x{pack.k}/{kb}")
        out = _registered_call(pack, kind, prog, args)
        t0 = _time_mod.perf_counter()
        tel, bundle = host_sync((out[-2], out[-1]),
                                "packed fused tail bundle")
        _costs.note_dispatch(fkey, _time_mod.perf_counter() - t0,
                             count=0)
        return out[:-2] + (tel, bundle)

    with span("packed fused sweep", tenants=pack.k, k_bucket=kb,
              lanes=n_lanes):
        out = call_with_backend_retry(run_packed,
                                      label="packed batched steady "
                                            "solve")
    res, quar, cert, amb, tofs, act, neg, lane_tel, bundle = \
        _split_fused_out(out, check_stability, has_tof)

    def _slice(tree, k):
        if tree is None:
            return None
        return jax.tree_util.tree_map(lambda a: a[k], tree)

    results = []
    for k, low in enumerate(pack.tenants):
        parts_k = (_slice(res, k), quar[k], _slice(cert, k),
                   _slice(amb, k), _slice(tofs, k), _slice(act, k),
                   _slice(neg, k), lane_tel[k], bundle[k])
        results.append(_fused_decide(
            low, conds_list[k], mask_list[k] if has_tof else None,
            opts, check_stability, pos_jac_tol, None, tier, backend,
            parts_k))
    return results


@hotpath
def packed_sweep_steady_state(specs, conds, tof_mask=None, x0=None,
                              opts: SolverOptions = SolverOptions(),
                              check_stability: bool = False,
                              pos_jac_tol: float = 1e-2) -> list:
    """Multi-tenant :func:`sweep_steady_state`: K mechanisms that lower
    into ONE ABI bucket run as one packed device dispatch (one host
    sync, one AOT executable, zero marginal compiles in a warm bucket)
    and return a LIST of per-tenant result dicts, each bitwise
    identical to what that mechanism's solo ``sweep_steady_state`` call
    would return.

    ``conds`` / ``tof_mask`` / ``x0`` may each be a single value
    (shared by every tenant) or a per-tenant sequence; lane counts must
    match across tenants (the request coalescer,
    :class:`parallel.dispatch.SweepCoalescer`, groups by
    ``(abi_fingerprint, lane count)`` so its packs satisfy this by
    construction).

    Degradations that fall back to per-tenant solo sweeps (results
    unchanged, the packing speedup forfeited, a ``degradation`` event
    recorded): a single tenant; the ABI gate off or a mechanism that
    fits no bucket; the fused tail disabled (``PYCATKIN_FUSED_SWEEP=0``
    or an active fault plan -- fault containment stays per-site).
    Tenants that lower into DIFFERENT buckets raise
    :class:`frontend.abi.AbiBucketError` instead: silently serializing
    a cross-bucket pack would hide the grouping bug upstream."""
    specs = list(specs)
    k = len(specs)
    if k == 0:
        return []

    def _per_tenant(v, name):
        vs = (list(v) if isinstance(v, (list, tuple)) else [v] * k)
        if len(vs) != k:
            raise ValueError(f"{name}: {len(vs)} entries for {k} "
                            f"tenants")
        return vs

    conds_list = _per_tenant(conds, "conds")
    masks = _per_tenant(tof_mask, "tof_mask")
    x0s = _per_tenant(x0, "x0")

    def _solo():
        return [sweep_steady_state(s, c, tof_mask=m, x0=x, opts=opts,
                                   check_stability=check_stability,
                                   pos_jac_tol=pos_jac_tol)
                for s, c, m, x in zip(specs, conds_list, masks, x0s)]

    if k == 1:
        # Degenerate pack: the solo path, so program keys/caches stay
        # byte-identical to the pre-packing world (:tK contract).
        return _solo()
    lows = [s if isinstance(s, _abi.AbiLowered) else _abi.maybe_lower(s)
            for s in specs]
    if any(low is None for low in lows) or not _fused_enabled():
        record_event("degradation", label="packed:solo-fallback",
                     detail="ABI lowering or the fused tail is "
                            "unavailable; running tenants as solo "
                            "sweeps", tenants=k)
        _metrics.counter(
            "pycatkin_packed_solo_fallbacks_total",
            "packed sweep requests degraded to per-tenant solo "
            "sweeps").inc()
        return _solo()
    pack = _abi.pack_lowered(lows)

    lanes = [jax.tree_util.tree_leaves(c)[0].shape[0]
             for c in conds_list]
    if len(set(lanes)) != 1:
        raise ValueError(f"packed tenants must share a lane count, "
                         f"got {lanes}")
    _metrics.counter(
        "pycatkin_packed_sweeps_total",
        "packed multi-tenant dispatches per tenant sub-bucket").inc(
            bucket=pack.abi_fingerprint)
    _metrics.histogram(
        "pycatkin_pack_occupancy",
        "real tenants over the pow2 tenant bucket",
        buckets=(0.25, 0.5, 0.75, 1.0)).observe(pack.occupancy)
    for low in pack.tenants:
        _metrics.counter(
            "pycatkin_abi_bucket_sweeps_total",
            "sweeps dispatched per ABI shape bucket").inc(
                bucket=low.abi_fingerprint)
    _metrics.counter("pycatkin_lanes_solved_total",
                     "lanes entering sweep_steady_state").inc(
                         k * lanes[0])

    conds_pad = [low.pad_conditions(c)
                 for low, c in zip(lows, conds_list)]
    has_tof = any(m is not None for m in masks)
    if has_tof and not all(m is not None for m in masks):
        raise ValueError("tof_mask must be given for every tenant or "
                         "none (the coalescer groups by TOF-ness)")
    masks_pad = ([low.pad_tof_mask(m) for low, m in zip(lows, masks)]
                 if has_tof else None)
    has_x0 = any(x is not None for x in x0s)
    if has_x0 and not all(x is not None for x in x0s):
        raise ValueError("x0 must be given for every tenant or none")
    x0_pad = ([low.pad_x0(x) for low, x in zip(lows, x0s)]
              if has_x0 else None)

    _t_sweep = _time_mod.perf_counter()
    try:
        outs = _packed_fused_sweep(pack, conds_pad, masks_pad, x0_pad,
                                   opts, check_stability, pos_jac_tol)
    finally:
        _metrics.histogram(
            "pycatkin_packed_sweep_wall_seconds",
            "packed multi-tenant sweep wall time").observe(
                _time_mod.perf_counter() - _t_sweep)
    for i, (low, out) in enumerate(zip(lows, outs)):
        out["y"] = low.unpad_y(jnp.asarray(out["y"]))
    return outs


def prewarm_packed_sweep_programs(specs, conds, tof_mask=None,
                                  opts: SolverOptions = SolverOptions(),
                                  check_stability: bool = False,
                                  pos_jac_tol: float = 1e-2,
                                  cache=None):
    """Load-or-compile the ONE packed fused executable a
    :func:`packed_sweep_steady_state` call over these tenants would
    dispatch (registry + AOT cache, no execution). The per-bucket
    rescue/tier-2 programs are solo-shaped and come from the ordinary
    :func:`prewarm_sweep_programs` -- a dirty tenant escalates through
    the same bucket zoo its solo run uses.

    Returns :class:`PrewarmStats`; a SECOND pack of fresh mechanisms in
    a warm ``(bucket, k_bucket, lanes)`` cell must report
    ``stats.compiled == 0`` -- the zero-marginal-compile gate bench.py
    and the packed CI lane assert."""
    specs = list(specs)
    k = len(specs)
    stats = PrewarmStats(0)
    stats.compiled = stats.loaded = stats.executed = 0
    stats.cache_writes = 0
    stats.cache = {}
    if k <= 1:
        return stats              # solo path owns the K=1 programs
    lows = [s if isinstance(s, _abi.AbiLowered) else _abi.maybe_lower(s)
            for s in specs]
    if any(low is None for low in lows) or not _fused_enabled():
        return stats
    pack = _abi.pack_lowered(lows)
    if cache is None:
        cache = compile_pool.AOTCache(
            fingerprint=compile_pool.spec_fingerprint(pack))
    elif cache is False:
        cache = compile_pool.AOTCache(root="off")

    def _per_tenant(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * k

    conds_list = _per_tenant(conds)
    masks = _per_tenant(tof_mask)
    has_tof = masks[0] is not None
    kb = pack.k_bucket
    backend = _resolve_backend()
    tier = _precision.active_tier()
    fast = _fast_pass_opts(opts)
    conds_st = pack.stack_tenants(
        [low.pad_conditions(c) for low, c in zip(lows, conds_list)])
    tail = ((pack.stack_tenants(
        [jnp.asarray(low.pad_tof_mask(m))
         for low, m in zip(lows, masks)]),) if has_tof else ())
    n_lanes = jax.tree_util.tree_leaves(conds_list[0])[0].shape[0]
    keys = jnp.broadcast_to(
        jax.random.split(jax.random.PRNGKey(0), n_lanes),
        (kb, n_lanes, 2))
    prog = _packed_fused_sweep_program(pack.program_spec, fast,
                                       pos_jac_tol, backend, has_tof,
                                       check_stability, tier=tier)
    kind = _packed_kind(fast, pos_jac_tol, backend, has_tof,
                        check_stability, tier, kb)
    args = _prog_args(pack, (conds_st, keys, None) + tail)
    key = compile_pool.program_key(kind, args)
    _costs.record(key, kind=kind,
                  label=f"packed fused sweep @{n_lanes} x{kb}")
    stats = PrewarmStats(1)
    stats.compiled = stats.loaded = stats.executed = 0
    stats.cache_writes = 0
    pspec = pack.program_spec
    if compile_pool.lookup(pspec, key) is None:
        exe = None
        try:
            exe = cache.load(key)
        except compile_pool.CacheMismatch:
            exe = None
        if exe is not None:
            compile_pool.register(pspec, key, exe)
            stats.loaded = 1
        else:
            _san_recompile.note_compile(
                f"packed fused sweep @{n_lanes} x{kb}")
            # Compile is authoritative: force the fingerprint so a key
            # collision raises AT the compile site, not a dispatch
            # later (trace-ident sanitizer).
            _san_trace_ident.note_jaxpr(kind, key, prog, args,
                                        force=True)
            exe = call_with_backend_retry(
                lambda: prog.lower(*args).compile(),
                label=f"compile:packed fused sweep @{n_lanes} x{kb}")
            _metrics.counter("pycatkin_compile_total",
                             "fresh XLA compiles through the compile "
                             "pool").inc()
            cache.save(key, exe,
                       sharding=compile_pool.args_sharding_fingerprint(
                           args))
            _costs.record(key, kind=kind,
                          cost=_costs.harvest_cost(exe),
                          source="compiled")
            compile_pool.register(pspec, key, exe)
            stats.compiled = 1
    else:
        stats.loaded = 1
    stats.cache_writes = cache.writes
    stats.cache = cache.stats()
    return stats


@hotpath
def _quarantine_mask(res, quarantined=None):
    """Per-lane NaN quarantine: lanes FLAGGED converged whose stored
    solution or residual is non-finite are silently-poisoned results (a
    `nan`-kind fault overwrites float leaves but cannot touch the bool
    success flag; genuine device corruption looks the same). Demote
    them to failed so the rescue ladder re-solves them and no
    downstream reduction trusts their values. Returns ``(res, mask)``
    with ``mask`` ORed into ``quarantined`` when given."""
    from ..solvers.newton import lane_finite_mask
    finite = lane_finite_mask(res.x, res.residual)
    q_new = jnp.asarray(res.success) & ~finite
    q = q_new if quarantined is None else jnp.asarray(quarantined) | q_new
    return res._replace(success=jnp.asarray(res.success) & finite), q


# The cross-lane verdict reductions of one sweep, packed into a single
# int32 bundle (see solvers.newton.packed_sweep_diagnostics): a clean
# sweep's tail materializes exactly this one vector. Plain module-level
# jit: it caches per (shapes, which-optional-args) signature.
@jax.jit
def _tail_bundle(success, quarantined, ambiguous, demoted, n_neg):
    from ..solvers.newton import packed_sweep_diagnostics
    return packed_sweep_diagnostics(success, quarantined, ambiguous,
                                    demoted, n_neg)


# Device-side lane-telemetry pack for the LEGACY split tail (the fused
# program computes its own copy in-program); rides the "sweep tail
# bundle" sync so the legacy clean path's sync count does not grow.
# ``tier`` is the per-lane tier column (int32 [lanes] or scalar 0).
@jax.jit
def _lane_telemetry_bundle(iterations, chords, residual, tier):
    from ..solvers.newton import packed_lane_telemetry
    return packed_lane_telemetry(iterations, chords, residual,
                                 tier=tier)


# Histogram buckets for the lane telemetry feed: iteration/chord counts
# follow a 1..1000 ladder (the solver caps max_steps well below 1000);
# residual decades span the f64 convergence range.
_LANE_COUNT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                       500.0, 1000.0)
_LANE_DECADE_BUCKETS = (-16.0, -14.0, -12.0, -10.0, -8.0, -6.0, -4.0,
                        -2.0, 0.0)


@hotpath
def _note_lane_telemetry(tel, spec):
    """Feed one sweep's materialized [lanes, 5] telemetry pack into the
    per-lane histograms, labeled by the ABI bucket the sweep ran in
    (``unbucketed`` for legacy per-mechanism programs). Bulk
    ``observe_many`` -- one lock acquisition per column, not per lane."""
    if tel is None:
        return
    bucket = str(getattr(spec, "abi_fingerprint", None) or "unbucketed")
    tel = np.asarray(tel)  # sync-ok: pack already materialized by caller
    _metrics.histogram(
        "pycatkin_lane_iterations",
        "per-lane solver iteration counts",
        buckets=_LANE_COUNT_BUCKETS).observe_many(
            tel[:, 0], abi_bucket=bucket)
    _metrics.histogram(
        "pycatkin_lane_chords",
        "per-lane accepted chord re-solves",
        buckets=_LANE_COUNT_BUCKETS).observe_many(
            tel[:, 1], abi_bucket=bucket)
    _metrics.histogram(
        "pycatkin_lane_residual_decade",
        "per-lane final-residual decade (floor log10)",
        buckets=_LANE_DECADE_BUCKETS).observe_many(
            tel[:, 2], abi_bucket=bucket)


@hotpath
def _host_lane_telemetry(res, quar, strategy_codes,
                         first_pass_tier: int = 0):
    """Host-side twin of :func:`solvers.newton.packed_lane_telemetry`
    for the FAILURE path, where the merged result already lives in host
    memory and the strategy column carries the rescue ladder's verdict
    per lane: same columns, same decade clipping as the device pack.
    ``first_pass_tier``: the precision-tier code of the FIRST solving
    pass; stamped only on lanes it accepted (strategy 0, successful,
    not quarantined) -- every rescue-ladder product is f64 (code 0)."""
    it = np.asarray(res.iterations).astype(np.int32)  # sync-ok: failure path
    ch = getattr(res, "chords", None)
    ch = (np.asarray(ch).astype(np.int32) if ch is not None  # sync-ok: failure path
          else np.zeros_like(it))
    r = np.asarray(res.residual, dtype=np.float64)  # sync-ok: failure path
    with np.errstate(divide="ignore", invalid="ignore"):
        dec = np.floor(np.log10(np.where(r > 0, r, 1.0)))
    dec = np.where(r > 0, dec, -99.0)
    dec = np.where(np.isfinite(r), dec, 99.0)
    dec = np.clip(dec, -99, 99).astype(np.int32)
    strat = np.where(np.asarray(quar).astype(bool),  # sync-ok: failure path
                     np.int32(STRATEGY_CODES["quarantine"]),
                     np.asarray(strategy_codes, dtype=np.int32))  # sync-ok: failure path
    strat = strat.astype(np.int32)
    ok = np.asarray(res.success).astype(bool)  # sync-ok: failure path
    tcol = np.where(ok & (strat == 0), np.int32(first_pass_tier),
                    np.int32(0)).astype(np.int32)
    return np.stack([it, ch, dec, strat, tcol], axis=-1)


@hotpath
def _finish_sweep(spec: ModelSpec, conds: Conditions, res,
                  opts: SolverOptions, tof_mask, check_stability: bool,
                  pos_jac_tol: float, backend: Optional[str] = None,
                  mesh: Optional[Mesh] = None, tier: str = "f64"):
    """Shared sweep tail: quarantine, rescue ladder, stability
    verdict/demote loop, TOF/activity -- everything downstream of the
    first solving pass (used by both sweep_steady_state and
    continuation_sweep). ``tier``: the precision tier the FIRST pass
    ran in -- telemetry bookkeeping only; every rung of the rescue
    ladder below runs pure f64 regardless.

    Sync-lean structure: the quarantine mask, the stability screen, the
    TOF/activity program and every cross-lane count are dispatched
    SPECULATIVELY (assuming the common clean sweep) with no per-stage
    materialization; ONE packed int bundle
    (:func:`solvers.newton.packed_sweep_diagnostics`) then crosses to
    the host. A clean sweep assembles its result from the already-
    computed device arrays -- two blocking host syncs total including
    the solve fence (enforced by tests/test_sync_budget.py, budget
    <= 3). Any failure/ambiguity falls back to the exact legacy
    sequence (rescue ladder, tier-2 eigensolve, demote loop), paying
    its per-stage syncs only on the failure path, with the speculative
    screen reused when the ladder did not run (res.x unchanged).
    """
    backend = _resolve_backend(backend)
    sh_full = None
    if mesh is not None:
        sh_full = _subset_sharding(
            mesh, jax.tree_util.tree_leaves(conds)[0].shape[0])
    res, quar = _quarantine_mask(res)
    succ0 = jnp.asarray(res.success)
    if sh_full is not None:
        # Pin the DERIVED masks' layout: eager elementwise ops on
        # sharded inputs leave the output sharding to the compiler,
        # and the screen/TOF program keys fingerprint it.
        succ0 = jax.device_put(succ0, sh_full)
    mask_arr = jnp.asarray(tof_mask) if tof_mask is not None else None

    def run_tail():
        # Speculative clean-path tail: every dispatch is async; the
        # ONE materialization (the packed bundle) rides inside this
        # retried unit so an execution-time transport flake re-runs
        # the whole (pure) tail.
        cert = amb = n_amb_dev = None
        if check_stability:
            cert, amb, n_amb_dev = _registered_call(
                spec, _screen_kind(pos_jac_tol, backend),
                _stability_screen_program(_prog_spec(spec), pos_jac_tol,
                                          backend),
                (conds, res.x, succ0))
            ok_spec = succ0 & cert
            if sh_full is not None:
                ok_spec = jax.device_put(ok_spec, sh_full)
            demoted = succ0 & ~cert
        else:
            ok_spec = succ0
            demoted = None
        tofs = act = n_neg_dev = None
        if tof_mask is not None:
            tofs, act, n_neg_dev = _registered_call(
                spec, "tof", _tof_program(_prog_spec(spec)),
                (conds, res.x, mask_arr, ok_spec))
        bundle = _tail_bundle(succ0, quar, amb, demoted, n_neg_dev)
        tel_dev = _lane_telemetry_bundle(
            res.iterations, getattr(res, "chords", None), res.residual,
            jnp.where(succ0, jnp.int32(_precision.TIER_CODES[tier]),
                      jnp.int32(0)))
        tel, counts = host_sync((tel_dev, bundle), "sweep tail bundle")
        return (cert, amb, n_amb_dev, tofs, act, tel, counts)

    with span("sweep tail"):
        (cert, amb, n_amb_dev, tofs, act, lane_tel,
         counts) = call_with_backend_retry(run_tail, label="sweep tail")
    nf, nq, n_amb, n_dem, n_neg = (int(c) for c in counts)

    if nf == 0 and (not check_stability
                    or (n_amb == 0 and n_dem == 0)):
        # Clean sweep: everything already computed; no further syncs.
        _note_lane_telemetry(lane_tel, spec)
        out = {"y": res.x, "success": res.success,
               "residual": res.residual, "iterations": res.iterations,
               "attempts": res.attempts, "quarantined": quar,
               "lane_telemetry": lane_tel}
        for name in ("rate_ok", "pos_ok", "sums_ok", "dt_exit",
                     "chords"):
            v = getattr(res, name, None)
            if v is not None:
                out[name] = v
        if check_stability:
            out["stable"] = cert
            out["success"] = jnp.logical_and(jnp.asarray(res.success),
                                             jnp.asarray(cert))
        if tof_mask is not None:
            out["tof"] = tofs
            out["activity"] = act
            _warn_negative_tof(n_neg)
        return out

    # Failure path: the legacy per-stage sequence, bit-for-bit. The
    # speculative tail already paid for the counts, so the ladder
    # decision costs no extra round trip; its tof/screen outputs are
    # reused only where res.x provably did not change.
    #
    # Three-pass rescue ladder (polish -> full PTC -> LM; the failed
    # count threads through as a host int -- each materialization call
    # costs ~0.1-1 s on the tunneled backend). The seeded passes use
    # converged NEIGHBORS (continuation):
    # measured on the 256x256 volcano's 269 phase-boundary lanes, the
    # ladder needs max 2 attempts / 216 accumulated iterations with
    # neighbor seeds vs 6 attempts / 1091 iterations from the lanes'
    # own failed iterates -- 5x less union work through the SAME
    # compiled program (the warm wall is latency-bound at this bucket
    # width, ~2 s either way; the headroom pays on harder grids).
    nf0 = nf
    # Per-lane rescue-strategy codes (telemetry column 3): 0 until a
    # ladder rung actually recovers the lane; quarantine stamps last.
    strat_h = np.zeros(
        jax.tree_util.tree_leaves(conds)[0].shape[0], dtype=np.int32)
    if nf > 0:
        # Seeded near-Newton polish first: the cheap pass that
        # converges the whole tail in the common case (see
        # _polish_opts). The full ladder and the LM strategy remain
        # behind it for whatever survives.
        res, nf = _rescue(spec, conds, res, _polish_opts(opts), "ptc",
                          neighbor_seed=True, n_failed=nf, mesh=mesh,
                          codes=strat_h, code=STRATEGY_CODES["polish"])
    if nf > 0:
        res, nf = _rescue(spec, conds, res, opts, "ptc",
                          neighbor_seed=True, n_failed=nf, mesh=mesh,
                          codes=strat_h, code=STRATEGY_CODES["ptc"])
    if nf > 0:
        res, nf = _rescue(spec, conds, res, opts, "lm", n_failed=nf,
                          mesh=mesh, codes=strat_h,
                          code=STRATEGY_CODES["lm"])
    if nf0 > 0:
        # Re-check after the ladder: a poisoned RESCUE dispatch can
        # write fresh non-finite "successes" (fault sites rescue[*]);
        # only the failure path pays this extra scalar round trip.
        res, quar = _quarantine_mask(res, quar)
        nq = int(host_sync(jnp.sum(quar), "post-ladder quarantine"))
    if nq > 0:
        from ..robustness.ladder import record_quarantine
        record_quarantine(
            np.flatnonzero(
                host_sync(quar, "quarantine lanes")).tolist(),
            label="quarantine:sweep")
    if check_stability:
        # The speculative screen is exact iff the ladder never ran
        # (res.x unchanged); the TPU emulated-f64 case -- clean solve,
        # many ambiguous lanes -- lands here and skips re-running
        # tier 1 entirely.
        pre = ((cert, amb, n_amb) if nf0 == 0 else None)
        stable = stability_mask(spec, conds, res.x, pos_tol=pos_jac_tol,
                                ok=res.success, backend=backend,
                                precomputed=pre, mesh=mesh)
        # Converged-but-UNSTABLE lanes (e.g. the middle root of a
        # bistable mechanism) get the facade's random-restart treatment
        # (api/system.py find_steady: up to 3 retries from fresh
        # guesses) instead of being abandoned: demote them to failed,
        # re-solve WITHOUT their poisoned iterate (restarting on an
        # unstable root reconverges to it with zero residual), and
        # re-judge. Reference solver.py:102-120 verdict-and-retry.
        # The demote decision crosses to the host as one scalar per
        # round (see stability_mask on materialization-call cost).
        for round_i in range(3):
            demoted = jnp.asarray(res.success) & ~stable
            if int(host_sync(jnp.sum(demoted), "demote count")) == 0:
                break
            res = res._replace(
                success=jnp.asarray(res.success) & stable)
            res, _ = _rescue(spec, conds, res, opts, "ptc",
                             seed=17 + round_i, use_x0=False, mesh=mesh,
                             codes=strat_h,
                             code=STRATEGY_CODES["demote"])
            stable = stability_mask(spec, conds, res.x,
                                    pos_tol=pos_jac_tol,
                                    ok=res.success, backend=backend,
                                    mesh=mesh)
    out = {"y": res.x, "success": res.success, "residual": res.residual,
           "iterations": res.iterations, "attempts": res.attempts,
           "quarantined": quar}
    # Per-lane forensic diagnostics (verdict breakdown + exit
    # pseudo-step) ride along whenever the solver produced them.
    for name in ("rate_ok", "pos_ok", "sums_ok", "dt_exit", "chords"):
        v = getattr(res, name, None)
        if v is not None:
            out[name] = v
    # The speculative device telemetry pack is stale once the ladder
    # rewrote lanes; rebuild it host-side from the merged result (the
    # failure path pays per-stage syncs anyway) with the ladder's
    # strategy verdicts in column 3 and the first pass's tier stamped
    # on the lanes it accepted (column 4).
    tel = _host_lane_telemetry(
        res, quar, strat_h,
        first_pass_tier=_precision.TIER_CODES[tier])
    out["lane_telemetry"] = tel
    _note_lane_telemetry(tel, spec)
    if check_stability:
        out["stable"] = stable
        out["success"] = jnp.logical_and(jnp.asarray(res.success),
                                         jnp.asarray(stable))
    if tof_mask is not None:
        tprog = _tof_program(_prog_spec(spec))
        ok_arr = jnp.asarray(out["success"])
        if sh_full is not None:
            ok_arr = jax.device_put(ok_arr, sh_full)

        def run_tof():
            # The n_neg materialization doubles as the execution sync
            # inside the retried unit (see batch_steady_state).
            t, a, nn = _registered_call(spec, "tof", tprog,
                                        (conds, res.x, mask_arr,
                                         ok_arr))
            return t, a, int(host_sync(nn, "tof sign check"))

        with span("tof/activity"):
            tofs, act, n_neg = call_with_backend_retry(
                run_tof, label="tof/activity")
        out["tof"] = tofs
        out["activity"] = act
        # Deterministic host-side sign check (NOT an async device
        # callback, which the tunneled axon backend silently skips): a
        # reverse-running lane must never win a volcano argmax with no
        # visible signal. Reduced on device; one scalar crosses.
        _warn_negative_tof(n_neg)
    return out


@hotpath
def continuation_sweep(spec: ModelSpec, conds: Conditions, order,
                       tof_mask=None,
                       opts: SolverOptions = SolverOptions(),
                       stage_opts: Optional[SolverOptions] = None,
                       check_stability: bool = False,
                       pos_jac_tol: float = 1e-2):
    """Warm-started sweep along a continuation axis.

    ``order``: [n_stages, m] integer lane indices covering every lane
    exactly once, ordered so physically adjacent conditions share a
    stage boundary (e.g. a T x p x dE grid staged along T). Stage 0
    solves cold; every later stage seeds from the PREVIOUS stage's
    solutions -- the reference's own sweep pattern (presets.py
    run_temperatures carries each point's solution into the next), which
    slashes Newton iterations for large per-lane systems where every
    iteration pays a full Jacobian + LU (bench config 5). All stages
    share ONE compiled program (same [m]-lane shape), and the stage
    chain pipelines on device (x0 flows stage-to-stage as device
    arrays; no host sync until the shared finishing tail).

    ``stage_opts``: solver pacing for the seeded stages (default: start
    near Newton -- dt0=1, fast growth, single attempt; a seeded lane
    that still fails lands in the ordinary rescue ladder). Returns the
    same dict as :func:`sweep_steady_state`, in original lane order.
    """
    low = _abi.maybe_lower(spec)
    if low is not None:
        out = continuation_sweep(low, low.pad_conditions(conds), order,
                                 tof_mask=low.pad_tof_mask(tof_mask),
                                 opts=opts, stage_opts=stage_opts,
                                 check_stability=check_stability,
                                 pos_jac_tol=pos_jac_tol)
        out["y"] = low.unpad_y(jnp.asarray(out["y"]))
        return out

    order = np.asarray(order)  # sync-ok: host-built index plan, not device data
    n_stages, m = order.shape
    n_lanes = len(jax.tree_util.tree_leaves(conds)[0])
    # A malformed order would silently place solutions on the wrong
    # lanes with success=True -- wrong physics, no error. Refuse.
    if not np.array_equal(np.sort(order.ravel()), np.arange(n_lanes)):
        raise ValueError(
            "continuation_sweep: `order` must contain every lane index "
            f"exactly once (got shape {order.shape} for {n_lanes} lanes)")
    dyn = jnp.asarray(spec.dynamic_indices)
    first = _fast_pass_opts(opts)
    cont = stage_opts or opts._replace(dt0=1.0, dt_grow_min=10.0,
                                       max_steps=60, max_attempts=1)
    subs = [jax.tree_util.tree_map(lambda a: jnp.asarray(a)[order[s]],
                                   conds)
            for s in range(n_stages)]

    def stage_keys(s):
        # Rebuilt per dispatch (and per retry): the stage program
        # donates its key buffer, and slicing the one full split keeps
        # the key VALUES identical to the pre-donation behavior (the
        # prefix stability of jax.random.split is not relied upon).
        return jax.random.split(jax.random.PRNGKey(0),
                                n_stages * m)[s * m:(s + 1) * m]

    # Stage dispatches ride the retry for compile-time flakes only: a
    # per-stage materialization would serialize the host into the
    # stage chain and destroy the on-device x0 pipelining this function
    # exists for. Execution-time flakes surface at the finishing tail's
    # scalar check; callers needing full execution-retry coverage can
    # re-invoke (the sweep is pure).
    stage_res = [None] * n_stages
    # Direct program dispatch (no registry): the ABI operand prepend is
    # applied explicitly via _prog_args on each stage call.
    first_prog = _steady_program(_prog_spec(spec), first)
    stage_res[0] = call_with_backend_retry(
        lambda: first_prog(*_prog_args(spec,
                                       (subs[0], stage_keys(0), None))),
        label="continuation stage 0")
    prog = _steady_program(_prog_spec(spec), cont)
    for s in range(1, n_stages):
        x0 = stage_res[s - 1].x[:, dyn]
        stage_res[s] = call_with_backend_retry(
            lambda s=s, x0=x0: prog(*_prog_args(spec,
                                                (subs[s], stage_keys(s),
                                                 x0))),
            label=f"continuation stage {s}")

    # Reassemble into original lane order (pure device ops).
    inv = np.argsort(order.ravel())
    res = jax.tree_util.tree_map(
        lambda *leaves: jnp.concatenate(leaves, axis=0)[inv], *stage_res)
    return _finish_sweep(spec, conds, res, opts, tof_mask,
                         check_stability, pos_jac_tol,
                         backend=_resolve_backend())


def _polish_opts(opts: SolverOptions) -> SolverOptions:
    """Pacing for the seeded rescue POLISH pass: near-Newton from the
    first step (dt0 huge recovers Newton; rejection-shrink still
    globalizes), single attempt, short cap. Derived in ONE place so
    :func:`prewarm_sweep_programs` and :func:`_finish_sweep` compile
    the identical program (the cache keys on the options value).
    Measured on the 256x256 volcano's 269 phase-boundary lanes:
    neighbor-seeded polish converges 269/269 in max 52 / mean 3.9
    iterations, 0.12 s warm -- vs ~1.7-2 s for the default-paced full
    ladder whose attempt 0 spends ~100 iterations ramping dt from
    1e-9 on lanes that start a stone's throw from a root."""
    return opts._replace(dt0=1.0e6, dt_grow_min=30.0, max_steps=60,
                         max_attempts=1)


def _fast_pass_opts(opts: SolverOptions) -> SolverOptions:
    """The capped single-attempt first-pass options, derived in ONE
    place: :func:`sweep_steady_state`, :func:`continuation_sweep` and
    :func:`prewarm_sweep_programs` must agree exactly -- the compiled-
    program caches key on the options value, so a drifted copy would
    prewarm a program the sweep never runs (voiding the no-in-band-
    compile guarantee with zero visible signal)."""
    return opts._replace(max_steps=min(opts.max_steps, 100),
                         max_attempts=1)


class PrewarmStats(int):
    """:func:`prewarm_sweep_programs` return value: an ``int`` (the
    program count, backward compatible with every existing caller) that
    additionally carries the compile/cache breakdown as attributes:
    ``compiled`` (fresh XLA compiles), ``loaded`` (AOT cache hits),
    ``cache_writes``, ``executed`` (programs also run once), and
    ``cache`` (the :class:`compile_pool.AOTCache` stats dict)."""

    compiled: int = 0
    loaded: int = 0
    cache_writes: int = 0
    executed: int = 0
    cache: dict = {}


def prewarm_program_count(buckets=(64, 128, 256), aot_buckets=(),
                          tier2_buckets=(), tier2_aot_buckets=(),
                          tof: bool = True,
                          check_stability: bool = True,
                          transient_k_buckets=None) -> int:
    """Programs a :func:`prewarm_sweep_programs` call with this layout
    ensures, WITHOUT compiling anything: ONE fused full-shape sweep
    program (solve + quarantine + tier-0 screen + TOF + diagnostics --
    the ``tof``/``check_stability`` flags select the program VARIANT,
    they no longer add programs) + ONE consolidated rescue program per
    solve bucket + one subset-Jacobian program per tier-2 bucket (only
    reachable with stability on). ``bench.py --smoke`` holds the
    production layout to ``PREWARM_PROGRAM_BUDGET`` through this
    arithmetic (the full bench is too expensive for the CI lane to
    actually prewarm)."""
    del tof                                   # variant flag, not a program
    n = 1                                     # fused full-shape sweep
    n += len(set(buckets) | set(aot_buckets))          # rescue
    if check_stability:
        n += len(set(tier2_buckets) | set(tier2_aot_buckets))  # tier-2 jac
    if transient_k_buckets is not None:
        # prewarm_transient_programs: one solo fused transient program
        # plus one packed program per pow2 tenant sub-bucket (None
        # means no transient prewarm at all; () warms solo only).
        n += 1 + len({1 << (int(x) - 1).bit_length()
                      for x in transient_k_buckets if int(x) > 1})
    return n


def prewarm_sweep_programs(spec: ModelSpec, conds: Conditions,
                           tof_mask=None,
                           opts: SolverOptions = SolverOptions(),
                           buckets=(64, 128, 256),
                           aot_buckets=(),
                           tier2_buckets=(),
                           tier2_aot_buckets=(),
                           check_stability: bool = True,
                           pos_jac_tol: float = 1e-2,
                           verbose: bool = False,
                           cache=None,
                           workers: int | None = None,
                           mesh: Optional[Mesh] = None):
    """Compile (or load from the on-disk AOT executable cache) every
    program :func:`sweep_steady_state` can touch at this lane count, up
    to rescue/ambiguous subsets of ``max(buckets + aot_buckets)`` lanes.

    The sweep's hot path compiles lazily: the rescue ladder, the
    x0-free demote re-solve and the stability tier-2 Jacobian all
    compile at the failed/ambiguous subset's pow2 bucket shape the
    first time lanes actually fail -- which lands tens of seconds of
    remote compile (plus its transport flake risk, the round-4 bench
    crash) inside a timed trial or a production solve. One call here
    front-loads: the FUSED full-shape sweep program
    (:func:`_fused_sweep_program` -- solve, quarantine, tier-0 screen,
    TOF/activity and the diagnostics bundle in one executable; the r05
    standalone fast-pass/screen/TOF programs are gone from the zoo),
    ONE consolidated rescue program per pow2 solve bucket
    (strategy/seededness/pacing are runtime arguments of
    :func:`_rescue_program` -- the r05 zoo's four per-bucket variants
    collapsed into it), and the subset Jacobian at the ``tier2_*``
    shapes only. The standalone screen/TOF programs still exist for
    the legacy split tail (``PYCATKIN_FUSED_SWEEP=0``, fault plans,
    continuation sweeps) but compile in-band there -- rare paths do
    not get zoo slots.

    ``check_stability`` (and ``tof_mask``-ness) is part of the fused
    program's identity now -- the r05 layout shared one fast-pass
    executable across both settings, the fused executable cannot --
    so prewarm with the SAME value the sweeps will pass, as
    ``bench.py`` and the dispatch workers do.

    Compile/fast-pass OVERLAP (vs the r05 sequential loop, 136.6 s for
    32 programs): the tail-program job list is built from ABSTRACT
    result shapes (``jax.eval_shape`` on the fast pass -- no execution
    needed), so every ``.lower().compile()`` not satisfied by the AOT
    cache is submitted to the compile pool
    (:func:`compile_pool.submit_compile`; XLA compiles release the GIL)
    BEFORE the fast pass executes, and runs concurrently with it.
    Resulting executables are serialized into the cache
    (:class:`compile_pool.AOTCache` -- a restarted process deserializes
    instead of compiling) and published in the process-wide registry
    that the sweep hot path consults, so warmed programs are what a
    sweep actually runs. Set ``PYCATKIN_PREWARM_OVERLAP=0`` to
    serialize (compile first, then execute) for debugging.

    ``buckets`` are compiled AND executed once (runtime paging and
    dispatch paths then fully hot); ``aot_buckets`` are compiled/loaded
    only -- cheaper to warm; a later in-band hit executes the
    registered AOT executable with no trace or compile.
    ``tier2_buckets`` warm (execute) the subset-Jacobian program --
    the stability tier-2's ambiguous subset follows a different count
    distribution than the rescue's failed subset (floored at
    ``TIER2_MIN_BUCKET`` on the hot path), and it is BACKEND-dependent:
    the Lyapunov certificate's error margin tracks the backend's unit
    roundoff, so it abstains on <~1 % of volcano lanes on true-f64 CPU
    but ~14 % on the emulated-f64 TPU (measured: warmup and trial
    ambiguous counts both ~9.5k -> bucket 16384). Put the production
    backend's likely shapes here and other scales in
    ``tier2_aot_buckets``. A sweep whose failed subset pads beyond the
    largest bucket still compiles in-band.

    ``mesh``: prewarm the SHARDED program variants a
    ``sweep_steady_state(mesh=...)`` call will dispatch -- conds are
    lane-sharded up front and every program key carries the sharding
    fingerprint, so mesh and single-device executables never collide
    in the registry or the AOT cache.

    ``cache``: an :class:`compile_pool.AOTCache` (None builds one from
    ``PYCATKIN_AOT_CACHE`` bound to this spec's fingerprint; False
    disables the disk layer). ``workers``: compile-pool width (None
    reads ``PYCATKIN_COMPILE_WORKERS``).

    Returns a :class:`PrewarmStats` (an ``int``: programs touched --
    bounded by ``PREWARM_PROGRAM_BUDGET`` for the production bench
    layout, asserted by ``bench.py --smoke``).
    Every compile/load/execute rides the transient-error retry, so a
    flake can never escape to the caller's timed region.
    """
    # ABI gate: prewarm against the lowered/padded system -- the zoo
    # then keys on the shape bucket, so a SECOND mechanism landing in
    # the same bucket resolves every program from the registry with
    # zero compiles (asserted by bench.py --smoke).
    low = _abi.maybe_lower(spec)
    if low is not None:
        return prewarm_sweep_programs(
            low, low.pad_conditions(conds),
            tof_mask=low.pad_tof_mask(tof_mask), opts=opts,
            buckets=buckets, aot_buckets=aot_buckets,
            tier2_buckets=tier2_buckets,
            tier2_aot_buckets=tier2_aot_buckets,
            check_stability=check_stability, pos_jac_tol=pos_jac_tol,
            verbose=verbose, cache=cache, workers=workers, mesh=mesh)

    import time as _time

    def _log(msg):
        if verbose:
            import sys as _sys
            print(f"prewarm: {msg}", file=_sys.stderr, flush=True)

    def timed_retry(fn, label):
        t0 = _time.perf_counter()
        out = call_with_backend_retry(fn, label=label)
        _log(f"{label}: {_time.perf_counter() - t0:.2f} s")
        return out

    if cache is None:
        cache = compile_pool.AOTCache(
            fingerprint=compile_pool.spec_fingerprint(spec))
    elif cache is False:
        cache = compile_pool.AOTCache(root="off")
    _log(f"AOT cache: {cache.root or 'disabled'}; "
         f"compile pool width {workers or compile_pool.compile_workers()}")

    # Registry identity: the shape bucket under ABI, the spec itself
    # otherwise (must match what _registered_call consults at sweep
    # time). Job "args" carry the ABI operand prepend so program_key()
    # and lower() see the dispatch-time signature.
    pspec = _prog_spec(spec)

    def _resolve(kind, prog, args, label):
        """Registry/cache lookup for one program; returns True when an
        executable is already available (registered now or before)."""
        key = compile_pool.program_key(kind, args)
        # Name the ledger row whatever happens next: the cost numbers
        # arrive from cache.save/load, but kind/label only prewarm
        # knows (program keys are hashes).
        _costs.record(key, kind=kind, label=label)
        if compile_pool.lookup(pspec, key) is not None:
            return key, True
        try:
            exe = cache.load(key)
        except compile_pool.CacheMismatch as e:
            _log(f"{label}: stale AOT entry ({e}); recompiling")
            exe = None
        if exe is not None:
            compile_pool.register(pspec, key, exe)
            _log(f"{label}: loaded from AOT cache")
            return key, True
        return key, False

    def _compile_and_publish(job):
        """Pool task: compile one program, serialize + register it.
        Cache entries record the argument sharding fingerprint, so a
        sharded executable is never deserialized into a process whose
        device population cannot satisfy it (silent miss, recompile)."""
        _san_recompile.note_compile(job["label"])
        # Compile is authoritative: force the fingerprint so a key
        # collision raises AT the compile site (trace-ident sanitizer).
        _san_trace_ident.note_jaxpr(job["kind"], job["key"],
                                    job["prog"], job["args"],
                                    force=True)
        exe = call_with_backend_retry(
            lambda: job["prog"].lower(*job["args"]).compile(),
            label=f"compile:{job['label']}")
        _metrics.counter("pycatkin_compile_total",
                         "fresh XLA compiles through the compile "
                         "pool").inc()
        cache.save(job["key"], exe,
                   sharding=compile_pool.args_sharding_fingerprint(
                       job["args"]))
        # Direct harvest too: cache.save only harvests when the disk
        # layer is enabled, and every prewarmed program must own a
        # ledger row regardless (bench.py --smoke costs_ok gate).
        _costs.record(job["key"], kind=job["kind"], label=job["label"],
                      cost=_costs.harvest_cost(exe), source="compiled")
        compile_pool.register(pspec, job["key"], exe)
        return exe

    n_compiled = 0
    n_loaded = 0

    def _partition(jobs_batch):
        """Resolve each job against the registry/AOT cache; return the
        jobs that still need a fresh compile."""
        nonlocal n_loaded
        to_compile = []
        for job in jobs_batch:
            key, have = _resolve(job["kind"], job["prog"], job["args"],
                                 job["label"])
            job["key"] = key
            if have:
                n_loaded += 1
            else:
                to_compile.append(job)
        return to_compile

    def _ensure(jobs_batch):
        """Load-or-compile a batch of jobs concurrently (blocking)."""
        nonlocal n_compiled
        to_compile = _partition(jobs_batch)
        if to_compile:
            t0 = _time.perf_counter()
            compile_pool.map_compile(
                [lambda j=job: _compile_and_publish(j)
                 for job in to_compile], workers)
            n_compiled += len(to_compile)
            _log(f"compiled {len(to_compile)} program(s) concurrently "
                 f"in {_time.perf_counter() - t0:.2f} s")

    leaves = jax.tree_util.tree_leaves(conds)
    n = leaves[0].shape[0]
    sharding = _subset_sharding(mesh, n)
    if sharding is not None:
        conds = jax.device_put(conds, sharding)
    backend = _resolve_backend(mesh=mesh)
    dyn = jnp.asarray(spec.dynamic_indices)

    def _keys_full():
        # Rebuilt per dispatch: the solve programs donate their key
        # buffer, so a retried run must never re-feed a consumed array.
        k = jax.random.split(jax.random.PRNGKey(0), n)
        return jax.device_put(k, sharding) if sharding is not None else k

    # --- the fused sweep program first (blocking: everything else's
    # result shapes derive from it). Solve + quarantine + tier-0
    # screen + TOF/activity + the diagnostics bundle are ONE program;
    # its kind/key must match what _fused_sweep dispatches exactly. ---
    fast_opts = _fast_pass_opts(opts)
    has_tof = tof_mask is not None
    mask_arr = jnp.asarray(tof_mask) if has_tof else None
    tail = (mask_arr,) if has_tof else ()
    # Warm the ACTIVE precision tier's fused program only: the tiered
    # variant is the f32 bulk + f64 polish as sequential stages of ONE
    # fused trace (a static branch pair, not a second zoo entry), so
    # the program count -- and PREWARM_PROGRAM_BUDGET -- is unchanged.
    # The rescue/jac programs below stay pure f64 under every tier.
    tier = _precision.active_tier()
    fast_kind = _fused_kind(fast_opts, pos_jac_tol, backend, has_tof,
                            check_stability, sharding, tier=tier)
    fast_prog = _fused_sweep_program(pspec, fast_opts, pos_jac_tol,
                                     backend, has_tof, check_stability,
                                     sharding, tier=tier)
    fast_job = {"kind": fast_kind, "prog": fast_prog,
                "args": _prog_args(spec,
                                   (conds, _keys_full(), None) + tail),
                "label": f"fused sweep @{n}"}
    _ensure([fast_job])

    # --- build the FULL job list from abstract result shapes: no
    # execution has happened yet, so the tail compiles can overlap the
    # fast pass below. ys-dependent arguments enter the jobs as
    # jax.ShapeDtypeStruct (lower() and program_key() only consume
    # shape/dtype/sharding); phase C builds the concrete arrays. ---
    shapes = jax.eval_shape(
        fast_prog, *_prog_args(spec, (conds, _keys_full(), None) + tail))
    x_dtype = shapes[0].x.dtype
    n_species = shapes[0].x.shape[1]

    def _sds(shape, dtype, bsh=None):
        if bsh is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=bsh)

    jobs: list[dict] = []
    seen_keys: set = set()

    def _add(kind, prog, args, label, execute, fence, exec_args=None):
        # Dedup on the program key: e.g. the same jac bucket named in
        # both `tier2_buckets` and `tier2_aot_buckets` once. The ABI
        # operand prepend is baked into job args here (so key/lower
        # match dispatch); exec_args stay legacy -- phase C dispatches
        # through _registered_call, which prepends internally.
        args = _prog_args(spec, args)
        key = compile_pool.program_key(kind, args)
        if key in seen_keys:
            return
        seen_keys.add(key)
        jobs.append({"kind": kind, "prog": prog, "args": args,
                     "label": label, "execute": execute,
                     "fence": fence, "key": key,
                     "exec_args": exec_args})

    solve_fence = lambda r: jnp.sum(r.residual)           # noqa: E731
    jac_fence = lambda J: jnp.sum(                        # noqa: E731
        jnp.where(jnp.isfinite(J), J, 0.0))

    def _bucket_conds(b):
        idx = np.arange(b) % n
        sub = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[idx],
                                     conds)
        return idx, _place_subset(mesh, b, sub)

    def _add_rescue_bucket(b, execute):
        # ONE consolidated program covers the whole ladder at this
        # bucket: polish / full PTC / LM / unseeded demote re-solve
        # are runtime scalars of _rescue_program, and the scalars'
        # VALUES never enter the program key -- so the four r05
        # variants share this single compile.
        idx, sub = _bucket_conds(b)
        bsh = _subset_sharding(mesh, b)
        keys_b = jax.random.split(jax.random.PRNGKey(1), b)
        if mesh is not None:
            keys_b = _place_subset(mesh, b, keys_b)
        scal = (np.int32(0), np.bool_(True),
                np.float64(opts.dt0), np.float64(opts.dt_grow_min),
                np.int64(opts.max_steps), np.int64(opts.max_attempts))
        tag = "" if execute else "aot "

        def exec_args(res, b=b, idx=idx, sub=sub, scal=scal):
            keys = jax.random.split(jax.random.PRNGKey(1), b)
            x0 = jnp.asarray(res.x)[idx][:, dyn]
            if mesh is not None:
                keys, x0 = _place_subset(mesh, b, keys, x0)
            return (sub, keys, x0) + scal

        _add(_rescue_kind(opts, bsh),
             _rescue_program(pspec, _pacing_key(opts), bsh),
             (sub, keys_b, _sds((b, int(dyn.size)), x_dtype, bsh))
             + scal,
             f"{tag}rescue @{b}", execute, solve_fence, exec_args)

    def _add_jac(b, execute):
        idx, sub = _bucket_conds(b)
        bsh = _subset_sharding(mesh, b)
        tag = "" if execute else "aot "

        def exec_args(res, b=b, idx=idx, sub=sub):
            ysub = jnp.asarray(res.x)[idx]
            if mesh is not None:
                ysub = _place_subset(mesh, b, ysub)
            return (sub, ysub)

        _add("jac", _jacobian_program(pspec),
             (sub, _sds((b, n_species), x_dtype, bsh)),
             f"{tag}tier-2 jac @{b}", execute, jac_fence, exec_args)

    for b in buckets:
        _add_rescue_bucket(b, True)
    for b in aot_buckets:
        _add_rescue_bucket(b, False)
    if check_stability:
        # Jacobian shapes come from the tier2 knobs ONLY: the hot
        # path's TIER2_MIN_BUCKET floor makes small jac shapes
        # unreachable, so warming one per solve bucket (the r05
        # layout) paid compiles the sweep could never hit.
        for b in tier2_buckets:
            _add_jac(b, True)
        for b in tier2_aot_buckets:
            _add_jac(b, False)

    def run_fast():
        out = _registered_call(spec, fast_kind, fast_prog,
                               (conds, _keys_full(), None) + tail)
        r = out[0]
        np.asarray(jnp.sum(r.residual))      # sync inside the retry
        return r

    # --- phase B: satisfy every tail job from cache or the compile
    # pool, OVERLAPPED with the fast-pass execution (compiles release
    # the GIL; the device runs the fast pass while host threads
    # compile the tail). PYCATKIN_PREWARM_OVERLAP=0 serializes. ---
    overlap = os.environ.get("PYCATKIN_PREWARM_OVERLAP", "1").strip() \
        .lower() not in ("0", "off", "none", "disabled", "false")
    if overlap:
        to_compile = _partition(jobs)
        t0 = _time.perf_counter()
        pending = compile_pool.submit_compile(
            [lambda j=job: _compile_and_publish(j)
             for job in to_compile], workers)
        res = timed_retry(run_fast, f"fused sweep @{n}")
        pending.wait()
        if to_compile:
            n_compiled += len(to_compile)
            _log(f"compiled {len(to_compile)} program(s) overlapped "
                 f"with the fast pass in "
                 f"{_time.perf_counter() - t0:.2f} s")
    else:
        _ensure(jobs)
        res = timed_retry(run_fast, f"fused sweep @{n}")
    n_executed = 1

    # --- phase C: run the executed buckets once (device is serial),
    # with concrete arguments built fresh INSIDE each retried unit
    # (the rescue program donates keys and x0). ---
    for job in jobs:
        if not job["execute"]:
            continue

        def run(j=job):
            # exec_args are LEGACY args (_registered_call prepends the
            # ABI operands); the stored job args already carry them, so
            # that fallback dispatches against the bucket identity.
            if j["exec_args"] is not None:
                out = _registered_call(spec, j["kind"], j["prog"],
                                       j["exec_args"](res))
            else:
                out = _registered_call(pspec, j["kind"], j["prog"],
                                       j["args"])
            np.asarray(j["fence"](out))      # sync inside the retry
            return out

        timed_retry(run, job["label"])
        n_executed += 1

    stats = PrewarmStats(1 + len(jobs))
    stats.compiled = n_compiled
    stats.loaded = n_loaded
    stats.cache_writes = cache.writes
    stats.executed = n_executed
    stats.cache = cache.stats()
    _metrics.counter("pycatkin_prewarm_programs_total",
                     "programs ensured by prewarm, by how they were "
                     "obtained").inc(n_compiled, source="compiled")
    _metrics.counter("pycatkin_prewarm_programs_total").inc(
        n_loaded, source="loaded")
    _log(f"{int(stats)} programs ({n_compiled} compiled, {n_loaded} "
         f"loaded/registered, {n_executed} executed once)")
    return stats


def warm_from_aot_cache(spec: ModelSpec, conds: Conditions, tof_mask=None,
                        opts: SolverOptions = SolverOptions(),
                        check_stability: bool = False,
                        pos_jac_tol: float = 1e-2,
                        cache=None) -> int:
    """Register any AOT-cached executables matching this sweep's
    full-shape programs -- no compilation, no execution, no device
    work; a cache miss is free. Returns the number of executables
    registered.

    The zero-cost sibling of :func:`prewarm_sweep_programs` for
    processes that solve exactly one sweep and exit (the dispatch
    workers, parallel/dispatch.py): executing programs just to warm
    runtime caches would double their solve cost, but deserializing
    executables some earlier process already compiled is nearly free.
    The whole clean sweep is ONE fused program now
    (:func:`_fused_sweep_program`), so one registry entry covers the
    worker's entire happy path."""
    low = _abi.maybe_lower(spec)
    if low is not None:
        return warm_from_aot_cache(
            low, low.pad_conditions(conds),
            tof_mask=low.pad_tof_mask(tof_mask), opts=opts,
            check_stability=check_stability, pos_jac_tol=pos_jac_tol,
            cache=cache)

    if cache is None:
        cache = compile_pool.AOTCache(
            fingerprint=compile_pool.spec_fingerprint(spec))
    if not cache.enabled:
        return 0
    pspec = _prog_spec(spec)
    n = jax.tree_util.tree_leaves(conds)[0].shape[0]
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    fast_opts = _fast_pass_opts(opts)
    backend = _resolve_backend()
    tier = _precision.active_tier()
    has_tof = tof_mask is not None
    tail = (jnp.asarray(tof_mask),) if has_tof else ()
    jobs = [(_fused_kind(fast_opts, pos_jac_tol, backend, has_tof,
                         check_stability, tier=tier),
             _fused_sweep_program(pspec, fast_opts, pos_jac_tol, backend,
                                  has_tof, check_stability, tier=tier),
             _prog_args(spec, (conds, keys, None) + tail))]
    n_loaded = 0
    for kind, _prog, args in jobs:
        key = compile_pool.program_key(kind, args)
        if compile_pool.lookup(pspec, key) is not None:
            continue
        try:
            exe = cache.load(key)
        except compile_pool.CacheMismatch:
            continue                       # cannot recompile here
        if exe is not None:
            compile_pool.register(pspec, key, exe)
            _costs.record(key, kind=kind)
            n_loaded += 1
    return n_loaded


def shard_conditions(conds: Conditions, mesh: Mesh):
    """Place a lane-batched Conditions pytree on a mesh (lane-sharded)."""
    axis = mesh.axis_names[0]
    return jax.device_put(conds, NamedSharding(mesh, P(axis)))
