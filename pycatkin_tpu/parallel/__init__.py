from .batch import (batch_steady_state, batch_transient,
                    continuation_sweep, make_mesh, shard_conditions,
                    stack_conditions, sweep_steady_state)
from .dispatch import dispatch_sweep, load_conditions, save_conditions
