from . import network, rates, thermo
