"""Vectorized thermochemistry kernels (JAX).

Free-energy contributions for ALL species at once as pure functions of
(T, p) and static padded arrays -- the TPU-native replacement for the
reference's per-object lazy evaluation (reference state.py:247-386).
Units: eV throughout; T in K; p in Pa; frequencies in Hz; masses in amu;
moments of inertia in amu*A^2.

Shapes: n_s species, F padded vibrational modes.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..constants import (JtoeV, LOG_ROT_CONST, LOG_TRANS_CONST, h, kB)


def zero_point_energy(freq: jnp.ndarray, fmask: jnp.ndarray) -> jnp.ndarray:
    """ZPE[eV] = 0.5*h*sum(f) per species (reference state.py:266-287).

    freq: [n_s, F] Hz (padded with zeros); fmask: [n_s, F] 1 for modes that
    enter the sum (padding and truncated modes excluded).
    """
    return 0.5 * h * jnp.sum(freq * fmask, axis=-1) * JtoeV


def vibrational_energy(T, freq: jnp.ndarray, fmask: jnp.ndarray) -> jnp.ndarray:
    """Harmonic vibrational free energy incl. ZPE per species
    (reference state.py:289-318):
    Gvibr = ZPE + kB*T*sum(ln(1 - exp(-h*f/kB*T))) [eV].

    Species with no active modes return exactly their (zero) ZPE.
    """
    zpe = zero_point_energy(freq, fmask)
    x = freq * h / (kB * T)
    # Guard padded slots (f=0 -> log(0)): mask before the log.
    log_term = jnp.where(fmask > 0, jnp.log1p(-jnp.exp(-jnp.where(fmask > 0, x, 1.0))), 0.0)
    return zpe + kB * T * jnp.sum(log_term, axis=-1) * JtoeV


def translational_energy(T, p, mass: jnp.ndarray, is_gas: jnp.ndarray) -> jnp.ndarray:
    """Ideal-gas translational free energy per species
    (reference state.py:320-338):
    Gtran = -kB*T*ln[(kB*T/p) * (2*pi*m*kB*T/h^2)^1.5] [eV]; 0 for
    non-gas species.

    Assembled in log space from the precomputed LOG_TRANS_CONST: the raw
    2*pi*m_kg*kB (~6e-49) underflows TPU's f32-ranged f64 emulation.
    """
    m_amu = jnp.where(is_gas > 0, mass, 1.0)
    log_q = jnp.log(kB * T / p) + 1.5 * (LOG_TRANS_CONST +
                                         jnp.log(m_amu * T))
    return jnp.where(is_gas > 0, -kB * T * log_q * JtoeV, 0.0)


def rotational_energy(T, inertia: jnp.ndarray, sigma: jnp.ndarray,
                      is_gas: jnp.ndarray, is_linear: jnp.ndarray) -> jnp.ndarray:
    """Rigid-rotor rotational free energy per species
    (reference state.py:340-365). Linear molecules (2 nonzero moments):
    Gr = -kB*T*ln(8*pi^2*kB*T*I/(sigma*h^2)) with I = sqrt(prod of nonzero
    moments); non-linear:
    Gr = -kB*T*ln(sqrt(pi)/sigma * (8*pi^2*kB*T/h^2)^1.5 * sqrt(prod I)).
    """
    # All in amu*A^2 with the unit conversion folded into LOG_ROT_CONST:
    # the raw I_kgm2 (~1e-45) sits at the edge of TPU's f32-ranged f64
    # emulation. Linear: geometric-mean moment of the nonzero pair.
    prod_amu = jnp.prod(jnp.where(inertia > 0, inertia, 1.0), axis=-1)
    log_q_lin = LOG_ROT_CONST + jnp.log(T * jnp.sqrt(prod_amu) / sigma)
    log_q_nonlin = (0.5 * jnp.log(jnp.pi) - jnp.log(sigma) +
                    1.5 * (LOG_ROT_CONST + jnp.log(T)) +
                    0.5 * jnp.log(prod_amu))
    g = -kB * T * jnp.where(is_linear > 0, log_q_lin, log_q_nonlin) * JtoeV
    # Gas species without inertia data (their free energy never enters a
    # reaction) get 0 rather than a NaN that would poison the matmuls.
    has_inertia = jnp.sum(inertia, axis=-1) > 0
    return jnp.where((is_gas > 0) & has_inertia, g, 0.0)


def thermal_contributions(T, p, *, freq, fmask, mass, sigma, inertia,
                          is_gas, is_linear, mix,
                          gvibr0, gvibr_mask, gtran0, gtran_mask,
                          grota0, grota_mask):
    """All three thermal free-energy contributions, with input-file
    overrides and gas-mixture (``gasdata``) corrections applied.

    ``mix`` is an [n_s, n_s] matrix of gas-mixture fractions: row i holds
    the fraction of gas state j co-adsorbed with species i (reference
    state.py:335-338,362-365) -- the translational/rotational contributions
    of those gas states are fraction-weighted onto species i.

    Returns (Gvibr, Gtran, Grota) in eV, each [n_s].
    """
    gv = vibrational_energy(T, freq, fmask)
    gt = translational_energy(T, p, mass, is_gas)
    gr = rotational_energy(T, inertia, sigma, is_gas, is_linear)
    gv = jnp.where(gvibr_mask > 0, gvibr0, gv)
    gt = jnp.where(gtran_mask > 0, gtran0, gt)
    gr = jnp.where(grota_mask > 0, grota0, gr)
    gt = gt + mix @ gt
    gr = gr + mix @ gr
    return gv, gt, gr
