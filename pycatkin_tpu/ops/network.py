"""Reaction-network RHS and Jacobian kernels (JAX).

The per-reaction Python scatter loops of the reference
(old_system.py:202-313, system.py:345-508) become two gathers and one
matmul: with padded reactant/product index arrays, the rate of reaction j
is ``k_j * prod_a y_ext[idx[j, a]]`` and the species balance is a single
stoichiometric matrix-vector product -- MXU-friendly and exactly
differentiable, so the Jacobian is ``jax.jacfwd`` of the RHS.

Conventions (identical to the reference legacy engine, which produced all
golden numbers): gas entries of y are in bar and enter rates as Pa
(y * 1e5); surface/adsorbate entries are coverages; ``stoich_fwd`` /
``stoich_rev`` fold the reaction ``scaling`` factor and the per-gas-row
``site_density`` factor (old_system.py:239-247) into the matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..constants import bartoPa

# Reactor type codes (canonical definition; frontend.spec re-exports).
REACTOR_ID = 0
REACTOR_CSTR = 1


def reaction_rates(y, kf, kr, *, reac_idx, prod_idx, is_gas):
    """Forward/reverse rates of every reaction, [n_r] each.

    reac_idx/prod_idx: [n_r, A] species indices padded with n_s (a virtual
    species of constant activity 1). Gas species contribute their partial
    pressure in Pa (reference old_system.py:218-225).
    """
    y_eff = jnp.where(is_gas > 0, y * bartoPa, y)
    y_ext = jnp.concatenate([y_eff, jnp.ones(1, dtype=y.dtype)])
    fwd = kf * jnp.prod(y_ext[reac_idx], axis=-1)
    rev = kr * jnp.prod(y_ext[prod_idx], axis=-1)
    return fwd, rev


def species_rhs(y, kf, kr, *, reac_idx, prod_idx, is_gas, stoich):
    """Chemistry-only dy/dt = S_w @ (r_fwd - r_rev), [n_s].

    ``stoich`` [n_s, n_r] carries +/- stoichiometric counts already
    weighted by reaction scaling and (for gas rows) site density.
    """
    fwd, rev = reaction_rates(y, kf, kr, reac_idx=reac_idx,
                              prod_idx=prod_idx, is_gas=is_gas)
    return stoich @ (fwd - rev)


def reactor_rhs(y, t, kf, kr, *, reac_idx, prod_idx, is_gas, stoich,
                is_adsorbate, reactor_type, sigma_over_bar, inv_tau, inflow):
    """Full reactor ODE right-hand side (reference reactor.py:89-189).

    - InfiniteDilution: gas rows are clamped (multiplied by 0); adsorbate
      rows evolve.
    - CSTR: gas rows are scaled by sigma/bartoPa (site rate -> bar rate,
      sigma = kB*T*A_cat/V precomputed by the caller) and gain the flow
      term (inflow - y)/tau.
    """
    chem = species_rhs(y, kf, kr, reac_idx=reac_idx, prod_idx=prod_idx,
                       is_gas=is_gas, stoich=stoich)
    if reactor_type == REACTOR_ID:
        return chem * is_adsorbate
    row_scale = jnp.where(is_adsorbate > 0, 1.0, sigma_over_bar)
    flow = jnp.where(is_gas > 0, (inflow - y) * inv_tau, 0.0)
    return chem * row_scale + flow


def reactor_rhs_and_scale(y, t, kf, kr, *, reac_idx, prod_idx, is_gas,
                          stoich, is_adsorbate, reactor_type,
                          sigma_over_bar, inv_tau, inflow):
    """(dy/dt, gross) where ``gross`` is the per-species GROSS flux --
    |S| @ (fwd + rev) plus |flow| terms -- under the same reactor row
    transforms as the RHS.

    The gross flux is the convergence yardstick for steady solves: a
    state is steady when net production is small *relative to gross
    throughput*; an absolute dy/dt tolerance is unreachable by
    finite-precision cancellation when gross fluxes are large.
    """
    fwd, rev = reaction_rates(y, kf, kr, reac_idx=reac_idx,
                              prod_idx=prod_idx, is_gas=is_gas)
    S_abs = jnp.abs(stoich)
    chem = stoich @ (fwd - rev)
    # |fwd|,|rev|: off-manifold iterates (negative coverages) can flip
    # rate signs; the scale must stay a positive flux magnitude.
    gross = S_abs @ (jnp.abs(fwd) + jnp.abs(rev))
    if reactor_type == REACTOR_ID:
        return chem * is_adsorbate, gross * is_adsorbate
    row_scale = jnp.where(is_adsorbate > 0, 1.0, sigma_over_bar)
    flow = jnp.where(is_gas > 0, (inflow - y) * inv_tau, 0.0)
    gflow = jnp.where(is_gas > 0, (inflow + jnp.abs(y)) * inv_tau, 0.0)
    return chem * row_scale + flow, gross * row_scale + gflow


def make_jacobian(rhs_fn):
    """Analytic-by-autodiff Jacobian of an RHS closure: y -> d(rhs)/dy.

    This IS the solvers' hot path: XLA batches the n_s JVP passes into
    efficient fused code on TPU. :func:`reactor_jacobian` below computes
    the same matrix in closed form (the reference's hand derivation,
    vectorized); measured slower on TPU, it serves as the independent
    implementation for Jacobian parity tests.
    """
    return jax.jacfwd(rhs_fn)


def _excl_products(P):
    """[n_r, A] -> [n_r, A] products over all OTHER columns (exclusive
    product via left/right cumulative products -- no division, so floored
    or zero factors cannot poison the result)."""
    ones = jnp.ones_like(P[:, :1])
    left = jnp.concatenate([ones, jnp.cumprod(P[:, :-1], axis=1)], axis=1)
    right = jnp.concatenate(
        [jnp.cumprod(P[:, :0:-1], axis=1)[:, ::-1], ones], axis=1)
    return left * right


def chem_jacobian(y, kf, kr, *, reac_idx, prod_idx, is_gas, stoich):
    """Closed-form d(species_rhs)/dy, [n_s, n_s] (the reference's
    hand-derived Jacobian, old_system.py:250-313 / system.py:437-508,
    vectorized): d(fwd_k)/dy_i = kf_k * sum over slots holding i of the
    product of the OTHER slot factors, times d(y_eff_i)/dy_i (bar->Pa
    for gas). Repeated slots (stoichiometric powers y^c) sum to the
    correct c * y^(c-1) * rest. Dense one-hot contractions build the
    [n_r, n_s] rate Jacobian (see the inline comment on why not
    scatter-add); the species Jacobian is a single matmul. Agreement
    with ``jax.jacfwd`` of the RHS is pinned by
    tests/test_analytic_jacobian.py (the autodiff path is what the
    solvers use -- it measures faster on TPU)."""
    n_s = y.shape[0]
    y_eff = jnp.where(is_gas > 0, y * bartoPa, y)
    y_ext = jnp.concatenate([y_eff, jnp.ones(1, dtype=y.dtype)])
    unit = jnp.where(is_gas > 0, bartoPa, 1.0)

    # Slot->species one-hot masks are built from STATIC index arrays, so
    # XLA constant-folds them; the padding index n_s compares False
    # everywhere and drops out. Dense einsum instead of scatter-add:
    # TPU scatters serialize, and these are in the Newton hot loop.
    oh_r = (jnp.asarray(reac_idx)[:, :, None] ==
            jnp.arange(n_s)[None, None, :]).astype(y.dtype)
    oh_p = (jnp.asarray(prod_idx)[:, :, None] ==
            jnp.arange(n_s)[None, None, :]).astype(y.dtype)
    cf = kf[:, None] * _excl_products(y_ext[reac_idx])
    cr = kr[:, None] * _excl_products(y_ext[prod_idx])
    Jf = jnp.einsum("ra,ran->rn", cf, oh_r)
    Jr = jnp.einsum("ra,ran->rn", cr, oh_p)
    return (stoich @ (Jf - Jr)) * unit[None, :]


def reactor_jacobian(y, t, kf, kr, *, reac_idx, prod_idx, is_gas, stoich,
                     is_adsorbate, reactor_type, sigma_over_bar, inv_tau,
                     inflow):
    """Closed-form d(reactor_rhs)/dy under the same row transforms as
    :func:`reactor_rhs` (reference reactor.py:103-181)."""
    J = chem_jacobian(y, kf, kr, reac_idx=reac_idx, prod_idx=prod_idx,
                      is_gas=is_gas, stoich=stoich)
    if reactor_type == REACTOR_ID:
        return J * is_adsorbate[:, None]
    row_scale = jnp.where(is_adsorbate > 0, 1.0, sigma_over_bar)
    J = J * row_scale[:, None]
    return J - jnp.diag(jnp.where(is_gas > 0, inv_tau, 0.0))
