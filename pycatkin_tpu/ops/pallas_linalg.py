"""Pallas batched dense LU kernels for the Newton direction solve.

The sweep hot path bottoms out in repeated small dense factorize/solve
(one per Newton/PTC/LM iteration per lane). The XLA-op kernels in
:mod:`pycatkin_tpu.ops.linalg` express that as ``lax.fori_loop`` bodies
over full ``[n, n]`` tiles, which leaves the schedule to XLA: every
elimination step round-trips the tile through whatever layout the
fusion picked. These kernels instead pin the WHOLE per-lane
factorization resident in VMEM for the duration of the loop: one
kernel invocation factors one lane's matrix start-to-finish, and the
lane axis batches over it (``jax.vmap``'s ``pallas_call`` batching
rule lifts the lane axis into the kernel grid, one grid program per
lane -- which is exactly the "one grid program per lane-tile" shape on
TPU).

Supported shapes are the static ABI species buckets
(:data:`PALLAS_BUCKETS`): bucket-padded systems are what the hot loop
actually solves under ``PYCATKIN_ABI=1``, the padded ghost lanes carry
``x' = -x`` so the Jacobian is ``blkdiag(J, -I)`` and factors
harmlessly (the ``-1`` diagonal pivots are exact), and a static n is
what lets the kernel claim its VMEM up front. Everything else falls
back to the XLA path at the dispatch seam
(:func:`pycatkin_tpu.ops.linalg.select_solver`).

Numerics mirror ``ops/linalg`` step for step -- partial pivoting with
first-max row selection, the same elimination update, the same
triangular recurrences -- but expressed gather/scatter-free: row
swaps, row/column extraction and the permutation apply are all
``where``-selects driven by 2D-``broadcasted_iota`` one-hot masks
(exact selects, never ``0 * x`` products, so Inf/NaN quarantine lanes
stay merely non-finite instead of poisoning neighbours). A singular
lane divides by a zero pivot and yields non-finite output, exactly the
quarantine semantics the XLA path has.

On anything that is not a TPU the kernels run under Pallas
``interpret=True`` (the kernel body lowers to ordinary XLA HLO under
jit -- full speed, no hardware dependency), which is what the
equivalence corpus in ``tests/test_pallas_linalg.py`` and the
``bench.py --smoke`` ``kernels_ok`` gate pin against the XLA path.
Tier selection, program-key tagging (``:kpl``) and the auto/fallback
policy live in :mod:`pycatkin_tpu.precision`
(``PYCATKIN_LINALG_KERNEL``); docs/perf_pallas_linalg.md is the full
contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..lint.hotpath import hotpath

#: The static ABI species buckets the kernels are built for
#: (frontend/abi bucket table). The dispatch seam only routes an n
#: through Pallas when it is exactly one of these.
PALLAS_BUCKETS = (16, 32, 128, 512)


def supported(n: int) -> bool:
    """Whether the Pallas kernels serve systems of size ``n`` (static
    ABI bucket sizes only -- everything else stays on the XLA path)."""
    return int(n) in PALLAS_BUCKETS


def _interpret() -> bool:
    """Pallas interpret mode: on for every non-TPU backend, so the
    kernels are runnable (and CI-provable) anywhere; compiled Mosaic
    only on real TPU hardware."""
    return jax.default_backend() != "tpu"


def _row_ids(n: int):
    """``[0..n)`` as int32 via 2D iota (TPU requires >= 2D iota)."""
    return lax.broadcasted_iota(jnp.int32, (n, 1), 0)[:, 0]


def _pick_row(M, oh):
    """Row ``i`` of ``M`` where ``oh`` one-hots ``i`` -- a masked
    select + sum (n-1 exact zeros plus the row), never a gather."""
    return jnp.sum(jnp.where(oh[:, None], M, jnp.zeros((), M.dtype)),
                   axis=0)


def _pick_col(M, oh):
    """Column ``j`` of ``M`` where ``oh`` one-hots ``j``."""
    return jnp.sum(jnp.where(oh[None, :], M, jnp.zeros((), M.dtype)),
                   axis=1)


def _factor_body(A, perm, rid):
    """The full pivoted elimination loop over a resident ``[n, n]``
    value. Mirrors ``linalg._lu_step`` arithmetic exactly (same
    multiplier and update expressions), with one-hot selects in place
    of the dynamic row/column indexing."""
    n = A.shape[-1]
    zero = jnp.zeros((), A.dtype)

    def step(k, carry):
        A, perm = carry
        oh_col = rid == k
        col = jnp.abs(_pick_col(A, oh_col))
        col = jnp.where(rid < k, -jnp.inf, col)
        # First row attaining the column max == argmax (linalg uses
        # jnp.argmax; identical for the finite pivots that matter).
        p = jnp.min(jnp.where(col == jnp.max(col), rid, n))
        p = p.astype(jnp.int32)
        oh_k = rid == k
        oh_p = rid == p
        row_k = _pick_row(A, oh_k)
        row_p = _pick_row(A, oh_p)
        A = jnp.where(oh_k[:, None], row_p[None, :],
                      jnp.where(oh_p[:, None], row_k[None, :], A))
        pk = jnp.sum(jnp.where(oh_k, perm, 0)).astype(jnp.int32)
        pp = jnp.sum(jnp.where(oh_p, perm, 0)).astype(jnp.int32)
        perm = jnp.where(oh_k, pp,
                         jnp.where(oh_p, pk, perm)).astype(jnp.int32)
        # Eliminate below the pivot; store multipliers in column k.
        colk = _pick_col(A, oh_col)
        pivot = jnp.sum(jnp.where(oh_k, colk, zero))
        factors = jnp.where(rid > k, colk / pivot, zero)
        rowk = _pick_row(A, oh_k)
        upd = jnp.where(rid >= k, rowk, zero)
        A = A - factors[:, None] * upd[None, :]
        colk_new = _pick_col(A, oh_col)
        col_store = jnp.where(rid > k, factors, colk_new)
        A = jnp.where(oh_col[None, :], col_store[:, None], A)
        return A, perm

    return lax.fori_loop(0, n - 1, step, (A, perm))


def _permute_rhs(b, perm, rid):
    """``b[perm]`` as an exact one-hot select (no gather): output row
    r takes input row ``perm[r]`` wherever the [n, n] match mask hits."""
    sel = perm[:, None] == rid[None, :]
    zero = jnp.zeros((), b.dtype)
    return jnp.sum(jnp.where(sel[:, :, None], b[None, :, :], zero),
                   axis=1)


def _solve_body(LU, y, rid):
    """Forward/backward substitution over resident values, mirroring
    ``linalg.lu_solve``'s masked row-dot recurrences term for term
    (same contraction, so per-step results agree bitwise)."""
    n = LU.shape[-1]
    zero = jnp.zeros((), LU.dtype)

    def fwd(i, y):
        oh = rid == i
        row = _pick_row(LU, oh)
        s = jnp.where(rid < i, row, zero) @ y
        yi = _pick_row(y, oh)
        return jnp.where(oh[:, None], (yi - s)[None, :], y)

    def bwd(j, x):
        i = n - 1 - j
        oh = rid == i
        row = _pick_row(LU, oh)
        s = jnp.where(rid > i, row, zero) @ x
        dii = jnp.sum(jnp.where(oh, row, zero))
        xi = _pick_row(x, oh)
        return jnp.where(oh[:, None], ((xi - s) / dii)[None, :], x)

    y = lax.fori_loop(0, n, fwd, y)
    return lax.fori_loop(0, n, bwd, y)


def _lu_kernel(a_ref, lu_ref, perm_ref):
    """Factor one resident lane: A -> (LU, perm), all in VMEM."""
    A = a_ref[...]
    rid = _row_ids(A.shape[-1])
    LU, perm = _factor_body(A, rid, rid)
    lu_ref[...] = LU
    perm_ref[...] = perm


def _lu_solve_kernel(lu_ref, perm_ref, b_ref, x_ref):
    """Solve one resident lane given a prior factorization."""
    LU = lu_ref[...]
    rid = _row_ids(LU.shape[-1])
    y = _permute_rhs(b_ref[...].astype(LU.dtype), perm_ref[...], rid)
    x_ref[...] = _solve_body(LU, y, rid)


def _factor_solve_kernel(a_ref, b_ref, x_ref):
    """Fused factorize-then-solve: one kernel, the matrix never leaves
    VMEM between the factorization and the substitution passes."""
    A = a_ref[...]
    rid = _row_ids(A.shape[-1])
    LU, perm = _factor_body(A, rid, rid)
    y = _permute_rhs(b_ref[...].astype(LU.dtype), perm, rid)
    x_ref[...] = _solve_body(LU, y, rid)


def _as_mat(b):
    """RHS to ``[n, k]`` (the kernels' fixed rank), remembering whether
    to squeeze back -- the same [n] / [n, k] convention linalg uses."""
    return (b[:, None], True) if b.ndim == 1 else (b, False)


@hotpath
def lu_factor(A: jnp.ndarray):
    """Pallas LU factorization with partial pivoting of one ``[n, n]``
    system (``vmap`` batches lanes into the kernel grid). Returns
    ``(LU, perm)`` in :func:`pycatkin_tpu.ops.linalg.lu_factor`'s
    convention; ``perm`` is int32."""
    n = A.shape[-1]
    return pl.pallas_call(
        _lu_kernel,
        out_shape=(jax.ShapeDtypeStruct((n, n), A.dtype),
                   jax.ShapeDtypeStruct((n,), jnp.int32)),
        interpret=_interpret(),
    )(A)


@hotpath
def lu_solve(LU: jnp.ndarray, perm: jnp.ndarray, b: jnp.ndarray):
    """Pallas triangular solve for :func:`lu_factor` output.
    ``b``: [n] or [n, k]."""
    n = LU.shape[-1]
    bm, squeeze = _as_mat(b)
    x = pl.pallas_call(
        _lu_solve_kernel,
        out_shape=jax.ShapeDtypeStruct(bm.shape, LU.dtype),
        interpret=_interpret(),
    )(LU, perm.astype(jnp.int32), bm)
    return x[:, 0] if squeeze else x


@hotpath
def factor_solve(A: jnp.ndarray, b: jnp.ndarray):
    """Fused factorize-then-solve of ``A x = b`` in one kernel
    (matches ``linalg.solve``'s call contract at bucket shapes)."""
    bm, squeeze = _as_mat(b)
    x = pl.pallas_call(
        _factor_solve_kernel,
        out_shape=jax.ShapeDtypeStruct(bm.shape, A.dtype),
        interpret=_interpret(),
    )(A, bm)
    return x[:, 0] if squeeze else x


@hotpath
def make_msolve(M: jnp.ndarray):
    """Factor ``M`` once, return a solve closure reusable for several
    RHS -- :func:`pycatkin_tpu.ops.linalg.make_msolve`'s contract, so
    the chord-reuse Newton path re-uses the Pallas factorization per
    chord step."""
    LU, perm = lu_factor(M)
    return lambda r: lu_solve(LU, perm, r)
