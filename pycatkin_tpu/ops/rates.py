"""Rate-constant kernels (JAX).

TST/collision-theory rate constants for the whole network at once
(reference rate_constants.py:6-96, reaction.py:94-168). Reaction-type
dispatch is resolved at spec-build time into static masks; here everything
is branch-free ``where`` algebra so it jits, vmaps and differentiates.

Units: T [K], barriers/reaction energies [J/mol], masses [amu], areas
[m^2], moments of inertia [amu*A^2]. Arrhenius/desorption constants in
[1/s]; adsorption in [1/(s*Pa)].
"""

from __future__ import annotations

import jax.numpy as jnp

from ..constants import (LOG_DES_LIN, LOG_DES_POLY, R, ROT_THETA_AMU,
                         SQRT_2PI_AMU_KB, h, kB)


def prefactor(T):
    """TST prefactor kB*T/h [1/s] (reference rate_constants.py:89-96)."""
    return kB * T / h


def k_arrhenius(T, prefac, barrier):
    """A*exp(-Ea/RT) (reference rate_constants.py:6-13)."""
    return prefac * jnp.exp(-barrier / (R * T))


def k_adsorption(T, mass, area):
    """Collision-theory sticking rate [1/(s*Pa)]
    (reference rate_constants.py:16-23).

    area/sqrt(2*pi*m*kB*T) with the SI constant product precomputed
    host-side (raw m_kg*kB ~6e-49 underflows TPU's f32-ranged f64)."""
    return area / (SQRT_2PI_AMU_KB * jnp.sqrt(mass * T))


def k_desorption(T, mass, area, sigma, inertia, is_polyatomic, des_en):
    """Desorption from detailed balance with the gas rotational partition
    function (reference rate_constants.py:26-53).

    Non-linear polyatomic (3 nonzero moments): T^3.5 law over all three
    rotational temperatures; otherwise linear: T^3 law with the largest
    moment. ``des_en`` in J/mol. Assembled in log space: kB^2/h^3 (~7e53)
    overflows TPU's f32-ranged f64 emulation.
    """
    # Rotational temperatures in K from moments in amu*A^2 (in-range).
    theta = ROT_THETA_AMU / jnp.where(inertia > 0, inertia, 1.0)
    log_theta_prod = jnp.sum(
        jnp.where(inertia > 0, jnp.log(theta), 0.0), axis=-1)
    log_poly = (LOG_DES_POLY + 3.5 * jnp.log(T) +
                jnp.log(area * mass / sigma) - log_theta_prod)
    I_max = jnp.max(inertia, axis=-1)
    theta_lin = ROT_THETA_AMU / jnp.where(I_max > 0, I_max, 1.0)
    log_lin = (LOG_DES_LIN + 3.0 * jnp.log(T) +
               jnp.log(area * mass / sigma) - jnp.log(theta_lin))
    log_coeff = jnp.where(is_polyatomic > 0, log_poly, log_lin)
    return jnp.exp(log_coeff - des_en / (R * T))


def keq_thermo(T, rxn_en):
    """exp(-dG/RT) (reference rate_constants.py:66-73)."""
    return jnp.exp(-rxn_en / (R * T))


def rate_constants(T, *, dGrxn, dErxn, dGa_fwd,
                   is_arr, is_ads, is_des, is_ghost, reversible,
                   area, gas_mass, gas_sigma, gas_inertia, gas_polyatomic,
                   kscale, collision_des: bool = False):
    """Forward/reverse rate constants for every reaction [n_r].

    Dispatch masks (static, from the spec) reproduce reference
    reaction.py:118-168:
    - ``is_arr``: Arrhenius reac_type OR an activated step (TS present /
      user barrier): kf = (kBT/h)exp(-max(dGa_fwd,0)/RT), kr = kf/Keq.
    - ``is_ads``: non-activated adsorption: kf = kads; kr by the selected
      desorption model.
    - ``is_des``: non-activated desorption: mirror of adsorption.
    - ``is_ghost``: kf = kr = 0 (energy bookkeeping only).
    ``reversible`` zeroes kr when 0. ``kscale`` multiplies both kf and kr
    (the degree-of-rate-control perturbation channel, reference
    old_system.py:214-217, which preserves Keq).

    Desorption model (``collision_des``):
    - False (default, 'detailed_balance'): the reverse of adsorption is
      kads/Keq and the forward of desorption is kads*Keq -- the upstream
      PyCatKin convention that produced every golden regression value and
      is exactly detailed-balance consistent with the free-energy
      landscape.
    - True ('collision'): the fork's statistical-rate rewrite (reference
      reaction.py:134-162): desorption uses the rotational partition
      function formula ``kdes`` with the *electronic* desorption energy.
      Requires gas moments of inertia.

    Returns (kf, kr, Keq).
    """
    pre = prefactor(T)
    barrier = jnp.maximum(dGa_fwd, 0.0)
    keq = keq_thermo(T, dGrxn)

    kf_arr = k_arrhenius(T, pre, barrier)
    kr_arr = kf_arr / keq

    kf_ads = k_adsorption(T, gas_mass, area)
    if collision_des:
        kr_ads = k_desorption(T, gas_mass, area, gas_sigma, gas_inertia,
                              gas_polyatomic, -dErxn)
        kf_des = k_desorption(T, gas_mass, area, gas_sigma, gas_inertia,
                              gas_polyatomic, dErxn)
    else:
        kr_ads = kf_ads / keq
        kf_des = k_adsorption(T, gas_mass, area) * keq
    kr_des = k_adsorption(T, gas_mass, area)

    kf = jnp.where(is_arr > 0, kf_arr,
                   jnp.where(is_ads > 0, kf_ads,
                             jnp.where(is_des > 0, kf_des, 0.0)))
    kr = jnp.where(is_arr > 0, kr_arr,
                   jnp.where(is_ads > 0, kr_ads,
                             jnp.where(is_des > 0, kr_des, 0.0)))
    kf = jnp.where(is_ghost > 0, 0.0, kf)
    kr = jnp.where(is_ghost > 0, 0.0, kr) * (reversible > 0)
    return kf * kscale, kr * kscale, keq
