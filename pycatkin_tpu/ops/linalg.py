"""Dense linear algebra kernels built from arithmetic ops only.

XLA:TPU provides no float64 LuDecomposition custom call (it implements
only F32/C64), but double precision is part of this framework's numerical
contract: stiff microkinetic Jacobians carry rate constants spanning ~30
decades (SURVEY.md §7.3). These kernels implement LU factorization with
partial pivoting and triangular solves as plain jnp arithmetic inside
``lax.fori_loop``, so they compile for any dtype on any backend and
``vmap`` cleanly over solver lanes.

Systems here are small (n <= a few hundred: species counts, scaling
states), so the O(n) sequential pivot loop with O(n^2) vectorized row
updates is the right shape for the TPU -- each update is a fused
broadcast multiply-add over a [n, n] tile.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import precision as _precision


# Sequential steps per device dispatch in the very-large-n LU kernels.
# Unrolling LU_UNROLL row/column steps inside each fori body keeps the
# sequential-kernel count bounded (tail steps masked out with `where`).
LU_UNROLL = 32

# Width of the statically-unrolled panels in the blocked factorization.
# NOTE (round-3 measurement): the fully-static blocked kernel is
# numerically exact but its unrolled HLO (one-hot pivot matmuls +
# per-panel concats under f64 emulation) blows TPU compile time past
# 10 minutes at n=190, so it is NOT wired into the default dispatch --
# the chunk-unrolled sequential kernels below compile in seconds and
# run within ~1.2x of it. Kept for CPU use and as the reference
# implementation for a future Pallas panel kernel.
LU_BLOCK = 48


def _lu_step(A, perm, k, idx):
    """One partial-pivoted column elimination step. ``k`` may be traced
    (dynamic row/column indexing lowers to dynamic slices); callers must
    mask out steps with k >= n-1."""
    col = jnp.abs(A[:, k])
    col = jnp.where(idx < k, -jnp.inf, col)
    p = jnp.argmax(col)
    # Swap rows k and p (and the permutation entries).
    rk, rp = A[k], A[p]
    A = A.at[k].set(rp).at[p].set(rk)
    pk, pp = perm[k], perm[p]
    perm = perm.at[k].set(pp).at[p].set(pk)
    # Eliminate below the pivot; store multipliers in column k.
    pivot = A[k, k]
    factors = jnp.where(idx > k, A[:, k] / pivot, jnp.zeros_like(pivot))
    # Update only columns >= k: columns < k hold already-stored L
    # multipliers and must not be touched by the elimination.
    upd = jnp.where(idx >= k, A[k], 0.0)
    A = A - factors[:, None] * upd[None, :]
    A = A.at[:, k].set(jnp.where(idx > k, factors, A[:, k]))
    return A, perm


def _unit_lower_solve(L, B, strict=True):
    """Solve L y = B for unit-lower-triangular L ([b, b] static, small)
    by fully unrolled forward substitution. ``strict``: L's strictly
    lower part is read, the diagonal is taken as 1."""
    b = L.shape[-1]
    y = B
    for r in range(1, b):
        y = y.at[r].add(-(L[r, :r] @ y[:r]))
    return y


def lu_factor_blocked(A: jnp.ndarray, block: int = LU_BLOCK):
    """Right-looking blocked LU with partial pivoting, statically
    unrolled (no sequential device loops).

    The round-3 profile of bench config 5 (128 lanes x n=190, TPU v5e)
    showed the column-at-a-time lu_factor at ~132-155 ms: every one of
    its ~190 sequential steps rewrites the FULL [n, n] tile (~n^3 total
    element writes) through tiny non-MXU kernels. Here elimination
    writes stay inside a [n, block] panel (n^2*block total) and the
    trailing update collapses into one matmul per panel that XLA puts on
    the MXU, with the whole schedule unrolled at trace time. Pivot row
    exchanges use one-hot arithmetic inside the panel; the accumulated
    panel permutation is applied to the left/trailing blocks by a
    one-hot permutation matmul (MXU) once per panel.

    Returns (LU, perm) in the same convention as :func:`lu_factor`.
    """
    n = A.shape[-1]
    idx = jnp.arange(n)
    perm = jnp.arange(n)
    dtype = A.dtype
    for k0 in range(0, n, block):
        b = min(block, n - k0)
        P_blk = A[:, k0:k0 + b]                      # [n, b] static slice
        pvec = jnp.arange(n)
        carange = jnp.arange(b)
        for c in range(b):                            # static column steps
            j = k0 + c
            col = jnp.abs(P_blk[:, c])
            col = jnp.where(idx < j, -jnp.inf, col)
            p = jnp.argmax(col)
            oh = (idx == p).astype(dtype)
            # Swap rows j <-> p of the panel (one-hot arithmetic) and of
            # the permutation vector.
            row_j = P_blk[j]
            row_p = oh @ P_blk
            P_blk = P_blk.at[j].set(row_p)
            P_blk = P_blk - oh[:, None] * (row_p - row_j)[None, :]
            pj, pp = pvec[j], pvec[p]
            pvec = pvec.at[j].set(pp).at[p].set(pj)
            # Eliminate below the pivot, panel columns only.
            pivot = P_blk[j, c]
            factors = jnp.where(idx > j, P_blk[:, c] / pivot,
                                jnp.zeros_like(pivot))
            upd = jnp.where(carange > c, P_blk[j], 0.0)
            P_blk = P_blk - factors[:, None] * upd[None, :]
            P_blk = P_blk.at[:, c].set(jnp.where(idx > j, factors,
                                                 P_blk[:, c]))
        # Net panel permutation as a one-hot matrix: row i of the
        # permuted block is old row pvec[i].
        P_mat = (pvec[:, None] == idx[None, :]).astype(dtype)
        parts = []
        if k0 > 0:
            parts.append(P_mat @ A[:, :k0])           # swap stored L rows
        parts.append(P_blk)
        if k0 + b < n:
            trail = P_mat @ A[:, k0 + b:]
            L11 = jnp.tril(P_blk[k0:k0 + b, :], -1)
            U12 = _unit_lower_solve(L11, trail[k0:k0 + b])
            L21 = P_blk[k0 + b:, :]
            T22 = trail[k0 + b:] - L21 @ U12
            parts.append(jnp.concatenate([trail[:k0], U12, T22], axis=0))
        A = jnp.concatenate(parts, axis=1)
        perm = perm[pvec]
    return A, perm


def lu_factor(A: jnp.ndarray, unroll: int = LU_UNROLL):
    """LU factorization with partial pivoting.

    Returns (LU, perm): LU holds L (unit diagonal, below) and U (on and
    above the diagonal); perm is the row permutation applied to A.
    ``unroll`` column steps run inside each sequential loop iteration.
    """
    n = A.shape[-1]
    idx = jnp.arange(n)
    steps = n - 1
    n_outer = max(-(-steps // unroll), 0)

    def outer(o, state):
        A, perm = state
        for d in range(unroll):
            k = o * unroll + d
            A2, perm2 = _lu_step(A, perm, k, idx)
            # Mask padded tail steps (k >= n-1): garbage from the
            # clamped dynamic indices (incl. 0-pivot inf/nan) is
            # discarded by the select.
            valid = k < steps
            A = jnp.where(valid, A2, A)
            perm = jnp.where(valid, perm2, perm)
        return A, perm

    LU, perm = lax.fori_loop(0, n_outer, outer, (A, jnp.arange(n)))
    return LU, perm


def lu_solve_blocked(LU: jnp.ndarray, perm: jnp.ndarray, b: jnp.ndarray,
                     block: int = LU_BLOCK):
    """Blocked triangular solves for lu_factor output, statically
    unrolled: within-block substitution + one cross-block matmul per
    block (the sequential row recurrence only ever spans ``block``
    rows). b: [n] or [n, k]."""
    n = LU.shape[-1]
    vec = b.ndim == 1
    y = (b[perm, None] if vec else b[perm]).astype(LU.dtype)
    # Forward: unit-lower L.
    for k0 in range(0, n, block):
        bb = min(block, n - k0)
        rhs = y[k0:k0 + bb] - LU[k0:k0 + bb, :k0] @ y[:k0]
        blkL = jnp.tril(LU[k0:k0 + bb, k0:k0 + bb], -1)
        y = y.at[k0:k0 + bb].set(_unit_lower_solve(blkL, rhs))
    # Backward: upper U with diagonal.
    x = y
    for k0 in reversed(range(0, n, block)):
        bb = min(block, n - k0)
        rhs = x[k0:k0 + bb] - LU[k0:k0 + bb, k0 + bb:] @ x[k0 + bb:]
        U = LU[k0:k0 + bb, k0:k0 + bb]
        z = rhs
        for r in reversed(range(bb)):
            z = z.at[r].set((z[r] - U[r, r + 1:] @ z[r + 1:]) / U[r, r])
        x = x.at[k0:k0 + bb].set(z)
    return x[:, 0] if vec else x


def lu_solve(LU: jnp.ndarray, perm: jnp.ndarray, b: jnp.ndarray,
             unroll: int = LU_UNROLL):
    """Solve A x = b given lu_factor output. b: [n] or [n, k].

    Chunk-unrolled sequential row recurrences (``unroll`` rows per loop
    iteration); see :func:`lu_solve_blocked` for the static variant."""
    n = LU.shape[-1]
    idx = jnp.arange(n)
    vec = b.ndim == 1
    y0 = (b[perm, None] if vec else b[perm]).astype(LU.dtype)
    n_outer = -(-n // unroll)

    def fwd(o, y):
        for d in range(unroll):
            i = o * unroll + d
            s = jnp.where(idx < i, LU[i], 0.0) @ y
            y2 = y.at[i].set(y[i] - s)
            y = jnp.where(i < n, y2, y)
        return y

    def bwd(o, x):
        for d in range(unroll):
            j = o * unroll + d
            i = n - 1 - j
            s = jnp.where(idx > i, LU[i], 0.0) @ x
            x2 = x.at[i].set((x[i] - s) / LU[i, i])
            x = jnp.where(i >= 0, x2, x)
        return x

    y = lax.fori_loop(0, n_outer, fwd, y0)
    x = lax.fori_loop(0, n_outer, bwd, y)
    return x[:, 0] if vec else x


# Below this size the O(n) factorization loop is unrolled at trace time:
# every step becomes static-index arithmetic (one-hot matmul row gathers,
# no scatters), which XLA fuses into a handful of vectorized TPU ops --
# crucial when the solve sits inside a vmapped while_loop over 1e4-1e5
# solver lanes. Larger systems fall back to the fori_loop LU.
UNROLL_MAX = 48
_UNROLL_MAX = UNROLL_MAX  # backward-compat alias


class SolverChoice(NamedTuple):
    """One resolved solve-path choice from :func:`select_solver`.

    ``path`` names the kernel family (``"pallas"`` | ``"gauss"`` |
    ``"lu"``), ``make_solve(M)`` factors once and returns a reusable
    solve closure (the chord-reuse contract), ``solve(A, b)`` is the
    one-shot direct solve. ``tier`` / ``kernel`` record what the
    selection resolved to (introspection; dtypes always flow from the
    operands themselves)."""
    path: str
    n: int
    tier: str
    kernel: str
    make_solve: Callable
    solve: Callable


def _make_inv_solve(M: jnp.ndarray):
    """Small-n factor-once path: explicit Gauss-Jordan inverse, solves
    collapse to matvecs (beats sequential substitution on TPU)."""
    Minv = inv(M)
    return lambda r: Minv @ r


def _make_lu_solve(M: jnp.ndarray):
    """Large-n factor-once path: sequential LU + triangular solves."""
    lu, piv = lu_factor(M)
    return lambda r: lu_solve(lu, piv, r)


def _lu_solve_once(A: jnp.ndarray, b: jnp.ndarray):
    return lu_solve(*lu_factor(A), b)


def select_solver(n: int, tier: str = None,
                  backend: str = None) -> SolverChoice:
    """THE dispatch seam for dense direction solves: every solve-path
    decision (Newton direction kernel, chord reuse, tier-2 Jacobian
    solves) resolves through here, so there is exactly one place the
    small-n/large-n policy and the Pallas/XLA kernel tier
    (``PYCATKIN_LINALG_KERNEL``, docs/perf_pallas_linalg.md) live.

    - Pallas kernel resolved AND ``n`` is a static ABI bucket size:
      the VMEM-resident batched LU of
      :mod:`pycatkin_tpu.ops.pallas_linalg` (fused factorize+solve;
      ``make_solve`` reuses the factorization per chord step).
    - else ``n <= UNROLL_MAX``: trace-time-unrolled Gauss-Jordan
      (:func:`gauss_solve` / explicit :func:`inv` for reuse).
    - else: the chunk-unrolled sequential :func:`lu_factor` /
      :func:`lu_solve`.

    With the kernel resolved to ``xla`` (the default off-TPU) the
    selection reproduces the historical :func:`solve` /
    :func:`make_msolve` behavior exactly -- byte-identical programs.
    """
    n = int(n)
    if tier is None:
        tier = _precision.active_tier()
    kernel = _precision.linalg_kernel(backend)
    if kernel == "pallas" and _pallas().supported(n):
        plk = _pallas()
        return SolverChoice("pallas", n, tier, kernel,
                            plk.make_msolve, plk.factor_solve)
    if n <= UNROLL_MAX:
        return SolverChoice("gauss", n, tier, kernel,
                            _make_inv_solve, gauss_solve)
    return SolverChoice("lu", n, tier, kernel,
                        _make_lu_solve, _lu_solve_once)


def _pallas():
    """Lazy import of the Pallas kernel module (keeps plain-XLA users
    off the jax.experimental.pallas import path entirely)."""
    from . import pallas_linalg
    return pallas_linalg


def make_msolve(M: jnp.ndarray):
    """Factor M once, return a solve closure reusable for several RHS.

    Thin shim over :func:`select_solver` (the single dispatch seam):
    small systems get an explicit Gauss-Jordan inverse (subsequent
    solves are matvecs), large ones an LU factorization with
    triangular solves, bucket-shaped systems the Pallas kernel when
    that tier is resolved.
    """
    return select_solver(M.shape[-1]).make_solve(M)


def _pivot_swap(M, k, idx):
    """Swap row k with the partial-pivot row, gather-free.

    The pivot row is selected with a one-hot matvec and written back with
    arithmetic masking, so the whole exchange is mul/add (no dynamic
    gather/scatter lanes under vmap)."""
    col = jnp.abs(M[:, k])
    col = jnp.where(idx < k, -jnp.inf, col)
    oh_p = (idx == jnp.argmax(col)).astype(M.dtype)
    row_k = M[k]
    row_p = oh_p @ M
    M = M.at[k].set(row_p)                      # static-index update
    return M - oh_p[:, None] * (row_p - row_k)[None, :]


def gauss_solve(A: jnp.ndarray, b: jnp.ndarray):
    """Partial-pivoted Gauss-Jordan solve, fully unrolled (static n).

    b: [n] or [n, k]. Eliminates above and below the pivot each step, so
    no triangular substitution pass remains at the end.
    """
    n = A.shape[-1]
    idx = jnp.arange(n)
    vec = b.ndim == 1
    # Row equilibration: microkinetic Jacobians carry rows scaled over
    # ~30 decades; plain partial pivoting then picks by row magnitude
    # rather than by conditioning and the elimination overflows. Scaling
    # each row of [A | b] to unit max norm leaves x unchanged and makes
    # partial pivoting effective.
    row_max = jnp.max(jnp.abs(A), axis=-1, keepdims=True)
    r = jnp.where(row_max > 0, 1.0 / row_max, 1.0)
    M = jnp.concatenate([A * r, (b[:, None] if vec else b) * r], axis=-1)
    for k in range(n):
        M = _pivot_swap(M, k, idx)
        row_k = M[k] / M[k, k]
        M = M.at[k].set(row_k)
        factors = jnp.where(idx == k, 0.0, M[:, k])
        M = M - factors[:, None] * row_k[None, :]
    x = M[:, n:]
    return x[:, 0] if vec else x


def inv(A: jnp.ndarray) -> jnp.ndarray:
    """Matrix inverse by unrolled Gauss-Jordan (static n).

    Used where one matrix serves several right-hand sides (the ODE
    solver's frozen iteration matrix): the subsequent solves collapse to
    matvecs, which beat sequential triangular substitution on TPU.
    """
    n = A.shape[-1]
    return gauss_solve(A, jnp.eye(n, dtype=A.dtype))


def solve(A: jnp.ndarray, b: jnp.ndarray):
    """Solve A x = b (square, dense) for any dtype on any backend.
    Thin shim over :func:`select_solver` (the single dispatch seam)."""
    return select_solver(A.shape[-1]).solve(A, b)


def scaling_solve(A: jnp.ndarray, b: jnp.ndarray):
    """Knob-independent solve for the scaling-relation system.

    The linear-scaling network in :func:`engine.free_energies` couples a
    handful of descriptor states (``n_sc`` is a few, never a Pallas ABI
    bucket), and its builders are cached WITHOUT the kernel/tier knobs
    in their keys. Routing it through :func:`select_solver` would make
    those traces depend on ``PYCATKIN_LINALG_KERNEL`` — exactly the
    stale-trace class PCL014 polices. This path reads no runtime
    config: unrolled Gauss-Jordan up to ``UNROLL_MAX``, sequential LU
    beyond — the historical ``kernel=xla`` selection, byte-identical
    under every knob setting.
    """
    if A.shape[-1] <= UNROLL_MAX:
        return gauss_solve(A, b)
    return _lu_solve_once(A, b)


def make_mixed_solve(A: jnp.ndarray):
    """Factor A once in hardware float32, return an iteratively-refined
    solve closure: row-equilibrate in f64 (keeps the cast in f32 range
    and makes partial pivoting magnitude-meaningful), factor the f32
    cast with the same sequential kernel, refine each solve with one
    f64-residual correction pass. Returns solve_fn(b) -> x in A.dtype.

    Round-4 TPU measurements (tools/exp_jac_perm.py, [128, 190, 190]):
    2.4x faster than the emulated-f64 LU (51 vs 130 ms; XLA's native
    f32 LuDecomposition custom call is unusable -- it kernel-faults
    inside vmapped while_loops, docs/perf_config5.md §5), refined
    directions good to ~1e-10 relative for cond(A) up to ~1e7 --
    including severely ROW-scaled systems, which equilibration absorbs.
    NOT wired into the steady-solver direction solve: stiff-kinetics
    PTC matrices measure cond ~1e10-1e15 AFTER equilibration (the
    stiffness is spectral, not a scaling artifact), refinement cannot
    contract there, and the solve stalls (docs/perf_config5.md §9).
    The honest prospective use is implicit-integrator stage matrices
    I - h*gamma*J, whose conditioning is moderated by the accuracy-
    limited step size h.
    """
    dtype = A.dtype
    row_max = jnp.max(jnp.abs(A), axis=-1, keepdims=True)
    r = jnp.where(row_max > 0, 1.0 / row_max, 1.0)
    As = A * r                                   # equilibrated, f64
    LU32, perm = lu_factor(As.astype(jnp.float32))  # pclint: disable=PCL005 -- f32 is intrinsic to this mixed-precision refinement algorithm, not a tier choice

    def solve_fn(b):
        # b: [n] or [n, k] (the module's RHS convention); the row scale
        # r is [n, 1], which broadcasts correctly over matrix RHS but
        # must be squeezed for vector RHS.
        bs = b * (r[..., 0] if b.ndim == r.ndim - 1 else r)
        # Magnitude-normalize the RHS (per column) before the f32 casts:
        # equilibration absorbs A's row scaling but not b's size, so
        # |bs| beyond ~3.4e38 would overflow the cast and residuals
        # below f32's denormal floor would flush to zero. The system is
        # linear -- scale to unit max, solve, undo on the way out.
        bmax = jnp.max(jnp.abs(bs), axis=0)
        bscale = jnp.where((bmax > 0) & jnp.isfinite(bmax), bmax, 1.0)
        bn = bs / bscale
        x = lu_solve(LU32, perm, bn.astype(jnp.float32)).astype(dtype)  # pclint: disable=PCL005 -- f32 is intrinsic to this mixed-precision refinement algorithm, not a tier choice
        res = bn - As @ x                        # f64 residual
        dx = lu_solve(LU32, perm, res.astype(jnp.float32)).astype(dtype)  # pclint: disable=PCL005 -- f32 is intrinsic to this mixed-precision refinement algorithm, not a tier choice
        return (x + dx) * bscale

    return solve_fn
