"""Dense linear algebra kernels built from arithmetic ops only.

XLA:TPU provides no float64 LuDecomposition custom call (it implements
only F32/C64), but double precision is part of this framework's numerical
contract: stiff microkinetic Jacobians carry rate constants spanning ~30
decades (SURVEY.md §7.3). These kernels implement LU factorization with
partial pivoting and triangular solves as plain jnp arithmetic inside
``lax.fori_loop``, so they compile for any dtype on any backend and
``vmap`` cleanly over solver lanes.

Systems here are small (n <= a few hundred: species counts, scaling
states), so the O(n) sequential pivot loop with O(n^2) vectorized row
updates is the right shape for the TPU -- each update is a fused
broadcast multiply-add over a [n, n] tile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lu_factor(A: jnp.ndarray):
    """LU factorization with partial pivoting.

    Returns (LU, perm): LU holds L (unit diagonal, below) and U (on and
    above the diagonal); perm is the row permutation applied to A.
    """
    n = A.shape[-1]
    idx = jnp.arange(n)

    def body(k, state):
        A, perm = state
        col = jnp.abs(A[:, k])
        col = jnp.where(idx < k, -jnp.inf, col)
        p = jnp.argmax(col)
        # Swap rows k and p (and the permutation entries).
        rk, rp = A[k], A[p]
        A = A.at[k].set(rp).at[p].set(rk)
        pk, pp = perm[k], perm[p]
        perm = perm.at[k].set(pp).at[p].set(pk)
        # Eliminate below the pivot; store multipliers in column k.
        pivot = A[k, k]
        factors = jnp.where(idx > k, A[:, k] / pivot, jnp.zeros_like(pivot))
        # Update only columns >= k: columns < k hold already-stored L
        # multipliers and must not be touched by the elimination.
        upd = jnp.where(idx >= k, A[k], 0.0)
        A = A - factors[:, None] * upd[None, :]
        A = A.at[:, k].set(jnp.where(idx > k, factors, A[:, k]))
        return A, perm

    LU, perm = lax.fori_loop(0, n - 1, body, (A, jnp.arange(n)))
    return LU, perm


def lu_solve(LU: jnp.ndarray, perm: jnp.ndarray, b: jnp.ndarray):
    """Solve A x = b given lu_factor output. b: [n] or [n, k]."""
    n = LU.shape[-1]
    idx = jnp.arange(n)
    vec = b.ndim == 1
    y0 = (b[perm, None] if vec else b[perm]).astype(LU.dtype)

    def fwd(i, y):
        s = jnp.where(idx < i, LU[i], 0.0) @ y
        return y.at[i].set(y[i] - s)

    def bwd(j, x):
        i = n - 1 - j
        s = jnp.where(idx > i, LU[i], 0.0) @ x
        return x.at[i].set((x[i] - s) / LU[i, i])

    y = lax.fori_loop(0, n, fwd, y0)
    x = lax.fori_loop(0, n, bwd, y)
    return x[:, 0] if vec else x


# Below this size the O(n) factorization loop is unrolled at trace time:
# every step becomes static-index arithmetic (one-hot matmul row gathers,
# no scatters), which XLA fuses into a handful of vectorized TPU ops --
# crucial when the solve sits inside a vmapped while_loop over 1e4-1e5
# solver lanes. Larger systems fall back to the fori_loop LU.
UNROLL_MAX = 48
_UNROLL_MAX = UNROLL_MAX  # backward-compat alias


def make_msolve(M: jnp.ndarray):
    """Factor M once, return a solve closure reusable for several RHS.

    Encapsulates the small-n/large-n dispatch policy: small systems get
    an explicit Gauss-Jordan inverse (subsequent solves are matvecs),
    large ones an LU factorization with triangular solves.
    """
    if M.shape[-1] <= UNROLL_MAX:
        Minv = inv(M)
        return lambda r: Minv @ r
    lu, piv = lu_factor(M)
    return lambda r: lu_solve(lu, piv, r)


def _pivot_swap(M, k, idx):
    """Swap row k with the partial-pivot row, gather-free.

    The pivot row is selected with a one-hot matvec and written back with
    arithmetic masking, so the whole exchange is mul/add (no dynamic
    gather/scatter lanes under vmap)."""
    col = jnp.abs(M[:, k])
    col = jnp.where(idx < k, -jnp.inf, col)
    oh_p = (idx == jnp.argmax(col)).astype(M.dtype)
    row_k = M[k]
    row_p = oh_p @ M
    M = M.at[k].set(row_p)                      # static-index update
    return M - oh_p[:, None] * (row_p - row_k)[None, :]


def gauss_solve(A: jnp.ndarray, b: jnp.ndarray):
    """Partial-pivoted Gauss-Jordan solve, fully unrolled (static n).

    b: [n] or [n, k]. Eliminates above and below the pivot each step, so
    no triangular substitution pass remains at the end.
    """
    n = A.shape[-1]
    idx = jnp.arange(n)
    vec = b.ndim == 1
    # Row equilibration: microkinetic Jacobians carry rows scaled over
    # ~30 decades; plain partial pivoting then picks by row magnitude
    # rather than by conditioning and the elimination overflows. Scaling
    # each row of [A | b] to unit max norm leaves x unchanged and makes
    # partial pivoting effective.
    row_max = jnp.max(jnp.abs(A), axis=-1, keepdims=True)
    r = jnp.where(row_max > 0, 1.0 / row_max, 1.0)
    M = jnp.concatenate([A * r, (b[:, None] if vec else b) * r], axis=-1)
    for k in range(n):
        M = _pivot_swap(M, k, idx)
        row_k = M[k] / M[k, k]
        M = M.at[k].set(row_k)
        factors = jnp.where(idx == k, 0.0, M[:, k])
        M = M - factors[:, None] * row_k[None, :]
    x = M[:, n:]
    return x[:, 0] if vec else x


def inv(A: jnp.ndarray) -> jnp.ndarray:
    """Matrix inverse by unrolled Gauss-Jordan (static n).

    Used where one matrix serves several right-hand sides (the ODE
    solver's frozen iteration matrix): the subsequent solves collapse to
    matvecs, which beat sequential triangular substitution on TPU.
    """
    n = A.shape[-1]
    return gauss_solve(A, jnp.eye(n, dtype=A.dtype))


def solve(A: jnp.ndarray, b: jnp.ndarray):
    """Solve A x = b (square, dense) for any dtype on any backend."""
    if A.shape[-1] <= _UNROLL_MAX:
        return gauss_solve(A, b)
    LU, perm = lu_factor(A)
    return lu_solve(LU, perm, b)
