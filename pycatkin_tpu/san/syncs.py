"""Sync sanitizer: the single-sync budget, enforced at the pull site.

``tests/test_sync_budget.py`` proves a clean sweep stays within
``MAX_CLEAN_SYNCS`` *counted* materializations -- but a raw
``np.asarray(device_array)`` somewhere off the counted choke point is
invisible to the counter: it silently re-adds the ~1 s tunnel round
trip the whole architecture exists to avoid. This sanitizer patches
the three pull seams a device array can cross --

- ``numpy.asarray`` / ``numpy.array`` (callers resolve them through
  the module dict at call time, so the patch intercepts every
  ``np.asarray(...)`` in the tree),
- ``jax.device_get``,

-- and inside a :func:`strict` region raises
:class:`~pycatkin_tpu.san.SyncSanError` the moment one of them
receives a device array WITHOUT flowing through
``utils.profiling.host_sync`` (which wraps its materialization in
:func:`counted`). The region also takes an optional budget: counted
syncs beyond it raise at the ``host_sync`` call site with the label
trail of everything already spent.

Patching is process-global but PASSIVE: outside a strict region the
wrappers forward immediately (one ContextVar read), so installing
under ``PYCATKIN_SAN=1`` does not perturb the rest of the suite.

Known blind spot: ``float(x)`` / ``int(x)`` on a device scalar pulls
through ``Array.__float__``, which offers no patchable module seam --
PCL001 catches that idiom statically on the hot path.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading

from . import SyncSanError

# Active strict region, or None. The dict is the region's mutable
# state: {"label", "budget", "count", "labels"}.
_strict: contextvars.ContextVar = contextvars.ContextVar(
    "pycatkin_san_strict", default=None)
# True while utils.profiling.host_sync is materializing: its pulls are
# the counted, sanctioned ones.
_counted: contextvars.ContextVar = contextvars.ContextVar(
    "pycatkin_san_counted", default=False)

_install_lock = threading.Lock()
_installed = False


def _is_device_value(x) -> bool:
    """True when ``x`` is (or contains, for small containers) a JAX
    device array -- the payloads whose pull costs a tunnel round
    trip."""
    try:
        import jax
    except Exception:
        return False
    if isinstance(x, jax.Array):
        return True
    if isinstance(x, (tuple, list)):
        return any(isinstance(v, jax.Array) for v in x)
    if isinstance(x, dict):
        return any(isinstance(v, jax.Array) for v in x.values())
    return False


def _trip(seam: str) -> None:
    region = _strict.get()
    raise SyncSanError(
        f"sync sanitizer: uncounted device->host pull via {seam} "
        f"inside strict region {region['label']!r} -- route it "
        f"through utils.profiling.host_sync (counted) or move it off "
        f"the hot path; counted so far: {region['labels']}")


def _guard(orig, seam: str):
    def wrapper(x, *args, **kwargs):
        if (_strict.get() is not None and not _counted.get()
                and _is_device_value(x)):
            _trip(seam)
        return orig(x, *args, **kwargs)
    wrapper.__name__ = getattr(orig, "__name__", seam)
    wrapper.__wrapped__ = orig
    return wrapper


def install() -> None:
    """Patch the pull seams (idempotent, process-global, passive
    outside strict regions)."""
    global _installed
    with _install_lock:
        if _installed:
            return
        import numpy
        numpy.asarray = _guard(numpy.asarray, "np.asarray")
        numpy.array = _guard(numpy.array, "np.array")
        try:
            import jax
            jax.device_get = _guard(jax.device_get, "jax.device_get")
        except Exception:
            pass
        _installed = True


def installed() -> bool:
    return _installed


@contextlib.contextmanager
def counted():
    """Mark the enclosed pulls as flowing through the counted choke
    point (used by ``utils.profiling.host_sync`` only)."""
    token = _counted.set(True)
    try:
        yield
    finally:
        _counted.reset(token)


def note_counted_sync(label: str) -> None:
    """Budget hook, called by ``host_sync`` per counted sync (when the
    sanitizer layer is enabled): over-budget counted syncs raise at
    the host_sync call site, label trail attached."""
    region = _strict.get()
    if region is None:
        return
    region["count"] += 1
    region["labels"].append(label or "<unlabeled>")
    budget = region["budget"]
    if budget is not None and region["count"] > budget:
        raise SyncSanError(
            f"sync sanitizer: counted sync #{region['count']} "
            f"({label!r}) exceeds the strict region "
            f"{region['label']!r} budget of {budget}; spent on: "
            f"{region['labels']}")


@contextlib.contextmanager
def strict(budget=None, label: str = "strict-sync"):
    """Arm the sanitizer for the enclosed region: uncounted device
    pulls raise immediately; counted syncs beyond ``budget`` (None =
    unlimited) raise at the choke point. Yields the region state dict
    (``count`` / ``labels``) for assertions."""
    install()
    region = {"label": label, "budget": budget, "count": 0,
              "labels": []}
    token = _strict.set(region)
    try:
        yield region
    finally:
        _strict.reset(token)
