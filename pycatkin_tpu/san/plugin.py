"""pytest plugin arming the sanitizer layer (loaded via
``pytest_plugins`` in ``tests/conftest.py``).

Under ``PYCATKIN_SAN=1`` (the ``make test-san`` lane) this installs
the passive halves at session start: the sync-seam patches (inert
outside ``strict()`` regions) and the recompile recorder (inert until
``mark_warm()``). Tests that drive a tripwire on purpose carry the
``san`` marker so the lane can be selected with ``-m san``; everything
else runs undisturbed -- that the ordinary suite stays green under the
armed sanitizers is itself part of the acceptance contract.
"""

from __future__ import annotations

from . import enabled, install


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "san: sanitizer selftests (tripwire injection; run via "
        "'make test-san' or -m san)")
    if enabled():
        install()
