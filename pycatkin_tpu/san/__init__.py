"""pcsan: runtime sanitizers for the repo's performance contracts.

The static rules (:mod:`pycatkin_tpu.lint`) catch the IDIOMS of
contract violations; this package catches the violations themselves,
at the moment they happen, with the failing program/operand/callback
in the exception message. Four tripwires, all off unless
``PYCATKIN_SAN=1`` (or a test/bench arms them explicitly):

- **recompile sanitizer** (:mod:`.recompile`): after ``mark_warm()``,
  any fresh XLA compile -- or any never-seen program key reaching the
  dispatch seam -- raises :class:`RecompileSanError` naming the
  program key and the operand whose shape/dtype churned the cache key.
  The runtime teeth behind the zero-compile contract
  (docs/serving.md's warm-cell gate).
- **sync sanitizer** (:mod:`.syncs`): inside a ``strict()`` region,
  a device->host pull (``np.asarray`` / ``np.array`` /
  ``jax.device_get`` on a device array) that does not flow through the
  counted ``utils.profiling.host_sync`` choke point raises
  :class:`SyncSanError` at the pull site; a region budget bounds the
  counted syncs too. The runtime teeth behind the single-sync budget
  (``tests/test_sync_budget.py``).
- **event-loop stall sanitizer** (:mod:`.stall`): asyncio's
  slow-callback debug hook, armed on the serve loop with threshold
  ``PYCATKIN_SAN_STALL_S`` (default 0.2 s); the ``watchdog()`` context
  collects stall warnings and raises :class:`StallSanError` at exit.
  The runtime teeth behind PCL010's lexical check.
- **trace-ident sanitizer** (:mod:`.trace_ident`): fingerprints the
  jaxpr of every registered program; two distinct jaxprs under one
  program key raise :class:`TraceIdentSanError` at the compile site,
  identical jaxprs under knob-differing keys are counted as zoo
  bloat. The runtime teeth behind PCL014/PCL015's static key
  discipline (``bench.py --smoke``'s ``keys_ok`` gate).

Wiring: ``make test-san`` runs the suite with ``PYCATKIN_SAN=1``
(the pytest plugin :mod:`.plugin` arms everything), ``bench.py
--smoke`` runs its smoke sweep under all three and reports ``san_ok``,
and :class:`serve.server.SweepServer` arms the recompile + stall
sanitizers on its own loop when enabled. Known runtime blind spot:
``float(x)``/``int(x)`` scalar pulls bypass every patchable seam --
PCL001 owns those statically.
"""

from __future__ import annotations

import os

ENV = "PYCATKIN_SAN"


def enabled() -> bool:
    """True when ``PYCATKIN_SAN`` asks for the sanitizer layer."""
    return os.environ.get(ENV, "").lower() in ("1", "on", "true", "yes")


class SanError(RuntimeError):
    """Base of every sanitizer trip (never raised itself)."""


class RecompileSanError(SanError):
    """A compile (or never-seen program key) surfaced after
    ``mark_warm()`` -- the zero-compile contract broke."""


class SyncSanError(SanError):
    """An uncounted or over-budget device->host pull inside a strict
    sync region -- the single-sync contract broke."""


class StallSanError(SanError):
    """A callback held the event loop past the stall threshold -- the
    non-blocking serve contract broke."""


class TraceIdentSanError(SanError):
    """Two distinct jaxprs observed under one program key -- the
    one-key-one-trace contract broke (wrong-answer risk)."""


def install() -> None:
    """Arm every passive sanitizer (idempotent): the sync patches
    record-and-check only inside ``strict()`` regions, the recompile
    recorder only trips after ``mark_warm()``, the trace-ident
    recorder only trips on a fingerprint collision."""
    from . import recompile, syncs, trace_ident
    syncs.install()
    recompile.activate()
    trace_ident.activate()


__all__ = ["ENV", "enabled", "install", "SanError", "RecompileSanError",
           "SyncSanError", "StallSanError", "TraceIdentSanError"]
