"""Trace-identity sanitizer: one program key <-> one jaxpr, enforced.

The program zoo's whole correctness story rests on a single invariant:
a program key (kind string + operand signature + toolchain,
:func:`~pycatkin_tpu.parallel.compile_pool.program_key`) names exactly
one traced program. PR 18's stale-kernel bug was this invariant
breaking silently -- two distinct traces served under one key, the
wrong one winning depending on env-flip order. PCL014 now polices the
cache-key side statically; this sanitizer polices the trace side
dynamically:

- **Collision (hard error):** two *distinct* jaxpr fingerprints
  observed under one program key raise
  :class:`~pycatkin_tpu.san.TraceIdentSanError` at the second
  observation site (the compile site, for registered compiles) --
  a key collision is a wrong-answer risk, never a perf footnote.
- **Duplicate (counted):** the *same* jaxpr fingerprint under two
  knob-differing keys (same base kind after
  :func:`~pycatkin_tpu.parallel.compile_pool.strip_kind_tags`) is
  legal but bloats the zoo against ``PREWARM_PROGRAM_BUDGET``;
  :func:`duplicate_groups` / :func:`stats` expose the count so
  ``bench.py --smoke`` and perfwatch can report it.

Fingerprints are sha256 over the whitespace-canonicalized
``jax.make_jaxpr`` text of the program on its concrete operands --
the pre-XLA trace identity, stable across processes for a fixed
jax version (the program key already pins the toolchain). They are
recorded into AOT cache entries and pack manifests
(``compile_pool.AOTCache.save`` / ``export_cache_pack``), and
``import_cache_pack`` replays them through :func:`note_jaxpr`, so an
imported pack whose fingerprints contradict locally-traced programs
trips the same error.

Everything is a no-op until :func:`activate` (armed by
:func:`pycatkin_tpu.san.install` under ``PYCATKIN_SAN=1``, by
``bench.py --smoke``'s keys gate, and by the ``aot_pack`` selftest).
Tracing failures (e.g. a program that cannot be abstractly retraced)
are counted, never raised: the sanitizer must not take down a path
the real dispatch handles fine.
"""

from __future__ import annotations

import hashlib
import re
import threading

from . import TraceIdentSanError

_lock = threading.Lock()
_active = False
_by_key: dict = {}      # key -> (kind, fingerprint)
_by_fp: dict = {}       # fingerprint -> [(kind, key), ...]
_collisions: list = []  # (key, kind, old_fp, new_fp)
_failures: int = 0      # fingerprinting attempts that raised

_WS = re.compile(r"\s+")


def activate() -> None:
    global _active
    _active = True


def deactivate() -> None:
    global _active
    _active = False


def is_active() -> bool:
    return _active


def reset() -> None:
    """Forget every recorded fingerprint, collision and failure."""
    global _failures
    with _lock:
        _by_key.clear()
        _by_fp.clear()
        _collisions.clear()
        _failures = 0


def fingerprint(prog, args) -> str:
    """Jaxpr fingerprint of ``prog`` on concrete ``args``: sha256 of
    the whitespace-canonicalized ``jax.make_jaxpr`` text, truncated to
    32 hex chars (the program-key width). Raises whatever the trace
    raises -- callers decide whether failure is fatal."""
    import jax

    text = str(jax.make_jaxpr(prog)(*args))
    return hashlib.sha256(_WS.sub(" ", text).strip()
                          .encode()).hexdigest()[:32]


def note_jaxpr(kind: str, key: str, prog=None, args=None,
               fp: str = None, force: bool = False) -> None:
    """Record (or verify) the jaxpr fingerprint of one program key.

    Dispatch-seam callers pass ``prog``/``args`` and let already-seen
    keys return without retracing; compile-site callers pass
    ``force=True`` (a compile is rare and authoritative -- a collision
    must raise AT the compile site). Pack import passes a precomputed
    ``fp``. Raises :class:`TraceIdentSanError` when ``key`` was
    already bound to a different fingerprint."""
    global _failures
    if not _active:
        return
    if fp is None:
        if prog is None:
            return
        if not force:
            with _lock:
                if key in _by_key:
                    return
        try:
            fp = fingerprint(prog, args or ())
        except Exception:
            with _lock:
                _failures += 1
            return
    with _lock:
        bound = _by_key.get(key)
        if bound is None:
            _by_key[key] = (kind, fp)
            _by_fp.setdefault(fp, []).append((kind, key))
            return
        if bound[1] == fp:
            return
        _collisions.append((key, kind, bound[1], fp))
        old_kind, old_fp = bound
    raise TraceIdentSanError(
        f"trace-ident sanitizer: program key {key[:16]}... already "
        f"bound to jaxpr {old_fp[:12]} (kind {old_kind!r}) but this "
        f"{'compile' if force or prog is not None else 'record'} "
        f"carries a DIFFERENT jaxpr {fp[:12]} (kind {kind!r}) -- one "
        f"key must name one trace; a missing cache-key knob (PCL014) "
        f"or a kind-string tag violation (PCL015) is the usual cause")


def fingerprint_for(key: str) -> str | None:
    with _lock:
        bound = _by_key.get(key)
    return bound[1] if bound else None


def entry_fields(key: str) -> dict:
    """Fields the AOT cache stamps into an entry/manifest for ``key``:
    ``{"trace_ident": fp, "kind": kind}`` when the key was observed,
    else ``{}`` (entries written by unarmed processes stay legal)."""
    with _lock:
        bound = _by_key.get(key)
    if bound is None:
        return {}
    return {"trace_ident": bound[1], "kind": bound[0]}


def duplicate_groups() -> list:
    """Knob-induced zoo bloat: groups of >= 2 keys sharing one jaxpr
    fingerprint whose kinds also share a stripped base kind -- i.e.
    keys that differ ONLY in grammar tags yet trace to the identical
    program. Each group is ``(fingerprint, [(kind, key), ...])``."""
    from ..parallel import compile_pool

    out = []
    with _lock:
        groups = {fp: list(members) for fp, members in _by_fp.items()
                  if len(members) >= 2}
    for fp, members in sorted(groups.items()):
        bases = {compile_pool.strip_kind_tags(kind)
                 for kind, _ in members}
        if len(bases) < len({kind for kind, _ in members}):
            out.append((fp, members))
    return out


def stats() -> dict:
    """Snapshot for gates and reports: program/fingerprint counts,
    collision count (MUST be zero -- a nonzero count means an error
    was swallowed upstream), knob-duplicate groups, trace failures."""
    dups = duplicate_groups()
    with _lock:
        return {
            "programs": len(_by_key),
            "fingerprints": len(_by_fp),
            "collisions": len(_collisions),
            "duplicate_groups": len(dups),
            "duplicate_keys": sum(len(m) for _, m in dups),
            "trace_failures": _failures,
        }
