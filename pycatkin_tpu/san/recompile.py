"""Recompile sanitizer: the zero-compile contract, enforced at runtime.

A warm serving cell must never compile: every program it can dispatch
was built during warmup (``SweepServer.warm`` / the AOT pack import),
and a compile after ``mark_warm()`` is a 10-60 s latency cliff hiding
behind one unlucky request. The serving layer already *measures* this
(the zero-compile-rate gate); this sanitizer makes the first violation
LOUD and attributed instead of a statistic:

- :func:`note_program` sits on the dispatch seam
  (``parallel.batch._registered_call`` -- every solo/packed/fused
  program passes through it). While warming it records each program
  key with its operand shape signature; after :func:`mark_warm`, a
  never-seen key raises :class:`~pycatkin_tpu.san.RecompileSanError`
  naming the program kind, the key, and the first operand leaf whose
  shape/dtype/sharding differs from the nearest warm signature -- the
  operand that churned the cache key.
- :func:`note_compile` sits on the two explicit ``lower().compile()``
  sites (packed flush, prewarm pool); after :func:`mark_warm` any
  fresh XLA compile raises, whatever its key.

Everything is a no-op until :func:`activate` (the pytest plugin, the
serve layer and ``bench.py --smoke`` call it when ``PYCATKIN_SAN`` is
on): one module-bool check per dispatch when cold.
"""

from __future__ import annotations

import threading

from . import RecompileSanError

_lock = threading.Lock()
_active = False
_warm = False
_seen: dict = {}        # kind -> {key: shape signature}


def activate() -> None:
    global _active
    _active = True


def deactivate() -> None:
    global _active
    _active = False


def reset() -> None:
    """Back to cold: forget every recorded program and the warm mark
    (tests; also the right call after an intentional re-warm)."""
    global _warm
    with _lock:
        _warm = False
        _seen.clear()


def mark_warm() -> None:
    """Declare warmup over: from here on, new program keys and fresh
    compiles raise."""
    global _warm
    with _lock:
        _warm = True


def is_warm() -> bool:
    return _warm


def is_active() -> bool:
    return _active


def _signature(args) -> str:
    from ..parallel import compile_pool
    return compile_pool._shape_signature(args)


def _diff_operand(kind: str, sig: str) -> str:
    """Human-readable locator of the operand that churned the key:
    compare the tripping signature against every warm signature of the
    same kind and report the first differing leaf of the closest
    match (same leaf count preferred)."""
    new_parts = sig.split("|")
    candidates = [s.split("|") for s in _seen.get(kind, {}).values()]
    if not candidates:
        return "no warm program of this kind was ever recorded"
    same_len = [c for c in candidates if len(c) == len(new_parts)]
    if not same_len:
        return (f"operand tree shape changed: {len(new_parts) - 1} "
                f"leaves vs {sorted({len(c) - 1 for c in candidates})} "
                f"in every warm signature of this kind")
    best, best_eq = None, -1
    for c in same_len:
        eq = sum(a == b for a, b in zip(c, new_parts))
        if eq > best_eq:
            best, best_eq = c, eq
    if best[0] != new_parts[0]:
        return "operand treedef changed (argument structure, not shapes)"
    for i, (old, new) in enumerate(zip(best[1:], new_parts[1:])):
        if old != new:
            return (f"operand leaf {i} churned the cache key: warm saw "
                    f"{old}, this call carries {new}")
    return "signature differs only in its treedef repr"


def note_program(kind: str, key: str, args) -> None:
    """Dispatch-seam hook: record (cold) or verify (warm) one program
    key. Called by ``parallel.batch._registered_call`` on EVERY
    registered-program dispatch."""
    if not _active:
        return
    with _lock:
        kinds = _seen.setdefault(kind, {})
        if key in kinds:
            return
        if not _warm:
            kinds[key] = _signature(args)
            return
        detail = _diff_operand(kind, _signature(args))
    raise RecompileSanError(
        f"recompile sanitizer: program kind {kind!r} reached the "
        f"dispatch seam with never-seen key {key[:16]}... after "
        f"mark_warm() -- this call will trace+compile in-band on a "
        f"warm cell; {detail}")


def note_compile(label: str) -> None:
    """Compile-site hook: a fresh XLA compile is about to run. Raises
    when the cell is warm (whatever the key -- a warm cell compiles
    nothing)."""
    if not _active or not _warm:
        return
    raise RecompileSanError(
        f"recompile sanitizer: fresh XLA compile ({label}) after "
        f"mark_warm() -- a warm cell must dispatch only prebuilt "
        f"executables (warm more programs, or widen the AOT pack)")
