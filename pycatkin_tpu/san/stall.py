"""Event-loop stall sanitizer: the serve loop's non-blocking contract.

PCL010 lexically bans blocking calls inside ``serve/`` async bodies,
but a stall can arrive through anything the lexical net cannot see --
a library call that blocks internally, a "fast" computation that is
not, an offload someone forgot. asyncio already HAS the detector:
debug mode times every callback/task step and logs a warning when one
holds the loop longer than ``loop.slow_callback_duration``. This
module turns that warning into a hard failure:

- :func:`arm` (await it on the loop under test, or let
  ``SweepServer.start`` do it when ``PYCATKIN_SAN=1``) enables debug
  mode and sets the threshold from ``PYCATKIN_SAN_STALL_S``
  (default 0.2 s);
- :func:`watchdog` wraps the test body, captures asyncio's
  "Executing <Handle/Task ...> took N seconds" warnings via a logging
  handler, and raises :class:`~pycatkin_tpu.san.StallSanError` at
  exit quoting every stalled callback.

The split matters: the warning fires INSIDE the loop (where raising
would land in asyncio's internals), the raise happens at the
test/bench seam where it can fail the right unit of work.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re

from . import StallSanError

STALL_ENV = "PYCATKIN_SAN_STALL_S"
_DEFAULT_STALL_S = 0.2

# asyncio/base_events.py emits exactly this shape in debug mode.
_STALL_RE = re.compile(r"Executing .* took .* seconds")


def threshold_s() -> float:
    """The stall threshold (``PYCATKIN_SAN_STALL_S``, seconds)."""
    try:
        return float(os.environ.get(STALL_ENV, _DEFAULT_STALL_S))
    except ValueError:
        return _DEFAULT_STALL_S


async def arm(stall_s=None) -> float:
    """Enable slow-callback detection on the RUNNING loop; returns the
    threshold applied."""
    import asyncio
    loop = asyncio.get_running_loop()
    loop.set_debug(True)
    s = threshold_s() if stall_s is None else float(stall_s)
    loop.slow_callback_duration = s
    return s


class _StallHandler(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.stalls: list = []

    def emit(self, record):
        msg = record.getMessage()
        if _STALL_RE.search(msg):
            self.stalls.append(msg)


@contextlib.contextmanager
def watchdog(raise_on_stall: bool = True):
    """Capture slow-callback warnings from any loop armed inside the
    block; yields the handler (``.stalls`` is the evidence list) and
    raises :class:`StallSanError` at exit when any callback stalled."""
    logger = logging.getLogger("asyncio")
    handler = _StallHandler()
    logger.addHandler(handler)
    # Debug-mode warnings are dropped before reaching handlers if the
    # asyncio logger's effective level is above WARNING.
    prior_level = logger.level
    if logger.getEffectiveLevel() > logging.WARNING:
        logger.setLevel(logging.WARNING)
    try:
        yield handler
    finally:
        logger.setLevel(prior_level)
        logger.removeHandler(handler)
    if handler.stalls and raise_on_stall:
        raise StallSanError(
            "event-loop stall sanitizer: callback(s) held the serve "
            "loop past its threshold:\n  " + "\n  ".join(handler.stalls))
