"""PCL014 cache-key completeness: the interprocedural taint engine.

PR 18's bug class, machine-enforced. A runtime-resolved knob
(``PYCATKIN_LINALG_KERNEL``) was read inside functions reachable from
``lru_cache``d jitted-program builders; the knob was not part of the
builders' cache keys, so an env flip silently served a stale trace of
the other kernel tier. The fix threaded the RESOLVED knob through every
builder as an explicit cache parameter (``precision.kernel_keyed``) --
by hand, nine builders at a time. This module turns that contract into
a lint rule over the :class:`~pycatkin_tpu.lint.project_index.
ProjectIndex` call graph:

    for every ``functools.lru_cache``d builder, walk everything its
    body can reach; if the walk hits a CONFIG SOURCE -- a function
    reading a ``PYCATKIN_*`` environment key, or a declared resolver
    like :func:`pycatkin_tpu.precision.linalg_kernel` -- the builder
    must thread that source as an explicit cache-key axis (the
    ``kernel_keyed`` decorator for the kernel family, an explicit
    ``tier`` parameter for the tier family), or carry a reasoned
    inline suppression at its ``def`` line.

Sources come in two layers:

- **Detected**: any package function whose body reads
  ``os.environ.get`` / ``os.getenv`` / ``os.environ[...]`` on a
  ``PYCATKIN_*`` string -- literal, or a module-level constant
  (``KERNEL_ENV = "PYCATKIN_LINALG_KERNEL"``) resolved through this
  module's constant table. Module-level reads (import-time process
  config) are not attributed to any function and are out of scope.
- **Declared**: :data:`CONFIG_RESOLVERS` names the blessed resolver
  functions and the cache-key mechanism that satisfies each family.
  Declared resolvers are BFS *barriers*: their internal env reads are
  their own business (``linalg_kernel`` absorbing
  ``_interpret_forced``), reaching the resolver is what taints.

:data:`TAINT_ABSORBERS` is the third, deliberately short list: call
sites that consume a source WITHOUT baking it into the caller's trace.
Today that is exactly ``ops.linalg.select_solver``'s tier read -- the
tier there is shape introspection only (operand dtypes carry the
precision; flipping the tier cannot change the emitted jaxpr), while
its KERNEL read is the real trace-time bake the kernel family keys on.

Satisfaction is deliberately strict for the kernel family: only the
``kernel_keyed`` decorator counts, not a bare ``kernel`` parameter --
the parameter without the wrapper is never filled with the resolved
knob, which is precisely the PR 18 tripwire this rule must reproduce
when one decorator is deleted.

Runs once per lint pass over the shared index (``needs_index = True``),
cached on the whole-package content key like PCL013.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Optional

from .core import Checker, Finding, register

ENV_PREFIX = "PYCATKIN_"


@dataclass(frozen=True)
class ConfigResolver:
    """One declared runtime-config resolver function."""

    family: str          # short name for messages ("kernel", "tier")
    env: str             # the env key the resolver reads
    #: How a builder keys on this family: ``("decorator", name)`` --
    #: the builder must be wrapped by ``name`` -- or ``("param", name)``
    #: -- the builder must take ``name`` as an explicit argument.
    keyed_by: tuple


#: The blessed config-resolver registry: (module relpath, function
#: name) -> how cached builders must key on it. Reaching one of these
#: taints the builder with its family; the resolver's own body is a
#: BFS barrier (its internal env reads are absorbed).
CONFIG_RESOLVERS = {
    ("pycatkin_tpu/precision.py", "linalg_kernel"): ConfigResolver(
        family="kernel", env="PYCATKIN_LINALG_KERNEL",
        keyed_by=("decorator", "kernel_keyed")),
    ("pycatkin_tpu/precision.py", "kernel_tag"): ConfigResolver(
        family="kernel", env="PYCATKIN_LINALG_KERNEL",
        keyed_by=("decorator", "kernel_keyed")),
    ("pycatkin_tpu/precision.py", "active_tier"): ConfigResolver(
        family="tier", env="PYCATKIN_PRECISION_TIER",
        keyed_by=("param", "tier")),
}

#: (module relpath, function name) -> families its subtree absorbs.
#: ``select_solver``'s ``tier=None -> active_tier()`` default is shape
#: introspection only: the operand dtypes carry the precision, so the
#: tier can never change the trace this call emits. Its KERNEL read is
#: NOT absorbed -- that one is the trace-time bake PR 18 tripped on.
TAINT_ABSORBERS = {
    ("pycatkin_tpu/ops/linalg.py", "select_solver"):
        frozenset({"tier"}),
}


def _decorator_names(node) -> list:
    """Trailing names of every decorator on ``node`` (``lru_cache(...)``
    -> ``lru_cache``, ``_precision.kernel_keyed`` -> ``kernel_keyed``)."""
    out = []
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name):
            out.append(target.id)
        elif isinstance(target, ast.Attribute):
            out.append(target.attr)
    return out


def is_cached_builder(node) -> bool:
    """Whether a function node is ``functools.lru_cache``-decorated
    (the ``_lru_cache`` import alias counts; ``functools.cache`` is the
    same trap)."""
    return any(name in ("lru_cache", "_lru_cache", "cache")
               for name in _decorator_names(node))


def _param_names(node) -> set:
    a = node.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg is not None:
        names.add(a.vararg.arg)
    if a.kwarg is not None:
        names.add(a.kwarg.arg)
    return names


def module_str_constants(tree) -> dict:
    """Top-level ``NAME = "literal"`` assignments of one module AST --
    the constant table env-key arguments resolve through."""
    out = {}
    for top in tree.body:
        targets = []
        if isinstance(top, ast.Assign):
            targets = top.targets
            value = top.value
        elif isinstance(top, ast.AnnAssign) and top.value is not None:
            targets = [top.target]
            value = top.value
        else:
            continue
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, str)):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = value.value
    return out


def _env_key_of(node, constants: dict) -> Optional[str]:
    """The env-key string an ``os.environ``/``getenv`` argument node
    resolves to (None when dynamic)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    return None


def _is_environ(node) -> bool:
    """``os.environ`` (Attribute) -- the base of ``.get`` and ``[...]``."""
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os")


def env_reads(fn_node, constants: dict) -> set:
    """Every ``PYCATKIN_*`` env key a function's body reads through the
    three blessed idioms (``os.environ.get``, ``os.getenv``,
    ``os.environ[...]``), resolved through the module constant table."""
    keys = set()
    for node in ast.walk(fn_node):
        arg = None
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and _is_environ(f.value) and node.args):
                arg = node.args[0]
            elif (isinstance(f, ast.Attribute) and f.attr == "getenv"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os" and node.args):
                arg = node.args[0]
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            arg = node.slice
        if arg is None:
            continue
        key = _env_key_of(arg, constants)
        if key is not None and key.startswith(ENV_PREFIX):
            keys.add(key)
    return keys


@dataclass
class TaintHit:
    """One config source reached from one builder."""

    source: tuple        # (relpath, fname) of the source function
    resolver: Optional[ConfigResolver]   # None for detected env reads
    env_keys: tuple      # env keys read (detected sources)
    chain: tuple         # (relpath, fname) call chain builder -> source


class TaintEngine:
    """Interprocedural taint over one ProjectIndex: which config
    sources each function can transitively reach."""

    def __init__(self, index):
        self.index = index
        # (relpath, fname) -> frozenset of PYCATKIN_* keys read directly
        self._direct: dict = {}
        self._constants: dict = {}
        for relpath, mod in index.modules.items():
            consts = module_str_constants(mod.src.tree)
            self._constants[relpath] = consts
            for fname, info in mod.functions.items():
                keys = env_reads(info.node, consts)
                if keys:
                    self._direct[(relpath, fname)] = frozenset(keys)

    def direct_sources(self) -> dict:
        """(relpath, fname) -> env keys, for every detected env-reading
        function (the registry the docs quote)."""
        return dict(self._direct)

    def trace(self, root) -> list:
        """Every :class:`TaintHit` reachable from ``root`` ((relpath,
        fname)), honoring resolver barriers and absorber masks. BFS, so
        the reported chain is a shortest witness path."""
        hits: dict = {}
        start = (root, frozenset())
        parents = {start: None}
        queue = deque([start])
        while queue:
            state = queue.popleft()
            (node, masked) = state
            resolver = CONFIG_RESOLVERS.get(node)
            if resolver is not None and node != root:
                if resolver.family not in masked and node not in hits:
                    hits[node] = TaintHit(
                        source=node, resolver=resolver, env_keys=(),
                        chain=self._chain(parents, state))
                continue                      # barrier: do not expand
            direct = self._direct.get(node)
            # The builder's OWN body reading env is the worst offender
            # (no indirection to audit), so the root is not exempt --
            # unless the root is itself a declared resolver, whose
            # internal reads are its contract.
            if (direct and node not in hits
                    and not (node == root and node in CONFIG_RESOLVERS)):
                hits[node] = TaintHit(
                    source=node, resolver=None,
                    env_keys=tuple(sorted(direct)),
                    chain=self._chain(parents, state))
            next_masked = masked | TAINT_ABSORBERS.get(node, frozenset())
            for callee in self.index.callees(*node):
                nxt = (callee, next_masked)
                if nxt not in parents:
                    parents[nxt] = state
                    queue.append(nxt)
        return [hits[k] for k in sorted(hits)]

    @staticmethod
    def _chain(parents, state) -> tuple:
        out = []
        while state is not None:
            out.append(state[0])
            state = parents[state]
        return tuple(reversed(out))


def _fmt_chain(chain) -> str:
    return " -> ".join(f"{rel}:{fn}" for rel, fn in chain)


@register
class CacheKeyChecker(Checker):
    rule = "PCL014"
    name = "cache-key-completeness"
    description = ("lru_cache'd program builder transitively reaches a "
                   "runtime-config source (PYCATKIN_* env read / "
                   "declared resolver) that is not threaded as an "
                   "explicit cache-key axis (kernel_keyed / tier-style "
                   "parameter)")
    needs_index = True

    def wants(self, relpath: str) -> bool:
        return False                  # project-level rule only

    def check_file(self, src) -> Iterable[Finding]:
        return ()

    def check_project(self, index) -> Iterable[Finding]:
        # Registry drift is a finding, not a crash: a declared resolver
        # that no longer resolves means the registry (or the function)
        # moved and the rule is silently blind to its family.
        for (relpath, fname) in sorted(CONFIG_RESOLVERS):
            mod = index.modules.get(relpath)
            if mod is None or fname not in mod.functions:
                yield Finding(
                    rule=self.rule, path="pycatkin_tpu/lint/dataflow.py",
                    lineno=1, col=0,
                    message=(f"CONFIG_RESOLVERS declares "
                             f"{relpath}:{fname} but no such function "
                             f"exists in the index -- update the "
                             f"resolver registry"))
        engine = TaintEngine(index)
        for relpath in sorted(index.modules):
            mod = index.modules[relpath]
            for fname in sorted(mod.functions):
                info = mod.functions[fname]
                if not is_cached_builder(info.node):
                    continue
                yield from self._check_builder(engine, relpath, fname,
                                               info, mod)

    def _check_builder(self, engine, relpath, fname, info, mod):
        decorators = _decorator_names(info.node)
        params = _param_names(info.node)
        seen_families = set()
        for hit in engine.trace((relpath, fname)):
            if hit.resolver is not None:
                fam = hit.resolver.family
                if fam in seen_families:
                    continue
                seen_families.add(fam)
                mech, name = hit.resolver.keyed_by
                satisfied = (name in decorators if mech == "decorator"
                             else name in params)
                if satisfied:
                    continue
                want = (f"wrap it with @{name}" if mech == "decorator"
                        else f"take an explicit `{name}` parameter")
                msg = (f"`{fname}` is lru_cache'd but its trace "
                       f"transitively resolves the {fam} knob "
                       f"({hit.resolver.env}) via "
                       f"{_fmt_chain(hit.chain[1:])} -- an env flip "
                       f"would serve a stale cached program; {want} so "
                       f"the resolved knob joins the cache key, or "
                       f"suppress here with the reason the trace is "
                       f"{fam}-invariant")
            else:
                how = ("directly in its body" if len(hit.chain) == 1
                       else f"via {_fmt_chain(hit.chain[1:])}")
                msg = (f"`{fname}` is lru_cache'd but "
                       f"{'transitively ' if len(hit.chain) > 1 else ''}"
                       f"reads {', '.join(hit.env_keys)} {how} -- "
                       f"thread the resolved value through as an "
                       f"explicit cache parameter (kernel_keyed-style), "
                       f"or suppress here with the reason the trace "
                       f"cannot depend on it")
            yield Finding(
                rule=self.rule, path=relpath, lineno=info.lineno,
                col=getattr(info.node, "col_offset", 0), message=msg,
                source=mod.src.line(info.lineno).strip(),
                end_lineno=info.lineno)
