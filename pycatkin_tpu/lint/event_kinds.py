"""PCL008 event-kinds: every ``record_event`` kind is documented in
docs/failure_model.md.

Structured telemetry events are addressed by their ``kind`` string (the
first argument of ``utils.profiling.record_event`` /
``obs.RunTrace.record``): consumers filter by kind
(``peek_events("rescue")``, forensics' degradation/retry drain,
``tools/obsview.py``), so an event recorded under a kind nobody
documented is telemetry nobody will ever look at -- and a typo'd kind
(``"degredation"``) silently vanishes from every report. The kind
vocabulary is therefore a closed registry: the "Event-kind registry"
table of docs/failure_model.md. A ``record_event`` call whose literal
kind is not backticked there is a finding; dynamic (non-literal) kinds
cannot be statically checked and are skipped.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .core import Checker, Finding, SourceFile, register

DOC_RELPATH = os.path.join("docs", "failure_model.md")

# Callees whose first positional (or ``kind=``) argument is an
# event-kind string. ``record`` alone would false-positive on every
# unrelated .record() method, so only the profiling entry points are
# matched.
KIND_FUNCS = frozenset({"record_event"})


def event_kinds(tree) -> list:
    """(kind, node) pairs for every literal-kind ``record_event`` call
    in one module's AST."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = getattr(func, "id", None) or getattr(func, "attr", "")
        if fname not in KIND_FUNCS:
            continue
        kind_node = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "kind":
                kind_node = kw.value
        if isinstance(kind_node, ast.Constant) \
                and isinstance(kind_node.value, str):
            out.append((kind_node.value, node))
    return out


def documented_kinds(doc_path: str) -> set:
    """Every backticked token in the failure-model doc (the event-kind
    registry table rows; sharing the token pool with PCL002's
    fault-site labels is harmless -- kinds and labels never collide)."""
    with open(doc_path, encoding="utf-8") as fh:
        return set(re.findall(r"`([^`\n]+)`", fh.read()))


@register
class EventKindChecker(Checker):
    rule = "PCL008"
    name = "event-kinds"
    description = ("record_event kind not documented in "
                   "docs/failure_model.md")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def __init__(self, doc_path: Optional[str] = None):
        super().__init__()
        self._doc_path = doc_path
        self._documented: Optional[set] = None

    @property
    def doc_path(self) -> str:
        return self._doc_path or os.path.join(self.root, DOC_RELPATH)

    def documented(self) -> set:
        if self._documented is None:
            self._documented = documented_kinds(self.doc_path)
        return self._documented

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        kinds = event_kinds(src.tree)
        if not kinds:
            return
        documented = self.documented()
        rel_doc = DOC_RELPATH.replace(os.sep, "/")
        for kind, node in kinds:
            if kind in documented:
                continue
            yield self.finding(
                src, node,
                f"undocumented event kind `{kind}` -- add it, "
                f"backticked, to the event-kind registry in {rel_doc}")
