"""PCL011 lock-discipline: guarded attributes are touched under their
lock.

An attribute initialized with a trailing ``# guarded-by: <lock>``
comment declares a locking contract for its owning class::

    class SweepCoalescer:
        def __init__(self):
            self._lock = threading.Lock()
            self._groups = {}       # guarded-by: _lock

Every ``self._groups`` access (read or write) in any OTHER method of
the class must then sit lexically inside a ``with self._lock:`` (or
``async with``) block. The declaring method itself -- ``__init__``
construction happens before the object is published to other threads
-- is exempt. Deliberately lock-free accesses (benign racy reads like
a ``pending`` progress counter) carry an inline
``# pclint: disable=PCL011 -- <why the race is benign>``.

This is a LEXICAL check: helper methods documented as
"caller must hold the lock" need a suppression at their access sites
(which is exactly the reviewed paper trail such helpers should carry).
Accesses from OUTSIDE the class body are not checked -- the contract
is an implementation-discipline rule, not an escape analysis.

Seeded on: :class:`parallel.dispatch.SweepCoalescer` (queue dicts),
:class:`obs.metrics.MetricsRegistry` / ``_Instrument`` (instrument
tables), :class:`obs.trace.RunTrace` (event/sync state) and the
elastic scheduler's heartbeat bookkeeping
(:class:`robustness.scheduler._Heartbeat`).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .core import Checker, Finding, SourceFile, register

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?"
                         r"(?P<lock>[A-Za-z_]\w*)")


def _self_attr(node) -> str | None:
    """``attr`` for an ``self.<attr>`` expression, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _declarations(src: SourceFile, cls: ast.ClassDef) -> dict:
    """{attr: (lock, declaring-method-name)} from ``# guarded-by``
    comments on ``self.<attr> = ...`` assignments anywhere in the
    class body."""
    out: dict = {}
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(method):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            else:
                continue
            attrs = [a for a in map(_self_attr, targets)
                     if a is not None]
            if not attrs:
                continue
            for i in src.span_lines(node.lineno,
                                    getattr(node, "end_lineno", None)):
                m = _GUARDED_RE.search(src.line(i))
                if m:
                    for attr in attrs:
                        out[attr] = (m.group("lock"), method.name)
                    break
    return out


def _with_locks(stmt) -> set:
    """Lock attr names taken by one with/async-with statement
    (``with self._lock:`` / ``with self._lock as h:``)."""
    out = set()
    for item in stmt.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
    return out


@register
class LockDisciplineChecker(Checker):
    rule = "PCL011"
    name = "lock-discipline"
    description = ("access to a '# guarded-by: <lock>' attribute "
                   "outside a 'with self.<lock>:' block")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for top in ast.walk(src.tree):
            if isinstance(top, ast.ClassDef):
                yield from self._check_class(src, top)

    def _check_class(self, src: SourceFile, cls: ast.ClassDef):
        decls = _declarations(src, cls)
        if not decls:
            return
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            declared_here = {a for a, (_, m) in decls.items()
                             if m == method.name}
            yield from self._check_body(
                src, cls, method, method.body, decls, declared_here,
                held=frozenset())

    def _check_body(self, src, cls, method, body, decls, exempt, held):
        for stmt in body:
            yield from self._check_node(src, cls, method, stmt, decls,
                                        exempt, held)

    def _check_node(self, src, cls, method, node, decls, exempt, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | _with_locks(node)
            for item in node.items:
                yield from self._check_node(src, cls, method,
                                            item.context_expr, decls,
                                            exempt, held)
            yield from self._check_body(src, cls, method, node.body,
                                        decls, exempt, inner)
            return
        attr = _self_attr(node)
        if attr is not None and attr in decls and attr not in exempt:
            lock, declared_in = decls[attr]
            if lock not in held:
                yield self.finding(
                    src, node,
                    f"`self.{attr}` is guarded by `self.{lock}` "
                    f"(declared in {cls.name}.{declared_in}) but "
                    f"accessed outside `with self.{lock}:` in "
                    f"`{method.name}`")
            return          # don't descend into self.<attr> again
        for child in ast.iter_child_nodes(node):
            yield from self._check_node(src, cls, method, child, decls,
                                        exempt, held)
