"""PCL009 metric-names: every metric emitted via ``obs.metrics`` is
documented in the docs/observability.md metrics catalog.

Prometheus-style metrics are addressed by name: dashboards, the
perfwatch sentinel and the smoke gates all key on the literal strings
handed to ``counter(...)`` / ``gauge(...)`` / ``histogram(...)``. A
metric emitted under a name the catalog does not list is telemetry
nobody will find (and a renamed metric silently orphans every consumer
of the old name). The name vocabulary is therefore a closed registry:
the metrics catalog table of docs/observability.md. An instrument call
in the package whose literal name is not backticked there is a
finding; dynamic (non-literal) names cannot be statically checked and
are skipped, as are scratch registries outside ``pycatkin_tpu/``
(tests and tools may mint throwaway names).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .core import Checker, Finding, SourceFile, register

DOC_RELPATH = os.path.join("docs", "observability.md")

# Callees whose first positional (or ``name=``) argument is a metric
# name: the module-level get-or-create entry points of obs.metrics and
# the same-named MetricsRegistry methods they delegate to.
METRIC_FUNCS = frozenset({"counter", "gauge", "histogram"})


def metric_names(tree) -> list:
    """(name, node) pairs for every literal-name instrument call in
    one module's AST."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = getattr(func, "id", None) or getattr(func, "attr", "")
        if fname not in METRIC_FUNCS:
            continue
        name_node = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "name":
                name_node = kw.value
        if isinstance(name_node, ast.Constant) \
                and isinstance(name_node.value, str):
            out.append((name_node.value, node))
    return out


def documented_names(doc_path: str) -> set:
    """Every backticked token in the observability doc (the metrics
    catalog rows; sharing the token pool with the doc's other backticks
    is harmless -- metric names are namespaced ``pycatkin_*``)."""
    with open(doc_path, encoding="utf-8") as fh:
        return set(re.findall(r"`([^`\n]+)`", fh.read()))


@register
class MetricNameChecker(Checker):
    rule = "PCL009"
    name = "metric-names"
    description = ("metric name not documented in the "
                   "docs/observability.md metrics catalog")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def __init__(self, doc_path: Optional[str] = None):
        super().__init__()
        self._doc_path = doc_path
        self._documented: Optional[set] = None

    @property
    def doc_path(self) -> str:
        return self._doc_path or os.path.join(self.root, DOC_RELPATH)

    def documented(self) -> set:
        if self._documented is None:
            self._documented = documented_names(self.doc_path)
        return self._documented

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        names = metric_names(src.tree)
        if not names:
            return
        documented = self.documented()
        rel_doc = DOC_RELPATH.replace(os.sep, "/")
        for mname, node in names:
            if mname in documented:
                continue
            yield self.finding(
                src, node,
                f"undocumented metric `{mname}` -- add it, backticked, "
                f"to the metrics catalog in {rel_doc}")
