"""The sweep hot-path registry: ONE place naming the functions whose
host-sync behavior is contractual.

Membership is declared AT THE FUNCTION, not in a hand-maintained list:
decorating a function with :func:`hotpath` (a runtime no-op) puts it on
the registry, and the static side recovers the same set by scanning the
``@hotpath`` decorations in :data:`HOT_PATH_SCAN_FILES` with ``ast`` --
no import of JAX-heavy modules, so the linter stays fast and robust.
Three enforcement mechanisms consume this module and must agree exactly:

- ``tests/test_sync_budget.py`` holds a clean sweep to
  :data:`MAX_CLEAN_SYNCS` counted materializations at runtime;
- the ``PCL001`` host-sync checker (:mod:`pycatkin_tpu.lint.host_sync`,
  ``make lint``) statically flags raw materialization idioms inside the
  decorated functions;
- the ``PCL013`` fused-tail-integrity checker
  (:mod:`pycatkin_tpu.lint.fused_tail`) walks the ProjectIndex call
  graph from the fused/packed sweep bodies and fails when a reachable
  sync-calling function is NOT decorated -- the drift class the old
  hand-maintained list suffered from is now a lint error.

To put a new function under the contract, decorate it with
``@hotpath`` -- nothing else to update anywhere.
"""

from __future__ import annotations

import ast
import os
from functools import lru_cache

from .core import REPO_ROOT

# A clean (zero-failure) sweep_steady_state may spend at most this many
# counted blocking device->host materializations (tightened from the
# ISSUE-3 budget of 3 by the fused one-dispatch tail, which spends 1:
# the packed diagnostics bundle. The legacy split tail
# (PYCATKIN_FUSED_SWEEP=0, fault plans) spends 2: solve fence + packed
# tail bundle -- still within budget).
MAX_CLEAN_SYNCS = 2

# Inline annotation marking a reviewed failure-path transfer. Honored on
# ANY line of a multi-line call (the pre-pclint lint only matched the
# call's first line).
SYNC_ANNOTATION = "# sync-ok:"

# Files scanned for ``@hotpath`` decorations (repo-relative posix
# paths). A decorated function in an UNLISTED file is invisible to the
# static side, so PCL013's drift test also asserts the runtime registry
# (populated at import) stays inside this file set.
HOT_PATH_SCAN_FILES = ("pycatkin_tpu/engine.py",
                       "pycatkin_tpu/parallel/batch.py",
                       "pycatkin_tpu/ops/pallas_linalg.py")

# Runtime half of the registry: (module, qualname) of every function
# decorated in THIS process. Filled as modules import; the static scan
# below is authoritative for lint/tests (it needs no imports).
_RUNTIME_REGISTRY: set = set()


def hotpath(fn):
    """Declare ``fn`` part of the sweep hot path (host-sync contract:
    PCL001 static scan + tests/test_sync_budget.py runtime budget).
    Returns ``fn`` unchanged -- zero call overhead; decoration is pure
    registration."""
    _RUNTIME_REGISTRY.add((getattr(fn, "__module__", ""),
                           getattr(fn, "__qualname__", fn.__name__)))
    return fn


def runtime_registry() -> frozenset:
    """(module, qualname) pairs decorated so far in this process."""
    return frozenset(_RUNTIME_REGISTRY)


def _decorator_names(node) -> set:
    out = set()
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name):
            out.add(target.id)
        elif isinstance(target, ast.Attribute):
            out.add(target.attr)
    return out


def _scan_file(path: str) -> frozenset:
    """Top-level function names decorated ``@hotpath`` in one file
    (empty when the file is missing/unparsable -- the lint pass reports
    syntax errors separately as PCL000)."""
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return frozenset()
    return frozenset(
        top.name for top in tree.body
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef))
        and "hotpath" in _decorator_names(top))


@lru_cache(maxsize=8)
def _scan(root: str) -> dict:
    return {rel: _scan_file(os.path.join(root, rel))
            for rel in HOT_PATH_SCAN_FILES
            if os.path.isfile(os.path.join(root, rel))}


def hot_path_files(root: str = REPO_ROOT) -> dict:
    """file (posix path relative to ``root``) -> decorated function
    names, from the static ``@hotpath`` scan."""
    return dict(_scan(root))


def hot_functions_for(relpath: str, root: str = REPO_ROOT):
    """Hot-function set for a repo-relative posix path (None when the
    file carries no hot-path contract)."""
    return _scan(root).get(relpath.replace("\\", "/"))


def _union(root: str = REPO_ROOT) -> frozenset:
    out = set()
    for names in _scan(root).values():
        out |= names
    return frozenset(out)


# Back-compatible module-level views (consumed by lint/__init__ and the
# budget test). Computed from the decorator scan at import time -- the
# hand-maintained list these used to be is gone.
HOT_FUNCTIONS = _union()
HOT_PATH_FILES: dict = hot_path_files()
