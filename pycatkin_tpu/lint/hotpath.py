"""The sweep hot-path registry: ONE place naming the functions whose
host-sync behavior is contractual.

Two enforcement mechanisms consume this module and must agree exactly:

- ``tests/test_sync_budget.py`` holds a clean sweep to
  :data:`MAX_CLEAN_SYNCS` counted materializations at runtime;
- the ``PCL001`` host-sync checker (:mod:`pycatkin_tpu.lint.host_sync`,
  ``make lint``) statically flags raw materialization idioms inside the
  registered functions.

Before this module existed the function list lived twice (the lint
script and the budget test) and could silently drift: a function added
to the hot path but only one list would be half-enforced. Add new
hot-path files/functions HERE, nowhere else.
"""

from __future__ import annotations

# A clean (zero-failure) sweep_steady_state may spend at most this many
# counted blocking device->host materializations (tightened from the
# ISSUE-3 budget of 3 by the fused one-dispatch tail, which spends 1:
# the packed diagnostics bundle. The legacy split tail
# (PYCATKIN_FUSED_SWEEP=0, fault plans) spends 2: solve fence + packed
# tail bundle -- still within budget).
MAX_CLEAN_SYNCS = 2

# Inline annotation marking a reviewed failure-path transfer. Honored on
# ANY line of a multi-line call (the pre-pclint lint only matched the
# call's first line).
SYNC_ANNOTATION = "# sync-ok:"

# The sweep hot path: functions a clean (zero-failure) sweep executes,
# plus the failure-path functions whose syncs must stay labeled.
HOT_FUNCTIONS = frozenset({
    "batch_steady_state", "sweep_steady_state", "_finish_sweep",
    "_fused_sweep", "_assemble_clean", "_stability_tier2",
    "_rescue", "_quarantine_mask", "stability_mask",
    "continuation_sweep",
    # Packed multi-tenant batching: the packed dispatch + the shared
    # post-bundle triage. A stray materialization in _fused_decide
    # would multiply by K tenants, so it is held to the same
    # discipline (the packed clean path spends exactly ONE counted
    # sync total, regardless of K -- test_sync_budget.py pins it).
    "packed_sweep_steady_state", "_packed_fused_sweep",
    "_split_fused_out", "_fused_decide",
})

# file (posix path relative to the repo root) -> hot function names.
# The PCL001 checker scans exactly these files.
HOT_PATH_FILES: dict[str, frozenset[str]] = {
    "pycatkin_tpu/parallel/batch.py": HOT_FUNCTIONS,
}


def hot_functions_for(relpath: str):
    """Hot-function set for a repo-relative posix path (None when the
    file carries no hot-path contract)."""
    return HOT_PATH_FILES.get(relpath.replace("\\", "/"))
