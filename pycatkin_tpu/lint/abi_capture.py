"""PCL007 abi-spec-capture: program-builder closures in
``parallel/batch.py`` must not read ``spec.<array>`` numpy fields.

The mechanism ABI (frontend/abi.py) exists because program bodies that
close over a ``ModelSpec``'s numpy arrays constant-fold them into the
compiled executable -- the program's identity then includes the
mechanism, every new mechanism re-pays the compile wall, and AOT packs
serve exactly one mechanism. An ABI program body instead reads those
arrays from the ``TracedSpec`` bound to its traced operands
(``tspec = spec.bind(ops)``), so one executable serves every mechanism
in the shape bucket.

This rule pins that boundary statically: inside any top-level
``*_program`` builder in ``parallel/batch.py``, a nested function or
lambda (the closure that becomes the jitted program body) reading a
known ModelSpec ARRAY field off the builder's ``spec`` parameter is a
finding. Scalar statics (``n_species``, ``reactor_type``,
``rnames``...) are trace-shaping by design and stay legal, as do array
reads in the builder's own (host-side, trace-time) body -- only reads
*inside the closure* become baked XLA constants.

The legacy constant-folded branches of the builders do exactly this on
purpose (they are the ``PYCATKIN_ABI=0`` path); those survivors live in
the committed ``lint_baseline.json``, so the rule's job is to stop NEW
program bodies from quietly re-growing mechanism-keyed constants.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, SourceFile, register

# ModelSpec numpy-array fields (frontend/spec.py): the operand pytree
# fields of frontend.abi._OPERAND_FIELDS plus the host-only arrays.
# Kept as a literal so the linter imports no package code (core.py
# contract); test_pclint.py cross-checks it against the dataclass.
SPEC_ARRAY_FIELDS = frozenset({
    "freq", "fmask", "mass", "sigma", "inertia", "is_gas", "is_linear",
    "mix", "gelec0", "add0", "gvibr0", "gvibr_mask", "gtran0",
    "gtran_mask", "grota0", "grota_mask", "gfree0", "gfree_mask",
    "scl_idx", "scl_b", "scl_We", "scl_Ws", "scl_WuE",
    "udar_mask", "udar_Ce", "udar_Cg", "udar_CuE", "udar_CuG",
    "SR", "SP", "ST", "has_TS", "reversible", "base_reversible",
    "is_arr_type", "is_ads", "is_des", "is_ghost", "is_user", "area",
    "rscaling", "site_density", "gas_mass", "gas_sigma", "gas_inertia",
    "gas_polyatomic", "reac_idx", "prod_idx", "stoich", "is_adsorbate",
    "is_gas_dyn", "dynamic_indices", "adsorbate_indices", "gas_indices",
    "groups",
})

_BUILDER_SUFFIX = "_program"


def _spec_param(fn: ast.FunctionDef) -> str | None:
    """The builder's spec parameter name ('spec' by convention), None
    when the builder takes no such argument."""
    for a in fn.args.posonlyargs + fn.args.args:
        if a.arg == "spec":
            return a.arg
    return None


def _param_names(fn) -> set:
    args = fn.args
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


class _ClosureScan(ast.NodeVisitor):
    """Collect ``spec.<array>`` attribute reads inside nested
    functions/lambdas of one builder, skipping scopes that rebind or
    shadow the spec name (their ``spec`` is not the builder's)."""

    def __init__(self, spec_name: str):
        self.spec_name = spec_name
        self.hits: list = []
        self._depth = 0        # >0 once inside a nested function

    def _enter(self, node, body):
        if self.spec_name in _param_names(node):
            return             # shadowed: not the builder's spec
        self._depth += 1
        for child in body:
            self.visit(child)
        self._depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._enter(node, node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        self._enter(node, [node.body])

    def visit_Attribute(self, node: ast.Attribute):
        if (self._depth > 0
                and isinstance(node.value, ast.Name)
                and node.value.id == self.spec_name
                and node.attr in SPEC_ARRAY_FIELDS):
            self.hits.append(node)
        self.generic_visit(node)


@register
class AbiCaptureChecker(Checker):
    rule = "PCL007"
    name = "abi-spec-capture"
    description = ("program-builder closure captures a spec.<array> "
                   "numpy field as an XLA constant (read it from the "
                   "bound TracedSpec instead)")
    scope = ("pycatkin_tpu/parallel/batch.py",)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for top in src.tree.body:
            if not isinstance(top, ast.FunctionDef):
                continue
            if not top.name.endswith(_BUILDER_SUFFIX):
                continue
            spec_name = _spec_param(top)
            if spec_name is None:
                continue
            scan = _ClosureScan(spec_name)
            # Walk only the builder's direct statements: array reads in
            # the builder's own body run at trace-setup time on the
            # host and are fine; only closure-captured reads bake
            # constants.
            for stmt in top.body:
                scan.visit(stmt)
            for node in scan.hits:
                yield self.finding(
                    src, node,
                    f"`{spec_name}.{node.attr}` captured inside a "
                    f"`{top.name}` program closure becomes a "
                    f"mechanism-keyed XLA constant; bind traced "
                    f"operands (`tspec = spec.bind(ops)`) and read "
                    f"`tspec.{node.attr}`")
