"""pclint core: findings, the checker plugin registry, unified inline
suppressions, and the package file-walker.

Every correctness contract this repo enforces statically (host-sync
budget, fault-site registry, jit purity, tracer hygiene, dtype policy,
env-var registry) is one :class:`Checker` subclass with a stable rule
ID (``PCL001``..); ``tools/pclint.py`` / ``make lint`` runs them all
over the whole tree and fails on any unsuppressed finding.

Suppression is unified across all rules:

- inline: ``# pclint: disable=PCL003 -- <reason>`` on any line the
  flagged node spans (``disable=all`` silences every rule; several
  rules separate with commas);
- baseline: a committed ``lint_baseline.json`` of grandfathered
  findings (:mod:`pycatkin_tpu.lint.baseline`), so new rules can land
  without rewriting history while NEW findings still fail the build;
- ``PCL001`` additionally honors the legacy ``# sync-ok: <reason>``
  annotation (the pre-pclint syntax, kept so reviewed hot-path
  transfers need no churn).

This module imports nothing from the rest of the package (and no JAX),
so the linter stays importable and fast even when the tree under
analysis is broken.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

# repo root: pycatkin_tpu/lint/core.py -> pycatkin_tpu/lint -> package
# -> repo.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Scanned by default: the package, its tooling, tests and examples plus
# the top-level entry scripts. Checkers narrow further via wants().
DEFAULT_ROOTS = ("pycatkin_tpu", "tools", "tests", "examples")
DEFAULT_TOP_FILES = ("bench.py", "bench_suite.py", "__graft_entry__.py")

# Never walked: caches, VCS internals, and the seeded-violation fixture
# corpus (tests/lint_fixtures) that exists to be flagged ON PURPOSE by
# the fixture tests -- explicit file arguments still reach it.
EXCLUDE_DIRS = frozenset({"__pycache__", ".git", ".jax_aot_cache",
                          ".ipynb_checkpoints", ".pclint_cache",
                          "lint_fixtures"})

_SUPPRESS_RE = re.compile(
    r"#\s*pclint:\s*disable=(?P<rules>[A-Za-z0-9_,\s]+?)"
    r"\s*(?:--\s*(?P<reason>.*))?$")


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str                 # repo-relative posix path
    lineno: int
    col: int
    message: str
    source: str = ""          # stripped source line (fingerprint input)
    end_lineno: Optional[int] = None   # span end, for suppression match
    suppressed: Optional[str] = None   # None | "inline" | "baseline"
    reason: str = ""                   # suppression reason, if any

    def location(self) -> str:
        return f"{self.path}:{self.lineno}"


@dataclass
class LintResult:
    """Everything one run produced: all findings (suppressed included)
    plus scan bookkeeping for the reports."""

    findings: list[Finding] = field(default_factory=list)
    n_files: int = 0
    rules: list[str] = field(default_factory=list)

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed is None]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed is not None]


class SourceFile:
    """One parsed source file handed to every checker: text, lines,
    lazily-built AST, and the per-line inline-suppression table."""

    def __init__(self, path: str, relpath: str, text: Optional[str] = None):
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        if text is None:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        self.text = text
        self.lines = text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._disable: Optional[dict] = None

    @property
    def tree(self) -> ast.AST:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.path)
        return self._tree

    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def span_lines(self, lineno: int, end_lineno: Optional[int]):
        """Source lines a node spans (multi-line calls suppress on ANY
        of their lines)."""
        return range(lineno, (end_lineno or lineno) + 1)

    def _disables(self) -> dict:
        if self._disable is None:
            table = {}
            for i, ln in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(ln)
                if not m:
                    continue
                spec = m.group("rules").strip()
                rules = (frozenset({"all"}) if spec.lower() == "all"
                         else frozenset(r.strip().upper()
                                        for r in spec.split(",")
                                        if r.strip()))
                table[i] = (rules, (m.group("reason") or "").strip())
            self._disable = table
        return self._disable

    def disabled(self, rule: str, lineno: int,
                 end_lineno: Optional[int] = None) -> Optional[str]:
        """The suppression reason when ``rule`` is inline-disabled on
        any line of the span, else None ('' when no reason given)."""
        table = self._disables()
        for i in self.span_lines(lineno, end_lineno):
            hit = table.get(i)
            if hit is not None:
                rules, reason = hit
                if "all" in rules or rule in rules:
                    return reason
        return None


class Checker:
    """Base class for one lint rule.

    Subclasses set ``rule`` (stable ``PCLnnn`` ID), ``name`` (kebab
    slug used in reports), ``description``, and ``scope`` (repo-relative
    posix path prefixes the rule applies to), then implement
    :meth:`check_file`. Register with :func:`register` so the runner
    discovers them. ``self.root`` is set by the runner before any
    :meth:`check_file` call (checkers that read docs resolve them
    against it).
    """

    rule = "PCL000"
    name = "base"
    description = ""
    scope: tuple = ("",)      # prefix "" = every scanned file
    # Cross-module rules set True and implement check_project(); the
    # runner builds ONE ProjectIndex per run, after the per-file walk,
    # and hands it to each such checker.
    needs_index = False

    def __init__(self):
        self.root = REPO_ROOT

    def wants(self, relpath: str) -> bool:
        relpath = relpath.replace("\\", "/")
        return relpath.endswith(".py") and any(
            relpath.startswith(prefix) for prefix in self.scope)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        raise NotImplementedError

    def check_project(self, index) -> Iterable[Finding]:
        """Cross-module pass over the shared ProjectIndex (only called
        when ``needs_index`` is True)."""
        return ()

    def finding(self, src: SourceFile, node, message: str) -> Finding:
        """Finding at an AST node, source line attached."""
        lineno = getattr(node, "lineno", 1)
        return Finding(
            rule=self.rule, path=src.relpath, lineno=lineno,
            col=getattr(node, "col_offset", 0), message=message,
            source=src.line(lineno).strip(),
            end_lineno=getattr(node, "end_lineno", None))


_REGISTRY: dict[str, type] = {}


def register(cls):
    """Class decorator adding a Checker subclass to the runner's
    registry (keyed by rule ID; re-registration replaces)."""
    _REGISTRY[cls.rule] = cls
    return cls


def all_checkers() -> list[Checker]:
    """Instances of every registered checker, rule-ID order. Imports
    the built-in checker modules on first use so plain
    ``import pycatkin_tpu.lint.core`` stays dependency-free."""
    from . import (abi_capture, async_blocking,  # noqa: F401
                   atomic_write, dataflow, dtype, env_registry,
                   event_kinds, fault_sites, fused_tail, host_sync,
                   key_tags, lock_discipline, metric_names, purity,
                   tracer)
    return [_REGISTRY[rule]() for rule in sorted(_REGISTRY)]


def checkers_for(rules) -> list[Checker]:
    """Checker instances for the given rule IDs or names (raises on an
    unknown selector -- a typo must not silently lint nothing)."""
    available = {c.rule: c for c in all_checkers()}
    by_name = {c.name: c for c in available.values()}
    picked = []
    for sel in rules:
        key = sel.strip()
        c = available.get(key.upper()) or by_name.get(key.lower())
        if c is None:
            known = ", ".join(f"{c.rule}({c.name})"
                              for c in available.values())
            raise KeyError(f"unknown rule {sel!r}; known: {known}")
        if c not in picked:
            picked.append(c)
    return picked


def iter_source_paths(root: str, paths=None):
    """(abspath, relpath) for every Python file to scan. ``paths``
    (files or directories, absolute or root-relative) override the
    default roots; explicitly named files bypass EXCLUDE_DIRS."""
    if paths:
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isfile(ap):
                yield ap, os.path.relpath(ap, root)
            else:
                yield from _walk_dir(ap, root)
        return
    for sub in DEFAULT_ROOTS:
        yield from _walk_dir(os.path.join(root, sub), root)
    for fname in DEFAULT_TOP_FILES:
        ap = os.path.join(root, fname)
        if os.path.isfile(ap):
            yield ap, fname


def _walk_dir(top: str, root: str):
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in EXCLUDE_DIRS)
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                ap = os.path.join(dirpath, fname)
                yield ap, os.path.relpath(ap, root)


def _apply_inline(src: SourceFile, findings: Iterable[Finding]):
    """Mark findings the file inline-suppresses; yields every finding
    (suppressed ones carry suppressed='inline' + the reason)."""
    for f in findings:
        reason = src.disabled(f.rule, f.lineno, f.end_lineno)
        if reason is not None:
            f.suppressed = "inline"
            f.reason = reason
        yield f


def lint_file(checker: Checker, path: str, relpath: Optional[str] = None,
              root: Optional[str] = None) -> list[Finding]:
    """Run ONE checker over ONE file (fixture tests and the legacy
    shim scripts use this; scope filtering is bypassed on purpose)."""
    checker.root = root or REPO_ROOT
    if relpath is None:
        try:
            relpath = os.path.relpath(path, checker.root)
        except ValueError:            # different drive (windows)
            relpath = os.path.basename(path)
    src = SourceFile(path, relpath)
    return list(_apply_inline(src, checker.check_file(src)))


def run_lint(root: Optional[str] = None, checkers=None,
             paths=None, cache=None) -> LintResult:
    """Walk the tree, run every (selected) checker on the files in its
    scope, apply inline suppressions, then run the cross-module
    (``needs_index``) checkers once over a shared ProjectIndex.
    Baseline suppression is applied by the caller
    (:mod:`pycatkin_tpu.lint.cli`) so programmatic users can inspect
    the raw findings. ``cache`` (a :class:`pycatkin_tpu.lint.cache.
    LintCache`) short-circuits unchanged files; the caller saves it."""
    root = root or REPO_ROOT
    if checkers is None:
        checkers = all_checkers()
    for c in checkers:
        c.root = root
    result = LintResult(rules=[c.rule for c in checkers])
    for path, relpath in iter_source_paths(root, paths):
        wanted = [c for c in checkers if c.wants(relpath)]
        if not wanted:
            continue
        src = SourceFile(path, relpath)
        result.n_files += 1
        key = None
        if cache is not None and cache.enabled:
            key = cache.file_key(src.relpath, src.text,
                                 [c.rule for c in wanted])
            hit = cache.get(key)
            if hit is not None:
                result.findings.extend(hit)
                continue
        try:
            src.tree
        except SyntaxError as e:
            f = Finding(
                rule="PCL000", path=src.relpath,
                lineno=e.lineno or 1, col=e.offset or 0,
                message=f"syntax error: {e.msg}",
                source=(e.text or "").strip())
            result.findings.append(f)
            if key is not None:
                cache.put(key, [f])
            continue
        file_findings: list[Finding] = []
        for c in wanted:
            file_findings.extend(_apply_inline(src, c.check_file(src)))
        if key is not None:
            cache.put(key, file_findings)
        result.findings.extend(file_findings)
    project = [c for c in checkers if c.needs_index]
    if project:
        result.findings.extend(_run_project(root, project, cache))
    result.findings.sort(key=lambda f: (f.path, f.lineno, f.rule))
    return result


def _run_project(root: str, project, cache) -> list[Finding]:
    """The cross-module pass: one ProjectIndex, every needs_index
    checker, inline suppression resolved through the index's own
    SourceFiles. Cached on the WHOLE-package content key -- any edit
    under the package re-runs it."""
    key = None
    if cache is not None and cache.enabled:
        key = cache.project_key([c.rule for c in project])
        hit = cache.get(key)
        if hit is not None:
            return hit
    from .project_index import ProjectIndex
    index = ProjectIndex.build(root)
    out: list[Finding] = []
    for c in project:
        for f in c.check_project(index):
            mod = index.modules.get(f.path)
            if mod is not None:
                reason = mod.src.disabled(f.rule, f.lineno, f.end_lineno)
                if reason is not None:
                    f.suppressed = "inline"
                    f.reason = reason
            out.append(f)
    if key is not None:
        cache.put(key, out)
    return out
