"""``python -m pycatkin_tpu.lint`` == ``tools/pclint.py``."""

import sys

from .cli import main

sys.exit(main())
