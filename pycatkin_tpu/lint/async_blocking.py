"""PCL010 async-blocking: nothing blocks the serve event loop.

The serving layer (``pycatkin_tpu/serve``) is ONE asyncio loop; a
single blocking call inside an ``async def`` stalls every in-flight
request behind it (the SLA-aware flush deadlines of the coalescer are
only as good as the loop's tick). The sanctioned idiom is offload:
``await asyncio.to_thread(...)`` / ``loop.run_in_executor(...)`` --
passing a blocking CALLABLE is fine, CALLING it on the loop is not.

Flagged lexically inside ``async def`` bodies (nested sync ``def``
bodies excluded -- they execute wherever they are invoked, which for
the serve tree is a worker thread):

- ``time.sleep`` (use ``asyncio.sleep``);
- blocking file I/O: builtin ``open`` (offload it);
- blocking process/socket construction: ``subprocess.run/call/
  check_output/check_call/Popen``, ``socket.create_connection``,
  ``urllib.request.urlopen``, ``os.system``;
- future/thread joins: ``.result()`` / ``.join()`` method calls
  (``concurrent.futures`` results and thread joins block; await the
  asyncio future instead);
- host-sync pulls: ``host_sync(...)``, ``jax.device_get``,
  ``np.asarray`` (a device materialization parks the loop for a full
  tunnel round trip -- the worst offender of all).

The runtime counterpart is the event-loop stall sanitizer
(:mod:`pycatkin_tpu.san.stall`), which catches what escapes the
lexical net (docs/static_analysis.md "Sanitizers").
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, SourceFile, register

# (module-ish base, attr) calls that block.
_BLOCKING_ATTR_CALLS = {
    ("time", "sleep"): "time.sleep blocks the loop; use asyncio.sleep",
    ("os", "system"): "os.system blocks the loop; offload via "
                      "asyncio.to_thread",
    ("subprocess", "run"): "subprocess.run blocks the loop",
    ("subprocess", "call"): "subprocess.call blocks the loop",
    ("subprocess", "check_output"): "subprocess.check_output blocks "
                                    "the loop",
    ("subprocess", "check_call"): "subprocess.check_call blocks the "
                                  "loop",
    ("socket", "create_connection"): "blocking socket connect; use "
                                     "asyncio.open_connection",
    ("jax", "device_get"): "device->host pull on the event loop; "
                           "offload the sweep to a worker thread",
    ("np", "asarray"): "np.asarray may materialize a device array on "
                       "the loop; offload it",
}

# Bare-name calls that block.
_BLOCKING_NAME_CALLS = {
    "open": "blocking file I/O on the event loop; offload via "
            "asyncio.to_thread",
    "host_sync": "counted host sync on the event loop; offload the "
                 "sweep to a worker thread",
    "input": "blocking stdin read on the event loop",
}

# Method attrs that block regardless of receiver.
_BLOCKING_METHODS = {
    "result": ".result() blocks the loop; await the asyncio future",
    "join": ".join() blocks the loop; offload via asyncio.to_thread",
}


def _attr_base(f: ast.Attribute):
    return f.value.id if isinstance(f.value, ast.Name) else None


def _blocking_reason(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return _BLOCKING_NAME_CALLS.get(f.id)
    if isinstance(f, ast.Attribute):
        base = _attr_base(f)
        if base is not None:
            hit = _BLOCKING_ATTR_CALLS.get((base, f.attr))
            if hit is not None:
                return hit
            if base in ("str", "os", "path", "json"):
                return None          # common safe receivers
        return _BLOCKING_METHODS.get(f.attr)
    return None


def _join_is_str(node: ast.Call) -> bool:
    """``"sep".join(...)`` / ``sep.join(parts)`` string joins are not
    thread joins: a literal-string receiver, or a single iterable
    argument of strings, is the overwhelmingly common case -- only
    no-arg ``x.join()`` (thread API) is unambiguous, so we flag
    ``.join`` ONLY when called with no arguments."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "join"
            and bool(node.args or node.keywords))


@register
class AsyncBlockingChecker(Checker):
    rule = "PCL010"
    name = "async-blocking"
    description = ("blocking call (sleep/file/socket I/O, .result()/"
                   ".join(), host-sync pull) lexically inside an "
                   "async def in serve/; offload via asyncio.to_thread"
                   "/run_in_executor")
    scope = ("pycatkin_tpu/serve/",)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._check_async(src, node)

    def _check_async(self, src: SourceFile, fn: ast.AsyncFunctionDef):
        yield from self._walk_body(src, fn, fn.body)

    def _walk_body(self, src, fn, body):
        for stmt in body:
            yield from self._walk_node(src, fn, stmt)

    def _walk_node(self, src, fn, node):
        # Nested sync defs run off-loop (serve hands them to worker
        # threads); nested async defs are checked by the outer walk.
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Call):
            reason = _blocking_reason(node)
            if reason is not None and not _join_is_str(node):
                yield self.finding(
                    src, node,
                    f"{reason} (inside `async def {fn.name}`)")
        for child in ast.iter_child_nodes(node):
            yield from self._walk_node(src, fn, child)
