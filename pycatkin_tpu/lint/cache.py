"""Incremental lint cache: unchanged files are not re-checked.

``make lint`` runs every rule over every file on every invocation; the
AST passes are cheap individually but the walk is O(repo) and the CI
lane pays it twice (text + SARIF). This module gives :func:`run_lint`
a content-addressed result cache in ``.pclint_cache/cache.json``:

- the PER-FILE key is ``sha1(relpath | file sha | sorted rule ids |
  salt)`` -- touch the file, change which rules apply, or change the
  linter itself and the entry misses;
- the ``salt`` hashes every ``pycatkin_tpu/lint/*.py`` source and every
  ``docs/*.md`` the doc-backed checkers consult, so editing a RULE (or
  the env/metric registries in the docs) invalidates everything without
  any manual versioning;
- PROJECT-LEVEL results (PCL013, computed over the whole
  :class:`~pycatkin_tpu.lint.project_index.ProjectIndex`) are keyed on
  a content hash of EVERY package file: any edit anywhere under
  ``pycatkin_tpu/`` re-runs the cross-module pass, which is exactly its
  invalidation contract.

Suppression state is cache-safe by construction: inline suppressions
are a function of file content (in the key) and the baseline is applied
by the CLI *after* results leave the cache. The cache file itself is
written tmp + ``os.replace`` (PCL012 practices what it preaches) and a
corrupt/alien cache file is treated as empty, never an error.
``pclint --no-cache`` bypasses reads and writes entirely.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict
from typing import Iterable, Optional

from .core import Finding, iter_source_paths

CACHE_DIRNAME = ".pclint_cache"
CACHE_VERSION = 1

# Hashed into the salt: the linter's own code plus the docs-as-registry
# files rules validate against (PCL006 env table, PCL009 metric table).
_SALT_DIRS = (("pycatkin_tpu/lint", ".py"), ("docs", ".md"))


def _sha_bytes(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _sha_text(text: str) -> str:
    return _sha_bytes(text.encode("utf-8", "replace"))


def compute_salt(root: str) -> str:
    """Hash of the linter sources + consulted docs: the cache's
    self-invalidation lever."""
    h = hashlib.sha1()
    for sub, ext in _SALT_DIRS:
        top = os.path.join(root, sub)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(ext):
                    continue
                ap = os.path.join(dirpath, fname)
                try:
                    with open(ap, "rb") as fh:
                        h.update(fname.encode())
                        h.update(fh.read())
                except OSError:
                    continue
    return h.hexdigest()


def project_content_key(root: str) -> str:
    """Cheap (no-parse) content hash over every package module -- the
    project-level (PCL013) cache key input. Matches the ProjectIndex
    invalidation contract: ANY package edit changes it."""
    from .project_index import INDEX_ROOTS
    h = hashlib.sha1()
    for path, relpath in iter_source_paths(root, paths=INDEX_ROOTS):
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            continue
        h.update(relpath.replace("\\", "/").encode())
        h.update(_sha_bytes(data).encode())
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return asdict(f)


def _finding_from_dict(d: dict) -> Finding:
    return Finding(**d)


class LintCache:
    """Content-addressed finding cache for one lint run.

    Usage: construct, hand to :func:`run_lint(..., cache=...)`, call
    :meth:`save` afterwards. Only keys touched THIS run survive the
    save -- entries for contents that no longer exist age out for free.
    """

    def __init__(self, root: str, enabled: bool = True):
        self.root = root
        self.enabled = enabled
        self.path = os.path.join(root, CACHE_DIRNAME, "cache.json")
        self.hits = 0
        self.misses = 0
        self._salt: Optional[str] = None
        self._entries: dict = {}
        self._touched: dict = {}
        if enabled:
            self._load()

    # -- keys ----------------------------------------------------------
    @property
    def salt(self) -> str:
        if self._salt is None:
            self._salt = compute_salt(self.root)
        return self._salt

    def file_key(self, relpath: str, text: str, rule_ids) -> str:
        payload = "|".join((relpath.replace("\\", "/"),
                            _sha_text(text),
                            ",".join(sorted(rule_ids)), self.salt))
        return _sha_text(payload)

    def project_key(self, rule_ids) -> str:
        payload = "|".join(("<project>", project_content_key(self.root),
                            ",".join(sorted(rule_ids)), self.salt))
        return _sha_text(payload)

    # -- lookup --------------------------------------------------------
    def get(self, key: str) -> Optional[list]:
        if not self.enabled:
            return None
        hit = self._entries.get(key)
        if hit is None:
            self.misses += 1
            return None
        self.hits += 1
        self._touched[key] = hit
        try:
            return [_finding_from_dict(d) for d in hit]
        except TypeError:            # schema drift: treat as miss
            self.misses += 1
            self.hits -= 1
            del self._touched[key]
            return None

    def put(self, key: str, findings: Iterable[Finding]) -> None:
        if not self.enabled:
            return
        self._touched[key] = [_finding_to_dict(f) for f in findings]

    # -- persistence ---------------------------------------------------
    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return
        if (not isinstance(data, dict)
                or data.get("version") != CACHE_VERSION
                or data.get("salt") != self.salt):
            return                   # linter changed: start cold
        entries = data.get("entries")
        if isinstance(entries, dict):
            self._entries = entries

    def save(self) -> None:
        if not self.enabled:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": CACHE_VERSION, "salt": self.salt,
                       "entries": self._touched}, fh)
        os.replace(tmp, self.path)
