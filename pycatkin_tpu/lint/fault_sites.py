"""PCL002 fault-sites: every fault-site label is documented in
docs/failure_model.md.

The failure subsystem addresses faults by dispatch-site label (the
``label=`` strings of ``call_with_backend_retry`` /
``run_chunk_with_ladder`` / ``record_event`` / ``record_quarantine``,
the label argument of ``timed_retry``, and ``site = ...``
assignments). A label in code but not in the doc is an undocumented
failure branch: a fault plan targeting it works, but nobody reading
the failure model knows it exists.

F-string labels are normalized by replacing each interpolated field
with ``<i>`` (consecutive fields collapse, so ``f"rescue[{a}{b}]"``
and ``f"rescue[{s}]"`` both become ``rescue[<i>]``); dynamic labels
cannot be statically checked and are skipped. A normalized label must
appear backticked in the doc.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .core import Checker, Finding, SourceFile, register

DOC_RELPATH = os.path.join("docs", "failure_model.md")

# Only these callees take fault-site labels; collecting every `label=`
# kwarg would false-positive on matplotlib legend labels.
LABEL_FUNCS = frozenset({"call_with_backend_retry",
                         "run_chunk_with_ladder", "record_event",
                         "record_quarantine", "timed_retry"})
SITE_NAMES = frozenset({"site", "_site"})


def normalize(node) -> Optional[str]:
    """Literal or f-string label -> normalized site string (None for
    dynamic expressions)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("<i>")
        return re.sub(r"(<i>)+", "<i>", "".join(parts))
    return None


class SiteCollector(ast.NodeVisitor):
    """Collect (normalized_label, node) pairs from one module."""

    def __init__(self):
        self.sites: list[tuple[str, ast.AST]] = []

    def _add(self, node, value):
        label = normalize(value)
        if label is not None:
            self.sites.append((label, node))

    def visit_Call(self, node):
        func = node.func
        fname = getattr(func, "id", None) or getattr(func, "attr", "")
        if fname in LABEL_FUNCS:
            for kw in node.keywords:
                if kw.arg == "label":
                    self._add(node, kw.value)
            if fname == "timed_retry" and len(node.args) >= 2:
                self._add(node, node.args[1])
        self.generic_visit(node)

    def visit_Assign(self, node):
        if any(isinstance(t, ast.Name) and t.id in SITE_NAMES
               for t in node.targets):
            self._add(node, node.value)
        self.generic_visit(node)


def documented_labels(doc_path: str) -> set:
    """Every backticked token in the failure-model doc."""
    with open(doc_path, encoding="utf-8") as fh:
        return set(re.findall(r"`([^`\n]+)`", fh.read()))


@register
class FaultSiteChecker(Checker):
    rule = "PCL002"
    name = "fault-sites"
    description = ("fault-site label not documented in "
                   "docs/failure_model.md")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def __init__(self, doc_path: Optional[str] = None):
        super().__init__()
        self._doc_path = doc_path
        self._documented: Optional[set] = None

    @property
    def doc_path(self) -> str:
        return self._doc_path or os.path.join(self.root, DOC_RELPATH)

    def documented(self) -> set:
        if self._documented is None:
            self._documented = documented_labels(self.doc_path)
        return self._documented

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        collector = SiteCollector()
        collector.visit(src.tree)
        if not collector.sites:
            return
        documented = self.documented()
        rel_doc = DOC_RELPATH.replace(os.sep, "/")
        for label, node in collector.sites:
            if label in documented:
                continue
            yield self.finding(
                src, node,
                f"undocumented fault-site label `{label}` -- add it, "
                f"backticked, to {rel_doc}")


def collect_sites(package: str, rel_to: Optional[str] = None):
    """Legacy-shaped entry for ``tools/lint_fault_sites.py``: every
    statically-known fault-site label under ``package`` as sorted
    (label, relpath, lineno) triples."""
    rel_to = rel_to or os.path.dirname(package)
    found = []
    for dirpath, dirnames, filenames in os.walk(package):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            src = SourceFile(path, os.path.relpath(path, rel_to))
            collector = SiteCollector()
            collector.visit(src.tree)
            rel = os.path.relpath(path, rel_to)
            found += [(label, rel, node.lineno)
                      for label, node in collector.sites]
    return sorted(found)
