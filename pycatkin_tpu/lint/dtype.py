"""PCL005 dtype-discipline: no hardcoded float dtypes in the numerical
kernels (``ops/``, ``solvers/``).

The x64 policy is process-global and owned by the package root
(``pycatkin_tpu/__init__`` enables ``jax_enable_x64`` unless
``PYCATKIN_TPU_X64=0``; TPU-safe precomputed constants live in
``constants.py``). A kernel that spells ``np.float64`` /
``jnp.float64`` / ``dtype="float64"`` directly pins precision at one
call site: under the TPU's emulated f64 (float32 exponent RANGE --
see constants.py) or a deliberate x64-off run, that one site silently
diverges from every other kernel, and stiff chemical ODE solves fail
in the worst way -- plausible-looking wrong numbers. Inherit dtypes
from the inputs, or derive them from the policy in one place.

The same discipline covers the other direction: a raw ``jnp.float32``
/ ``astype("float32")`` downcast bypasses the precision-tier layer
(``pycatkin_tpu/precision.py`` -- the ONE blessed entry point, keyed by
``PYCATKIN_PRECISION_TIER``). An ad-hoc f32 cast runs reduced-precision
math that the tier's f64 polish-and-verify acceptance contract never
checks, so verdicts can silently degrade. Route every downcast through
``precision.bulk_dtype`` / ``precision.cast_bulk`` (the precision
module itself is the policy seam and is outside this rule's scope).

Host-side interop that genuinely needs a concrete dtype (e.g. handing
numpy a deterministic scratch array) suppresses inline with a reason
or lives in the committed baseline.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, SourceFile, register

_FLOAT_BASES = frozenset({"np", "numpy", "jnp"})


@register
class DtypeChecker(Checker):
    rule = "PCL005"
    name = "dtype-discipline"
    description = ("hardcoded float dtype in a numerical kernel; "
                   "inherit the dtype, route f64 through the x64 "
                   "policy (constants.py / PYCATKIN_TPU_X64) and f32 "
                   "through the precision-tier helper "
                   "(pycatkin_tpu.precision)")
    scope = ("pycatkin_tpu/ops/", "pycatkin_tpu/solvers/")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr == "float64"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in _FLOAT_BASES):
                yield self.finding(
                    src, node,
                    f"hardcoded {node.value.id}.float64 in a "
                    f"numerical kernel; inherit the dtype from the "
                    f"inputs or derive it from the x64 policy")
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "float32"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in _FLOAT_BASES):
                yield self.finding(
                    src, node,
                    f"raw {node.value.id}.float32 downcast in a "
                    f"numerical kernel bypasses the precision-tier "
                    f"layer; use pycatkin_tpu.precision.bulk_dtype / "
                    f"cast_bulk (the one blessed entry point)")
            elif (isinstance(node, ast.Constant)
                    and node.value == "float64"):
                yield self.finding(
                    src, node,
                    "bare \"float64\" dtype literal in a numerical "
                    "kernel; inherit the dtype from the inputs or "
                    "derive it from the x64 policy")
            elif (isinstance(node, ast.Constant)
                    and node.value == "float32"):
                yield self.finding(
                    src, node,
                    "bare \"float32\" dtype literal in a numerical "
                    "kernel bypasses the precision-tier layer; use "
                    "pycatkin_tpu.precision.bulk_dtype / cast_bulk "
                    "(the one blessed entry point)")
