"""Cross-module project index for pclint.

The per-file checkers (PCL001..PCL012) see one AST at a time, which is
exactly the blind spot the hand-maintained hot-path registry papered
over: whether a function is ON the sweep hot path is a property of the
CALL GRAPH, not of any single file. :class:`ProjectIndex` parses every
package module once and exposes

- per-module ASTs and content hashes (the hashes also drive the
  incremental lint cache's invalidation, :mod:`pycatkin_tpu.lint.cache`);
- a name-resolution table per module (top-level functions, ``from x
  import y`` aliases, imported-module aliases);
- a conservative function-level call graph with reachability queries.

Resolution is deliberately LIGHT: a call edge is recorded when the
callee resolves to a top-level function of the same module, to a
``from``-imported function of another package module, or to
``alias.func`` through an imported-module alias. Method calls and
dynamic dispatch are not chased -- a cross-module rule built on this
index (PCL013) trades exhaustiveness for zero false edges, the right
trade for a gating linter.

Checkers opt in by setting ``needs_index = True`` and implementing
``check_project(index)`` (see :class:`pycatkin_tpu.lint.core.Checker`);
the runner builds ONE index per run and hands it to each of them after
the per-file walk.
"""

from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field
from typing import Optional

from .core import SourceFile, iter_source_paths

# Only package modules join the call graph: tests/tools/examples call
# INTO the package but are never on the sweep hot path themselves.
INDEX_ROOTS = ("pycatkin_tpu",)

PACKAGE = "pycatkin_tpu"


@dataclass
class FunctionInfo:
    """One top-level function (or method, qualname ``Class.name``)."""

    name: str
    relpath: str
    lineno: int
    end_lineno: Optional[int]
    node: ast.AST
    # Called names as written: bare identifiers from ``f(...)`` plus
    # ``alias.attr`` pairs from ``mod.f(...)``.
    calls: set = field(default_factory=set)          # {str}
    attr_calls: set = field(default_factory=set)     # {(base, attr)}


@dataclass
class ModuleInfo:
    relpath: str
    path: str
    sha: str
    src: SourceFile
    functions: dict = field(default_factory=dict)    # name -> FunctionInfo
    # local name -> (module relpath, original name) for
    # ``from .x import y [as z]`` where .x resolves inside the package.
    from_imports: dict = field(default_factory=dict)
    # local alias -> module relpath for ``from .. import engine`` /
    # ``import pycatkin_tpu.engine as engine``.
    module_aliases: dict = field(default_factory=dict)


def _sha_text(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8", "replace")).hexdigest()


def _module_relpath(dotted: str) -> Optional[str]:
    """``pycatkin_tpu.parallel.batch`` -> its repo-relative file path
    (None for names outside the package; packages map to __init__.py)."""
    if dotted != PACKAGE and not dotted.startswith(PACKAGE + "."):
        return None
    return dotted.replace(".", "/") + ".py"


def _resolve_relative(relpath: str, level: int, module: str) -> str:
    """Absolute dotted name of a relative import written in ``relpath``
    (``level`` leading dots, ``module`` the trailing name, may be '')."""
    pkg_parts = relpath[:-len(".py")].replace("\\", "/").split("/")
    if pkg_parts[-1] == "__init__":
        pkg_parts = pkg_parts[:-1]
    else:
        pkg_parts = pkg_parts[:-1]          # containing package
    base = pkg_parts[:len(pkg_parts) - (level - 1)] if level > 1 \
        else pkg_parts
    return ".".join(base + ([module] if module else []))


def _collect_calls(fn_node, info: FunctionInfo) -> None:
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            info.calls.add(f.id)
        elif isinstance(f, ast.Attribute) and isinstance(f.value,
                                                         ast.Name):
            info.attr_calls.add((f.value.id, f.attr))


class ProjectIndex:
    """Parsed view of every package module plus the call graph."""

    def __init__(self, root: str):
        self.root = root
        self.modules: dict = {}               # relpath -> ModuleInfo

    # -- construction --------------------------------------------------
    @classmethod
    def build(cls, root: str) -> "ProjectIndex":
        idx = cls(root)
        for path, relpath in iter_source_paths(root, paths=INDEX_ROOTS):
            idx._add_file(path, relpath.replace("\\", "/"))
        return idx

    def _add_file(self, path: str, relpath: str) -> None:
        try:
            src = SourceFile(path, relpath)
            tree = src.tree
        except (OSError, SyntaxError):
            return                           # PCL000 reports it already
        mod = ModuleInfo(relpath=relpath, path=path,
                         sha=_sha_text(src.text), src=src)
        for top in tree.body:
            if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(top.name, relpath, top.lineno,
                                    getattr(top, "end_lineno", None),
                                    top)
                _collect_calls(top, info)
                mod.functions[top.name] = info
            elif isinstance(top, ast.ClassDef):
                for item in top.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f"{top.name}.{item.name}"
                        info = FunctionInfo(qual, relpath, item.lineno,
                                            getattr(item, "end_lineno",
                                                    None), item)
                        _collect_calls(item, info)
                        mod.functions[qual] = info
            elif isinstance(top, ast.ImportFrom):
                dotted = _resolve_relative(relpath, top.level,
                                           top.module or "") \
                    if top.level else (top.module or "")
                target = _module_relpath(dotted)
                for alias in top.names:
                    local = alias.asname or alias.name
                    if target is not None:
                        # ``from .x import y``: y may be a function of
                        # x OR a submodule of package x.
                        sub = _module_relpath(f"{dotted}.{alias.name}")
                        mod.from_imports[local] = (target, alias.name)
                        if sub is not None:
                            mod.module_aliases.setdefault(local, sub)
            elif isinstance(top, ast.Import):
                for alias in top.names:
                    target = _module_relpath(alias.name)
                    if target is not None:
                        local = alias.asname or alias.name.split(".")[0]
                        mod.module_aliases[local] = target
        self.modules[relpath] = mod

    # -- cache invalidation hook ---------------------------------------
    def content_key(self) -> str:
        """One hash covering every indexed file: any edit anywhere in
        the package changes it (the PCL013 cache key)."""
        h = hashlib.sha1()
        for relpath in sorted(self.modules):
            h.update(relpath.encode())
            h.update(self.modules[relpath].sha.encode())
        return h.hexdigest()

    # -- resolution / call graph ---------------------------------------
    def _module_file(self, relpath: str) -> Optional[ModuleInfo]:
        m = self.modules.get(relpath)
        if m is None and relpath.endswith(".py"):
            # package import: pycatkin_tpu/engine.py vs engine/__init__
            m = self.modules.get(relpath[:-3] + "/__init__.py")
        return m

    def resolve(self, relpath: str, name: str):
        """``(ModuleInfo, FunctionInfo)`` the bare name ``name`` used in
        module ``relpath`` refers to, or None."""
        mod = self.modules.get(relpath)
        if mod is None:
            return None
        fn = mod.functions.get(name)
        if fn is not None:
            return mod, fn
        imp = mod.from_imports.get(name)
        if imp is not None:
            target_rel, orig = imp
            target = self._module_file(target_rel)
            if target is not None:
                fn = target.functions.get(orig)
                if fn is not None:
                    return target, fn
        return None

    def resolve_attr(self, relpath: str, base: str, attr: str):
        """``(ModuleInfo, FunctionInfo)`` for ``base.attr(...)`` where
        ``base`` is an imported-module alias, or None."""
        mod = self.modules.get(relpath)
        if mod is None:
            return None
        target_rel = mod.module_aliases.get(base)
        if target_rel is None:
            return None
        target = self._module_file(target_rel)
        if target is None:
            return None
        fn = target.functions.get(attr)
        return (target, fn) if fn is not None else None

    def callees(self, relpath: str, fname: str):
        """Resolved ``(relpath, fname)`` edges out of one function."""
        mod = self.modules.get(relpath)
        if mod is None or fname not in mod.functions:
            return []
        info = mod.functions[fname]
        out = []
        for name in sorted(info.calls):
            hit = self.resolve(relpath, name)
            if hit is not None:
                out.append((hit[0].relpath, hit[1].name))
        for base, attr in sorted(info.attr_calls):
            hit = self.resolve_attr(relpath, base, attr)
            if hit is not None:
                out.append((hit[0].relpath, hit[1].name))
        return out

    def reachable(self, roots) -> set:
        """Every ``(relpath, fname)`` reachable from ``roots`` (roots
        included) over the resolved call graph."""
        seen = set()
        stack = [r for r in roots]
        while stack:
            node = stack.pop()
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.callees(*node):
                if nxt not in seen:
                    stack.append(nxt)
        return seen
