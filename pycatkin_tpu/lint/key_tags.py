"""PCL015 key-tag-discipline: kind-string tags obey the declared grammar.

Program kinds compose runtime-knob tags (precision tier, direction
kernel, sharding, tenant count) as ordered suffixes; the order and the
literals used to be informal prose spread over three perf docs. They
are now ONE declared artifact -- ``KIND_TAG_GRAMMAR`` in
:mod:`pycatkin_tpu.parallel.compile_pool` -- and this rule checks the
tree against it:

1. **Declaration integrity** -- the grammar parses as a pure literal,
   every entry's helper function exists in its declared owner module,
   and the helper's body actually constructs the declared literal (a
   helper edited away from its grammar row is drift, caught here).
2. **Composition order** -- any f-string or string-concatenation that
   calls two or more tag helpers must call them in grammar order
   (tier, kernel, sharding, tenant). Out-of-order tags produce keys
   that never match their prewarmed/exported twins: silent zoo bloat.
3. **Tag ownership** -- the tag literals themselves may appear only in
   their owner modules (plus the grammar declaration and the tag
   helpers' home, ``precision.py`` / ``compile_pool.py``). Everyone
   else must go through the helpers (``tier_of_tag`` /
   ``kernel_of_tag`` / ``strip_kind_tags``), so a grammar change is a
   one-module change.

The grammar is read from ``compile_pool.py``'s AST -- lint never
imports package code -- so this rule needs the project index and is
cached on the whole-package content key: editing the grammar or any
tag helper invalidates the cached verdict.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, register

GRAMMAR_MODULE = "pycatkin_tpu/parallel/compile_pool.py"
GRAMMAR_NAME = "KIND_TAG_GRAMMAR"
_REQUIRED_KEYS = ("name", "literal", "strip", "owner", "helper")

# Literals shorter than this are too generic to police by substring
# (the tenant tag ":t" would match ":tof", ":tier", ...); those tags
# are still covered by the declaration and ordering checks.
_MIN_OWNED_LITERAL = 4


def load_grammar(tree: ast.AST):
    """(grammar tuple, assign node) parsed out of the compile_pool AST,
    or (None, None) when the declaration is missing or not a pure
    literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == GRAMMAR_NAME
                   for t in node.targets):
            continue
        try:
            grammar = ast.literal_eval(node.value)
        except ValueError:
            return None, node
        return grammar, node
    return None, None


def _docstring_nodes(tree: ast.AST) -> set:
    """id()s of every docstring Constant: prose, not key material."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Module, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        body = getattr(node, "body", [])
        if (body and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            out.add(id(body[0].value))
    return out


def _str_constants(node: ast.AST, skip: set = frozenset()):
    """Every non-docstring string-Constant descendant (f-string parts
    included) with its anchor node."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and id(sub) not in skip):
            yield sub


def _helper_call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _concat_roots(tree: ast.AST):
    """Top-level string-composition expressions: JoinedStr (f-strings)
    and + -chains, widest-first so each composition is checked once."""
    seen = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.JoinedStr, ast.BinOp)):
            if isinstance(node, ast.BinOp) and \
                    not isinstance(node.op, ast.Add):
                continue
            if id(node) in seen:
                continue
            for sub in ast.walk(node):
                if sub is not node:
                    seen.add(id(sub))
            yield node


@register
class KeyTagChecker(Checker):
    rule = "PCL015"
    name = "key-tag-discipline"
    description = ("kind-string tag construction disagrees with the "
                   "declared KIND_TAG_GRAMMAR (order, literal, or "
                   "ownership)")
    needs_index = True

    def wants(self, relpath: str) -> bool:
        return False                  # project-level rule only

    def check_file(self, src) -> Iterable[Finding]:
        return ()

    def check_project(self, index) -> Iterable[Finding]:
        mod = index.modules.get(GRAMMAR_MODULE)
        if mod is None:
            yield self._drift(f"{GRAMMAR_MODULE} is not in the project "
                              f"index; the tag grammar cannot be checked")
            return
        grammar, decl = load_grammar(mod.src.tree)
        if grammar is None:
            where = f"line {decl.lineno}" if decl is not None else "anywhere"
            yield self._drift(
                f"{GRAMMAR_NAME} not parseable as a pure literal at "
                f"{where} of {GRAMMAR_MODULE}; keep the declaration "
                f"literal so lint can read it without importing")
            return

        bad = [e for e in grammar
               if not isinstance(e, dict)
               or any(k not in e for k in _REQUIRED_KEYS)]
        if bad:
            yield self._drift(
                f"{GRAMMAR_NAME} entries must be dicts with keys "
                f"{_REQUIRED_KEYS}; got {bad[0]!r}")
            return

        yield from self._check_declaration(index, grammar, decl)
        order = {e["helper"]: i for i, e in enumerate(grammar)}
        names = [e["name"] for e in grammar]
        for relpath, m in sorted(index.modules.items()):
            yield from self._check_order(relpath, m, order, names)
            yield from self._check_ownership(relpath, m, grammar, decl)

    # -- 1. declaration integrity ------------------------------------
    def _check_declaration(self, index, grammar, decl):
        for entry in grammar:
            owner, helper = entry["owner"], entry["helper"]
            mod = index.modules.get(owner)
            info = mod.functions.get(helper) if mod else None
            if info is None:
                yield self._drift(
                    f"grammar tag `{entry['name']}` declares helper "
                    f"`{helper}` in {owner}, which does not exist "
                    f"(update {GRAMMAR_NAME} alongside the helper)",
                    lineno=decl.lineno)
                continue
            lit = entry["literal"]
            skip = _docstring_nodes(info.node)
            built = any(lit in c.value
                        for c in _str_constants(info.node, skip))
            if not built:
                yield Finding(
                    rule=self.rule, path=owner, lineno=info.lineno,
                    col=getattr(info.node, "col_offset", 0),
                    message=(f"tag helper `{helper}` no longer "
                             f"constructs its declared literal "
                             f"`{lit}` (grammar tag "
                             f"`{entry['name']}`); update "
                             f"{GRAMMAR_NAME} in {GRAMMAR_MODULE} in "
                             f"the same change"),
                    source=mod.src.line(info.lineno).strip(),
                    end_lineno=info.lineno)

    # -- 2. composition order ----------------------------------------
    def _check_order(self, relpath, mod, order, names):
        for root in _concat_roots(mod.src.tree):
            calls = []
            for sub in ast.walk(root):
                if isinstance(sub, ast.Call):
                    name = _helper_call_name(sub)
                    if name in order:
                        calls.append((sub.lineno, sub.col_offset,
                                      order[name], name, sub))
            calls.sort(key=lambda t: (t[0], t[1]))
            ranks = [c[2] for c in calls]
            if ranks == sorted(ranks):
                continue
            first_bad = next(c for i, c in enumerate(calls)
                             if i and c[2] < calls[i - 1][2])
            node = first_bad[4]
            yield Finding(
                rule=self.rule, path=relpath, lineno=node.lineno,
                col=node.col_offset,
                message=(f"kind-string tags composed out of grammar "
                         f"order: `{first_bad[3]}` must come before "
                         f"`{calls[calls.index(first_bad) - 1][3]}` "
                         f"(declared order: {', '.join(names)}; see "
                         f"{GRAMMAR_NAME} in {GRAMMAR_MODULE})"),
                source=mod.src.line(node.lineno).strip(),
                end_lineno=getattr(node, "end_lineno", node.lineno))

    # -- 3. tag ownership --------------------------------------------
    def _check_ownership(self, relpath, mod, grammar, decl):
        if relpath.startswith("pycatkin_tpu/lint/"):
            return                    # lint machinery talks about tags
        allowed_always = {GRAMMAR_MODULE, "pycatkin_tpu/precision.py"}
        skip = _docstring_nodes(mod.src.tree)
        for entry in grammar:
            lit = entry["literal"]
            if len(lit) < _MIN_OWNED_LITERAL:
                continue
            if relpath in allowed_always or relpath == entry["owner"]:
                continue
            for const in _str_constants(mod.src.tree, skip):
                if lit not in const.value:
                    continue
                yield Finding(
                    rule=self.rule, path=relpath, lineno=const.lineno,
                    col=const.col_offset,
                    message=(f"literal kind-tag `{lit}` (grammar tag "
                             f"`{entry['name']}`) outside its owner "
                             f"{entry['owner']}: parse tags with the "
                             f"inverse helpers (precision.tier_of_tag "
                             f"/ kernel_of_tag, "
                             f"compile_pool.strip_kind_tags) instead "
                             f"of matching substrings"),
                    source=mod.src.line(const.lineno).strip(),
                    end_lineno=getattr(const, "end_lineno",
                                       const.lineno))

    def _drift(self, message: str, lineno: int = 1) -> Finding:
        return Finding(
            rule=self.rule, path=GRAMMAR_MODULE, lineno=lineno, col=0,
            message=message, source="", end_lineno=lineno)
