"""PCL001 host-sync: no uncounted blocking device->host
materializations on the sweep hot path.

On the tunneled production backend each blocking materialization costs
~0.8-1.2 s of round trip regardless of payload (docs/index.md
"Performance"), so every intentional hot-path transfer must flow
through ``utils.profiling.host_sync`` -- the counted choke point
``tests/test_sync_budget.py`` holds to the contractual budget -- or
carry a reviewed ``# sync-ok: <reason>`` annotation.

The checker walks the files of the hot-path registry
(:mod:`pycatkin_tpu.lint.hotpath` -- ONE list shared with the budget
test) and flags, inside registered functions only (nested closures
included: they run on the hot path), the two raw idioms that history
shows creep in during refactors:

- ``np.asarray(...)`` (blocking copy of a device array)
- ``int(jnp....)`` / ``float(jnp....)`` (scalar pull of a device
  value) -- positional OR keyword arguments (the pre-pclint script
  only inspected ``args[0]``)

``# sync-ok:`` is honored on ANY line a multi-line call spans (the
pre-pclint script only matched the call's first line), as is the
unified ``# pclint: disable=PCL001`` syntax.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import Checker, Finding, SourceFile, register
from .hotpath import HOT_FUNCTIONS, SYNC_ANNOTATION, hot_functions_for


def _is_np_asarray(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "asarray"
            and isinstance(f.value, ast.Name) and f.value.id == "np")


def _mentions_jnp(expr: ast.AST) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id == "jnp"
               for sub in ast.walk(expr))


def _is_scalar_pull(node: ast.Call) -> bool:
    """``int(...)``/``float(...)`` whose argument expression mentions
    jnp -- a device scalar pulled to the host. Inspects every
    positional AND keyword argument; ``int(host_sync(...))`` is the
    counted idiom, not a bypass."""
    f = node.func
    if not (isinstance(f, ast.Name) and f.id in ("int", "float")):
        return False
    exprs = list(node.args) + [kw.value for kw in node.keywords]
    if not exprs:
        return False
    for arg in exprs:
        if (isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name)
                and arg.func.id == "host_sync"):
            return False
    return any(_mentions_jnp(arg) for arg in exprs)


def _annotated(src: SourceFile, node: ast.AST) -> bool:
    """True when any line the node spans carries the legacy
    ``# sync-ok:`` annotation."""
    return any(SYNC_ANNOTATION in src.line(i)
               for i in src.span_lines(node.lineno,
                                       getattr(node, "end_lineno", None)))


@register
class HostSyncChecker(Checker):
    rule = "PCL001"
    name = "host-sync"
    description = ("raw device->host materialization on the sweep hot "
                   "path; route through utils.profiling.host_sync or "
                   "annotate '# sync-ok: <reason>'")

    def __init__(self, hot_paths: Optional[dict] = None):
        super().__init__()
        # relpath -> hot-function set; None = the shared registry.
        self.hot_paths = hot_paths

    def wants(self, relpath: str) -> bool:
        if self.hot_paths is not None:
            return relpath.replace("\\", "/") in self.hot_paths
        return hot_functions_for(relpath, self.root) is not None

    def _functions_for(self, relpath: str):
        if self.hot_paths is not None:
            hit = self.hot_paths.get(relpath.replace("\\", "/"))
        else:
            hit = hot_functions_for(relpath, self.root)
        # Direct lint_file() calls on fixture copies fall back to the
        # full registered-name union.
        return hit if hit is not None else HOT_FUNCTIONS

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        hot = self._functions_for(src.relpath)
        for top in src.tree.body:
            if not isinstance(top, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                continue
            if top.name not in hot:
                continue
            for node in ast.walk(top):
                if not isinstance(node, ast.Call):
                    continue
                if not (_is_np_asarray(node) or _is_scalar_pull(node)):
                    continue
                if _annotated(src, node):
                    continue
                kind = ("np.asarray" if _is_np_asarray(node)
                        else "int()/float() scalar pull")
                yield self.finding(
                    src, node,
                    f"uncounted host materialization ({kind}) in hot-"
                    f"path function `{top.name}`; route through "
                    f"utils.profiling.host_sync or annotate "
                    f"'{SYNC_ANNOTATION} <reason>'")


def collect_syncs(path: str, hot_functions=None):
    """Legacy-shaped entry for ``tools/lint_host_syncs.py``:
    ``(lineno, stripped source line)`` of every unannotated raw
    materialization inside a hot function of ``path``."""
    hot = frozenset(hot_functions) if hot_functions is not None \
        else HOT_FUNCTIONS
    import os
    rel = os.path.basename(path)
    checker = HostSyncChecker(hot_paths={rel: hot})
    src = SourceFile(path, rel)
    flagged = [(f.lineno, f.source) for f in checker.check_file(src)
               if src.disabled(f.rule, f.lineno, f.end_lineno) is None]
    return sorted(set(flagged))
