"""PCL013 fused-tail integrity: the hot-path registry matches the call
graph.

PCL001 only watches functions registered in the hot-path registry
(:mod:`pycatkin_tpu.lint.hotpath`, now the ``@hotpath`` decorator
scan). That leaves one drift class open: a function REACHABLE from the
fused/packed sweep bodies that materializes device values but was
never decorated -- its syncs are invisible to both the static check
and the budget test's attribution. This rule closes it over the
:class:`~pycatkin_tpu.lint.project_index.ProjectIndex` call graph:

    for every function reachable from the sweep roots
    (the decorated entry points themselves), if its body contains a
    PCL001-style sync primitive -- ``np.asarray(...)``,
    ``int()/float()`` over a jnp expression, or a counted
    ``host_sync(...)`` call -- it must be ``@hotpath``-decorated.

Fix by decorating the function (which puts it under PCL001's per-line
scrutiny, where reviewed transfers carry ``# sync-ok:``), or suppress
at the function's ``def`` line with a reason when the np.asarray is a
pure host-side conversion (numpy in, numpy out -- free, no device
round trip).

This is the cross-module rule: it runs once per lint pass over the
shared index (``needs_index = True`` / ``check_project``), not per
file, and the incremental cache keys it on the WHOLE index content
(any package edit re-runs it).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, register
from .host_sync import _is_np_asarray, _is_scalar_pull
from .hotpath import hot_path_files


def _sync_primitive(fn_node) -> ast.Call | None:
    """First PCL001-style sync primitive in a function body (nested
    defs included -- closures run on the caller's path), or None."""
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        if _is_np_asarray(node) or _is_scalar_pull(node):
            return node
        f = node.func
        if isinstance(f, ast.Name) and f.id == "host_sync":
            return node
        if (isinstance(f, ast.Attribute) and f.attr == "host_sync"):
            return node
    return None


@register
class FusedTailChecker(Checker):
    rule = "PCL013"
    name = "fused-tail"
    description = ("function reachable from the fused/packed sweep "
                   "bodies materializes device values but is not "
                   "@hotpath-decorated (hot-path registry drift)")
    needs_index = True

    def wants(self, relpath: str) -> bool:
        return False                  # project-level rule only

    def check_file(self, src) -> Iterable[Finding]:
        return ()

    def roots(self) -> set:
        """(relpath, fname) sweep entry points: every decorated
        function -- the fused/packed sweep bodies plus whatever they
        already pulled into the registry."""
        out = set()
        for rel, names in hot_path_files(self.root).items():
            out |= {(rel, n) for n in names}
        return out

    def check_project(self, index) -> Iterable[Finding]:
        registered = hot_path_files(self.root)
        for relpath, fname in sorted(index.reachable(self.roots())):
            if fname in registered.get(relpath, frozenset()):
                continue
            mod = index.modules.get(relpath)
            info = mod.functions.get(fname) if mod else None
            if info is None:
                continue
            call = _sync_primitive(info.node)
            if call is None:
                continue
            src = mod.src
            f = Finding(
                rule=self.rule, path=relpath, lineno=info.lineno,
                col=getattr(info.node, "col_offset", 0),
                message=(f"`{fname}` is reachable from the fused/"
                         f"packed sweep bodies and materializes "
                         f"device values (line {call.lineno}) but is "
                         f"not @hotpath-decorated; decorate it so "
                         f"PCL001 and the sync-budget test see it"),
                source=src.line(info.lineno).strip(),
                end_lineno=info.lineno)
            yield f
