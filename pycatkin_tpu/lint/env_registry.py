"""PCL006 env-registry: every ``PYCATKIN_*`` environment key referenced
anywhere in the tree must appear in the documented env-var registry
(docs/index.md, "Environment variable registry").

The framework grew real knobs PR over PR (``PYCATKIN_FAULTS``,
``PYCATKIN_VALIDATE``, ``PYCATKIN_AOT_CACHE``, ...). An env key read
by code but absent from the registry is an undocumented production
control: it changes behavior, nobody operating the system can discover
it, and two PRs can invent colliding names. This rule closes the
registry the same way PCL002 closes the fault-site registry.

Detection is deliberately blunt: ANY string literal that full-matches
``PYCATKIN_[A-Z0-9_]+`` counts as a reference -- ``os.environ.get``
reads, ``os.environ[...]`` writes, monkeypatched test knobs, env
pass-through lists. Blunt is right here: a key you set, forward, or
test is a key an operator can set, so it belongs in the registry.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .core import Checker, Finding, SourceFile, register

DOC_RELPATH = os.path.join("docs", "index.md")

_KEY_RE = re.compile(r"^PYCATKIN_[A-Z0-9_]+$")
_DOC_KEY_RE = re.compile(r"`(PYCATKIN_[A-Z0-9_]+)`")


def registered_keys(doc_path: str) -> set:
    """Every backticked PYCATKIN_* token in the registry doc."""
    with open(doc_path, encoding="utf-8") as fh:
        return set(_DOC_KEY_RE.findall(fh.read()))


@register
class EnvRegistryChecker(Checker):
    rule = "PCL006"
    name = "env-registry"
    description = ("PYCATKIN_* env key not in the documented registry "
                   "(docs/index.md)")
    scope = ("",)             # the whole scanned tree

    def __init__(self, doc_path: Optional[str] = None):
        super().__init__()
        self._doc_path = doc_path
        self._registered: Optional[set] = None

    @property
    def doc_path(self) -> str:
        return self._doc_path or os.path.join(self.root, DOC_RELPATH)

    def registered(self) -> set:
        if self._registered is None:
            self._registered = registered_keys(self.doc_path)
        return self._registered

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        seen_lines: set[tuple] = set()
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _KEY_RE.match(node.value)):
                continue
            key = node.value
            if key in self.registered():
                continue
            # One finding per (key, line): `K in os.environ` idioms can
            # mention the same literal twice on a line.
            dedup = (key, node.lineno)
            if dedup in seen_lines:
                continue
            seen_lines.add(dedup)
            yield self.finding(
                src, node,
                f"environment key `{key}` is not in the documented "
                f"registry -- add a row to docs/index.md "
                f"\"Environment variable registry\"")
