"""PCL003 jit-purity: no side effects inside jitted functions.

``jax.jit`` traces a function ONCE per (shapes, dtypes) signature and
replays the compiled XLA program thereafter: any Python side effect in
the body -- ``print``, reading ``os.environ``, Python/NumPy RNG,
wall-clock reads, ``global`` mutation -- executes at trace time only,
then silently never again. In stiff-kinetics kernels this is how
"debug prints that stopped printing" and "env knobs that stopped
knobbing" bugs are born; SPIN-ODE-style solver stacks treat trace
purity as a hard contract, and so do we.

Statically-detected jitted functions:

- decorated ``@jax.jit`` / ``@jit`` / ``@pjit`` /
  ``@partial(jax.jit, ...)``;
- any function whose NAME is passed (possibly nested under ``vmap``
  etc.) to a ``jax.jit(...)`` / ``pjit(...)`` call in the same module
  -- the repo's dominant ``return jax.jit(jax.vmap(solve_one))``
  closure-factory idiom.

Flagged inside those bodies (nested closures included -- they trace
too): ``print(...)``, ``os.environ`` / ``os.getenv`` reads, ``random.*``
and ``np.random.*`` calls, ``time.time``-family and ``datetime.now``
reads, and ``global`` declarations. ``jax.debug.print`` and
``jax.random`` are the blessed alternatives and are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import Checker, Finding, SourceFile, register

JIT_NAMES = frozenset({"jit", "pjit"})

_TIME_READS = frozenset({"time.time", "time.perf_counter",
                         "time.monotonic", "time.process_time",
                         "datetime.now", "datetime.utcnow",
                         "datetime.datetime.now",
                         "datetime.datetime.utcnow"})


def dotted(expr) -> str:
    """``a.b.c`` for an attribute chain ('' when not a plain chain).
    A leading-underscore alias of a module (``_os``, ``_time``) is
    normalized to the bare name -- the repo imports modules that way
    to keep them out of the public namespace."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id.lstrip("_") or expr.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(expr) -> bool:
    """True for a `jit`/`pjit` reference (bare name or attribute)."""
    if isinstance(expr, ast.Name):
        return expr.id in JIT_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in JIT_NAMES
    return False


def _is_jit_decorator(deco) -> bool:
    if _is_jit_expr(deco):
        return True
    if isinstance(deco, ast.Call):
        if _is_jit_expr(deco.func):
            return True
        # functools.partial(jax.jit, static_argnums=...)
        fname = dotted(deco.func)
        if fname.endswith("partial"):
            return any(_is_jit_expr(a) for a in deco.args)
    return False


def iter_jitted_functions(tree) -> Iterator[ast.FunctionDef]:
    """Every function def in the module that is statically known to be
    jitted (decorator form, or its name appears inside the positional
    arguments of a jit call anywhere in the module)."""
    jitted_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        jitted_names.add(sub.id)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if (node.name in jitted_names
                or any(_is_jit_decorator(d)
                       for d in node.decorator_list)):
            yield node


@register
class JitPurityChecker(Checker):
    rule = "PCL003"
    name = "jit-purity"
    description = ("Python side effect inside a jitted function "
                   "(runs at trace time only, then silently never "
                   "again)")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in iter_jitted_functions(src.tree):
            yield from self._check_body(src, fn)

    def _check_body(self, src: SourceFile, fn) -> Iterable[Finding]:
        where = f"inside jitted function `{fn.name}`"
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                yield self.finding(
                    src, node,
                    f"`global {', '.join(node.names)}` {where}: "
                    f"mutating module state under trace happens once, "
                    f"then never again")
                continue
            if isinstance(node, ast.Subscript):
                if dotted(node.value) == "os.environ":
                    yield self.finding(
                        src, node,
                        f"os.environ read {where}: the value is baked "
                        f"in at trace time; read it outside and close "
                        f"over the result")
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                yield self.finding(
                    src, node,
                    f"print() {where}: prints once at trace time, "
                    f"then silently never again; use "
                    f"jax.debug.print for traced values")
                continue
            name = dotted(f)
            if not name:
                continue
            if name.startswith("os.environ") or name == "os.getenv":
                yield self.finding(
                    src, node,
                    f"environment read ({name}) {where}: baked in at "
                    f"trace time; read it outside and close over the "
                    f"result")
            elif name.startswith("np.random.") \
                    or name.startswith("numpy.random."):
                yield self.finding(
                    src, node,
                    f"NumPy RNG ({name}) {where}: draws once at trace "
                    f"time and the compiled program replays the same "
                    f"constants; thread a jax.random key instead")
            elif name.startswith("random."):
                yield self.finding(
                    src, node,
                    f"Python RNG ({name}) {where}: draws once at "
                    f"trace time and the compiled program replays the "
                    f"same constants; thread a jax.random key instead")
            elif name in _TIME_READS:
                yield self.finding(
                    src, node,
                    f"wall-clock read ({name}) {where}: the timestamp "
                    f"is a trace-time constant; time around the "
                    f"jitted call, not inside it")
