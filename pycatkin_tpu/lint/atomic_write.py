"""PCL012 atomic-write protocol: no torn files in the protocol dirs.

The elastic scheduler's on-disk queue (``robustness/scheduler.py``) and
the serialization layer (``utils/io.py``) are multi-process protocol
surfaces: leases, done records, journals and checkpoints are read by
concurrent workers, lease thieves and crash-recovery replays. The
repo's established crash-atomic idioms are

- tmp + ``os.replace`` for last-writer-wins records (``_write_json``,
  ``atomic_save_results``);
- tmp + ``os.link`` for first-writer-wins records (``claim``,
  ``write_done`` -- hard-link create fails when the name exists, the
  one portable O_EXCL-with-payload primitive);
- append + flush + fsync with torn-tail repair for journals
  (``append_json_line``).

This rule flags, inside those two files only:

- ``os.rename`` anywhere (silently clobbers on POSIX, fails on
  Windows when the target exists; ``os.replace``/``os.link`` make the
  intent explicit);
- a bare ``open(path, "w"/"wb"/...)`` write in a function that never
  publishes via ``os.replace``/``os.link`` -- a reader can observe the
  half-written file. Write to a tmp name and publish atomically.

The function-level granularity is the point: ``claim`` opens a tmp
file and then ``os.link``\\ s it -- clean; a writer with no atomic
publish anywhere in its body is a torn read waiting to happen.
Genuinely exempt writes (e.g. a stop-marker whose CONTENT is
irrelevant) carry an inline ``# pclint: disable=PCL012 -- <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, SourceFile, register

_WRITE_MODES = ("w", "wt", "wb", "w+", "wb+", "x", "xb")


def _open_write_mode(node: ast.Call) -> str | None:
    """The write mode of an ``open(...)`` call, else None."""
    f = node.func
    if not (isinstance(f, ast.Name) and f.id == "open"):
        return None
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if mode.value in _WRITE_MODES else None
    return None


def _is_os_call(node: ast.Call, name: str) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == name
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _shallow_calls(body):
    """Every Call in ``body`` NOT inside a nested function def."""
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _publishes_atomically(fn) -> bool:
    """True when the function body calls ``os.replace`` or
    ``os.link`` -- the tmp-then-publish pattern."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and (
                _is_os_call(node, "replace") or _is_os_call(node, "link")):
            return True
    return False


@register
class AtomicWriteChecker(Checker):
    rule = "PCL012"
    name = "atomic-write"
    description = ("bare open(..., 'w') / os.rename in a protocol "
                   "file; use the tmp + os.replace / os.link "
                   "crash-atomic idioms")
    scope = ("pycatkin_tpu/robustness/scheduler.py",
             "pycatkin_tpu/utils/io.py")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        # Each call is attributed to its INNERMOST enclosing function
        # (the shallow iteration stops at nested defs, which are
        # visited on their own); module-level writes have no enclosing
        # publish to look for, so they are flagged unconditionally.
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                atomic = _publishes_atomically(node)
                for call in _shallow_calls(node.body):
                    yield from self._check_call(src, call, atomic,
                                                node.name)
        for call in _shallow_calls(src.tree.body):
            yield from self._check_call(src, call, False, "<module>")

    def _check_call(self, src, node, atomic: bool, where: str):
        if _is_os_call(node, "rename"):
            yield self.finding(
                src, node,
                f"os.rename in `{where}`: use os.replace (last-writer-"
                f"wins) or os.link (first-writer-wins) so the intent "
                f"is explicit and Windows semantics match")
            return
        mode = _open_write_mode(node)
        if mode is not None and not atomic:
            yield self.finding(
                src, node,
                f"bare open(..., {mode!r}) in `{where}` with no "
                f"os.replace/os.link publish in the function: a "
                f"concurrent reader can observe the torn file; write "
                f"to a tmp name and publish atomically")
