"""pclint command line (``tools/pclint.py`` / ``make lint`` /
``python -m pycatkin_tpu.lint``).

Exit status: 0 when every finding is suppressed (inline or baseline),
1 otherwise -- the CI contract. ``--update-baseline`` rewrites
``lint_baseline.json`` from the current active findings and exits 0.
"""

from __future__ import annotations

import argparse
import sys

from . import baseline as bl
from . import report
from .cache import LintCache
from .core import REPO_ROOT, all_checkers, checkers_for, run_lint


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pclint",
        description=("AST-based static analysis for pycatkin_tpu: "
                     "host-sync budget, fault-site registry, jit "
                     "purity, tracer hygiene, dtype policy, env-var "
                     "registry. See docs/static_analysis.md."))
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "package, tools, tests, examples and top-"
                        "level entry scripts)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule IDs or names to run "
                        "(e.g. PCL001,tracer-leak); default: all")
    p.add_argument("--format", choices=("text", "json", "sarif",
                                        "github"),
                   default="text", dest="fmt",
                   help="output format (default: text; `github` emits "
                        "::error workflow annotations for Actions)")
    p.add_argument("--root", default=REPO_ROOT,
                   help=argparse.SUPPRESS)
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: <root>/"
                        f"{bl.BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report grandfathered "
                        "findings as active)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current active "
                        "findings and exit 0")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the incremental result cache "
                        "(.pclint_cache/) -- re-check every file")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule IDs and exit")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also list suppressed findings (text format)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule}  {c.name:18s} {c.description}")
        return 0

    checkers = (checkers_for(args.rules.split(","))
                if args.rules else all_checkers())
    cache = LintCache(args.root, enabled=not args.no_cache)
    result = run_lint(root=args.root, checkers=checkers,
                      paths=args.paths or None, cache=cache)
    cache.save()

    baseline_path = args.baseline or bl.default_path(args.root)
    stale: list = []
    if args.update_baseline:
        n = bl.save(baseline_path, result.active)
        print(f"pclint: baseline updated -- {n} grandfathered "
              f"finding(s) written to {baseline_path}")
        return 0
    if not args.no_baseline:
        # Partial runs (rule/path filtered) must not report unrelated
        # baseline entries as stale.
        full_run = not args.rules and not args.paths
        result.findings, stale = bl.apply_to(result.findings,
                                             baseline_path)
        if not full_run:
            stale = []

    if args.fmt == "json":
        print(report.to_json(result))
    elif args.fmt == "sarif":
        print(report.to_sarif(result, checkers))
    elif args.fmt == "github":
        gh = report.to_github(result)
        if gh:
            print(gh)
    else:
        print(report.format_text(result,
                                 verbose_suppressed=args.verbose))
        for e in stale:
            print(f"pclint: note: stale baseline entry "
                  f"{e['fingerprint']} ({e['rule']} {e['path']}:"
                  f"{e['line']}) no longer matches -- prune it with "
                  f"--update-baseline")
    return 1 if result.active else 0


if __name__ == "__main__":
    sys.exit(main())
