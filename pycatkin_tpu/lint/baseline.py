"""Suppression baselines: grandfathered findings committed to
``lint_baseline.json`` so a new rule can land (and gate NEW code)
without first rewriting every historical occurrence it flags.

Fingerprints are content-addressed, NOT line-addressed: a finding is
identified by (rule, file, normalized source line, occurrence index
among identical lines), so unrelated edits that shift line numbers do
not invalidate the baseline, while editing the flagged line itself --
the moment a human touches it -- surfaces the finding for a real fix.

Workflow:

- ``python tools/pclint.py --update-baseline`` records every currently
  active finding (reviewed in the same PR like any other diff);
- a later run suppresses exactly those fingerprints (marked
  ``baseline`` in reports) and fails on anything new;
- entries whose code is gone are reported as stale so the file only
  ever shrinks.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from collections import defaultdict
from typing import Iterable, Optional

from .core import Finding

BASELINE_NAME = "lint_baseline.json"


def default_path(root: str) -> str:
    return os.path.join(root, BASELINE_NAME)


def _normalize(source: str) -> str:
    return re.sub(r"\s+", " ", source.strip())


def fingerprints(findings: Iterable[Finding]) -> list[str]:
    """Stable fingerprint per finding, order-aligned with the input.
    Identical (rule, path, source) triples are disambiguated by their
    lineno-ordered occurrence index."""
    findings = list(findings)
    groups: dict[tuple, list[Finding]] = defaultdict(list)
    for f in findings:
        groups[(f.rule, f.path, _normalize(f.source))].append(f)
    fp = {}
    for (rule, path, src), members in groups.items():
        members.sort(key=lambda f: (f.lineno, f.col))
        for k, f in enumerate(members):
            digest = hashlib.sha1(
                f"{rule}|{path}|{src}|{k}".encode()).hexdigest()[:16]
            fp[id(f)] = digest
    return [fp[id(f)] for f in findings]


def load(path: str) -> dict:
    """Baseline entries keyed by fingerprint ({} when absent)."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def save(path: str, findings: Iterable[Finding]) -> int:
    """Write the baseline for the given (active) findings; returns the
    entry count. Entries are sorted for diff-stable output."""
    findings = list(findings)
    entries = [
        {"fingerprint": fp, "rule": f.rule, "path": f.path,
         "line": f.lineno, "source": _normalize(f.source),
         "message": f.message}
        for fp, f in zip(fingerprints(findings), findings)
    ]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    payload = {
        "version": 1,
        "tool": "pclint",
        "note": ("Grandfathered findings. Regenerate with "
                 "`python tools/pclint.py --update-baseline`; entries "
                 "disappear automatically once the flagged line is "
                 "fixed or removed."),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)


def apply(findings: Iterable[Finding],
          entries: dict) -> tuple[list[Finding], list[dict]]:
    """Mark baseline-suppressed findings in place. Returns
    ``(findings, stale_entries)`` where stale entries matched nothing
    (their code was fixed -- prune them from the file)."""
    findings = list(findings)
    matched: set[str] = set()
    for fp, f in zip(fingerprints(findings), findings):
        if f.suppressed is None and fp in entries:
            f.suppressed = "baseline"
            f.reason = "grandfathered in " + BASELINE_NAME
            matched.add(fp)
    stale = [e for fp, e in sorted(entries.items())
             if fp not in matched]
    return findings, stale


def apply_to(findings: Iterable[Finding],
             path: Optional[str]) -> tuple[list[Finding], list[dict]]:
    """Convenience: load + apply (no-op on a missing file)."""
    return apply(findings, load(path) if path else {})
