"""pclint: the repo's unified static-analysis framework.

One extensible AST-checker pass (``tools/pclint.py`` / ``make lint`` /
``python -m pycatkin_tpu.lint``) enforcing every statically-checkable
correctness contract:

========  ================  =============================================
rule      name              contract
========  ================  =============================================
PCL001    host-sync         no uncounted device->host materializations
                            in the sweep hot path (hotpath registry
                            shared with tests/test_sync_budget.py)
PCL002    fault-sites       every fault-site label documented in
                            docs/failure_model.md
PCL003    jit-purity        no side effects inside jitted functions
PCL004    tracer-leak       no Python control flow / np.* host calls on
                            traced values inside jitted functions
PCL005    dtype-discipline  no hardcoded float64 in ops/ and solvers/
PCL006    env-registry      every PYCATKIN_* env key documented in
                            docs/index.md
PCL007    abi-spec-capture  no spec.<array> numpy reads inside
                            program-builder closures in
                            parallel/batch.py (use the bound
                            TracedSpec; docs/mechanism_abi.md)
PCL008    event-kinds       every record_event kind documented in
                            docs/failure_model.md
PCL009    metric-names      every metric name emitted via obs.metrics
                            documented in the docs/observability.md
                            metrics catalog
PCL010    async-blocking    no blocking calls (time.sleep, sync I/O,
                            future.result, device pulls) lexically
                            inside serve/ ``async def`` bodies;
                            asyncio.to_thread / run_in_executor are the
                            sanctioned offloads
PCL011    lock-discipline   attributes declared ``# guarded-by: <lock>``
                            are only touched inside ``with <lock>:``
                            in their class's methods
PCL012    atomic-write      no bare ``open(..., "w")`` / ``os.rename``
                            in the journal/scheduler protocol files;
                            publish via tmp + ``os.replace`` /
                            ``os.link`` / ``O_EXCL``
PCL013    fused-tail        cross-module: every function reachable from
                            the fused/packed sweep bodies (ProjectIndex
                            call graph) that materializes device values
                            is ``@hotpath``-decorated
PCL014    cache-key-        cross-module taint: every ``lru_cache``d
          completeness      program builder whose trace transitively
                            resolves a runtime config source
                            (``PYCATKIN_*`` env read or a declared
                            resolver like ``precision.linalg_kernel``)
                            threads that source as an explicit cache
                            parameter (``kernel_keyed`` / ``tier``)
PCL015    key-tag-          kind-string knob tags (tier/kernel/
          discipline        sharding/tenant) obey the single declared
                            ``KIND_TAG_GRAMMAR`` in
                            ``parallel/compile_pool.py``: helpers build
                            the declared literals, compositions follow
                            grammar order, literals stay in their owner
                            modules
========  ================  =============================================

Suppressions: inline ``# pclint: disable=<rule> -- <reason>`` (any line
of the flagged span) or the committed ``lint_baseline.json``
(:mod:`pycatkin_tpu.lint.baseline`). Results are cached content-
addressed in ``.pclint_cache/`` (:mod:`pycatkin_tpu.lint.cache`;
``--no-cache`` bypasses). Full docs: ``docs/static_analysis.md``; the
runtime companions (pcsan sanitizers) live in :mod:`pycatkin_tpu.san`.
"""

from __future__ import annotations

from . import baseline
from .core import (Checker, Finding, LintResult, all_checkers,
                   checkers_for, lint_file, register, run_lint)
from .hotpath import HOT_FUNCTIONS, HOT_PATH_FILES, MAX_CLEAN_SYNCS

__all__ = [
    "Checker", "Finding", "LintResult", "all_checkers", "checkers_for",
    "lint_file", "register", "run_lint", "lint_repo", "baseline",
    "HOT_FUNCTIONS", "HOT_PATH_FILES", "MAX_CLEAN_SYNCS",
]


def lint_repo(rules=None, root=None):
    """Run the full (or rule-filtered) lint with baseline suppression
    applied; returns the list of ACTIVE findings -- empty means the
    tree is clean. The programmatic face used by ``bench.py --smoke``."""
    from .core import REPO_ROOT
    root = root or REPO_ROOT
    checkers = checkers_for(rules) if rules else all_checkers()
    result = run_lint(root=root, checkers=checkers)
    result.findings, _ = baseline.apply_to(result.findings,
                                           baseline.default_path(root))
    return result.active
