"""PCL004 tracer-leak: no Python control flow or NumPy host calls on
traced values inside jitted functions.

Under ``jax.jit`` every array is a tracer. ``if``/``while``/``bool()``
on a traced expression raises ``TracerBoolConversionError`` -- but
only when that code path first traces, which for rescue-ladder /
failure-path branches can be deep into a production sweep.
``np.*`` calls on traced values either crash the trace
(``TracerArrayConversionError``) or, worse, silently constant-fold a
trace-time value into the compiled program -- the exact class of
silent wrongness that wrecks stiff chemical ODE solves. This checker
moves the detection to lint time.

Flagged inside statically-detected jitted functions (same detection as
PCL003, nested closures included):

- ``if <expr>`` / ``while <expr>`` where the test mentions ``jnp``
  (identity tests like ``x0 is None`` are static under jit and
  exempt);
- ``bool(<expr>)`` on a jnp expression or traced local;
- ``np.*``/``numpy.*`` calls whose arguments mention ``jnp``, a
  parameter of the jitted function, or a local derived from either
  (one-pass taint propagation through simple assignments).

Use ``jnp.where`` / ``lax.cond`` / ``lax.while_loop`` for traced
control flow, and ``jnp.*`` for math on traced values.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, SourceFile, register
from .purity import dotted, iter_jitted_functions


def _mentions(expr, names: set) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id in names
               for sub in ast.walk(expr))


def _param_names(fn) -> set:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _traced_names(fn) -> set:
    """Parameters of the jitted function plus locals assigned from
    expressions that mention jnp or an already-traced name -- a cheap
    forward taint pass, iterated to a fixpoint (loops/reassignments
    converge in <= a few passes; the walk order is lexical)."""
    traced = _param_names(fn) | {"jnp"}
    for _ in range(4):
        before = len(traced)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value = node.value
                targets = [node.target] if node.value is not None else []
            else:
                continue
            if value is None or not _mentions(value, traced):
                continue
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        traced.add(sub.id)
        if len(traced) == before:
            break
    return traced


def _is_static_test(test) -> bool:
    """`x is None` / `x is not None` style tests are resolved at trace
    time (None is not a tracer) and are legal under jit."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))


# jnp predicates over dtypes/shapes, not values: their results are
# trace-time Python constants, so branching on them is legal under jit
# (e.g. profiling._fence_arrays branches per-leaf on
# jnp.issubdtype(x.dtype, jnp.floating)).
_STATIC_JNP_CALLS = frozenset({
    "jnp.issubdtype", "jnp.isdtype", "jnp.result_type",
    "jnp.promote_types", "jnp.ndim", "jnp.shape", "jnp.size",
})


def _mentions_traced_jnp(expr) -> bool:
    """True when `jnp` appears in the expression OUTSIDE calls to the
    static (dtype/shape-level) predicates above."""
    if (isinstance(expr, ast.Call)
            and dotted(expr.func) in _STATIC_JNP_CALLS):
        return False
    if isinstance(expr, ast.Name):
        return expr.id == "jnp"
    return any(_mentions_traced_jnp(child)
               for child in ast.iter_child_nodes(expr))


@register
class TracerLeakChecker(Checker):
    rule = "PCL004"
    name = "tracer-leak"
    description = ("Python control flow or np.* host call on a traced "
                   "value inside a jitted function (compile-time "
                   "TracerBoolConversionError / silent constant-fold)")
    scope = ("pycatkin_tpu/", "tools/", "bench.py", "bench_suite.py")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in iter_jitted_functions(src.tree):
            yield from self._check_body(src, fn)

    def _check_body(self, src: SourceFile, fn) -> Iterable[Finding]:
        where = f"inside jitted function `{fn.name}`"
        traced = _traced_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                kw = "if" if isinstance(node, ast.If) else "while"
                if (_mentions_traced_jnp(node.test)
                        and not _is_static_test(node.test)):
                    yield self.finding(
                        src, node,
                        f"Python `{kw}` on a jnp expression {where}: "
                        f"raises TracerBoolConversionError at trace "
                        f"time; use jnp.where / lax.cond / "
                        f"lax.while_loop")
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "bool":
                exprs = list(node.args) + [k.value for k in node.keywords]
                if any(_mentions(e, traced) for e in exprs):
                    yield self.finding(
                        src, node,
                        f"bool() on a traced value {where}: raises "
                        f"TracerBoolConversionError at trace time")
                continue
            name = dotted(f)
            if not (name.startswith("np.")
                    or name.startswith("numpy.")):
                continue
            exprs = list(node.args) + [k.value for k in node.keywords]
            if any(_mentions(e, traced) for e in exprs):
                yield self.finding(
                    src, node,
                    f"{name}() on a traced value {where}: NumPy "
                    f"cannot consume tracers (crash or silent trace-"
                    f"time constant-fold); use the jnp equivalent")
