"""Finding renderers: human text, machine JSON, and SARIF 2.1.0 (the
interchange format CI annotation UIs ingest). One runner, three
faces -- checkers never format anything themselves."""

from __future__ import annotations

import json
from collections import Counter
from typing import Iterable

from .core import Checker, Finding, LintResult


def _counts(findings: Iterable[Finding]) -> Counter:
    return Counter(f.rule for f in findings)


def format_text(result: LintResult, verbose_suppressed: bool = False) -> str:
    """The ``make lint`` face: one line per active finding plus a
    summary; suppressed findings are summarized (listed with -v)."""
    out = []
    active = result.active
    for f in active:
        out.append(f"{f.location()}: {f.rule} {f.message}")
        if f.source:
            out.append(f"    {f.source}")
    if verbose_suppressed:
        for f in result.suppressed:
            why = f.suppressed + (f" ({f.reason})" if f.reason else "")
            out.append(f"{f.location()}: {f.rule} [suppressed: {why}] "
                       f"{f.message}")
    n_inline = sum(1 for f in result.suppressed
                   if f.suppressed == "inline")
    n_base = sum(1 for f in result.suppressed
                 if f.suppressed == "baseline")
    if active:
        per_rule = ", ".join(f"{r}={n}"
                             for r, n in sorted(_counts(active).items()))
        out.append(
            f"pclint: FAIL -- {len(active)} finding(s) [{per_rule}] in "
            f"{result.n_files} file(s); {n_inline} inline / {n_base} "
            f"baseline suppression(s). Fix, annotate '# pclint: "
            f"disable=<rule> -- <reason>', or (for legacy code) "
            f"re-baseline. See docs/static_analysis.md.")
    else:
        out.append(
            f"pclint: OK -- {result.n_files} file(s), rules "
            f"{','.join(result.rules)}; 0 findings ({n_inline} inline, "
            f"{n_base} baseline suppression(s))")
    return "\n".join(out)


def to_json(result: LintResult) -> str:
    """Machine face: every finding (suppressed included, labeled) plus
    the summary block, one JSON document."""
    doc = {
        "tool": "pclint",
        "files_scanned": result.n_files,
        "rules": result.rules,
        "counts": {
            "active": len(result.active),
            "suppressed_inline": sum(
                1 for f in result.suppressed if f.suppressed == "inline"),
            "suppressed_baseline": sum(
                1 for f in result.suppressed
                if f.suppressed == "baseline"),
        },
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.lineno,
             "col": f.col, "message": f.message, "source": f.source,
             "suppressed": f.suppressed, "reason": f.reason or None}
            for f in result.findings
        ],
    }
    return json.dumps(doc, indent=2)


def to_sarif(result: LintResult, checkers: Iterable[Checker]) -> str:
    """Minimal SARIF 2.1.0 log (active findings only; suppressed ones
    ride along in the SARIF ``suppressions`` field)."""
    rules_meta = [
        {"id": c.rule, "name": c.name,
         "shortDescription": {"text": c.description or c.name}}
        for c in checkers
    ]
    results = []
    for f in result.findings:
        entry = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.lineno,
                               "startColumn": f.col + 1},
                },
            }],
        }
        if f.suppressed is not None:
            kind = ("inSource" if f.suppressed == "inline"
                    else "external")
            entry["suppressions"] = [{
                "kind": kind,
                "justification": f.reason or f.suppressed,
            }]
        results.append(entry)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "pclint",
                                "informationUri":
                                    "docs/static_analysis.md",
                                "rules": rules_meta}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def _gh_escape(text: str) -> str:
    """GitHub workflow-command data escaping (the property values have
    their own, stricter escaping handled inline in :func:`to_github`)."""
    return (text.replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A"))


def to_github(result: LintResult) -> str:
    """GitHub Actions annotation commands, one ``::error`` line per
    ACTIVE finding: CI findings surface inline on the PR diff instead
    of buried in a job log. Suppressed findings emit nothing -- the
    annotation surface mirrors the exit code."""
    lines = []
    for f in result.active:
        path = _gh_escape(f.path).replace(",", "%2C").replace(
            ":", "%3A")
        title = _gh_escape(f"pclint {f.rule}").replace(
            ",", "%2C").replace(":", "%3A")
        lines.append(f"::error file={path},line={f.lineno},"
                     f"col={f.col + 1},title={title}::"
                     f"{_gh_escape(f.message)}")
    return "\n".join(lines)
