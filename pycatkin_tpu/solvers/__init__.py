from .newton import SolverOptions, SteadyStateResults, solve_steady
from .ode import ODEOptions, integrate, log_time_grid
