"""Steady-state solver: damped Newton with pseudo-transient continuation.

TPU-native replacement for the reference's scipy-based steady-state stack
(system.py:566-639 ``find_steady`` retry loop, solver.py:223-418
root/minimize/ode strategies). The solve is a bounded ``lax.while_loop``
so it jits, vmaps over condition grids, and runs entirely on device.

Strategy (one "attempt"):
- Pseudo-transient continuation (PTC / switched evolution relaxation):
  solve (I/dt - J) dx = F(x), x += dx, with dt adapted by the ratio of
  successive residual norms. dt -> inf recovers Newton; small dt is a
  damped, globally stabilising step. This is the standard robust scheme
  for stiff mean-field kinetics.
- Safeguards per step: non-finite updates shrink dt and are rejected;
  coverages are clamped to a tiny floor (reference min_tol semantics,
  system.py:54,328).

Retries (reference system.py:598-635 renormalize-and-retry semantics):
bounded outer ``lax.while_loop`` over attempts; each retry renormalizes
|x| onto its conservation groups and restarts PTC from either the
normalized iterate or a PRNG-keyed random guess (reference
system.py:586). Per-lane success flags make the whole thing
vmap-friendly: finished lanes simply stop improving.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from .. import precision as _precision
from ..ops import linalg


class SteadyStateResults(NamedTuple):
    """Steady-state solution + diagnostics (reference system.py:20-30,
    extended with structured per-solve diagnostics).

    x: full solution vector (gas entries included).
    success: convergence verdict.
    residual: max |dy/dt| over dynamic entries at the solution.
    iterations: total PTC iterations spent.
    attempts: retries consumed.

    The trailing per-lane diagnostic fields break the overall verdict
    into its three tests (:func:`_verdict_tests`) at the RETURNED
    iterate and expose the pseudo-time state the final attempt exited
    with -- `dt_exit` is the PTC pseudo-step (or the LM damping
    parameter under the 'lm' strategy): a tiny exit dt on a failed lane
    means the march was still fighting rejections, a huge one means it
    reached the Newton regime and stalled elsewhere. They default to
    None so pre-existing 5-field constructions keep working; the solver
    always fills them. ``chords`` counts the accepted chord re-solves
    (frozen-Jacobian steps) the solve spent -- 0 whenever
    ``chord_steps`` is off -- and feeds the per-lane solver telemetry
    (docs/perf_cost_ledger.md) alongside ``iterations``.
    """
    x: jnp.ndarray
    success: jnp.ndarray
    residual: jnp.ndarray
    iterations: jnp.ndarray
    attempts: jnp.ndarray
    rate_ok: jnp.ndarray | None = None
    pos_ok: jnp.ndarray | None = None
    sums_ok: jnp.ndarray | None = None
    dt_exit: jnp.ndarray | None = None
    chords: jnp.ndarray | None = None


class SolverOptions(NamedTuple):
    rate_tol: float = 1.0e-8     # absolute residual tolerance on max |dy/dt|
    rate_tol_rel: float = 1.0e-9  # tolerance relative to the gross-flux scale
    coverage_tol: float = 5.0e-2  # allowed deviation of group sums from 1
    neg_tol: float = 5.0e-3      # allowed negative-coverage excursion
    # PTC pacing. The conservative defaults (slow ramp from a tiny
    # pseudo-step) are the right trade for SMALL networks, where one
    # iteration is cheap and robustness across 1e4-1e5 heterogeneous
    # lanes dominates: round-3 measurement on the 256x256 COOx volcano
    # found aggressive pacing (dt0=1e-3, grow 6) HALVED throughput and
    # left 43/65536 lanes unconverged (0 under the defaults). For LARGE
    # per-lane systems the economics invert -- each iteration pays a
    # full n^2-Jacobian + n^3-LU, so ramp iterations are the cost
    # center: the same aggressive pacing solved bench config 5 (n_dyn
    # 190) 2.3x faster with unchanged convergence. Tune dt0/dt_grow_min
    # up for big stiff networks (see bench_suite.config_5,
    # docs/perf_config5.md).
    dt0: float = 1.0e-9          # initial pseudo-time step
    dt_max: float = 1.0e20
    dt_grow_min: float = 2.0     # guaranteed SER growth per accepted step
    max_steps: int = 200         # PTC iterations per attempt
    max_attempts: int = 5
    floor: float = 1.0e-32       # reference min_tol
    # Large-system iteration economics (round-4, docs/perf_config5.md):
    # at n_dyn ~ 190 each PTC iteration pays a full Jacobian (~33 ms) +
    # LU (~130 ms under f64 emulation). Chord steps amortize that cost:
    # after each Newton/PTC step, up to this many extra steps re-use the
    # SAME factorization (one residual + one triangular solve each -- no
    # new Jacobian/LU), kept only on strict residual decrease. Default
    # OFF; the big-network bench/sweep configs turn it on. At small n
    # (<= linalg.UNROLL_MAX) the direction kernel stays the chord-off
    # gauss_solve -- no factorization reuse, identical numerics to
    # chord_steps=0; the Jacobian dominates the body cost there anyway.
    # (A hardware-
    # f32 direction factorization was measured 2.4x faster but CANNOT
    # serve stiff kinetics: equilibrated PTC matrices carry cond
    # ~1e10-1e15, far beyond f32 refinement's ~1e7 ceiling -- the solver
    # stalled. Recorded in docs/perf_config5.md; kernel kept as
    # linalg.make_mixed_solve.)
    chord_steps: int = 0


def _normalize(x, groups_dyn, floor):
    """Renormalize each conservation group of the dynamic vector to sum 1,
    flooring at ``floor`` (reference system.py:305-328 ``_normalize_y``).
    Entries outside every group (e.g. CSTR gas unknowns) are untouched.
    """
    x = jnp.where(x < floor, floor, x)
    sums = groups_dyn @ x                      # [n_g]
    scale = groups_dyn.T @ jnp.where(sums > 0, 1.0 / sums, 1.0)  # [n_dyn]
    in_group = (groups_dyn.sum(axis=0) > 0)
    return jnp.where(in_group, x * scale, x)


def _rnorm(F, gross, opts: SolverOptions):
    """Normalized residual: max_i |F_i| / (atol + rtol*gross_i) -- the
    solve is converged when this is <= 1. ``gross`` is the per-species
    gross flux at the same point (net-vs-gross is the physically
    meaningful steadiness measure; an absolute dy/dt target is
    unreachable by cancellation when fluxes are large, in particular
    under TPU's double-float f64 emulation)."""
    return jnp.max(jnp.abs(F) / (opts.rate_tol + opts.rate_tol_rel * gross))


def _direction_factor(A, opts: SolverOptions | None):
    """Factor the Newton/PTC matrix once, return a solve closure (the
    one site for direction-kernel dispatch; chord steps re-use it).

    Always the full-precision arithmetic kernels (small n: equilibrated
    Gauss-Jordan, large n: sequential LU). With chord steps enabled the
    LARGE-n path factors once (LU) and re-uses the factorization per
    chord; the SMALL-n path deliberately keeps the direct per-RHS
    gauss_solve kernel -- chord-on and chord-off numerics then agree
    exactly for ill-conditioned stiff small networks (an explicit-
    inverse matvec is a different rounding path), and re-solving is
    cheap at unrolled sizes where the Jacobian, not the solve,
    dominates the body cost. Faster direction kernels were measured
    and REJECTED for this site, recorded in docs/perf_config5.md:
    XLA:TPU's native f32 LuDecomposition custom call kernel-faults
    inside vmapped while_loops, and the refined mixed-precision
    factorization (linalg.make_mixed_solve, 2.4x faster at
    [128, 190, 190]) stalls the solve outright -- stiff kinetics PTC
    matrices measure cond ~1e10-1e15 AFTER row equilibration, beyond
    f32 refinement's ~1e7 contraction ceiling, at every pseudo-time
    scale (the 1e-14 dt clip floor keeps I/dt from ever dominating a
    ||J|| ~ 1e16+ Jacobian).

    All of that policy now lives behind the one dispatch seam,
    ``linalg.select_solver`` (docs/perf_pallas_linalg.md): the Pallas
    kernel tier (``PYCATKIN_LINALG_KERNEL``) factors bucket-shaped
    systems once and reuses the VMEM-resident factorization per chord
    step; the XLA tier reproduces the historical branching exactly --
    chord-enabled LARGE-n factors once (LU), SMALL-n keeps the direct
    per-RHS gauss_solve kernel (chord-on/chord-off numerics agree
    exactly, re-solving is cheap at unrolled sizes)."""
    n = A.shape[-1]
    choice = linalg.select_solver(n)
    if choice.path == "pallas":
        return choice.make_solve(A)
    if (opts is not None and opts.chord_steps > 0
            and n > linalg.UNROLL_MAX):
        return choice.make_solve(A)
    return lambda b: choice.solve(A, b)


def _direction_solve(A, b, opts: SolverOptions | None = None):
    """One-shot direction solve (kept for single-solve call sites)."""
    return _direction_factor(A, opts)(b)


def conservation_constraints(groups_dyn):
    """Row-replacement operators for the conservation constraints.

    Site conservation makes the dynamic Jacobian exactly singular at
    every root (each group indicator is a left null vector; the
    within-group rows of the residual are linearly dependent), so bare
    Newton degenerates near solutions. The exact, stiffness-stable fix:
    replace one row per nonempty group (its first member) with the
    constraint row G_g and zero that residual entry -- no information is
    lost (the replaced row equals minus the sum of its group partners)
    and every step satisfies G dx = 0, i.e. Newton walks along the
    conservation manifold through a nonsingular matrix.

    Returns (R [n, n], M [n]): replacement row contents and a 0/1 mask of
    rows to replace. Empty groups (e.g. a model with no adsorbates)
    replace nothing. Apply as ``where(M[:, None] > 0, R, A)`` and
    ``F * (1 - M)``; the IFT adjoint must use the SAME operators.
    """
    n = groups_dyn.shape[1]
    have = jnp.sum(groups_dyn > 0, axis=1) > 0
    con_rows = jnp.argmax(groups_dyn > 0, axis=1)
    R = jnp.zeros((n, n), groups_dyn.dtype)
    R = R.at[con_rows, :].add(jnp.where(have[:, None], groups_dyn, 0.0))
    M = jnp.zeros((n,), groups_dyn.dtype)
    M = M.at[con_rows].max(have.astype(groups_dyn.dtype))
    return R, M


def _ptc_attempt(fscale_fn, jac_fn, x0, groups_dyn, opts: SolverOptions):
    """One PTC run from x0; returns (x, normalized_residual, steps,
    dt_at_exit, chords_accepted).

    ``fscale_fn(x) -> (F, gross)`` returns the residual and the gross
    flux scale in one evaluation; both are carried between iterations so
    each step costs one Jacobian and one fresh evaluation.
    ``chords_accepted`` counts the chord re-solves whose accept test
    passed -- pure telemetry riding the carry (the counter never feeds
    back into the iterate, so the x/residual path is bitwise identical
    to the pre-counter solver)."""
    n = x0.shape[0]
    eye = jnp.eye(n, dtype=x0.dtype)
    R, M = conservation_constraints(groups_dyn)

    def cond(state):
        x, F, dt, fnorm, k, nch = state
        return (k < opts.max_steps) & (fnorm > 1.0)

    def body(state):
        x, F, dt, fnorm, k, nch = state
        J = jac_fn(x)
        A = jnp.where(M[:, None] > 0, R, eye / dt - J)
        solve_fn = _direction_factor(A, opts)
        dx = solve_fn(F * (1.0 - M))
        # Projected PTC: clamp nonnegative AND renormalize conservation
        # groups (reference min_tol flooring + _normalize_y semantics,
        # system.py:305-328). Negative coverages flip rate signs and
        # destabilize the march; a bare clamp alone creates a spurious
        # absorbing all-zero state (every rate 0 -> residual 0). The
        # dynamics conserve group sums, so near the manifold this
        # projection is a no-op to first order.
        x_new = _normalize(jnp.maximum(x + dx, 0.0), groups_dyn,
                           opts.floor)
        F_new, gross_new = fscale_fn(x_new)
        fnorm_new = _rnorm(F_new, gross_new, opts)
        # Chord steps: re-use the factorization against the fresh
        # residual (frozen-Jacobian Newton). Each costs one residual
        # evaluation + one triangular solve -- no Jacobian, no LU --
        # and is kept only on strict residual decrease, so a stale
        # direction can slow nothing down. The SER growth below then
        # sees the full (Newton + chords) residual drop. Each chord's
        # accept test measures against the PREVIOUS accepted point's
        # gross scale (comparable within the body; the yardstick moves
        # smoothly with x), but the scale FOLLOWS the accepted iterate,
        # and the body's outgoing residual is re-measured against the
        # final point's own scale -- so the while_loop exit test, the
        # verdict and the returned residual all use the same fresh
        # yardstick and a borderline lane cannot exit "converged" only
        # to fail the verdict and burn a full extra attempt.
        nch_step = jnp.zeros((), dtype=jnp.int32)
        for _ in range(opts.chord_steps):
            dxc = solve_fn(F_new * (1.0 - M))
            x_c = _normalize(jnp.maximum(x_new + dxc, 0.0), groups_dyn,
                             opts.floor)
            F_c, gross_c = fscale_fn(x_c)
            f_c = _rnorm(F_c, gross_new, opts)
            take = (jnp.isfinite(f_c) & jnp.all(jnp.isfinite(x_c))
                    & (f_c < fnorm_new))
            nch_step = nch_step + take.astype(jnp.int32)
            x_new = jnp.where(take, x_c, x_new)
            F_new = jnp.where(take, F_c, F_new)
            gross_new = jnp.where(take, gross_c, gross_new)
            fnorm_new = jnp.where(take, f_c, fnorm_new)
        if opts.chord_steps > 0:
            # Fresh-scale exit measure at the accepted point (see above).
            fnorm_new = _rnorm(F_new, gross_new, opts)
        finite = jnp.isfinite(fnorm_new) & jnp.all(jnp.isfinite(x_new))
        # Accept steps that do not blow the residual up; a mild increase
        # is tolerated (transient phase of the pseudo-time march).
        accept = finite & (fnorm_new <= 10.0 * fnorm)
        # SER with guaranteed geometric growth on accept: plain
        # residual-ratio SER stalls when dt is tiny (the residual barely
        # changes, ratio ~ 1, dt never grows). dt -> inf recovers Newton.
        grow = jnp.maximum(opts.dt_grow_min,
                           fnorm / jnp.maximum(fnorm_new, 1e-300))
        dt_new = jnp.where(accept,
                           jnp.clip(dt * jnp.minimum(grow, 1.0e6),
                                    1e-14, opts.dt_max),
                           dt * 0.25)
        x_next = jnp.where(accept, x_new, x)
        F_next = jnp.where(accept, F_new, F)
        fnorm_next = jnp.where(accept, fnorm_new, fnorm)
        # Chords are counted when their accept test passed, whether or
        # not the enclosing step is kept -- the device work was spent
        # either way, and telemetry measures spend.
        return (x_next, F_next, dt_new, fnorm_next, k + 1,
                nch + nch_step)

    F0, gross0 = fscale_fn(x0)
    f0 = _rnorm(F0, gross0, opts)
    x, F, dt, fnorm, k, nch = jax.lax.while_loop(
        cond, body, (x0, F0, jnp.asarray(opts.dt0, x0.dtype), f0, 0,
                     jnp.zeros((), dtype=jnp.int32)))
    # With chord steps the carried fnorm is already measured against the
    # accepted iterate's own gross scale (see the body), so no post-loop
    # re-measure is needed and loop exit == verdict yardstick.
    return x, fnorm, k, dt, nch


def _verdict_tests(x, fnorm, groups_dyn, opts: SolverOptions):
    """The three on-device convergence tests as separate flags."""
    rate_ok = fnorm <= 1.0
    pos_ok = jnp.all(x >= -opts.neg_tol)
    sums = groups_dyn @ x
    have_group = groups_dyn.sum(axis=1) > 0
    sums_ok = jnp.all(jnp.where(have_group,
                                jnp.abs(sums - 1.0) <= opts.coverage_tol,
                                True))
    return rate_ok, pos_ok, sums_ok


def lane_finite_mask(x, residual):
    """Per-lane finiteness of a batched solution block: every entry of
    the stored state AND the residual is finite. The quarantine layer
    (parallel/batch.py) demotes ``success & ~finite`` lanes -- a
    silently poisoned result's exact signature -- and the fused sweep
    tail packs the same mask into its diagnostics bundle, so both
    layers share this single definition."""
    return (jnp.all(jnp.isfinite(jnp.asarray(x)), axis=-1)
            & jnp.isfinite(jnp.asarray(residual)))


def packed_sweep_diagnostics(success, quarantined, ambiguous=None,
                             demoted=None, n_negative_tof=None):
    """Pack every cross-lane sweep verdict reduction into ONE small
    integer vector: ``[n_failed, n_quarantined, n_ambiguous, n_demoted,
    n_negative_tof]`` (absent entries report -1).

    The point is host-sync economics, not arithmetic: a sweep that
    fetched each of these scalars separately pays one blocking
    device->host round trip per fetch (~0.8-1.2 s each on the tunneled
    backend -- the r05 throughput regression). Packing them means a
    clean sweep materializes exactly one bundle
    (utils/profiling.host_sync) and branches on host ints from there.
    """
    def _count(v):
        return jnp.sum(v).astype(jnp.int32) if v is not None and (
            getattr(v, "ndim", 1) > 0) else (
            jnp.asarray(-1 if v is None else v, dtype=jnp.int32))

    return jnp.stack([
        jnp.sum(~jnp.asarray(success)).astype(jnp.int32),
        jnp.sum(jnp.asarray(quarantined)).astype(jnp.int32),
        _count(ambiguous),
        _count(demoted),
        _count(n_negative_tof),
    ])


# Rescue-strategy codes for the per-lane telemetry's ``strategy``
# column: 0 = solved by the fast pass (no rescue), then one code per
# rung of the rescue ladder in parallel/batch.py, in ladder order, plus
# the two terminal demotions. The registry is shared by the fused sweep
# tail (which stamps 0 on device), the host-side rescue merge (which
# overwrites the code of each rescued lane) and the obsview/heatmap
# renderers -- one table, no drift.
STRATEGY_CODES = {
    "clean": 0,
    "polish": 1,
    "ptc": 2,
    "lm": 3,
    "unseeded": 4,
    "demote": 5,
    "quarantine": 6,
}
STRATEGY_NAMES = {v: k for k, v in STRATEGY_CODES.items()}

# Column order of the packed per-lane telemetry array. ``tier`` records
# which precision tier produced the ACCEPTED iterate
# (pycatkin_tpu.precision.TIER_CODES: 0 = f64 -- including every
# rescue-ladder product, the ladder always runs f64 -- 1 = the f32 bulk
# + f64 polish pipeline).
LANE_TELEMETRY_FIELDS = ("iterations", "chords", "residual_decade",
                         "strategy", "tier")


def residual_decade(residual):
    """Final-residual decade per lane: ``floor(log10(residual))``
    clipped to [-99, 99] as int32, with 99 for a non-finite residual
    and -99 for an (unreachable in practice) exact zero. One decade is
    the resolution at which 'how converged is this lane' reads off a
    heatmap; the exact float residual stays available in
    ``SteadyStateResults.residual``."""
    r = jnp.asarray(residual)
    pos = jnp.where(r > 0, r, 1.0)
    dec = jnp.floor(jnp.log10(pos))
    dec = jnp.where(r > 0, dec, -99.0)
    dec = jnp.where(jnp.isfinite(r), dec, 99.0)
    return jnp.clip(dec, -99, 99).astype(jnp.int32)


def packed_lane_telemetry(iterations, chords, residual, strategy=0,
                          tier=0):
    """Per-lane solver telemetry as ONE ``[n, 5]`` int32 array
    (columns: :data:`LANE_TELEMETRY_FIELDS`). Computed inside the fused
    sweep program so it rides the existing single-sync bundle -- the
    clean path's sync count does not grow by adding lane-resolution
    telemetry (docs/perf_cost_ledger.md). ``tier`` (scalar or per-lane)
    is the precision-tier code of the accepted iterate
    (:data:`pycatkin_tpu.precision.TIER_CODES`)."""
    it = jnp.asarray(iterations)
    n = it.shape[0]
    ch = (jnp.zeros(n, dtype=jnp.int32) if chords is None
          else jnp.asarray(chords))
    strat = jnp.broadcast_to(jnp.asarray(strategy, dtype=jnp.int32), (n,))
    tcol = jnp.broadcast_to(jnp.asarray(tier, dtype=jnp.int32), (n,))
    return jnp.stack([it.astype(jnp.int32), ch.astype(jnp.int32),
                      residual_decade(residual), strat, tcol], axis=-1)


def _verdict(x, fnorm, groups_dyn, opts: SolverOptions):
    """Convergence tests (reference solver.py:69-120 minus the host-only
    eigenvalue check): normalized residual small, coverages non-negative,
    each site group sums to ~1."""
    rate_ok, pos_ok, sums_ok = _verdict_tests(x, fnorm, groups_dyn, opts)
    return rate_ok & pos_ok & sums_ok


def _score(x, fnorm, groups_dyn, opts: SolverOptions):
    """Lexicographic solution score (reference SolScore +
    compare_scores, solver.py:8-15,143-219): candidates are ranked
    first by how many convergence tests they pass, then by residual.
    Encoded as a single float: tests_passed * BIG - min(fnorm, BIG/2),
    with BIG small enough that the residual term survives f64 rounding
    (residual differences beyond BIG/2 don't rank -- both candidates are
    garbage there anyway); HIGHER is better."""
    rate_ok, pos_ok, sums_ok = _verdict_tests(x, fnorm, groups_dyn, opts)
    passed = (jnp.asarray(rate_ok, x.dtype) + jnp.asarray(pos_ok, x.dtype)
              + jnp.asarray(sums_ok, x.dtype))
    big = 1.0e6
    return passed * big - jnp.minimum(fnorm, 0.5 * big)


def _lm_attempt(fscale_fn, jac_fn, x0, groups_dyn, opts: SolverOptions):
    """Projected Levenberg-Marquardt minimization of the scaled residual
    norm -- the device analog of the reference's ``solve_minimize``
    strategy (solver.py:293-372: scipy minimize of max|residual| with
    bounds [0,1]). Where PTC marches pseudo-time, this descends
    ||F/scale||^2 directly, which escapes regions where the pseudo-time
    march cycles. Same projection (clamp + group renormalization) keeps
    iterates physical. Returns (x, normalized_residual, steps,
    lam_at_exit, chords_accepted) -- lam plays the dt_exit diagnostic
    role (damping at exit) and chords is always 0 (LM has no chord
    phase), so both strategies share one result layout."""
    n = x0.shape[0]
    eye = jnp.eye(n, dtype=x0.dtype)
    R, M = conservation_constraints(groups_dyn)

    def cond(state):
        x, F, gross, fnorm, lam, k = state
        return (k < opts.max_steps) & (fnorm > 1.0)

    def body(state):
        # (F, gross) at x ride the carry, so each iteration evaluates
        # the residual exactly once (at the trial point) -- XLA cannot
        # CSE across the while-loop boundary.
        x, F, gross, fnorm, lam, k = state
        # Frozen-scale Gauss-Newton model of the scaled residual; the
        # conservation rows replace their linearly-dependent partners
        # exactly as in the PTC step.
        scale = opts.rate_tol + opts.rate_tol_rel * gross
        J = jac_fn(x) / scale[:, None]
        JtJ = J.T @ J
        # Scale-invariant damping: lam multiplies the LARGEST diagonal
        # entry of JtJ, not bare identity -- J is residual-scaled
        # (entries ~1/rate_tol above the raw Jacobian), so JtJ entries
        # dwarf any bounded absolute lam and plain lam*eye degenerates
        # to undamped Gauss-Newton that rejects every step on hard
        # lanes. Anchoring lam to max diag makes the damping sweep
        # [1e-12, 1e12] span "pure Gauss-Newton" to "tiny gradient
        # step" regardless of the residual scaling. (Classic per-
        # variable Marquardt diag(JtJ) damping was measured to stall
        # outright on the COOx volcano test point: near-empty coverages
        # carry ~zero columns whose relative damping distorts the step
        # direction; the uniform max-diag anchor preserves the
        # Gauss-Newton direction as lam -> 0.)
        dmax = jnp.maximum(jnp.max(jnp.diag(JtJ)), 1e-300)
        A = jnp.where(M[:, None] > 0, R, JtJ + (lam * dmax) * eye)
        g = jnp.where(M > 0, 0.0, J.T @ (F / scale))
        # LM stays full-precision: JtJ squares the condition number, so
        # the f32 direction path is not offered here (LM is the rescue
        # strategy -- robustness over speed).
        dx = _direction_solve(A, -g * (1.0 - M))
        x_new = _normalize(jnp.maximum(x + dx, 0.0), groups_dyn,
                           opts.floor)
        F_new, gross_new = fscale_fn(x_new)
        fnorm_new = _rnorm(F_new, gross_new, opts)
        finite = jnp.isfinite(fnorm_new) & jnp.all(jnp.isfinite(x_new))
        accept = finite & (fnorm_new < fnorm)
        lam_new = jnp.where(accept, jnp.maximum(lam / 3.0, 1e-12),
                            jnp.minimum(lam * 10.0, 1e12))
        return (jnp.where(accept, x_new, x),
                jnp.where(accept, F_new, F),
                jnp.where(accept, gross_new, gross),
                jnp.where(accept, fnorm_new, fnorm),
                lam_new, k + 1)

    F0, gross0 = fscale_fn(x0)
    f0 = _rnorm(F0, gross0, opts)
    # Start essentially undamped (Gauss-Newton): with the max-diag
    # anchor a large initial lam means genuinely small steps, and near
    # the projection operators (clamp + group renormalization) a small
    # enough step changes nothing -- the strict-decrease accept test
    # then rejects forever and lam only ratchets up (measured stall on
    # the COOx volcano from a uniform start). Rejections ramp lam 10x
    # per iteration, so the damped regime is a few iterations away
    # whenever GN steps actually fail.
    x, F, gross, fnorm, lam, k = jax.lax.while_loop(
        cond, body, (x0, F0, gross0, f0, jnp.asarray(1e-10, x0.dtype), 0))
    return x, fnorm, k, lam, jnp.zeros((), dtype=jnp.int32)


def bulk_options(opts: SolverOptions, tier: str) -> SolverOptions:  # pclint: disable=PCL013 -- float(jnp.finfo(...).eps) is dtype metadata, no device value crosses
    """Tolerances the reduced-precision BULK march can actually reach.

    The f64 convergence test divides by ``rate_tol + rate_tol_rel *
    gross`` with rate_tol_rel ~ 1e-9, but an f32 residual evaluation
    carries ~eps32 * gross ~ 1.2e-7 * gross of roundoff noise -- two
    decades ABOVE the f64 denominator, so the f32 march can never
    satisfy the f64 test; it would burn max_steps grinding against its
    own noise floor. The bulk therefore runs against tolerances floored
    at its noise level (~32 eps_bulk relative, 1e-5 absolute): it exits
    as soon as the iterate is good to f32 accuracy, and the f64 polish
    pass squares that ~1e-7-relative error into full convergence. Only
    the bulk march uses these; the verdict ALWAYS uses the caller's
    original opts. Requires static (non-traced) tolerances -- the
    tiered path only runs in the statically-shaped fused fast pass."""
    eps_b = float(jnp.finfo(_precision.bulk_dtype(tier)).eps)
    return opts._replace(
        rate_tol=max(float(opts.rate_tol), 1.0e-5),
        rate_tol_rel=max(float(opts.rate_tol_rel), 32.0 * eps_b))


def _polish_newton(fscale_fn, jac_fn, x, groups_dyn,
                   opts: SolverOptions, steps: int):
    """Short full-Newton polish at verification precision: ``steps``
    conservation-constrained Newton iterations from ``x`` (the promoted
    bulk iterate), each kept only when finite and non-increasing in the
    caller's ORIGINAL normalized residual -- a diverging polish can
    therefore never make the iterate worse than the bulk handed over,
    and a hard lane simply exits unimproved and fails the verdict into
    the rescue ladder. Same projection (nonneg clamp + group
    renormalization) as the PTC body, so the polished iterate lives on
    the same manifold the f64 march walks. Returns (x, fnorm)."""
    R, M = conservation_constraints(groups_dyn)
    F, gross = fscale_fn(x)
    fnorm = _rnorm(F, gross, opts)

    def step(carry, _):
        x, F, fnorm = carry
        J = jac_fn(x)
        B = jnp.where(M[:, None] > 0, R, J)
        dx = _direction_solve(B, F * (1.0 - M), opts)
        x_new = _normalize(jnp.maximum(x - dx, 0.0), groups_dyn,
                           opts.floor)
        F_new, gross_new = fscale_fn(x_new)
        fnorm_new = _rnorm(F_new, gross_new, opts)
        keep = (jnp.isfinite(fnorm_new) & jnp.all(jnp.isfinite(x_new))
                & (fnorm_new <= fnorm))
        return (jnp.where(keep, x_new, x),
                jnp.where(keep, F_new, F),
                jnp.where(keep, fnorm_new, fnorm)), None

    (x, F, fnorm), _ = jax.lax.scan(step, (x, F, fnorm), None,
                                    length=steps)
    return x, fnorm


# f64 Newton polish steps after the reduced-precision bulk: each squares
# the bulk's ~1e-7-relative error (quadratic convergence from inside the
# Newton basin), so two steps land far below every f64 tolerance; the
# second buys slack for lanes the bulk left at the edge of its noise
# floor. More steps only pay f64-emulation cost on already-converged
# lanes (the monotone keep-test makes them no-ops).
POLISH_STEPS = 2


def solve_steady(fscale_fn: Callable, jac_fn: Callable, x0: jnp.ndarray,
                 groups_dyn: jnp.ndarray, opts: SolverOptions,
                 key: jnp.ndarray | None = None,
                 strategy: str = "ptc",
                 tier: str = "f64",
                 bulk_fns: tuple | None = None):
    """Robust steady solve of ``F(x) = 0`` for the dynamic vector.

    ``fscale_fn(x) -> (F, gross)``: residual plus per-species gross-flux
    scale (see :func:`_rnorm` for the convergence measure).
    groups_dyn: [n_g, n_dyn] conservation groups restricted to the dynamic
    indices (used for retry renormalization and the verdict).
    ``strategy``: 'ptc' (pseudo-transient Newton, the default and the
    batched hot path) or 'lm' (projected Levenberg-Marquardt descent of
    the scaled residual -- the reference's solve_minimize analog,
    solver.py:293-372). The choice is static: under ``vmap`` a runtime
    branch would execute BOTH solvers for every lane; callers instead
    re-run failed lanes with 'lm' in a second pass (the reference's own
    sequential strategy fallback).

    ``tier`` / ``bulk_fns`` (docs/perf_precision_tiers.md): under
    ``tier="f32-polish"`` with ``bulk_fns=(bulk_fscale_fn,
    bulk_jac_fn)`` -- the same closures evaluated at
    ``precision.bulk_dtype`` -- the whole attempt march (PTC or LM,
    chords included) runs in native f32 against :func:`bulk_options`
    tolerances, then :data:`POLISH_STEPS` full-f64 Newton steps polish
    the promoted iterate and the verdict is taken at the caller's
    ORIGINAL f64 opts. A lane that cannot be polished to the f64
    thresholds fails its verdict exactly like an f64 failure and falls
    through the caller's rescue ladder. The tiered path requires the
    dedicated static ``max_attempts == 1`` fast pass (the fused sweep's
    first pass); multi-attempt / traced-pacing solves (the rescue
    ladder) ignore the tier and stay pure f64.
    Returns (x, success, normalized_residual, iterations, attempts,
    rate_ok, pos_ok, sums_ok, dt_exit, chords) -- the trailing five are
    the per-lane forensic diagnostics of :class:`SteadyStateResults`:
    the verdict broken into its three tests at the returned iterate,
    the pseudo-step (PTC) or damping (LM) the final attempt exited
    with, plus the accepted chord re-solves spent (always 0 for LM or
    ``chord_steps=0``).
    """
    attempt_fn = _lm_attempt if strategy == "lm" else _ptc_attempt
    if (tier != "f64" and bulk_fns is not None
            and isinstance(opts.max_attempts, int)
            and opts.max_attempts == 1):
        # Precision-tiered dedicated path: f32 bulk march, f64
        # polish-and-verify. Mirrors the single-attempt path below --
        # same best-of {x0, x1} scoreboard, same verdict at the
        # caller's opts -- with the expensive march moved to native
        # matrix units.
        bulk_fscale_fn, bulk_jac_fn = bulk_fns
        bopts = bulk_options(opts, tier)
        F0, gross0 = fscale_fn(x0)
        f0 = _rnorm(F0, gross0, opts)
        xb, _, k, dt_exit, chords = attempt_fn(
            bulk_fscale_fn, bulk_jac_fn, _precision.cast_bulk(x0, tier),
            _precision.cast_bulk(groups_dyn, tier), bopts)
        x1, f1 = _polish_newton(fscale_fn, jac_fn,
                                _precision.cast_verify(xb), groups_dyn,
                                opts, steps=POLISH_STEPS)
        ok = _verdict(x1, f1, groups_dyn, opts)
        better = _score(x1, f1, groups_dyn, opts) > _score(x0, f0,
                                                          groups_dyn,
                                                          opts)
        x_out = jnp.where(ok | better, x1, x0)
        f_out = jnp.where(ok | better, f1, f0)
        rate_ok, pos_ok, sums_ok = _verdict_tests(x_out, f_out,
                                                  groups_dyn, opts)
        # Polish steps count as iterations (the device work was spent);
        # dt_exit is promoted so the tiered and plain results share one
        # output layout (dtype differences would split the vmapped
        # program's output signature).
        return (x_out, ok, f_out, k + POLISH_STEPS, jnp.asarray(1),
                rate_ok, pos_ok, sums_ok,
                _precision.cast_verify(dt_exit), chords)
    # The consolidated rescue program passes pacing knobs (dt0,
    # max_steps, max_attempts, ...) as traced values so one compiled
    # program serves every ladder rung; a traced max_attempts must take
    # the general retry loop below (whose while_loop condition handles
    # tracers), and only a static ==1 may select the dedicated path.
    if isinstance(opts.max_attempts, int) and opts.max_attempts == 1:
        # Dedicated single-attempt path (the batched sweep's capped
        # first pass): no retry while_loop, no PRNG restart machinery,
        # no multi-attempt scoreboard -- a measurably smaller compiled
        # program (every emulated-f64 op instance costs ~10-20 ms of
        # TPU compile; the volcano-scale program is compile-bound).
        # Semantics match the general path at max_attempts=1 exactly:
        # attempt 0 starts from the caller's guess verbatim, and the
        # lexicographic scoreboard degenerates to best-of {x0, x1}.
        F0, gross0 = fscale_fn(x0)
        f0 = _rnorm(F0, gross0, opts)
        x1, f1, k, dt_exit, chords = attempt_fn(fscale_fn, jac_fn, x0,
                                                groups_dyn, opts)
        ok = _verdict(x1, f1, groups_dyn, opts)
        better = _score(x1, f1, groups_dyn, opts) > _score(x0, f0,
                                                          groups_dyn,
                                                          opts)
        x_out = jnp.where(ok | better, x1, x0)
        f_out = jnp.where(ok | better, f1, f0)
        rate_ok, pos_ok, sums_ok = _verdict_tests(x_out, f_out,
                                                  groups_dyn, opts)
        return (x_out, ok, f_out, k, jnp.asarray(1),
                rate_ok, pos_ok, sums_ok, dt_exit, chords)
    if key is None:
        key = jax.random.PRNGKey(0)

    def attempt_cond(state):
        (x, best_x, best_f, best_s, success, iters, attempt, dt_exit,
         chords, key) = state
        return (attempt < opts.max_attempts) & (~success)

    def attempt_body(state):
        (x, best_x, best_f, best_s, success, iters, attempt, dt_exit,
         chords, key) = state
        # Attempt 0 trusts the caller's guess verbatim: even a 1e-9
        # renormalization perturbs residuals by k_max * 1e-9, and
        # restarts risk hopping to a different steady-state branch.
        # Attempt 1 renormalizes (reference system.py:630); attempts
        # >= 2 restart from random guesses (reference system.py:586).
        x_norm = _normalize(jnp.abs(x), groups_dyn, opts.floor)
        key, sub = jax.random.split(key)
        rand = _normalize(jax.random.uniform(sub, x.shape, dtype=x.dtype),
                          groups_dyn, opts.floor)
        x_start = jnp.where(attempt == 0, x,
                            jnp.where(attempt == 1, x_norm, rand))
        x_new, fnorm, k, dt_new, nch = attempt_fn(fscale_fn, jac_fn,
                                                  x_start, groups_dyn,
                                                  opts)
        ok = _verdict(x_new, fnorm, groups_dyn, opts)
        # Lexicographic scoreboard across attempts (reference
        # compare_scores): tests passed first, residual second.
        s_new = _score(x_new, fnorm, groups_dyn, opts)
        better = s_new > best_s
        best_x = jnp.where(better, x_new, best_x)
        best_f = jnp.where(better, fnorm, best_f)
        best_s = jnp.where(better, s_new, best_s)
        return (x_new, best_x, best_f, best_s, ok, iters + k,
                attempt + 1, dt_new, chords + nch, key)

    F0, gross0 = fscale_fn(x0)
    f0 = _rnorm(F0, gross0, opts)
    s0 = _score(x0, f0, groups_dyn, opts)
    init = (x0, x0, f0, s0, jnp.asarray(False), 0, 0,
            jnp.asarray(opts.dt0, x0.dtype),
            jnp.zeros((), dtype=jnp.int32), key)
    (x, best_x, best_f, best_s, success, iters, attempts, dt_exit,
     chords, _) = jax.lax.while_loop(attempt_cond, attempt_body, init)
    x_out = jnp.where(success, x, best_x)
    Fx, grossx = fscale_fn(x)
    f_out = jnp.where(success, _rnorm(Fx, grossx, opts), best_f)
    rate_ok, pos_ok, sums_ok = _verdict_tests(x_out, f_out, groups_dyn,
                                              opts)
    return (x_out, success, f_out, iters, attempts,
            rate_ok, pos_ok, sums_ok, dt_exit, chords)


def deflation_basis(groups_dyn) -> "np.ndarray":
    """Orthonormal basis Q [n_dyn, m] of the complement of the
    conservation rows -- the deflated subspace the Lyapunov stability
    certificate works in.

    The group indicators g are LEFT null vectors of every steady
    Jacobian (the dynamics conserve g.y exactly, so g.J = 0): range(J)
    lies in g-perp, g-perp is J-invariant, and the quotient block is
    exactly zero. The spectrum therefore splits EXACTLY as
    eig(J) = eig(Q.T J Q) + {0 per independent group}, and the
    conservation-null eigenvalues (always <= the positive stability
    tolerance) can be deflated away before certifying. Host-side numpy
    (static per spec; the result enters jitted programs as a
    constant)."""
    import numpy as np
    G = np.asarray(groups_dyn, dtype=float)
    G = G[(G > 0).any(axis=1)] if G.size else G.reshape(0, G.shape[-1])
    n = np.asarray(groups_dyn).shape[-1]
    if G.shape[0] == 0:
        return np.eye(n)
    _, s, Vt = np.linalg.svd(G)
    rank = int((s > 1e-12 * max(s[0], 1.0)).sum())
    return np.ascontiguousarray(Vt[rank:].T)


def deflation_basis_for_spec(spec) -> "np.ndarray":
    """:func:`deflation_basis` for a ModelSpec's dynamic block -- the
    ONE recipe (group rows restricted to the dynamic indices) shared by
    the production stability screen and the certificate tests, so the
    tests always validate the exact Q the screen uses."""
    import numpy as np
    groups_dyn = np.asarray(spec.groups)[:, np.asarray(
        spec.dynamic_indices)]
    return deflation_basis(groups_dyn)


# Deflated dimension above which the batched Lyapunov certificate is
# skipped (its kron system is m^2 x m^2 per lane; beyond this the
# Gershgorin tier + host eig fallback carry the verdict alone).
LYAPUNOV_MAX_DIM = 8


def effective_unit_roundoff(dtype, backend: str | None = None) -> float:
    """Effective unit roundoff of ``dtype`` arithmetic on ``backend``.

    CPU and CUDA/ROCm GPUs have native IEEE f64 (finfo eps); anything
    else -- TPU, axon, future accelerators -- is assumed to emulate f64
    as double-f32 pairs with ~49 mantissa bits (constants.py:33), i.e.
    16x finfo eps per op (sound-first default). The emulation factor
    applies ONLY to 64-bit floats: f32 (the precision-tier bulk dtype)
    is native on every supported backend, so its roundoff is plain
    finfo eps everywhere. ``backend=None`` reads
    ``jax.default_backend()`` at CALL time -- callers that own a mesh/
    device set must pass the platform of the devices the program will
    actually run on (ADVICE r5: a program explicitly placed on a
    non-default device must not inherit the default backend's margin,
    and cached programs must not bake in a stale choice)."""
    if backend is None:
        backend = jax.default_backend()
    native = (backend in ("cpu", "gpu", "cuda", "rocm")
              or jnp.finfo(dtype).bits < 64)
    return (1.0 if native else 16.0) * float(jnp.finfo(dtype).eps)


def lyapunov_certified_stable(J, Q, tol, eps_eff: float | None = None):
    """Device-side SOUND one-way stability certificate via a deflated
    Lyapunov solve (jittable / vmappable; small m only).

    Gershgorin discs are hopeless for stiff kinetics Jacobians (the
    conservation-null eigenvalue sits at ~0 with disc radius ~||J||;
    measured on the COOx volcano the plain certificate clears 0.3 % of
    lanes). This tier instead works in the conservation-deflated
    subspace (:func:`deflation_basis` -- the deflation is exact) and
    certifies ``max Re eig(J) <= tol`` by explicitly constructing a
    Lyapunov witness for ``A = (Q^T J Q - tol I)/scale``:

        solve  (I (x) A^T + A^T (x) I) vec(P) = -vec(I)
        S = sym(P),  R = A^T S + S A + I

    If S is positive definite (elimination pivots with a rounding
    margin) and ||R||_2 < 1 (symmetric Gershgorin row-sum bound plus a
    Higham-style per-entry forward-error matrix
    ``E = 4(m+2) eps_eff (|A|^T|S| + |S||A| + I)``, where ``eps_eff``
    is the BACKEND's unit roundoff -- finfo eps on true-f64 CPU, 16x
    that on TPU's double-f32 f64 emulation (~2^-49, constants.py:33)
    -- the error actually incurred computing R, which stays tight even
    when ||S|| ~ 1/sep is huge; a cruder 64 eps m^2 max|S| margin was
    measured to force abstention on 13 % of volcano lanes whose true
    residuals were fine), then A^T S + S A = R - I is negative
    definite with S > 0 --
    a complete Lyapunov stability proof for A, hence Re eig(J) < tol.
    Every check runs on the COMPUTED matrices, so a bad solve
    (ill-conditioned kron system near marginal stability) can only
    ABSTAIN, never falsely certify; lanes that abstain fall through to
    the host eigensolve exactly as before. Verified against dense eig
    on adversarial random matrices including +-1e-10-relative marginal
    bands: zero unsound certifications (40k sweep during round-5
    development; 800 re-checked on every test run,
    tests/test_verdicts.py).

    J: [n, n]; Q: [n, m] static with m >= 1 (callers gate m == 0 --
    an all-conservation spectrum -- to the other tiers); tol: scalar.
    ``eps_eff``: the executing backend's unit roundoff
    (:func:`effective_unit_roundoff`) -- the caller that owns the mesh/
    devices must supply it so a cached jitted program cannot bake in a
    margin chosen from a stale ``jax.default_backend()`` read; None
    falls back to the default backend AT TRACE TIME (only safe when
    the program runs on the default backend). Returns a bool scalar.

    NOTE on rigor (ADVICE r5): the ||R||_2 margin via E is a genuine
    Higham-style forward-error bound, but the positive-definiteness
    margin below (64 eps m max|S| on unpivoted elimination pivots) is
    EMPIRICALLY CALIBRATED, not a proven backward-error bound --
    element growth in unpivoted elimination on a near-indefinite S can
    in principle exceed it. "Never falsely certify" therefore rests on
    the 40k-matrix adversarial sweep plus the 800-matrix per-test-run
    re-check (tests/test_verdicts.py), and on the analytically exact
    (spot-checked at rtol 1e-6) conservation deflation -- see
    docs/failure_model.md for the empirical-status summary.
    """
    m = Q.shape[1]
    Qc = jnp.asarray(Q, dtype=J.dtype)
    B = Qc.T @ J @ Qc
    eye = jnp.eye(m, dtype=J.dtype)
    sc = jnp.maximum(jnp.max(jnp.abs(B)), 1e-300)
    A = (B - tol * eye) / sc
    K = (jnp.kron(eye, A.T) + jnp.kron(A.T, eye))
    p = linalg.solve(K, -eye.reshape(-1))
    S = 0.5 * (p.reshape(m, m) + p.reshape(m, m).T)
    R = A.T @ S + S @ A + eye
    R = 0.5 * (R + R.T)
    pmax = jnp.max(jnp.abs(S))
    # Effective unit roundoff of the EXECUTING backend (see
    # effective_unit_roundoff: finfo eps on native-f64 CPU/GPU, 16x on
    # emulated-f64 accelerators). The caller that owns the devices
    # passes eps_eff explicitly; the default-backend fallback here is
    # trace-time and only sound when the program runs there.
    eps = (effective_unit_roundoff(J.dtype) if eps_eff is None
           else float(eps_eff))
    absA, absS = jnp.abs(A), jnp.abs(S)
    E = 4.0 * (m + 2) * eps * (absA.T @ absS + absS @ absA + eye)
    E = 0.5 * (E + E.T)
    bound_R = jnp.max(jnp.sum(jnp.abs(R) + E, axis=1))
    ok = jnp.all(jnp.isfinite(S)) & (bound_R < 0.5)
    # PD of S: unrolled elimination pivots with a rounding margin.
    pd_margin = 64.0 * eps * m * pmax
    M = S
    idx = jnp.arange(m)
    for k in range(m):
        piv = M[k, k]
        ok = ok & (piv > pd_margin)
        denom = jnp.where(piv > pd_margin, piv, 1.0)
        M = M - jnp.where((idx > k)[:, None],
                          jnp.outer(M[:, k], M[k, :] / denom), 0.0)
    return ok


def stability_tolerance_from_scale(scale, pos_tol: float = 1e-2,
                                   eps: float | None = None):
    """Scale-aware stability threshold from a precomputed max|J|.

    Single source of the formula for BOTH verdict tiers (the on-device
    Gershgorin certificate feeds device-computed scales; the host eig
    pass feeds numpy ones) -- tuning the noise-floor constant here
    cannot desynchronize them. Accepts numpy OR jax arrays without
    forcing a transfer (eps is read from the dtype, the arithmetic
    stays in the input's array namespace). See
    :func:`stability_tolerance` for the rationale."""
    import numpy as np
    if eps is None:
        eps = float(np.finfo(getattr(scale, "dtype", float)).eps)
    return pos_tol + 64.0 * eps * scale


def stability_tolerance(jac, pos_tol: float = 1e-2):
    """Effective eigenvalue-stability threshold for a Jacobian (or batch).

    The reference uses a bare absolute ``pos_jac_tol=1e-2``
    (solver.py:74-106), which is meaningless for stiff kinetics: with
    ||J|| ~ 1e16, the conservation-law null eigenvalue alone carries
    O(eps*||J||) ~ O(1) of floating-point noise. The threshold therefore
    gets a scale-aware noise floor of 64*eps*max|J| -- eigenvalues below
    the floor are numerically indistinguishable from zero; genuinely
    unstable directions in such systems surface at the rate-constant
    scale, far above it. ``jac``: [..., n, n]; returns [...] thresholds.
    """
    import numpy as np
    jac = np.asarray(jac)
    scale = np.abs(jac).max(axis=(-2, -1))
    return stability_tolerance_from_scale(scale, pos_tol,
                                          np.finfo(jac.dtype).eps)


def jacobian_eigenvalues_stable(jac, pos_tol: float = 1e-2):
    """Host-side stability check: all Jacobian eigenvalues have real part
    below the scale-aware threshold (reference solver.py:102-106 verdict
    with the :func:`stability_tolerance` noise floor). Nonsymmetric
    ``eig`` is CPU-only in XLA, so call this outside jit on gathered
    results."""
    import numpy as np
    eig = np.linalg.eigvals(np.asarray(jac))
    return bool(np.all(eig.real <= stability_tolerance(jac, pos_tol)))
