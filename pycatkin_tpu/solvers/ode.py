"""Stiff ODE integration: two adaptive L-stable ESDIRK families.

TPU-native replacement for the reference's scipy ``solve_ivp(method='BDF')``
/ ``ode('lsoda')`` transient path (old_system.py:315-378) -- two
independent on-device methods, mirroring the reference's two scipy
integrator families:

1. ``trbdf2`` -- TR-BDF2 (ESDIRK2(3), Hosea & Shampine), the classic
   one-step L-stable workhorse and the default:

     stage 1 (TR):   g = y + (gamma*h/2) * (f(y) + f(g))
     stage 2 (BDF2): y1 = (g - (1-gamma)^2 y) / (gamma*(2-gamma))
                          + h*(1-gamma)/(2-gamma) * f(y1)
   with gamma = 2 - sqrt(2); both stages share the implicit coefficient
   d = gamma/2, so one LU of (I - d*h*J) serves both stage solves.

2. ``esdirk4`` -- ESDIRK4(3)6L[2]SA (Kennedy & Carpenter, NASA
   TM-2001-211038): six stages (first explicit), stiffly accurate,
   L-stable, 4th order with an embedded 3rd-order error estimate. All
   implicit stages share the coefficient d = 1/4, so the SAME frozen
   factorization serves all five stage solves. At tight tolerances the
   local error scales h^5 vs TR-BDF2's h^3, cutting step counts ~5-10x
   on smooth stiff transients (the accepted-step census on the COOx
   CSTR benchmark showed TR-BDF2 error-limited, not stability-limited,
   at rtol=1e-10 -- the order barrier, not robustness, set its cost).

Embedded error weights give the step controller; the raw error is
filtered through (I - d*h*J)^-1 for stiff reliability. Everything is
``lax.while_loop``/``scan`` -- jittable, vmappable, differentiable
(unrolled) -- and integration over huge spans (1e12..1e16 s, the
reference's integrate-to-steady-state pattern) works because the step
size grows geometrically once transients die.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..ops import linalg

GAMMA = 2.0 - math.sqrt(2.0)
D = GAMMA / 2.0
# 2nd-order solution weights (derived from the two-stage form).
B1 = 1.0 / (2.0 * (2.0 - GAMMA))
B2 = 1.0 / (2.0 * (2.0 - GAMMA))
B3 = (1.0 - GAMMA) / (2.0 - GAMMA)
# Embedded 3rd-order quadrature weights at c = [0, gamma, 1].
BH2 = 1.0 / (6.0 * GAMMA * (1.0 - GAMMA))
BH3 = 0.5 - GAMMA * BH2
BH1 = 1.0 - BH2 - BH3

# ESDIRK4(3)6L[2]SA tableau (Kennedy & Carpenter 2001, appendix C;
# exact rationals). First stage explicit; a_ii = 1/4 for i >= 2;
# stiffly accurate (b == last row of A), so y1 = z6.
E4_D = 0.25
E4_A = (
    (),                                                    # stage 1
    (0.25,),                                               # stage 2
    (8611.0 / 62500.0, -1743.0 / 31250.0),                 # stage 3
    (5012029.0 / 34652500.0, -654441.0 / 2922500.0,
     174375.0 / 388108.0),                                 # stage 4
    (15267082809.0 / 155376265600.0, -71443401.0 / 120774400.0,
     730878875.0 / 902184768.0, 2285395.0 / 8070912.0),    # stage 5
    (82889.0 / 524892.0, 0.0, 15625.0 / 83664.0,
     69875.0 / 102672.0, -2260.0 / 8211.0),                # stage 6
)
E4_B = E4_A[5] + (E4_D,)
E4_BHAT = (4586570599.0 / 29645900160.0, 0.0,
           178811875.0 / 945068544.0, 814220225.0 / 1159782912.0,
           -3700637.0 / 11593932.0, 61727.0 / 225920.0)

_NEWTON_ITERS = 6


class ODEOptions(NamedTuple):
    rtol: float = 1.0e-8
    atol: float = 1.0e-10
    h0: float = 1.0e-10         # initial step
    max_steps: int = 4000       # per save interval
    # Integrator family: 'trbdf2' (2nd order, the default) or 'esdirk4'
    # (4th order, ~5-10x fewer steps at tight tolerances; the
    # cross-check method and the fast path for accuracy-limited
    # transients like the CSTR benchmark).
    method: str = "trbdf2"
    safety: float = 0.9
    min_factor: float = 0.2
    max_factor: float = 8.0
    # Stage-Newton iterate clamps: bounds on y during implicit stage
    # solves. The defaults suit the chemistry layer (coverages in
    # [0, 1], gas partial pressures nonnegative and O(1) bar); callers
    # integrating differently-scaled systems must widen them. Runaway
    # iterates past the upper clamp would overflow the f32-ranged
    # exponent of TPU's f64 emulation and poison the step controller.
    # A converged stage solution SITTING ON either boundary rejects the
    # step (see _stage_solve), so both a mis-scaled system and a
    # spurious large-h stage root surface as rejections instead of a
    # silently pinned/hopped trajectory. The tight LOWER clamp is the
    # load-bearing one: at h far beyond the local timescale the frozen-
    # Jacobian stage Newton can converge onto a phantom near-equilibrium
    # of the rate equations (measured on the CH4 network: +-1e3 states
    # entered through a waived/filtered error test, which at huge h is
    # blind -- the stiff filter divides the estimate by h). Conservation
    # is preserved exactly by RK stages, so any large phantom root MUST
    # carry compensating NEGATIVE in-group entries (a +1+a phantom
    # coverage forces a -a partner in its site group); clamping below at
    # clamp_lo bounds the whole class: the projection squeezes phantom
    # roots against the boundary, so an accepted pseudo-state can sit at
    # most ~|clamp_lo| from the physical one, where the Newton finish
    # absorbs it. Real trajectories only go negative by
    # local-error-sized amounts (measured across the test mechanisms:
    # ~-1e-9 worst), so -1e-6 leaves three decades of headroom for
    # genuine dynamics while pinning phantoms to irrelevance.
    clamp: float = 1.0e3
    clamp_lo: float = -1.0e-6
    # Max relative state motion per error-waived (relaxed) step; see the
    # small-move gate in _advance_to. inf disables the gate.
    relax_dy: float = 0.1
    # Domain-steadiness relative tolerance used by the relax/finish
    # oracles (net flux <= steady_rel * gross flux). Matches the steady
    # solver's SolverOptions.rate_tol_rel default; thread a tightened
    # value here when tightening the solver, so transient error-test
    # waiving is judged at the same level.
    steady_rel: float = 1.0e-9


def _stage_solve(f, msolve, z0, rhs_const, h, scale, opts, d=D):
    """Solve z = rhs_const + d*h*f(z) by simplified Newton with the frozen
    factorized iteration matrix (I - d*h*J).

    Returns (z, converged): convergence is judged by the last correction
    being small relative to the error-control scale -- a silently
    unconverged stage must reject the step, otherwise conservation drifts
    on the huge steps taken near steady state.

    The iteration count is FIXED (no convergence-based early exit). A
    round-4 experiment exited once dz fell below 0.03*scale; it was
    reverted: the sloppier stage solutions changed which basin the CH4
    network's metastable plateau (t ~ 1e8 s) drained into -- the
    1e12-s integrate-to-steady tail landed on a NON-physical root
    (|dy| ~ 1 vs the scipy-BDF/PTC ground truth) -- while saving only
    ~2x of stage cost the 4th-order method had already made cheap.
    Full-depth stage polishing is part of the phantom-root defense in
    depth (see clamp_lo above), not an accuracy luxury.
    """
    def body(_, carry):
        z, _ = carry
        res = z - rhs_const - d * h * f(z)
        dz = msolve(res)
        # Clamp runaway iterates (ODEOptions.clamp/clamp_lo): an
        # overshooting iterate feeds k*prod(y) past the exponent range
        # of TPU's f32-ranged f64 emulation, and the resulting inf/nan
        # would poison the step controller instead of just costing a
        # rejection.
        z_new = jnp.clip(z - dz, opts.clamp_lo, opts.clamp)
        dz_norm = jnp.sqrt(jnp.mean((dz / scale) ** 2))
        return z_new, dz_norm
    z, dz_norm = jax.lax.fori_loop(0, _NEWTON_ITERS, body,
                                   (z0, jnp.asarray(jnp.inf, z0.dtype)))
    # A solution pinned on a clamp boundary is not a solution of the
    # stage equations (the clamp truncated it), and one that CONVERGED
    # against the lower bound is a phantom root (see ODEOptions.clamp_lo
    # rationale): reject the step so the controller shrinks h instead of
    # accepting a hopped/clamped trajectory.
    on_clamp = jnp.any((z >= opts.clamp) | (z <= opts.clamp_lo))
    converged = (dz_norm < 0.1) & ~on_clamp
    return z, converged


def _trbdf2_step(f, jac, y, t, h, opts: ODEOptions, f0=None):
    """One TR-BDF2 step attempt. Returns (y_new, err_ratio, ok).
    ``f0``: f(y) if the caller already evaluated it."""
    n = y.shape[0]
    eye = jnp.eye(n, dtype=y.dtype)
    J = jac(y)
    M = eye - D * h * J
    # One factorization serves both stages and the error filter.
    msolve = linalg.make_msolve(M)

    if f0 is None:
        f0 = f(y)
    scale0 = opts.atol + opts.rtol * jnp.abs(y)
    # TR stage to t + gamma*h
    g, conv1 = _stage_solve(f, msolve, y + GAMMA * h * f0,
                            y + D * h * f0, h, scale0, opts)
    fg = f(g)
    # BDF2 stage to t + h
    c_g = 1.0 / (GAMMA * (2.0 - GAMMA))
    c_y = (1.0 - GAMMA) ** 2 / (GAMMA * (2.0 - GAMMA))
    rhs_const = c_g * g - c_y * y
    y1, conv2 = _stage_solve(f, msolve, rhs_const + D * h * fg, rhs_const,
                             h, scale0, opts)
    f1 = f(y1)

    # Embedded error, stiffly filtered.
    err_raw = h * ((B1 - BH1) * f0 + (B2 - BH2) * fg + (B3 - BH3) * f1)
    err = msolve(err_raw)
    scale = opts.atol + opts.rtol * jnp.maximum(jnp.abs(y), jnp.abs(y1))
    err_ratio = jnp.sqrt(jnp.mean((err / scale) ** 2))
    ok = (jnp.isfinite(err_ratio) & jnp.all(jnp.isfinite(y1)) &
          conv1 & conv2)
    return y1, jnp.where(ok, err_ratio, jnp.inf), ok


def _esdirk4_step(f, jac, y, t, h, opts: ODEOptions, f0=None):
    """One ESDIRK4(3)6L[2]SA step attempt. Returns (y_new, err_ratio, ok).
    ``f0``: f(y) if the caller already evaluated it.

    All five implicit stages share d = 1/4, so one factorization of
    (I - d*h*J) serves the whole step; stage predictors reuse the
    accumulated explicit sum. Stiffly accurate: y_new is the last stage,
    so the scheme is L-stable and needs no separate solution assembly."""
    n = y.shape[0]
    eye = jnp.eye(n, dtype=y.dtype)
    J = jac(y)
    M = eye - E4_D * h * J
    msolve = linalg.make_msolve(M)

    if f0 is None:
        f0 = f(y)
    scale0 = opts.atol + opts.rtol * jnp.abs(y)

    ks = [f0]
    conv = jnp.asarray(True)
    z = y
    for i in range(1, 6):
        acc = y
        for j, a in enumerate(E4_A[i]):
            if a != 0.0:
                acc = acc + (a * h) * ks[j]
        # Predictor: previous stage value (the stages march across the
        # step, so z_{i-1} is the best cheap estimate of z_i).
        z, ci = _stage_solve(f, msolve, z, acc, h, scale0, opts, d=E4_D)
        conv = conv & ci
        # Stage derivative from the stage equation (exact to the stage
        # solve's own tolerance): k_i = (z - acc) / (d*h). One f
        # evaluation per stage saved, and the identity keeps the error
        # estimate consistent with what the stage actually produced.
        ks.append((z - acc) / (E4_D * h))
    y1 = z

    err_raw = h * sum((b - bh) * k
                      for b, bh, k in zip(E4_B, E4_BHAT, ks))
    err = msolve(err_raw)
    scale = opts.atol + opts.rtol * jnp.maximum(jnp.abs(y), jnp.abs(y1))
    err_ratio = jnp.sqrt(jnp.mean((err / scale) ** 2))
    ok = (jnp.isfinite(err_ratio) & jnp.all(jnp.isfinite(y1)) & conv)
    return y1, jnp.where(ok, err_ratio, jnp.inf), ok


# Controller exponent: err ~ h^(q+1) with q the embedded order, so the
# optimal-step factor is err_ratio^(-1/(q+1)).
_STEP_FNS = {"trbdf2": (_trbdf2_step, 1.0 / 3.0),
             "esdirk4": (_esdirk4_step, 1.0 / 4.0)}


def _advance_to(f, jac, y, t0, t1, h_init, opts: ODEOptions,
                steady_fn=None, relax_fn=None):
    """Adaptively integrate from t0 to t1. Returns (y(t1), last_h, ok).

    ``steady_fn(y) -> bool``: optional oracle declaring y steady at the
    device's arithmetic floor (the engine's net-vs-gross flux test);
    when it fires, the remaining span is skipped (y(t1) = y).

    ``relax_fn(y) -> bool``: optional looser oracle (the steady
    VERDICT's relative tolerance). When it holds, the local-error test
    is waived and the step factor forced up: near steady state the
    embedded error estimate is dominated by flux-cancellation noise
    (h * noise grows with h, capping h far below the remaining span on
    TPU's pair-emulated f64), yet accuracy no longer matters -- each
    L-stable step just relaxes toward the attractor, so huge steps
    cross integrate-to-steady tails (1e12..1e16 s) in a few iterations
    while the state keeps evolving (no premature freeze; stage
    convergence is still required)."""
    if opts.method not in _STEP_FNS:
        raise ValueError(f"unknown ODE method {opts.method!r}: "
                         f"use one of {sorted(_STEP_FNS)}")
    step_fn, ctrl_exp = _STEP_FNS[opts.method]

    def cond(state):
        y, t, h, k, ok = state
        return (t < t1) & (k < opts.max_steps) & ok

    def body(state):
        y, t, h, k, ok = state
        # Integrate-to-steady shortcut: once a constant-derivative
        # extrapolation over the WHOLE remaining span stays within the
        # error tolerance AND the domain oracle confirms relative
        # steadiness, the segment is done. Without this, huge trailing
        # spans (the reference's times=[0, 1e12..1e16] pattern) stall:
        # near steady state (I - d*h*J) inherits the conservation null
        # space of J at large h, the stage Newton degrades, and h
        # plateaus until max_steps is burned.
        #
        # The span criterion is NEVER applied on its own: a mode growing
        # exponentially from sub-atol amplitude (ignition/induction
        # transient) has a tiny instantaneous derivative but a huge
        # eventual change, so constant-derivative extrapolation would
        # skip it. Such a mode has net flux ~ gross flux, which the
        # relax/steady oracles (net <= tol * gross) reject -- gating on
        # them kills exactly that failure mode. Generic callers with no
        # oracle get no shortcut (they must integrate the whole span).
        f0 = f(y)
        remaining = t1 - t
        span_ok = jnp.all(jnp.abs(f0) * remaining
                          <= opts.atol + opts.rtol * jnp.abs(y))
        oracle = (steady_fn(y) if steady_fn is not None
                  else jnp.asarray(False))
        guard = relax_fn(y) if relax_fn is not None else oracle
        # The hard oracle alone also ends the segment: it certifies
        # steadiness at the arithmetic floor, where further stepping
        # only accumulates rounding noise.
        steady = oracle | (span_ok & guard)
        h_try = jnp.minimum(h, remaining)
        final = h >= remaining
        y_new, err_ratio, step_ok = step_fn(f, jac, y, t, h_try, opts,
                                            f0=f0)
        relaxed = (relax_fn(y) if relax_fn is not None
                   else jnp.asarray(False))
        # The waiver only covers noise-limited near-steady stepping, so
        # a relaxed step must barely MOVE the state. Without this gate,
        # a large-h stage Newton can converge onto a spurious root of
        # the stage equations far from the trajectory (measured on the
        # CH4 network: its metastable plateau at t~1e8 s hopped onto a
        # +-1e3 pseudo-state once h outgrew the plateau) and the waived
        # error test would accept the hop. Genuine relaxation tails move
        # ~nothing per step; genuine drift past the gate falls back to
        # the error test.
        small_move = (jnp.max(jnp.abs(y_new - y) / (1.0 + jnp.abs(y)))
                      <= opts.relax_dy)
        relaxed = relaxed & small_move
        accept = step_ok & ((err_ratio <= 1.0) | relaxed)
        factor = jnp.where(
            err_ratio > 0,
            opts.safety * err_ratio ** (-ctrl_exp),
            opts.max_factor)
        # jnp.clip propagates NaN: a non-finite factor (overflowed error
        # estimate on TPU's range-limited f64) must read as "shrink",
        # not poison h for the rest of the integration.
        factor = jnp.where(jnp.isfinite(factor), factor, opts.min_factor)
        factor = jnp.clip(factor, opts.min_factor, opts.max_factor)
        factor = jnp.where(relaxed & step_ok, opts.max_factor, factor)
        h_next = jnp.maximum(h_try * factor, 1e-300)
        y = jnp.where(accept & ~steady, y_new, y)
        # Land exactly on t1 when the step spans the remainder: t + h_try
        # can round to 1 ulp below t1, leaving a no-progress tail loop.
        t = jnp.where(steady, t1,
                      jnp.where(accept, jnp.where(final, t1, t + h_try), t))
        h_next = jnp.where(steady, h, h_next)
        # Declare failure only on persistent step collapse.
        still_ok = ok & (h_next > 1e-250)
        return (y, t, h_next, k + 1, still_ok)

    y, t, h, k, ok = jax.lax.while_loop(
        cond, body, (y, jnp.asarray(t0, y.dtype),
                     jnp.asarray(h_init, y.dtype), 0, jnp.asarray(True)))
    reached = t >= t1
    return y, h, ok & reached


def init_state(y0: jnp.ndarray, t0, opts: ODEOptions = ODEOptions()):
    """Integration carry (y, t, h, ok) positioned at t0."""
    return (y0, jnp.asarray(t0, y0.dtype),
            jnp.asarray(opts.h0, y0.dtype), jnp.asarray(True))


def integrate_state(f: Callable, jac: Callable, state, save_ts,
                    opts: ODEOptions = ODEOptions(),
                    steady_fn=None, relax_fn=None):
    """Advance an integration carry through ``save_ts`` (all >= state t).

    Returns (state, ys [len(save_ts), n]). The carry form lets callers
    split one long integration across several device calls (needed where
    a single multi-minute kernel would trip an execution watchdog) with
    one compiled program per chunk shape.

    Repeated save times are no-ops (t already >= t1), so padding a final
    short chunk with copies of the last time is safe.
    """
    def scan_body(carry, t_next):
        y, t, h, ok = carry
        y_new, h_new, seg_ok = _advance_to(f, jac, y, t, t_next, h, opts,
                                           steady_fn=steady_fn,
                                           relax_fn=relax_fn)
        ok = ok & seg_ok
        return (y_new, jnp.maximum(t, t_next), h_new, ok), y_new

    return jax.lax.scan(scan_body, state, jnp.asarray(save_ts))


def integrate(f: Callable, jac: Callable, y0: jnp.ndarray,
              save_ts: jnp.ndarray, opts: ODEOptions = ODEOptions(),
              steady_fn=None, relax_fn=None):
    """Integrate y' = f(y) (autonomous) and return y at ``save_ts``.

    save_ts: increasing times, save_ts[0] is the initial time (y0 is
    reported there). Returns (ys [len(save_ts), n], ok).
    ``steady_fn``/``relax_fn``: optional steadiness oracles, see
    :func:`_advance_to`.
    """
    state = init_state(y0, save_ts[0], opts)
    (yf, tf, hf, ok), ys = integrate_state(f, jac, state, save_ts[1:],
                                           opts, steady_fn=steady_fn,
                                           relax_fn=relax_fn)
    ys = jnp.concatenate([y0[None, :], ys], axis=0)
    return ys, ok


def log_time_grid(t0: float, t1: float, n: int = 200) -> jnp.ndarray:
    """Log-spaced output grid starting at t0 (reference
    old_system.py:363-368 convention: prepend 0, log-space the rest)."""
    start = t0 if t0 > 0 else 1.0e-8
    grid = jnp.logspace(jnp.log10(jnp.asarray(start)),
                        jnp.log10(jnp.asarray(t1)), n)
    return jnp.concatenate([jnp.zeros(1), grid])
