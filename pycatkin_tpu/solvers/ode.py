"""Stiff ODE integration: adaptive TR-BDF2 (ESDIRK2(3), L-stable).

TPU-native replacement for the reference's scipy ``solve_ivp(method='BDF')``
/ ``ode('lsoda')`` transient path (old_system.py:315-378). Hand-rolled
because no stiff integrator library ships in this environment; TR-BDF2
(Hosea & Shampine) is the classic one-step L-stable choice:

  stage 1 (TR):   g = y + (gamma*h/2) * (f(y) + f(g))
  stage 2 (BDF2): y1 = (g - (1-gamma)^2 y) / (gamma*(2-gamma))
                       + h*(1-gamma)/(2-gamma) * f(y1)
with gamma = 2 - sqrt(2); both stages share the implicit coefficient
d = gamma/2, so one LU of (I - d*h*J) serves both stage solves.

Embedded 3rd-order error weights give the step controller; the raw error
is filtered through (I - d*h*J)^-1 for stiff reliability. Everything is
``lax.while_loop``/``scan`` -- jittable, vmappable, differentiable
(unrolled) -- and integration over huge spans (1e12..1e16 s, the
reference's integrate-to-steady-state pattern) works because the step size
grows geometrically once transients die.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..ops import linalg

GAMMA = 2.0 - math.sqrt(2.0)
D = GAMMA / 2.0
# 2nd-order solution weights (derived from the two-stage form).
B1 = 1.0 / (2.0 * (2.0 - GAMMA))
B2 = 1.0 / (2.0 * (2.0 - GAMMA))
B3 = (1.0 - GAMMA) / (2.0 - GAMMA)
# Embedded 3rd-order quadrature weights at c = [0, gamma, 1].
BH2 = 1.0 / (6.0 * GAMMA * (1.0 - GAMMA))
BH3 = 0.5 - GAMMA * BH2
BH1 = 1.0 - BH2 - BH3

_NEWTON_ITERS = 6


class ODEOptions(NamedTuple):
    rtol: float = 1.0e-8
    atol: float = 1.0e-10
    h0: float = 1.0e-10         # initial step
    max_steps: int = 4000       # per save interval
    safety: float = 0.9
    min_factor: float = 0.2
    max_factor: float = 8.0


def _stage_solve(f, msolve, z0, rhs_const, h, scale):
    """Solve z = rhs_const + d*h*f(z) by simplified Newton with the frozen
    factorized iteration matrix (I - d*h*J).

    Returns (z, converged): convergence is judged by the last correction
    being small relative to the error-control scale -- a silently
    unconverged stage must reject the step, otherwise conservation drifts
    on the huge steps taken near steady state.
    """
    def body(_, carry):
        z, _ = carry
        res = z - rhs_const - D * h * f(z)
        dz = msolve(res)
        z_new = z - dz
        dz_norm = jnp.sqrt(jnp.mean((dz / scale) ** 2))
        return z_new, dz_norm
    z, dz_norm = jax.lax.fori_loop(0, _NEWTON_ITERS, body,
                                   (z0, jnp.asarray(jnp.inf, z0.dtype)))
    converged = dz_norm < 0.1
    return z, converged


def _trbdf2_step(f, jac, y, t, h, opts: ODEOptions):
    """One TR-BDF2 step attempt. Returns (y_new, err_ratio, ok)."""
    n = y.shape[0]
    eye = jnp.eye(n, dtype=y.dtype)
    J = jac(y)
    M = eye - D * h * J
    # One factorization serves both stages and the error filter.
    msolve = linalg.make_msolve(M)

    f0 = f(y)
    scale0 = opts.atol + opts.rtol * jnp.abs(y)
    # TR stage to t + gamma*h
    g, conv1 = _stage_solve(f, msolve, y + GAMMA * h * f0,
                            y + D * h * f0, h, scale0)
    fg = f(g)
    # BDF2 stage to t + h
    c_g = 1.0 / (GAMMA * (2.0 - GAMMA))
    c_y = (1.0 - GAMMA) ** 2 / (GAMMA * (2.0 - GAMMA))
    rhs_const = c_g * g - c_y * y
    y1, conv2 = _stage_solve(f, msolve, rhs_const + D * h * fg, rhs_const,
                             h, scale0)
    f1 = f(y1)

    # Embedded error, stiffly filtered.
    err_raw = h * ((B1 - BH1) * f0 + (B2 - BH2) * fg + (B3 - BH3) * f1)
    err = msolve(err_raw)
    scale = opts.atol + opts.rtol * jnp.maximum(jnp.abs(y), jnp.abs(y1))
    err_ratio = jnp.sqrt(jnp.mean((err / scale) ** 2))
    ok = (jnp.isfinite(err_ratio) & jnp.all(jnp.isfinite(y1)) &
          conv1 & conv2)
    return y1, jnp.where(ok, err_ratio, jnp.inf), ok


def _advance_to(f, jac, y, t0, t1, h_init, opts: ODEOptions):
    """Adaptively integrate from t0 to t1. Returns (y(t1), last_h, ok)."""

    def cond(state):
        y, t, h, k, ok = state
        return (t < t1) & (k < opts.max_steps) & ok

    def body(state):
        y, t, h, k, ok = state
        h_try = jnp.minimum(h, t1 - t)
        y_new, err_ratio, step_ok = _trbdf2_step(f, jac, y, t, h_try, opts)
        accept = step_ok & (err_ratio <= 1.0)
        factor = jnp.where(
            err_ratio > 0,
            opts.safety * err_ratio ** (-1.0 / 3.0),
            opts.max_factor)
        factor = jnp.clip(factor, opts.min_factor, opts.max_factor)
        h_next = jnp.maximum(h_try * factor, 1e-300)
        y = jnp.where(accept, y_new, y)
        t = jnp.where(accept, t + h_try, t)
        # Declare failure only on persistent step collapse.
        still_ok = ok & (h_next > 1e-250)
        return (y, t, h_next, k + 1, still_ok)

    y, t, h, k, ok = jax.lax.while_loop(
        cond, body, (y, jnp.asarray(t0, y.dtype),
                     jnp.asarray(h_init, y.dtype), 0, jnp.asarray(True)))
    reached = t >= t1
    return y, h, ok & reached


def integrate(f: Callable, jac: Callable, y0: jnp.ndarray,
              save_ts: jnp.ndarray, opts: ODEOptions = ODEOptions()):
    """Integrate y' = f(y) (autonomous) and return y at ``save_ts``.

    save_ts: increasing times, save_ts[0] is the initial time (y0 is
    reported there). Returns (ys [len(save_ts), n], ok).
    """
    def scan_body(carry, t_next):
        y, t, h, ok = carry
        y_new, h_new, seg_ok = _advance_to(f, jac, y, t, t_next, h, opts)
        ok = ok & seg_ok
        return (y_new, t_next, h_new, ok), y_new

    init = (y0, save_ts[0], jnp.asarray(opts.h0, y0.dtype), jnp.asarray(True))
    (yf, tf, hf, ok), ys = jax.lax.scan(scan_body, init, save_ts[1:])
    ys = jnp.concatenate([y0[None, :], ys], axis=0)
    return ys, ok


def log_time_grid(t0: float, t1: float, n: int = 200) -> jnp.ndarray:
    """Log-spaced output grid starting at t0 (reference
    old_system.py:363-368 convention: prepend 0, log-space the rest)."""
    start = t0 if t0 > 0 else 1.0e-8
    grid = jnp.logspace(jnp.log10(jnp.asarray(start)),
                        jnp.log10(jnp.asarray(t1)), n)
    return jnp.concatenate([jnp.zeros(1), grid])
