"""Device cost ledger: compile-time FLOP/byte/memory truth per program.

Host-side wall-clock spans (obs/trace.py) can say a region was slow but
not whether the device did more work or did the same work worse. This
module records, for every program in the zoo, the DEVICE-side cost that
XLA itself reports at compile time -- ``compiled.cost_analysis()``
(FLOPs, bytes accessed) and ``compiled.memory_analysis()`` (peak temp /
output / argument allocation) -- keyed by the same aot-key /
ABI-bucket program key the executable registry and AOT cache use, and
joins it with per-dispatch blocked-wall timings so achieved FLOP/s and
MFU fall out per program instead of per eyeball (the accounting
``tools/exp_mfu.py`` / ``tools/exp_roofline.py`` used to hand-roll).

Three producers feed the ledger (``parallel/compile_pool.py``):

- a fresh ``.lower().compile()`` harvests the analyses directly off the
  compiled executable (``source="compiled"``);
- an AOT cache hit replays the cost dict recorded in the cache entry at
  save time (``source="cache"``) -- the analyses are NOT recomputable
  from a deserialized executable on every backend, so they ride in the
  entry;
- a pack import carries the same dict through the pack manifest
  (``_entry_meta``), so a worker booted from a shipped pack still knows
  what its programs cost (``source="pack"``).

One consumer feeds timings: ``parallel/batch._registered_call`` notes
the blocked wall of every registered-executable dispatch
(:func:`note_dispatch`). ``snapshot()`` then derives achieved FLOP/s
and MFU against :data:`DEVICE_PEAKS` (the measured ceilings from
docs/perf_cost_ledger.md) for bench JSON, the run manifest and
``tools/perfwatch.py``.

No JAX imports at module scope -- the ledger must stay importable from
lint/CI tooling; :func:`harvest_cost` only touches the compiled object
it is handed.
"""

from __future__ import annotations

import math
import threading

from .. import precision as _precision

# Measured device ceilings (flop/s, bytes/s) for the MFU denominator,
# keyed by a lowercase substring of ``jax.devices()[0].device_kind``.
# The TPU v5e numbers are the microbenchmarked rooflines from
# docs/perf_cost_ledger.md (historical record: docs/perf_mfu.md): this
# workload is float64-EMULATED on v5e, so the honest compute ceiling is
# the measured f64-emulation FMA roofline (1.519e11 flop/s), not the
# 1.97e14 bf16 marketing peak; the byte ceiling is the measured HBM
# stream rate. Unknown device kinds (CPU included) get no peak and an
# MFU of None -- a fabricated denominator is worse than no MFU.
#
# ``flops_per_s_f32`` is the NATIVE-f32 compute ceiling used for
# precision-tiered programs (kind tagged ``:p32``): an f32-bulk solve
# scored against the f64-emulation roofline would report a flattering
# >100% MFU. PROVISIONAL value: the measured f64-emulation roofline
# scaled by the ~16x double-float FMA expansion (docs/perf_mfu.md);
# replace with a microbenchmarked number the first time the tiered
# bench runs on hardware (docs/perf_precision_tiers.md tracks this).
DEVICE_PEAKS = {
    "v5 lite": {"flops_per_s": 1.519e11, "flops_per_s_f32": 2.430e12,
                "bytes_per_s": 3.228e11},
    "v5e": {"flops_per_s": 1.519e11, "flops_per_s_f32": 2.430e12,
            "bytes_per_s": 3.228e11},
    "v5p": {"flops_per_s": 1.519e11, "flops_per_s_f32": 2.430e12,
            "bytes_per_s": 3.228e11},
}


def device_peak(device_kind) -> dict | None:
    """The measured ``{"flops_per_s", "flops_per_s_f32", "bytes_per_s"}``
    ceiling for a device kind, or None when no honest ceiling is
    known."""
    if not device_kind:
        return None
    kind = str(device_kind).lower()
    for key, peak in DEVICE_PEAKS.items():
        if key in kind:
            return dict(peak)
    return None


def peak_flops_for_tier(peak: dict | None, tier: str) -> float | None:
    """The compute ceiling a program of precision ``tier`` is honestly
    scored against: the native-f32 roofline for the f32-bulk tier
    (falling back to the f64 ceiling when no f32 number is recorded --
    an underestimated denominator only ever deflates MFU), the
    f64-emulation roofline otherwise."""
    if not peak:
        return None
    if tier == "f32-polish":
        return peak.get("flops_per_s_f32") or peak.get("flops_per_s")
    return peak.get("flops_per_s")


def flops_per_iteration(n_s: int, n_r: int, n_dyn: int,
                        n_reac_cols: int, chords: int = 0) -> float:
    """Analytic useful-FLOP model of ONE PTC Newton iteration (promoted
    from tools/exp_mfu.py so the framework and the experiment scripts
    share one formula): RHS evaluation + dense Jacobian (n_dyn RHS-cost
    columns) + LU solve + ``chords`` chord re-solves. This is the
    NUMERATOR of the useful-MFU metric -- XLA's cost_analysis counts
    every executed flop including padding; this counts the flops the
    algorithm needed."""
    R = 2.0 * n_r * n_reac_cols + 2.0 * 2.0 * n_s * n_r
    jac = n_dyn * R
    solve = (2.0 * n_dyn ** 3 if n_dyn <= 48
             else (2.0 / 3.0) * n_dyn ** 3)
    chord = chords * (2.0 * n_dyn ** 2 + R)
    return R + jac + solve + chord + 10.0 * n_dyn


def _as_float(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(f):
        return None
    return f


def harvest_cost(compiled) -> dict | None:
    """XLA's own cost/memory analyses of one compiled executable, as a
    plain JSON-able dict, or None when the backend exposes neither.

    Defensive by design: ``cost_analysis()`` returns a dict on current
    jax and a list-of-dicts on older releases, ``memory_analysis()`` is
    absent on some backends, and a deserialized AOT executable may
    refuse both -- every probe degrades to missing keys, never raises.
    Negative or non-finite values (sentinel artifacts of some backends)
    are dropped.
    """
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            flops = _as_float(ca.get("flops"))
            if flops is not None and flops >= 0:
                out["flops"] = flops
            by = _as_float(ca.get("bytes accessed"))
            if by is not None and by >= 0:
                out["bytes_accessed"] = by
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for field, key in (("temp_size_in_bytes", "temp_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("argument_size_in_bytes", "argument_bytes"),
                           ("generated_code_size_in_bytes",
                            "code_bytes")):
            v = _as_float(getattr(ma, field, None))
            if v is not None and v >= 0:
                out[key] = v
    except Exception:
        pass
    return out or None


class CostLedger:
    """Thread-safe per-program cost rows, keyed by program key.

    A row is ``{kind, label, source, flops, bytes_accessed, temp_bytes,
    output_bytes, argument_bytes, code_bytes, dispatches,
    blocked_wall_s}`` with absent analyses simply missing. ``record``
    merges (cost fields only fill gaps -- the compile-time harvest wins
    over a cache replay of itself), ``note_dispatch`` accumulates the
    blocked wall, ``snapshot`` derives achieved FLOP/s and MFU.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: dict = {}

    def record(self, key: str, kind: str = None, label: str = None,
               cost: dict = None, source: str = "compiled"):
        """Merge one program's identity + cost dict into its row."""
        with self._lock:
            row = self._rows.setdefault(
                str(key), {"dispatches": 0, "blocked_wall_s": 0.0})
            if kind is not None:
                row.setdefault("kind", str(kind))
            if label is not None:
                row.setdefault("label", str(label))
            if cost:
                for k, v in cost.items():
                    f = _as_float(v)
                    if f is not None and k not in row:
                        row[k] = f
                row.setdefault("source", str(source))

    def note_dispatch(self, key: str, wall_s: float, count: int = 1):
        """Accumulate one dispatch's blocked wall onto a program's row
        (creates a cost-less row for programs nobody harvested, so the
        dispatch count is never lost). ``count=0`` folds extra blocked
        wall -- e.g. the materialization that follows an async dispatch
        -- onto a dispatch that was already counted."""
        with self._lock:
            row = self._rows.setdefault(
                str(key), {"dispatches": 0, "blocked_wall_s": 0.0})
            row["dispatches"] += int(count)
            row["blocked_wall_s"] += float(wall_s)

    def row(self, key: str) -> dict | None:
        with self._lock:
            row = self._rows.get(str(key))
            return dict(row) if row is not None else None

    def keys(self) -> list:
        with self._lock:
            return sorted(self._rows)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def snapshot(self, device_kind: str = None) -> dict:
        """JSON-able ``{"programs": {key: row}, "totals": {...},
        "peak": {...}|None}`` with derived per-program
        ``achieved_flops_per_s`` and ``mfu`` (and byte-side
        ``achieved_bytes_per_s`` / ``hbm_util``) wherever a row has
        both a harvested cost and a nonzero blocked wall. MFU is
        against :func:`device_peak`; None when no ceiling is known
        (CPU) -- absent, not fabricated.

        Precision-tiered rows are scored against their OWN roofline:
        each row carries a ``tier`` (parsed from the ``:p32`` tag in
        its program kind, see :func:`pycatkin_tpu.precision.tier_of_tag`)
        and its mfu denominator is :func:`peak_flops_for_tier`. The
        aggregate ``totals["mfu"]`` divides total flops by the
        tier-weighted peak budget (sum of each row's own ceiling times
        its blocked wall -- identical to the historical formula when
        every program is f64), and ``totals["mfu_by_tier"]`` breaks the
        same ratio out per tier."""
        peak = device_peak(device_kind)
        with self._lock:
            rows = {k: dict(v) for k, v in self._rows.items()}
        tot_flops = tot_wall = tot_peak_budget = 0.0
        by_tier: dict = {}
        for row in rows.values():
            kind = str(row.get("kind", ""))
            tier = _precision.tier_of_tag(kind)
            row["tier"] = tier
            # Direction-kernel tier (kernel tag, KIND_TAG_GRAMMAR):
            # lets perfwatch score a Pallas-kernel program against its
            # XLA twin row-by-row. Only stamped on tagged rows so
            # pre-kernel snapshots stay byte-identical.
            kern = _precision.kernel_of_tag(kind)
            if kern != "xla":
                row["kernel"] = kern
            peak_f = peak_flops_for_tier(peak, tier)
            wall = row.get("blocked_wall_s", 0.0)
            n = row.get("dispatches", 0)
            flops = row.get("flops")
            by = row.get("bytes_accessed")
            if wall > 0 and n > 0:
                if flops is not None:
                    row["achieved_flops_per_s"] = flops * n / wall
                    tot_flops += flops * n
                    tot_wall += wall
                    t = by_tier.setdefault(tier,
                                           {"flops": 0.0, "wall": 0.0,
                                            "peak_budget": 0.0})
                    t["flops"] += flops * n
                    t["wall"] += wall
                    if peak_f:
                        row["mfu"] = (row["achieved_flops_per_s"]
                                      / peak_f)
                        tot_peak_budget += peak_f * wall
                        t["peak_budget"] += peak_f * wall
                if by is not None:
                    row["achieved_bytes_per_s"] = by * n / wall
                    if peak:
                        row["hbm_util"] = (row["achieved_bytes_per_s"]
                                           / peak["bytes_per_s"])
        totals = {"programs": len(rows),
                  "dispatches": sum(r.get("dispatches", 0)
                                    for r in rows.values()),
                  "blocked_wall_s": round(sum(
                      r.get("blocked_wall_s", 0.0)
                      for r in rows.values()), 6)}
        if tot_wall > 0:
            totals["achieved_flops_per_s"] = tot_flops / tot_wall
            if tot_peak_budget > 0:
                totals["mfu"] = tot_flops / tot_peak_budget
            mbt = {t: v["flops"] / v["peak_budget"]
                   for t, v in sorted(by_tier.items())
                   if v["peak_budget"] > 0}
            if mbt:
                totals["mfu_by_tier"] = mbt
        return {"programs": rows, "totals": totals, "peak": peak}

    def reset(self):
        with self._lock:
            self._rows.clear()


default_ledger = CostLedger()


def record(key: str, kind: str = None, label: str = None,
           cost: dict = None, source: str = "compiled"):
    default_ledger.record(key, kind=kind, label=label, cost=cost,
                          source=source)


def note_dispatch(key: str, wall_s: float, count: int = 1):
    default_ledger.note_dispatch(key, wall_s, count=count)


def ledger_snapshot(device_kind: str = None) -> dict:
    """Snapshot of the process-wide default ledger. When
    ``device_kind`` is None and JAX is already initialized, the live
    device kind is probed (never initializing a backend of its own --
    same rule as the run manifest)."""
    if device_kind is None:
        import sys
        if "jax" in sys.modules:
            try:
                import jax
                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = None
    return default_ledger.snapshot(device_kind)


def reset():
    default_ledger.reset()
