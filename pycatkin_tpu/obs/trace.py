"""Nestable run-scoped trace contexts (the ``RunTrace`` primitive).

One :class:`RunTrace` owns one run's telemetry: a thread-safe event
list (the same ``{"kind": ..., "t": ..., **fields}`` dicts the legacy
``utils.profiling`` API produced), span parenting, and PER-TRACE host-
sync accounting. The ambient trace rides a :mod:`contextvars` variable:

- with no ``run_trace()`` active, every call lands in the process
  root trace -- byte-for-byte the old global-event-list behavior, so
  no legacy call site breaks;
- inside ``with run_trace("trial 0") as tr:`` the same calls land in
  ``tr`` only, so two threads running under separate traces no longer
  pollute each other's ``sync_budget`` (the concurrency bug the old
  module docstring admitted: "a budget, not an attribution");
- worker threads see the trace of whoever SUBMITTED them only when the
  submitter propagates its context (``contextvars.copy_context()``,
  as robustness/chunked.py does for the double-buffered pipeline) --
  a thread pool inherits nothing by default.

Span parenting is context-local too: ``trace_span`` pushes its span id
onto a contextvar, so concurrently executing chunks become SIBLING
spans under the submitter's current span instead of interleaved
garbage. Everything here is pure host-side bookkeeping -- no JAX
imports, no device work, nothing on the sweep hot path but a lock and
a dict append.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time

_TRACE_IDS = itertools.count(1)

# Ambient trace + current span id. Default None (module root trace /
# no open span) so a fresh thread context degrades to legacy behavior.
_AMBIENT: contextvars.ContextVar = contextvars.ContextVar(
    "pycatkin_obs_trace", default=None)
_CURRENT_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "pycatkin_obs_span", default=None)


class RunTrace:
    """One run's telemetry scope: events, spans, per-trace syncs.

    All mutation happens under ``self.lock`` so dispatch workers and
    pipeline threads can record into the same trace concurrently.
    """

    def __init__(self, name: str = "run", parent: "RunTrace" = None):
        self.name = str(name)
        self.trace_id = next(_TRACE_IDS)
        self.parent = parent
        self.lock = threading.Lock()
        self.events: list = []       # guarded-by: lock
        self.sync_count = 0          # guarded-by: lock
        self.sync_labels: list = []  # guarded-by: lock
        # Monotonic base: Chrome-trace timestamps are exported relative
        # to this so a trace starts near ts=0.
        self.t0 = time.monotonic()
        self._span_ids = itertools.count(1)

    # -- event log (the legacy record/peek/drain contract) ------------
    def record(self, kind: str, **fields) -> dict:
        ev = {"kind": str(kind), "t": round(time.monotonic(), 3),
              **fields}
        with self.lock:
            self.events.append(ev)
        return ev

    def peek(self, kind: str | None = None) -> list:
        with self.lock:
            evs = list(self.events)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def drain(self) -> list:
        with self.lock:
            out = list(self.events)
            self.events.clear()
        return out

    # -- per-trace sync accounting -------------------------------------
    def note_sync(self, label: str = "", span_id=None) -> None:
        """Count one host sync against THIS trace and record a ``sync``
        instant event (carrying the enclosing span for the trace tree).
        """
        with self.lock:
            self.sync_count += 1
            self.sync_labels.append(label)
            self.events.append({
                "kind": "sync", "t": round(time.monotonic(), 3),
                "label": str(label), "ts": round(time.monotonic(), 6),
                "parent_id": span_id,
                "tid": threading.get_ident()})

    def next_span_id(self) -> int:
        with self.lock:
            return next(self._span_ids)


# The process root trace: where everything lands when no run_trace()
# is active (i.e. exactly the old module-global behavior).
_ROOT = RunTrace("root")


def root_trace() -> RunTrace:
    return _ROOT


def current_trace() -> RunTrace:
    """The ambient trace (root fallback -- never None)."""
    tr = _AMBIENT.get()
    return tr if tr is not None else _ROOT


def current_span_id():
    """Span id of the innermost open span in this context, or None."""
    return _CURRENT_SPAN.get()


@contextlib.contextmanager
def run_trace(name: str = "run"):
    """Open a run-scoped trace; every ``record_event``/``span``/
    ``host_sync``/``sync_budget`` call in this context (and in contexts
    copied from it) lands here instead of the root trace::

        with run_trace("trial 0") as tr:
            sweep_steady_state(...)
        chrome = chrome_trace(tr)
    """
    parent = _AMBIENT.get()
    tr = RunTrace(name, parent=parent)
    tok = _AMBIENT.set(tr)
    # A new trace starts its own span tree.
    tok_span = _CURRENT_SPAN.set(None)
    try:
        yield tr
    finally:
        _CURRENT_SPAN.reset(tok_span)
        _AMBIENT.reset(tok)


@contextlib.contextmanager
def trace_span(label: str, **fields):
    """The span primitive behind ``utils.profiling.span``: records ONE
    legacy-shaped span event on exit (``label``/``dur`` plus ``t``),
    extended with ``span_id``/``parent_id``/``t0``/``tid`` so exporters
    can rebuild the tree and the timeline. Exceptions still record (a
    span that died shows how long it ran)."""
    tr = current_trace()
    sid = tr.next_span_id()
    parent = _CURRENT_SPAN.get()
    tok = _CURRENT_SPAN.set(sid)
    t0_wall = time.perf_counter()
    t0_mono = time.monotonic()
    try:
        yield
    finally:
        _CURRENT_SPAN.reset(tok)
        tr.record("span", label=str(label),
                  dur=round(time.perf_counter() - t0_wall, 6),
                  span_id=sid, parent_id=parent,
                  t0=round(t0_mono, 6),
                  tid=threading.get_ident(), **fields)


def note_sync(label: str = "") -> None:
    """Count one host sync against the ambient trace (called by
    ``utils.profiling.host_sync`` IN ADDITION to the process-wide
    counter, which stays authoritative for ``sync_count()``)."""
    current_trace().note_sync(label, span_id=_CURRENT_SPAN.get())
