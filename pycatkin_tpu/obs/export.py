"""Trace export and span analysis.

Chrome ``trace_event`` JSON (the Trace Event Format; loadable in
Perfetto / ``chrome://tracing``) from a :class:`~.trace.RunTrace`,
plus the span-tree / top-N / outlier-attribution analysis shared by
``tools/obsview.py`` and bench.py (which used to hand-roll its
``outlier_span`` logic). Pure host-side JSON shuffling -- no JAX.
"""

from __future__ import annotations

import json

_US = 1e6      # trace_event timestamps are microseconds


def chrome_trace(trace) -> dict:
    """A :class:`RunTrace` as a Chrome ``trace_event`` JSON object.

    Spans become complete ("X") events (``ts``/``dur`` microseconds
    relative to the trace start, one row per recording thread); counted
    host syncs and every other event kind (degradation, rescue, retry)
    become instant ("i") events. Sync instants are named EXACTLY by
    their counted sync label, so the exported span tree reproduces the
    sync-budget labels (``sync_labels()``) verbatim.
    """
    base = trace.t0
    pid = 1
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": f"pycatkin run '{trace.name}' "
                                f"(trace {trace.trace_id})"}}]
    for ev in trace.peek():
        kind = ev.get("kind")
        tid = ev.get("tid", 0)
        args = {k: v for k, v in ev.items()
                if k not in ("kind", "t", "t0", "ts", "dur", "tid")
                and _jsonable(v)}
        if kind == "span":
            t0 = ev.get("t0")
            dur = float(ev.get("dur", 0.0))
            ts = ((t0 - base) if t0 is not None
                  else (ev.get("t", base) - base) - dur)
            events.append({
                "name": str(ev.get("label", "span")), "cat": "span",
                "ph": "X", "ts": round(ts * _US, 1),
                "dur": round(dur * _US, 1),
                "pid": pid, "tid": tid, "args": args})
        elif kind == "sync":
            ts = ev.get("ts", ev.get("t", base))
            events.append({
                "name": str(ev.get("label", "")), "cat": "sync",
                "ph": "i", "ts": round((ts - base) * _US, 1),
                "s": "t", "pid": pid, "tid": tid, "args": args})
        else:
            events.append({
                "name": str(kind), "cat": str(kind),
                "ph": "i", "ts": round((ev.get("t", base) - base) * _US, 1),
                "s": "t", "pid": pid, "tid": tid, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_name": trace.name,
                          "trace_id": trace.trace_id,
                          "sync_count": trace.sync_count,
                          "sync_labels": list(trace.sync_labels)}}


def _jsonable(v) -> bool:
    return isinstance(v, (str, int, float, bool, type(None)))


def write_chrome_trace(path: str, trace) -> dict:
    """Serialize :func:`chrome_trace` to ``path``; returns the object."""
    obj = chrome_trace(trace)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    return obj


def load_trace(path: str) -> dict:
    """Parse a Chrome trace JSON file (obsview's input)."""
    with open(path, encoding="utf-8") as fh:
        obj = json.load(fh)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path}: not a Chrome trace_event file "
                         f"(no traceEvents key)")
    return obj


# -- span-tree analysis (events = legacy span event dicts OR the
#    traceEvents of a loaded Chrome trace) -----------------------------

def _as_span_rows(events) -> list:
    """Normalize either representation into
    ``{label, dur_s, span_id, parent_id}`` rows."""
    rows = []
    for ev in events:
        if ev.get("ph") == "X":          # Chrome trace event
            rows.append({"label": ev.get("name", "span"),
                         "dur_s": float(ev.get("dur", 0.0)) / _US,
                         "span_id": ev.get("args", {}).get("span_id"),
                         "parent_id": ev.get("args", {}).get("parent_id")})
        elif ev.get("kind") == "span":   # RunTrace event
            rows.append({"label": ev.get("label", "span"),
                         "dur_s": float(ev.get("dur", 0.0)),
                         "span_id": ev.get("span_id"),
                         "parent_id": ev.get("parent_id")})
    return rows


def span_tree(events) -> list:
    """Root span nodes ``{label, dur_s, self_s, span_id, children}``
    rebuilt from parent links (spans with an unknown/absent parent are
    roots -- legacy events without ids degrade to a flat list)."""
    rows = _as_span_rows(events)
    nodes = {}
    for i, r in enumerate(rows):
        key = r["span_id"] if r["span_id"] is not None else f"anon{i}"
        nodes[key] = {**r, "children": []}
    roots = []
    for key, node in nodes.items():
        parent = node["parent_id"]
        if parent is not None and parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node["self_s"] = round(
            max(0.0, node["dur_s"]
                - sum(c["dur_s"] for c in node["children"])), 6)
    return roots


def span_summary(events) -> list:
    """Per-label aggregate rows (total/self seconds, count, max),
    sorted by total descending -- the obsview table."""
    agg: dict = {}
    def walk(node):
        row = agg.setdefault(node["label"],
                             {"label": node["label"], "count": 0,
                              "total_s": 0.0, "self_s": 0.0,
                              "max_s": 0.0})
        row["count"] += 1
        row["total_s"] += node["dur_s"]
        row["self_s"] += node["self_s"]
        row["max_s"] = max(row["max_s"], node["dur_s"])
        for c in node["children"]:
            walk(c)
    for root in span_tree(events):
        walk(root)
    rows = sorted(agg.values(), key=lambda r: -r["total_s"])
    for r in rows:
        for k in ("total_s", "self_s", "max_s"):
            r[k] = round(r[k], 6)
    return rows


def top_spans(events, n: int = 10) -> list:
    """The N individually slowest spans ``{label, dur_s}``."""
    rows = sorted(_as_span_rows(events), key=lambda r: -r["dur_s"])
    return [{"label": r["label"], "dur_s": round(r["dur_s"], 6)}
            for r in rows[:n]]


def format_span_table(events, top: int = 0) -> str:
    """Human span-tree rendering: indented tree + per-label summary
    (+ top-N slowest individual spans when ``top`` > 0)."""
    lines = []
    def walk(node, depth):
        lines.append(f"{'  ' * depth}{node['label']:<40.40s} "
                     f"total {node['dur_s']*1e3:10.3f} ms  "
                     f"self {node['self_s']*1e3:10.3f} ms")
        for c in sorted(node["children"], key=lambda c: -c["dur_s"]):
            walk(c, depth + 1)
    for root in sorted(span_tree(events), key=lambda r: -r["dur_s"]):
        walk(root, 0)
    lines.append("")
    lines.append(f"{'label':<40s} {'count':>5s} {'total ms':>12s} "
                 f"{'self ms':>12s} {'max ms':>12s}")
    for r in span_summary(events):
        lines.append(f"{r['label']:<40.40s} {r['count']:>5d} "
                     f"{r['total_s']*1e3:>12.3f} "
                     f"{r['self_s']*1e3:>12.3f} "
                     f"{r['max_s']*1e3:>12.3f}")
    if top:
        lines.append("")
        lines.append(f"top {top} slowest spans:")
        for r in top_spans(events, top):
            lines.append(f"  {r['label']:<40.40s} "
                         f"{r['dur_s']*1e3:>12.3f} ms")
    return "\n".join(lines)


def attribute_outlier(trial_spans: list, walls: list,
                      threshold: float = 1.1, cost_ledger: dict = None):
    """Name the span that dominates a slow-trial outlier.

    ``trial_spans`` is one ``{label: total_seconds}`` dict per trial,
    ``walls`` the matching trial walls. When the slowest trial exceeds
    the median by more than ``threshold``, returns ``{"label",
    "extra_s", "trial", "max_over_median"}`` for the span whose total
    grew the most between the median and slowest trials (bench.py's
    variance-forensics gate); else None.

    ``cost_ledger`` (a :meth:`CostLedger.snapshot` dict, optional)
    joins device-side truth onto the host-side verdict: the attribution
    gains a ``"programs"`` list naming the ledger rows with the most
    blocked wall, so a slow trial reads "the fused sweep program, 3
    dispatches, 41 ms blocked, MFU 0.31" instead of just a span label.
    """
    if not walls or len(walls) != len(trial_spans):
        return None
    median = sorted(walls)[len(walls) // 2]
    if median <= 0:
        return None
    max_over_median = round(max(walls) / median, 3)
    if max_over_median <= threshold:
        return None
    slow_i = walls.index(max(walls))
    med_i = walls.index(median)
    labels = set(trial_spans[slow_i]) | set(trial_spans[med_i])
    deltas = {lbl: trial_spans[slow_i].get(lbl, 0.0)
              - trial_spans[med_i].get(lbl, 0.0) for lbl in labels}
    if not deltas:
        return None
    dom = max(deltas, key=lambda k: deltas[k])
    out = {"label": dom, "extra_s": round(deltas[dom], 3),
           "trial": slow_i, "max_over_median": max_over_median}
    progs = (cost_ledger or {}).get("programs") or {}
    if progs:
        ranked = sorted(progs.items(),
                        key=lambda kv: -kv[1].get("blocked_wall_s", 0.0))
        out["programs"] = [
            {"key": k,
             **{f: row[f] for f in ("kind", "label", "dispatches",
                                    "blocked_wall_s", "flops",
                                    "achieved_flops_per_s", "mfu")
                if f in row}}
            for k, row in ranked[:3]]
    return out


# -- per-lane solver telemetry (packed [lanes, 5] int rows) -----------

# Mirrors solvers.newton.STRATEGY_CODES -- duplicated here because this
# module must stay importable without JAX (lint/CI tooling); the lane
# telemetry test asserts the two stay in sync. Tier names come straight
# from pycatkin_tpu.precision (itself JAX-free at import).
STRATEGY_NAMES = ("clean", "polish", "ptc", "lm", "unseeded", "demote",
                  "quarantine")
_STRATEGY_GLYPHS = ".Ptlud#"    # one glyph per code; '#' = quarantine


def _lane_rows(lane_telemetry) -> list:
    """Normalize a packed ``[lanes, 5]`` telemetry array (numpy array
    or nested lists: iterations, chords, residual decade, strategy
    code, precision-tier code) into plain int tuples."""
    rows = []
    for row in lane_telemetry:
        vals = [int(v) for v in row]
        if len(vals) != 5:
            raise ValueError(
                f"lane telemetry row has {len(vals)} fields, expected 5 "
                f"(iterations, chords, residual_decade, strategy, tier)")
        rows.append(tuple(vals))
    return rows


def lane_summary(lane_telemetry) -> dict:
    """Aggregate one sweep's packed per-lane telemetry into JSON:
    iteration/chord totals and extrema, the residual-decade histogram,
    per-strategy lane counts (``strategies`` maps name -> count,
    zero-count strategies omitted) and per-precision-tier counts
    (``tiers``: which tier produced each accepted iterate)."""
    from .. import precision as _precision
    rows = _lane_rows(lane_telemetry)
    if not rows:
        return {"lanes": 0}
    its = sorted(r[0] for r in rows)
    chs = [r[1] for r in rows]
    decades: dict = {}
    strategies: dict = {}
    tiers: dict = {}
    tier_names = _precision.TIER_NAMES
    for _, _, dec, strat, tier in rows:
        decades[dec] = decades.get(dec, 0) + 1
        name = (STRATEGY_NAMES[strat] if 0 <= strat < len(STRATEGY_NAMES)
                else f"code{strat}")
        strategies[name] = strategies.get(name, 0) + 1
        tname = (tier_names[tier] if 0 <= tier < len(tier_names)
                 else f"code{tier}")
        tiers[tname] = tiers.get(tname, 0) + 1
    return {
        "lanes": len(rows),
        "iterations": {"min": its[0], "median": its[len(its) // 2],
                       "max": its[-1], "total": sum(its)},
        "chords_total": sum(chs),
        "chords_max": max(chs),
        "residual_decades": {str(k): decades[k]
                             for k in sorted(decades)},
        "strategies": strategies,
        "tiers": tiers,
    }


def format_lane_heatmap(lane_telemetry, width: int = 64) -> str:
    """Human rendering of per-lane telemetry: a lane grid (one glyph
    per lane by rescue strategy, ``.`` = clean through ``#`` =
    quarantined), then the :func:`lane_summary` aggregates. The grid is
    row-major in lane order, ``width`` lanes per row -- adjacent lanes
    in the sweep grid stay adjacent on screen, so a bad corner of the
    condition grid shows up as a bad corner of the heatmap."""
    rows = _lane_rows(lane_telemetry)
    lines = [f"lane strategy heatmap ({len(rows)} lanes; "
             + " ".join(f"{g}={n}" for g, n
                        in zip(_STRATEGY_GLYPHS, STRATEGY_NAMES)) + "):"]
    for start in range(0, len(rows), max(1, width)):
        chunk = rows[start:start + max(1, width)]
        glyphs = "".join(
            _STRATEGY_GLYPHS[r[3]] if 0 <= r[3] < len(_STRATEGY_GLYPHS)
            else "?" for r in chunk)
        lines.append(f"  {start:>6d}  {glyphs}")
    s = lane_summary(rows)
    if s.get("lanes"):
        it = s["iterations"]
        lines.append(f"  iterations min/med/max {it['min']}/"
                     f"{it['median']}/{it['max']}  total {it['total']}")
        lines.append(f"  chords total {s['chords_total']}  "
                     f"max {s['chords_max']}")
        lines.append("  residual decades  "
                     + "  ".join(f"1e{k}:{v}" for k, v
                                 in s["residual_decades"].items()))
        lines.append("  strategies  "
                     + "  ".join(f"{k}:{v}" for k, v
                                 in s["strategies"].items()))
        if s.get("tiers"):
            lines.append("  accepted-iterate tiers  "
                         + "  ".join(f"{k}:{v}" for k, v
                                     in s["tiers"].items()))
    return "\n".join(lines)


def tenant_lane_summaries(tenant_telemetry) -> list:
    """Per-tenant :func:`lane_summary` for a packed multi-tenant
    sweep's stacked telemetry (``[K, lanes, 5]`` numpy array, or a list
    of per-tenant ``[lanes, 5]`` arrays -- the coalescer records the
    latter, one per tenant, real tenants only)."""
    return [lane_summary(t) for t in (tenant_telemetry or [])]


def format_tenant_heatmaps(tenant_telemetry, width: int = 64) -> str:
    """The lane heatmap grouped by tenant: one
    :func:`format_lane_heatmap` block per tenant of a packed sweep,
    headed by the tenant index and its quarantine-ish tail counts so a
    poisoned tenant is visually separable from its clean co-tenants."""
    tenants = list(tenant_telemetry or [])
    if not tenants:
        return "no per-tenant lane telemetry"
    lines = [f"packed sweep: {len(tenants)} tenant(s)"]
    for k, tel in enumerate(tenants):
        s = lane_summary(tel)
        rescued = sum(v for name, v in (s.get("strategies") or {}).items()
                      if name not in ("clean", STRATEGY_NAMES[0]))
        lines.append(f"-- tenant {k}: {s.get('lanes', 0)} lanes, "
                     f"{rescued} non-clean --")
        lines.append(format_lane_heatmap(tel, width=width))
    return "\n".join(lines)


# -- elastic worker lifecycle (events = the kind="worker" records the
#    scheduler appends to events.jsonl / report["events"]) -------------

def worker_summary(events) -> dict:
    """Aggregate an elastic run's worker-lifecycle events: counts per
    action plus restart totals per worker -- the ``--workers`` header
    line. Pure host-side; tolerant of mixed event streams (non-worker
    kinds are ignored)."""
    evs = [e for e in (events or []) if e.get("kind") == "worker"]
    actions: dict[str, int] = {}
    restarts: dict[str, int] = {}
    packs = 0
    pack_tenants = 0
    tenant_quarantined: dict[str, int] = {}
    for e in evs:
        act = str(e.get("action", "?"))
        actions[act] = actions.get(act, 0) + 1
        if act == "restart":
            lbl = str(e.get("label", "?"))
            restarts[lbl] = restarts.get(lbl, 0) + 1
        if act == "pack-flush":
            packs += 1
            tq = e.get("tenant_quarantined") or []
            pack_tenants += int(e.get("tenants", len(tq)) or 0)
            for k, n in enumerate(tq):
                if n:
                    key = f"{e.get('label', '?')}[{k}]"
                    tenant_quarantined[key] = (
                        tenant_quarantined.get(key, 0) + int(n))
    out = {"n_events": len(evs), "actions": actions,
           "restarts": restarts}
    if packs:
        out["packs"] = packs
        out["pack_tenants"] = pack_tenants
        out["tenant_quarantined"] = tenant_quarantined
    return out


def format_worker_timeline(events) -> str:
    """Chronological rendering of the lease/restart lifecycle: one line
    per worker event, timestamped relative to the first (the scheduler
    stamps wall-clock ``t``), action-aligned so spawn/exit/steal
    cascades read top to bottom::

          +0.000s  worker:0        spawn        pid=1234 incarnation=0
          +2.143s  worker:0        exit         signal-death (rc=-9)
          +2.150s  lease:t00000_4  task-killed  kills=1
    """
    evs = [e for e in (events or []) if e.get("kind") == "worker"]
    if not evs:
        return "no worker lifecycle events"
    known = [e for e in evs if isinstance(e.get("t"), (int, float))]
    t0 = min((e["t"] for e in known), default=0.0)
    lines = []
    s = worker_summary(evs)
    lines.append(f"worker lifecycle: {s['n_events']} event(s); "
                 + "  ".join(f"{k}:{v}"
                             for k, v in sorted(s["actions"].items())))
    for e in evs:
        t = e.get("t")
        stamp = (f"+{t - t0:8.3f}s" if isinstance(t, (int, float))
                 else " " * 10)
        extra = []
        for key in ("pid", "incarnation", "returncode", "exit_kind",
                    "kills", "cause", "owner", "stolen_from", "mid",
                    "children", "attempt", "delay_s", "restarts",
                    "task", "n_failed", "detail", "lanes", "tenants",
                    "k_bucket", "pack_occupancy", "tenant_quarantined"):
            if key in e and e[key] is not None:
                extra.append(f"{key}={e[key]}")
        lines.append(f"  {stamp}  {str(e.get('label', '?')):<18} "
                     f"{str(e.get('action', e.get('rung', '?'))):<16} "
                     + " ".join(extra))
    return "\n".join(lines)
