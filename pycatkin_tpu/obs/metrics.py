"""Process-wide metrics registry: counters, gauges, histograms.

Instruments are wired through the hot layers (compile_pool, batch,
ladder, retry, dispatch, host_sync) as pure host-side bookkeeping --
one lock and a dict update per increment, zero device work. Two export
surfaces:

- :func:`snapshot` -- a JSON-able dict (attached to bench results and
  asserted by tests);
- :func:`prometheus_text` -- Prometheus text exposition (version
  0.0.4), validated by :func:`validate_prometheus_text` in the
  ``make obs-check`` CI lane.

Metric names follow Prometheus convention (``pycatkin_*_total`` for
counters); the catalog lives in docs/observability.md. The registry is
process-wide and resettable (:func:`reset`) so tests can assert exact
deltas. No JAX imports here -- the module must stay importable from
lint/CI tooling.
"""

from __future__ import annotations

import re
import threading

# Powers-of-ten seconds ladder: wide enough for both a 50 ms CPU smoke
# sweep and a multi-minute cold compile.
DEFAULT_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0, 60.0, 600.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _label_key(labels: dict) -> str:
    """Canonical, sorted ``k="v"`` encoding (also the snapshot key;
    empty string for an unlabeled sample)."""
    return ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))


class _Instrument:
    """One named metric; holds one value (or histogram state) per
    label-set under the registry lock."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, lock: threading.Lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help_text
        self._lock = lock
        self._values: dict = {}   # guarded-by: _lock

    def _check_labels(self, labels: dict):
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")

    def values(self) -> dict:
        with self._lock:
            return dict(self._values)


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels):
        self._check_labels(labels)
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name, help_text, lock, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels):
        self._check_labels(labels)
        key = _label_key(labels)
        value = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = {"sum": 0.0, "count": 0,
                      "buckets": [0] * len(self.buckets)}
                self._values[key] = st
            st["sum"] += value
            st["count"] += 1
            for i, le in enumerate(self.buckets):
                if value <= le:
                    st["buckets"][i] += 1

    def observe_many(self, values, **labels):
        """Bulk-observe an array/iterable of values as ONE lock
        acquisition (the per-lane telemetry feed observes 10^4-10^5
        lane samples per sweep; a Python-loop ``observe`` per lane
        would dominate the host tail). Uses numpy's searchsorted when
        available, falling back to a pure-Python count."""
        self._check_labels(labels)
        key = _label_key(labels)
        try:
            import numpy as np
            vals = np.asarray(values, dtype=float).ravel()
            if vals.size == 0:
                return
            counts = np.searchsorted(np.sort(vals),
                                     np.asarray(self.buckets),
                                     side="right")
            total, n = float(vals.sum()), int(vals.size)
            per_bucket = [int(c) for c in counts]
        except ImportError:       # pure-Python fallback, same result
            vals = [float(v) for v in values]
            if not vals:
                return
            total, n = sum(vals), len(vals)
            per_bucket = [sum(1 for v in vals if v <= le)
                          for le in self.buckets]
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = {"sum": 0.0, "count": 0,
                      "buckets": [0] * len(self.buckets)}
                self._values[key] = st
            st["sum"] += total
            st["count"] += n
            for i, c in enumerate(per_bucket):
                st["buckets"][i] += c

    def values(self) -> dict:
        with self._lock:
            return {k: {"sum": st["sum"], "count": st["count"],
                        "buckets": list(st["buckets"])}
                    for k, st in self._values.items()}


class MetricsRegistry:
    """Get-or-create instrument registry; one per process by default
    (:data:`default_registry`), fresh instances for tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}  # guarded-by: _lock

    def _get(self, cls, name, help_text, **kwargs):
        with self._lock:
            inst = self._metrics.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}")
            return inst
        inst = cls(name, help_text, self._lock, **kwargs)
        with self._lock:
            return self._metrics.setdefault(name, inst)

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_text, buckets=buckets)

    def reset(self):
        """Drop every instrument (tests assert exact deltas)."""
        with self._lock:
            self._metrics.clear()

    # -- exports -------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able ``{"counters": {name: {labelkey: value}}, ...}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            out[m.kind + "s"][m.name] = m.values()
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (0.0.4) of every instrument."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            vals = m.values()
            if isinstance(m, Histogram):
                for key, st in sorted(vals.items()):
                    cum = 0
                    for le, n in zip(m.buckets, st["buckets"]):
                        cum += n
                        lbl = (key + "," if key else "") + f'le="{le}"'
                        lines.append(
                            f"{m.name}_bucket{{{lbl}}} {cum}")
                    lbl = (key + "," if key else "") + 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket{{{lbl}}} {st['count']}")
                    suffix = f"{{{key}}}" if key else ""
                    lines.append(f"{m.name}_sum{suffix} {st['sum']}")
                    lines.append(f"{m.name}_count{suffix} {st['count']}")
            else:
                for key, v in sorted(vals.items()):
                    suffix = f"{{{key}}}" if key else ""
                    lines.append(f"{m.name}{suffix} {v}")
        return "\n".join(lines) + "\n"


default_registry = MetricsRegistry()


def counter(name: str, help_text: str = "") -> Counter:
    return default_registry.counter(name, help_text)


def gauge(name: str, help_text: str = "") -> Gauge:
    return default_registry.gauge(name, help_text)


def histogram(name: str, help_text: str = "",
              buckets=DEFAULT_BUCKETS) -> Histogram:
    return default_registry.histogram(name, help_text, buckets=buckets)


def snapshot() -> dict:
    return default_registry.snapshot()


def prometheus_text() -> str:
    return default_registry.prometheus_text()


def reset():
    default_registry.reset()


# -- exposition lint ---------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                  # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\""     # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\")*\})?"  # more labels
    r" (-?[0-9.]+([eE][-+]?[0-9]+)?|[+-]Inf|NaN)$")

_VALID_TYPES = frozenset({"counter", "gauge", "histogram", "summary",
                          "untyped"})


def validate_prometheus_text(text: str) -> list:
    """Lint one exposition blob; returns a list of problem strings
    (empty = valid). Checks line grammar, declared TYPEs, and that
    every histogram carries its ``+Inf`` bucket and ``_sum``/``_count``
    series -- the failure modes a hand-rolled exporter actually has."""
    problems = []
    if text and not text.endswith("\n"):
        problems.append("exposition must end with a newline")
    typed: dict = {}
    seen_hist_parts: dict = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                problems.append(f"line {i}: malformed comment: {line!r}")
            elif parts[1] == "TYPE":
                if len(parts) < 4 or parts[3] not in _VALID_TYPES:
                    problems.append(
                        f"line {i}: bad TYPE declaration: {line!r}")
                else:
                    typed[parts[2]] = parts[3]
            continue
        if not _SAMPLE_RE.match(line):
            problems.append(f"line {i}: malformed sample: {line!r}")
            continue
        name = re.split(r"[{ ]", line, 1)[0]
        for base, t in typed.items():
            if t == "histogram" and name.startswith(base + "_"):
                part = name[len(base) + 1:]
                if part in ("bucket", "sum", "count"):
                    parts = seen_hist_parts.setdefault(base, set())
                    parts.add(part)
                    if part == "bucket" and 'le="+Inf"' in line:
                        parts.add("+Inf")
    for base, t in typed.items():
        if t != "histogram":
            continue
        parts = seen_hist_parts.get(base, set())
        for need in ("bucket", "sum", "count", "+Inf"):
            if parts and need not in parts:
                problems.append(
                    f"histogram {base} missing {need} series")
    return problems
