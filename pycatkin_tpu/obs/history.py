"""Rolling bench history and noise-aware regression baselines.

The BENCH_r*.json records the driver checks in at every round are a
performance time series nobody was reading except by eyeball. This
module ingests them into a rolling history, computes noise-aware
baselines (median +/- MAD per metric -- a single flaky round cannot
drag a mean), and flags the metrics of a candidate record that sit
beyond the noise band in the BAD direction, with dominant-span and
cost-ledger attribution when the records carry the forensics to name a
culprit. ``tools/perfwatch.py`` is the CLI face; ``make perfwatch`` /
the CI lane run its selftest (an injected 2x regression must be
flagged, an in-noise wobble must not).

Pure host-side JSON shuffling -- no JAX, importable from CI tooling.
"""

from __future__ import annotations

import glob
import json
import os
import re

# Tracked metrics: JSON key -> direction ("higher"/"lower" = which way
# is good). Keys missing from a record are skipped, so the same table
# serves full-bench and smoke records.
TRACKED_METRICS = {
    "value": "higher",              # pts/s, the headline throughput
    "mfu": "higher",                # achieved model-flop utilization
    "prewarm_warm_s": "lower",      # warm-disk restart cost
    "prewarm_warm_pack_s": "lower",  # warm-from-pack boot cost
    "max_over_median": "lower",     # trial variance
    # Serving SLOs (serve-soak records and bench smoke's serve gate;
    # pulled from the record's "serve" sub-object by extract_metrics).
    "serve_p50_s": "lower",         # median request latency
    "serve_p99_s": "lower",         # tail request latency
    "serve_zero_compile_rate": "higher",  # post-warmup compile hygiene
    "serve_mean_occupancy": "higher",     # achieved pack occupancy
    # Fleet-tier SLOs (chaos-drill records and bench smoke's router
    # gate; pulled from the record's "router" sub-object).
    "router_availability": "higher",  # answered-ok fraction under chaos
    "failover_p99_s": "lower",        # tail failure-to-answer latency
    # Durable-serving SLOs (chaos-drill router-crash mode and bench
    # smoke's durable gate; pulled from the "durable" sub-object).
    "router_recovery_s": "lower",     # SIGKILL-to-routable router wall
    "journal_replay_s": "lower",      # boot replay of the WAL backlog
    # Linalg microbench (bench.py --linalg; pulled from the record's
    # "linalg" sub-object): per-ABI-bucket MFU of the batched
    # factorize+solve against the MEASURED per-backend matmul ceiling
    # (docs/perf_pallas_linalg.md), so a direction-kernel regression
    # is caught bucket-by-bucket.
    "linalg_mfu_16": "higher",
    "linalg_mfu_32": "higher",
    "linalg_mfu_128": "higher",
    "linalg_mfu_512": "higher",
    # Fused transient lane (bench.py --transient; pulled from the
    # record's "transient" sub-object): whole-sweep dense-output
    # throughput of the fused single-dispatch path
    # (docs/perf_transient.md), baselined per backend like everything
    # else.
    "transient_pts_per_s": "higher",
}

# A regression must clear BOTH gates: beyond ``mad_k`` median absolute
# deviations of the history (noise-aware) AND beyond ``rel_floor``
# relative change (so a dead-quiet history with MAD ~ 0 does not flag
# every rounding wobble).
DEFAULT_MAD_K = 4.0
DEFAULT_REL_FLOOR = 0.10

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# Records that do not name their backend predate the field; every
# checked-in round before it was a TPU v5e run, so that is the
# historical default.
DEFAULT_BACKEND = "tpu"


def record_backend(record: dict) -> str:
    """The executing backend a bench record was measured on. CPU and
    TPU rounds are different physical experiments (throughput differs
    by orders of magnitude), so baselines must never mix them."""
    rec = _unwrap(record)
    return str(rec.get("backend") or DEFAULT_BACKEND).lower()


def _unwrap(record: dict) -> dict:
    """A BENCH_r*.json as checked in wraps the bench's JSON line under
    ``{"parsed": {...}}``; raw records pass through unchanged."""
    if isinstance(record, dict) and isinstance(record.get("parsed"),
                                               dict):
        return record["parsed"]
    return record if isinstance(record, dict) else {}


def extract_metrics(record: dict) -> dict:
    """``{metric: float}`` of every tracked, present, finite metric in
    one (possibly wrapped) bench record. ``mfu`` is pulled from the
    cost-ledger totals when the record carries one; ``serve_*``
    metrics fall back to the ``serve`` sub-object a serve-soak record
    (or the smoke gate) nests them under; ``router_availability`` /
    ``failover_p99_s`` likewise fall back to the ``router``
    sub-object of a chaos-drill record, ``router_recovery_s`` /
    ``journal_replay_s`` to its ``durable`` sub-object, and
    ``linalg_mfu_<bucket>`` to the ``linalg`` sub-object a
    ``bench.py --linalg`` record nests them under (as
    ``mfu_<bucket>``), and ``transient_pts_per_s`` to the
    ``transient`` sub-object of a ``bench.py --transient`` record."""
    rec = _unwrap(record)
    serve = rec.get("serve") if isinstance(rec.get("serve"),
                                           dict) else {}
    router = rec.get("router") if isinstance(rec.get("router"),
                                             dict) else {}
    durable = rec.get("durable") if isinstance(rec.get("durable"),
                                               dict) else {}
    linalg = rec.get("linalg") if isinstance(rec.get("linalg"),
                                             dict) else {}
    transient = rec.get("transient") if isinstance(
        rec.get("transient"), dict) else {}
    out = {}
    for key in TRACKED_METRICS:
        v = rec.get(key)
        if key == "mfu" and v is None:
            v = ((rec.get("cost_ledger") or {}).get("totals")
                 or {}).get("mfu")
        if v is None and key.startswith("serve_"):
            v = serve.get(key[len("serve_"):])
        if v is None and key == "router_availability":
            v = router.get("availability")
        if v is None and key == "failover_p99_s":
            v = router.get("failover_p99_s")
        if v is None and key in ("router_recovery_s",
                                 "journal_replay_s"):
            v = durable.get(key)
        if v is None and key.startswith("linalg_"):
            v = linalg.get(key[len("linalg_"):])
        if v is None and key == "transient_pts_per_s":
            v = transient.get("transient_pts_per_s")
        try:
            f = float(v)
        except (TypeError, ValueError):
            continue
        out[key] = f
    return out


def load_history(root: str, pattern: str = "BENCH_r*.json") -> list:
    """``[{"round", "path", "record", "metrics"}]`` for every parseable
    BENCH round file under ``root``, ascending round order. Unreadable
    files are skipped -- history ingest must never kill the watcher."""
    out = []
    for path in glob.glob(os.path.join(root, pattern)):
        m = _ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                record = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = _unwrap(record)
        out.append({"round": int(m.group(1)), "path": path,
                    "record": rec, "metrics": extract_metrics(rec),
                    "backend": record_backend(rec)})
    out.sort(key=lambda e: e["round"])
    return out


def baseline(values: list) -> dict | None:
    """Noise-aware baseline of one metric's history: ``{"median",
    "mad", "n"}`` (MAD = median absolute deviation -- robust to one
    flaky round in a way a mean/stddev is not). None for an empty
    history."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    n = len(vals)
    med = (vals[n // 2] if n % 2
           else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    dev = sorted(abs(v - med) for v in vals)
    mad = (dev[n // 2] if n % 2
           else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
    return {"median": med, "mad": mad, "n": n}


def flag_regressions(history: list, candidate: dict,
                     mad_k: float = DEFAULT_MAD_K,
                     rel_floor: float = DEFAULT_REL_FLOOR,
                     min_history: int = 3) -> list:
    """Findings for every tracked metric of ``candidate`` (a bench
    record, wrapped or raw) that regressed beyond the noise band of
    ``history`` (the output of :func:`load_history`, or any list of
    entries carrying ``"metrics"``).

    A metric is flagged only when (a) the history holds at least
    ``min_history`` SAME-BACKEND samples of it (a CPU smoke round
    compared against TPU throughput history would flag a 100x
    "regression" that is really a hardware change -- see
    :func:`record_backend`), and (b) the candidate sits beyond
    ``max(mad_k * MAD, rel_floor * |median|)`` of the median in the bad
    direction. Each finding carries the baseline and the attribution of
    :func:`attribute_regression`.
    """
    cand = extract_metrics(candidate)
    cand_backend = record_backend(candidate)
    history = [e for e in history
               if e.get("backend",
                        record_backend(e.get("record") or {}))
               == cand_backend]
    findings = []
    for metric, value in sorted(cand.items()):
        series = [e["metrics"][metric] for e in history
                  if metric in e.get("metrics", {})]
        base = baseline(series)
        if base is None or base["n"] < min_history:
            continue
        band = max(mad_k * base["mad"],
                   rel_floor * abs(base["median"]))
        delta = value - base["median"]
        bad = (delta < -band
               if TRACKED_METRICS[metric] == "higher"
               else delta > band)
        if not bad:
            continue
        ratio = (value / base["median"] if base["median"] else None)
        findings.append({
            "metric": metric, "value": value,
            "median": base["median"], "mad": base["mad"],
            "n_history": base["n"],
            "band": band,
            "ratio": round(ratio, 4) if ratio is not None else None,
            "direction": TRACKED_METRICS[metric],
            "attribution": attribute_regression(candidate, history),
        })
    return findings


def attribute_regression(candidate: dict, history: list) -> dict:
    """Best-effort blame for a flagged record: the candidate's own
    dominant-span outlier attribution (``outlier_span`` /
    ``outlier``), plus the cost-ledger programs whose per-program MFU
    dropped the most against the newest history record that also
    carries a ledger. Every probe degrades to absent keys."""
    cand = _unwrap(candidate)
    out: dict = {}
    span = cand.get("outlier") or cand.get("outlier_span")
    if isinstance(span, dict):
        out["dominant_span"] = {k: span[k]
                               for k in ("label", "extra_s")
                               if k in span}
    cled = (cand.get("cost_ledger") or {}).get("programs") or {}
    prior_led = {}
    for entry in reversed(history):
        rec = entry.get("record") or {}
        led = (rec.get("cost_ledger") or {}).get("programs") or {}
        if led:
            prior_led = led
            break
    drops = []
    for key, row in cled.items():
        mfu = row.get("mfu") or row.get("achieved_flops_per_s")
        prev = prior_led.get(key, {})
        pmfu = prev.get("mfu") or prev.get("achieved_flops_per_s")
        if mfu is None or pmfu is None or pmfu <= 0:
            continue
        if mfu < pmfu:
            drops.append({"key": key,
                          "label": row.get("label") or row.get("kind"),
                          "ratio": round(mfu / pmfu, 4)})
    if drops:
        drops.sort(key=lambda d: d["ratio"])
        out["cost_ledger_drops"] = drops[:3]
    return out
