"""Run-scoped observability: trace contexts, metrics, exports.

The telemetry floor under the robustness and parallel layers
(docs/observability.md). Three pieces, each importable without JAX so
tooling (``tools/obsview.py``, CI lanes) stays cheap:

- :mod:`pycatkin_tpu.obs.trace` -- nestable :class:`RunTrace` contexts
  (contextvars-based, thread-safe) that replace the old process-global
  event list in :mod:`pycatkin_tpu.utils.profiling`. The legacy
  ``record_event``/``span``/``host_sync``/``sync_budget`` API keeps
  working by routing to the ambient trace (root-trace fallback).
- :mod:`pycatkin_tpu.obs.metrics` -- a process-wide registry of
  counters/gauges/histograms wired through the hot layers, exportable
  as a JSON snapshot or Prometheus text exposition.
- :mod:`pycatkin_tpu.obs.export` / :mod:`pycatkin_tpu.obs.manifest` --
  Chrome ``trace_event`` JSON (Perfetto-loadable), span-tree summaries
  and per-lane telemetry heatmaps shared by bench.py and
  ``tools/obsview.py``, and the self-describing run manifest attached
  to bench JSON, journal headers and forensics reports.
- :mod:`pycatkin_tpu.obs.costs` / :mod:`pycatkin_tpu.obs.history` --
  the device cost ledger (compile-time FLOP/byte truth per program,
  joined with dispatch walls into per-program MFU) and the rolling
  bench history + noise-aware regression flagging behind
  ``tools/perfwatch.py``.
"""

from .costs import (CostLedger, device_peak,  # noqa: F401
                    flops_per_iteration, harvest_cost, ledger_snapshot)
from .export import (attribute_outlier, chrome_trace,  # noqa: F401
                     format_lane_heatmap, format_span_table,
                     format_tenant_heatmaps, format_worker_timeline,
                     lane_summary, load_trace, span_summary, span_tree,
                     tenant_lane_summaries, top_spans, worker_summary,
                     write_chrome_trace)
from .history import (baseline, extract_metrics,  # noqa: F401
                      flag_regressions, load_history)
from .manifest import run_manifest  # noqa: F401
from .metrics import (counter, default_registry, gauge,  # noqa: F401
                      histogram, prometheus_text,
                      validate_prometheus_text)
from .metrics import snapshot as metrics_snapshot  # noqa: F401
from .trace import (RunTrace, current_span_id, current_trace,  # noqa: F401
                    root_trace, run_trace)

__all__ = [
    "RunTrace", "run_trace", "current_trace", "current_span_id",
    "root_trace", "chrome_trace", "write_chrome_trace", "load_trace",
    "span_tree", "span_summary", "top_spans", "format_span_table",
    "attribute_outlier", "lane_summary", "format_lane_heatmap",
    "tenant_lane_summaries", "format_tenant_heatmaps",
    "worker_summary", "format_worker_timeline",
    "run_manifest", "counter", "gauge",
    "histogram", "default_registry", "metrics_snapshot",
    "prometheus_text", "validate_prometheus_text",
    "CostLedger", "harvest_cost", "ledger_snapshot", "device_peak",
    "flops_per_iteration",
    "load_history", "baseline", "flag_regressions", "extract_metrics",
]
