"""The run manifest: a self-describing record of what executed.

One dict answering, machine-readably, "what code, what backend, what
knobs" for a run -- attached to bench JSON, journal headers
(robustness/journal.py) and forensics reports (robustness/forensics.py)
so an artifact can be interpreted long after the shell that produced it
is gone. Every field is best-effort: a manifest must never kill the
run it describes, so each probe degrades to None instead of raising.

Schema (docs/observability.md): ``schema``, ``git``, ``backend``
(platform / device_count / device_kind), ``mesh`` (when given), ``env``
(every SET ``PYCATKIN_*`` knob, verbatim), ``registered_env_keys`` (the
PCL006 registry, so a reader can tell "unset" from "unknown"),
``jax_platforms``, ``abi`` (enabled + bucket fingerprint when a spec is
given), ``aot_key_version``, ``program_budget``, ``cost_ledger`` (the
obs/costs.py snapshot with per-program MFU, None until something
dispatched).
"""

from __future__ import annotations

import os
import subprocess
import sys

SCHEMA = "pycatkin-run-manifest/v1"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _git_describe():
    try:
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            cwd=_REPO_ROOT, capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        return None


def _backend_info():
    # Only report a backend that is ALREADY initialized: a manifest
    # probe must not pay (or fail) a backend bring-up of its own.
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        devs = jax.devices()
        return {"platform": devs[0].platform,
                "device_count": len(devs),
                "device_kind": devs[0].device_kind}
    except Exception:
        return None


def _mesh_info(mesh):
    if mesh is None:
        return None
    try:
        return {"devices": int(mesh.devices.size),
                "axis_names": [str(a) for a in mesh.axis_names],
                "shape": {str(k): int(v)
                          for k, v in dict(mesh.shape).items()}}
    except Exception:
        return None


def _registered_env_keys():
    try:
        from ..lint.env_registry import DOC_RELPATH, registered_keys
        return sorted(registered_keys(
            os.path.join(_REPO_ROOT, DOC_RELPATH)))
    except Exception:
        return None


def _abi_info(spec):
    info = {"enabled": False, "bucket": None}
    try:
        from ..frontend import abi
        info["enabled"] = abi.abi_enabled()
        if spec is not None:
            if isinstance(spec, abi.AbiLowered):
                info["bucket"] = spec.abi_fingerprint
            else:
                low = abi.maybe_lower(spec)
                if low is not None:
                    info["bucket"] = low.abi_fingerprint
    except Exception:
        pass
    return info


def _aot_key_version():
    try:
        from ..parallel.compile_pool import _KEY_VERSION
        return _KEY_VERSION
    except Exception:
        return None


def _cost_ledger():
    # Only when programs actually ran: an empty ledger means the run
    # never dispatched a registered executable, and None reads better
    # in the manifest than an all-zero snapshot.
    try:
        from . import costs
        if len(costs.default_ledger) == 0:
            return None
        return costs.ledger_snapshot()
    except Exception:
        return None


def _program_budget():
    # batch imports JAX; only consult it when the caller already did.
    if "pycatkin_tpu.parallel.batch" not in sys.modules:
        return None
    try:
        from ..parallel.batch import PREWARM_PROGRAM_BUDGET
        return int(PREWARM_PROGRAM_BUDGET)
    except Exception:
        return None


def run_manifest(mesh=None, spec=None) -> dict:
    """Build the manifest (see module docstring). ``mesh`` and ``spec``
    are optional context the caller already holds; everything else is
    probed from the process environment."""
    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith("PYCATKIN_")}
    return {
        "schema": SCHEMA,
        "git": _git_describe(),
        "backend": _backend_info(),
        "mesh": _mesh_info(mesh),
        "env": env,
        "registered_env_keys": _registered_env_keys(),
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "abi": _abi_info(spec),
        "aot_key_version": _aot_key_version(),
        "program_budget": _program_budget(),
        "cost_ledger": _cost_ledger(),
    }
