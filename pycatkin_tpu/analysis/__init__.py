from .energy_span import Energy, energy_span_model
from .grid import (FAIL_CONSERVATION, FAIL_RATE, average_neighborhood,
                   classify_failures, convergence_heatmap, make_heatmap)
from .uncertainty import Uncertainty
