from .energy_span import Energy, energy_span_model
