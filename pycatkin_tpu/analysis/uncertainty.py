"""Uncertainty quantification: correlated-noise Monte Carlo over the
energy landscape.

Reference semantics (/root/reference/pycatkin/classes/uncertainty.py:6-125):
per run, ONE Gaussian draw N(mu, sigma^2) is shared by every adsorbate
that appears in a reaction (energies are correlated -- a systematic DFT
functional error moves all binding energies together), and each
transition state is perturbed by that same draw scaled by an independent
U(0,1) variate. The reference then deep-copies the system per run and
integrates serially; here the noise vectors are just lanes of
``Conditions.eps`` and ALL runs (base + nruns) integrate as one batched
device program.

``get_mean_property_value`` keeps the reference's callback API (the
property handle receives a solved system-like object per run) while the
solves themselves stay batched.

Deliberate divergence from the reference: ``set_correlated_state_noises``
(reference uncertainty.py:67-96) OVERWRITES any pre-existing energy
modifier with the noise; here the noise is ADDED on top of baseline
``add_to_energy`` modifiers (entropy corrections etc.), so systems that
carry physical baseline modifiers keep them under UQ. For a reference-
identical ensemble, clear the modifiers before sampling.
"""

from __future__ import annotations

import numpy as np

from ..frontend.reactions import ReactionDerivedReaction
from ..frontend.states import ADSORBATE, TS
from ..parallel.batch import batch_transient, stack_conditions
from ..solvers.ode import log_time_grid


class Uncertainty:

    def __init__(self, sys, mu: float = 0.0, sigma: float = 0.01,
                 nruns: int = 1, seed: int = 0):
        self.sys = sys.copy()
        self.mu = mu
        self.sigma = sigma
        self.nruns = nruns
        self.rng = np.random.default_rng(seed)
        self.noisy_sys = None
        self.state_noises = None

    # ------------------------------------------------------------------
    def _reaction_states(self):
        """(adsorbate names, TS names) reachable through reactions,
        following ReactionDerivedReaction energy borrowing (reference
        uncertainty.py:44-65)."""
        ads, ts = [], []
        for rx in self.sys.reactions.values():
            base = (rx.base_reaction
                    if isinstance(rx, ReactionDerivedReaction) else rx)
            for s in list(base.reactants) + list(base.products):
                if s.state_type == ADSORBATE and s.name not in ads:
                    ads.append(s.name)
            for s in (base.TS or []):
                if s.name not in ts:
                    ts.append(s.name)
        return ads, ts

    def get_correlated_state_noises(self) -> dict:
        """One run's name -> noise map: shared Gaussian for adsorbates,
        Gaussian x U(0,1) per transition state."""
        noise = float(self.rng.normal(loc=self.mu, scale=self.sigma))
        ads, ts = self._reaction_states()
        noises = {name: noise for name in ads}
        for name in ts:
            noises[name] = noise * float(self.rng.uniform())
        return noises

    def noise_eps(self, state_noises: dict) -> np.ndarray:
        """Compile a name->noise map into an eps vector for Conditions."""
        spec = self.sys.spec
        eps = np.zeros(spec.n_species)
        for name, val in state_noises.items():
            eps[spec.sindex(name)] = val
        return eps

    # ------------------------------------------------------------------
    def get_noisy_sys_samples(self):
        """Solve base + nruns noisy transients as ONE batched program
        (replaces the reference's serial deepcopy-and-solve loop,
        uncertainty.py:98-113). Populates self.noisy_sys (run ->
        solved system view) and self.state_noises."""
        sys = self.sys
        spec = sys.spec
        self.state_noises = {0: {}}
        conds = [sys.conditions()]
        for run in range(1, self.nruns + 1):
            noises = self.get_correlated_state_noises()
            self.state_noises[run] = noises
            conds.append(sys.conditions(
                eps_extra={k: v for k, v in noises.items()}))
        batched = stack_conditions(conds)

        times = sys.params["times"]
        grid = np.asarray(log_time_grid(times[0], times[-1],
                                        sys.params.get("n_out", 300)))
        ys, ok = batch_transient(spec, batched, grid, sys._ode_options())
        ys = np.asarray(ys)
        if not bool(np.all(np.asarray(ok))):
            print("Warning: some UQ transients did not integrate cleanly")

        self.noisy_sys = {}
        for run in range(self.nruns + 1):
            # Full copy with the run's noise applied as energy modifiers,
            # so property handles that recompute quantities (rates, TOF,
            # re-solves) see the same perturbed landscape the batched
            # solve used.
            view = sys.copy()
            for name, val in self.state_noises[run].items():
                st = view.states[name]
                st.set_energy_modifier((st.add_to_energy or 0.0) + val)
            view.times = grid
            view.solution = ys[run]
            view.full_steady = None
            self.noisy_sys[run] = view
        return self.noisy_sys

    def get_mean_property_value(self, property_handle):
        """(values, mean, std) of ``property_handle(sys)`` over the noisy
        ensemble; index 0 is the unperturbed base run, excluded from the
        statistics (reference uncertainty.py:115-125)."""
        self.get_noisy_sys_samples()
        values = np.array([property_handle(self.noisy_sys[i])
                           for i in sorted(self.noisy_sys.keys())])
        return values, np.mean(values[1:]), np.std(values[1:])
