"""Energy landscapes and the Kozuch-Shaik energy span model.

Capability parity with the reference ``Energy`` class
(/root/reference/pycatkin/classes/energy.py:10-318): relative free /
electronic landscapes over ordered minima (each a *list* of states summed)
and the energy-span TOF estimate with TDTS/TDI identification and degrees
of TOF control. The numerical core (:func:`energy_span_model`) is a pure
jittable function of the landscape vector, so temperature sweeps vmap.

Drawing utilities live in :mod:`pycatkin_tpu.api.plotting`.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from ..constants import R, eVtokJ, h, kB

eVtoJmol = eVtokJ * 1.0e3


class EnergySpanResult(NamedTuple):
    tof: jnp.ndarray          # turnover frequency [1/s]
    espan: jnp.ndarray        # energy span [eV]
    i_tdts: jnp.ndarray       # landscape index of the TOF-determining TS
    i_tdi: jnp.ndarray        # landscape index of the TOF-determining interm.
    x_ts: jnp.ndarray         # [n_min] degree of TOF control per TS entry
    x_int: jnp.ndarray        # [n_min] degree of TOF control per intermediate
    eapp: jnp.ndarray         # apparent activation energy [kJ/mol]
    drxn: jnp.ndarray         # overall reaction free energy [J/mol]


def energy_span_model(vals: jnp.ndarray, is_ts: jnp.ndarray,
                      T) -> EnergySpanResult:
    """Energy span model over a relative landscape (reference
    energy.py:238-310).

    vals: [n_min] energies in eV relative to the first minimum; is_ts:
    [n_min] 1 for transition-state entries. The XTOF matrix couples every
    TS i with every intermediate j in (first, last) exclusive; when i >= j
    the overall reaction energy is subtracted (the cycle wraps).
    """
    n = vals.shape[0]
    vj = vals * eVtoJmol
    drxn = vj[-1]
    idx = jnp.arange(n)
    row_ok = (is_ts > 0) & (idx <= n - 2)
    col_ok = (is_ts == 0) & (idx >= 1) & (idx <= n - 2)
    mask = row_ok[:, None] & col_ok[None, :]
    wrap = (idx[:, None] >= idx[None, :]).astype(vj.dtype)
    X = vj[:, None] - vj[None, :] - wrap * drxn
    expX = jnp.where(mask, jnp.exp(X / (R * T)), 0.0)
    den = jnp.sum(expX)
    x_ts = jnp.sum(expX, axis=1) / den     # [n], nonzero on TS rows
    x_int = jnp.sum(expX, axis=0) / den    # [n], nonzero on intermediate cols
    i_tdts = jnp.argmax(x_ts)
    i_tdi = jnp.argmax(x_int)
    tof = (kB * T / h) * jnp.exp((-drxn / (R * T)) - 1.0) / den
    espan = vals[i_tdts] - vals[i_tdi]
    eapp = jnp.log(h * tof / (kB * T)) * (-R * T) * 1.0e-3
    return EnergySpanResult(tof=tof, espan=espan, i_tdts=i_tdts,
                            i_tdi=i_tdi, x_ts=x_ts, x_int=x_int,
                            eapp=eapp, drxn=drxn)


class Energy:
    """An ordered energy landscape built from lists of states.

    ``minima`` is a list of lists of State objects whose energies are
    summed per entry (reference energy.py:12-60); an entry containing any
    TS-typed state is a transition-state entry.
    """

    def __init__(self, name="landscape", minima=None, labels=None):
        self.name = name
        self.minima = minima
        if labels is not None:
            self.labels = labels
        else:
            self.labels = [entry[0].name for entry in minima]
        assert len(self.labels) == len(self.minima)
        self.energy_landscape = None
        self._system = None  # set by System.add_energy_landscape

    # ------------------------------------------------------------------
    def entry_matrix(self, snames: Sequence[str]) -> np.ndarray:
        """[n_min, n_s] counts of each species in each landscape entry."""
        sindex = {n: i for i, n in enumerate(snames)}
        M = np.zeros((len(self.minima), len(snames)))
        for i, entry in enumerate(self.minima):
            for st in entry:
                M[i, sindex[st.name]] += 1.0
        return M

    @property
    def is_ts(self) -> np.ndarray:
        return np.array([1.0 if any(s.state_type == "TS" for s in entry)
                         else 0.0 for entry in self.minima])

    def construct_energy_landscape(self, T, p, verbose=False):
        """Relative free/electronic landscape at (T, p) (reference
        energy.py:39-60). Requires an owning System (for the engine)."""
        sys = self._system
        assert sys is not None, "Energy landscape is not attached to a System"
        fe = sys.free_energy_table(T=T, p=p)
        M = self.entry_matrix(sys.snames)
        free = M @ np.asarray(fe.gfree)
        elec = M @ np.asarray(fe.gelec)
        is_ts = self.is_ts
        self.energy_landscape = {
            "free": {i: float(v - free[0]) for i, v in enumerate(free)},
            "electronic": {i: float(v - elec[0]) for i, v in enumerate(elec)},
            "isTS": {i: int(t) for i, t in enumerate(is_ts)},
            "T": T, "p": p,
        }
        return self.energy_landscape

    def _landscape_vector(self, T, p, etype="free", verbose=False):
        # Always recompute (reference energy.py:39-60 does the same): a
        # (T, p)-keyed cache silently serves stale landscapes after
        # descriptor/user-energy mutation at the same conditions.
        self.construct_energy_landscape(T=T, p=p, verbose=verbose)
        n = len(self.minima)
        return np.array([self.energy_landscape[etype][i] for i in range(n)])

    def evaluate_energy_span_model(self, T, p, etype="free", verbose=False,
                                   opath=None):
        """Reference-compatible evaluation (energy.py:238-318): returns
        (tof, Espan, TDTS, TDI, num_i, num_j, lTi, lIj)."""
        vals = self._landscape_vector(T, p, etype, verbose)
        is_ts = self.is_ts
        res = energy_span_model(jnp.asarray(vals), jnp.asarray(is_ts),
                                float(T))
        ts_rows = np.flatnonzero(is_ts > 0)
        int_cols = [i for i in range(1, len(vals) - 1) if is_ts[i] == 0]
        num_i = [float(res.x_ts[i]) for i in ts_rows]
        num_j = [float(res.x_int[j]) for j in int_cols]
        tdts = self.labels[int(res.i_tdts)]
        tdi = self.labels[int(res.i_tdi)]
        l_ti = [self.labels[i] for i in ts_rows]
        l_ij = [self.labels[i] for i in range(len(vals))
                if is_ts[i] == 0][1:-1]
        if verbose:
            print(f"Energy span ({T:.0f} K): TOF={float(res.tof):.3g} 1/s, "
                  f"Espan={float(res.espan):.3g} eV, TDTS={tdts}, TDI={tdi}")
        if opath is not None:
            with open(opath, "w") as fh:
                fh.write(str(float(res.tof)) + "\n")
                fh.write(", ".join([str(v) for v in num_i] + ["\n"]))
                fh.write(", ".join([str(v) for v in num_j] + ["\n"]))
        return (float(res.tof), float(res.espan), tdts, tdi,
                num_i, num_j, l_ti, l_ij)
