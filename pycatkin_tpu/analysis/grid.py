"""Descriptor-grid triage: failure classification, neighbor repair,
convergence and activity heatmaps.

Reference capability (/root/reference/pycatkin/functions/analysis.py):
descriptor scans routinely leave a few percent of grid points
unconverged; the workflow classifies each failure (site-conservation
violation vs rate residual, analysis.py:27-76), patches failed points
with the mean of their converged 8-neighbors (analysis.py:79-116), and
renders pass/fail plus smoothed log-TOF heatmaps (analysis.py:120-266).

Differences by design:
- reference ``check_convergence`` re-solves each failed point serially
  and hardcodes the COOx state names; here classification is vectorized
  over the already-collected batched diagnostics of ANY mechanism.
- reference ``average_neighborhood`` returns from inside its scan loop,
  so only the FIRST failed point is ever patched (analysis.py:116);
  here every failed point is repaired (documented fix, SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np

FAIL_CONSERVATION = "conservation"
FAIL_RATE = "rate"


def classify_failures(spec, results, coverage_tol: float = 5.0e-2):
    """Classify each failed lane of a batched SteadyStateResults.

    Returns (labels, detail): labels is a [lanes] object array with None
    for converged lanes, else FAIL_CONSERVATION (a site group does not
    sum to ~1 -- reference analysis.py:54-62) or FAIL_RATE (residual
    target missed, analysis.py:63-70).
    """
    x = np.asarray(results.x)
    ok = np.asarray(results.success).astype(bool)
    res = np.asarray(results.residual)
    sums = x @ np.asarray(spec.groups).T              # [lanes, n_g]
    cons_bad = np.any(np.abs(sums - 1.0) > coverage_tol, axis=-1)
    labels = np.full(ok.shape, None, dtype=object)
    labels[~ok & cons_bad] = FAIL_CONSERVATION
    labels[~ok & ~cons_bad] = FAIL_RATE
    detail = {
        "n_failed": int(np.sum(~ok)),
        "n_conservation": int(np.sum(~ok & cons_bad)),
        "n_rate": int(np.sum(~ok & ~cons_bad)),
        "worst_residual": float(np.max(res[~ok])) if np.any(~ok) else 0.0,
    }
    return labels, detail


def replay_lane(spec, conds, lane: int, x0=None,
                opts=None, strategies=("ptc", "lm"), verbose: bool = True):
    """Re-solve ONE lane of a batched sweep with full diagnostics -- the
    debugging half of the reference's ``check_convergence``, which
    re-solves each failed grid point on a rebuilt system to classify it
    (analysis.py:27-76). The batched path classifies from stored
    diagnostics; this helper is for interrogating a stubborn point:
    it runs each strategy in sequence from the given (or stored) guess,
    prints residual/iterations/attempts and the per-group coverage sums
    per strategy, and returns the best result.

    conds: the lane-batched Conditions of the sweep; lane: index into
    it. x0: optional [n_dyn] initial guess (e.g. the failed iterate from
    ``results.x``). Returns (SteadyStateResults, report dict).
    """
    import jax

    from .. import engine
    from ..solvers.newton import SolverOptions

    opts = opts or SolverOptions()
    cond = jax.tree_util.tree_map(lambda a: np.asarray(a)[lane], conds)
    groups = np.asarray(spec.groups)
    best, report = None, {"lane": int(lane), "tries": []}
    for strategy in strategies:
        res = engine.steady_state(spec, cond, x0=x0, opts=opts,
                                  strategy=strategy)
        y = np.asarray(res.x)
        entry = {
            "strategy": strategy,
            "success": bool(res.success),
            "residual": float(res.residual),
            "iterations": int(res.iterations),
            "attempts": int(res.attempts),
            "group_sums": (groups @ y).tolist(),
            "min_coverage": float(np.min(y[spec.dynamic_indices])),
            "stable": bool(engine.check_stability(spec, cond, y))
            if bool(res.success) else None,
        }
        report["tries"].append(entry)
        if verbose:
            print(f"replay lane {lane} [{strategy}]: "
                  f"success={entry['success']} "
                  f"residual={entry['residual']:.3e} "
                  f"iters={entry['iterations']} "
                  f"attempts={entry['attempts']} "
                  f"sums={np.round(entry['group_sums'], 6)} "
                  f"min_theta={entry['min_coverage']:.2e} "
                  f"stable={entry['stable']}")
        if best is None or (bool(res.success) and not bool(best.success)):
            best = res
        if bool(res.success):
            break
        x0 = np.asarray(res.x)[spec.dynamic_indices]  # chain strategies
    return best, report


def average_neighborhood(values: np.ndarray, success: np.ndarray):
    """Patch every failed grid point with the mean of its converged
    8-neighborhood (reference analysis.py:79-116, fixed to repair ALL
    failed points). NaN values count as failed. Points with no converged
    neighbor stay unpatched (still flagged failed).

    values: [Ni, Nj]; success: [Ni, Nj] bool.
    Returns (patched_values, patched_mask): patched_mask marks points
    that were repaired.
    """
    values = np.asarray(values, dtype=float)
    ok = np.asarray(success, dtype=bool) & np.isfinite(values)
    out = values.copy()
    patched = np.zeros_like(ok)
    Ni, Nj = values.shape
    bad = np.argwhere(~ok)
    for i, j in bad:
        i0, i1 = max(i - 1, 0), min(i + 2, Ni)
        j0, j1 = max(j - 1, 0), min(j + 2, Nj)
        nb_ok = ok[i0:i1, j0:j1]
        if np.any(nb_ok):
            out[i, j] = np.mean(values[i0:i1, j0:j1][nb_ok])
            patched[i, j] = True
    return out, patched


def convergence_heatmap(success, x=None, y=None, path=None, ax=None,
                        xlabel=None, ylabel=None):
    """Pass/fail grid image (reference analysis.py:120-140)."""
    import matplotlib.pyplot as plt
    success = np.asarray(success, dtype=float)
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4, 3.4))
    extent = None
    if x is not None and y is not None:
        extent = [np.min(y), np.max(y), np.min(x), np.max(x)]
    im = ax.imshow(success, origin="lower", extent=extent, aspect="auto",
                   cmap="RdYlGn", vmin=0.0, vmax=1.0)
    ax.set(xlabel=xlabel or "descriptor 2", ylabel=ylabel or "descriptor 1")
    if created:
        fig.colorbar(im, ax=ax).ax.set_ylabel("converged")
        fig.tight_layout()
        if path:
            fig.savefig(path, dpi=300)
        return fig, ax
    return None, ax


def make_heatmap(x, y, panels, labels=None, smooth_sigma: float = 1.0,
                 log_abs: bool = True, path=None, cmap="RdYlBu_r",
                 levels: int = 25, xlabel=None, ylabel=None):
    """Multi-panel Gaussian-smoothed contour maps over a descriptor grid
    (reference analysis.py:143-266).

    panels: one [Ni, Nj] array or a list of them (e.g. TOF and
    selectivity); ``log_abs`` renders log10|panel|.
    """
    import matplotlib.pyplot as plt
    from scipy.ndimage import gaussian_filter

    if isinstance(panels, np.ndarray) and panels.ndim == 2:
        panels = [panels]
    n = len(panels)
    fig, axes = plt.subplots(1, n, figsize=(4 * n, 3.4), squeeze=False)
    for k, panel in enumerate(panels):
        z = np.asarray(panel, dtype=float)
        if log_abs:
            z = np.log10(np.maximum(np.abs(z), 1e-300))
        if smooth_sigma:
            z = gaussian_filter(z, sigma=smooth_sigma)
        ax = axes[0, k]
        cs = ax.contourf(np.asarray(y), np.asarray(x), z, levels=levels,
                         cmap=plt.get_cmap(cmap))
        fig.colorbar(cs, ax=ax).ax.set_ylabel(
            (labels or [None] * n)[k] or
            ("log10|value|" if log_abs else "value"))
        ax.set(xlabel=xlabel or "descriptor 2",
               ylabel=ylabel or "descriptor 1")
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=300)
    return fig, axes
