from .system import System
