"""Preset batch workflows: canonical drivers over a loaded System.

Capability parity with the reference presets
(/root/reference/pycatkin/functions/presets.py): run / temperature and
parameter sweeps with optional steady-state solve and DRC, energy-span
sweeps, reaction/state energy exports, landscape comparison plots. CSV
artifact names and column layouts match the reference so downstream
tooling keeps working (one deliberate fix: state-energy columns are
labelled correctly -- the reference swaps the 'Translational' and
'Rotational' headers, presets.py:459-469).

Sweeps are executed through the batched engine (one vmapped device
program per sweep) instead of the reference's serial Python loops.
"""

from __future__ import annotations

import os
import sys
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from .. import engine
from .. import precision as _precision
from ..parallel.batch import (batch_steady_state, batch_transient,
                              stack_conditions)
from ..robustness.ladder import run_chunk_with_ladder
from ..solvers.ode import log_time_grid


def _ensure_dir(path):
    if path and not os.path.isdir(path):
        os.makedirs(path, exist_ok=True)


# Cached jitted sweep programs (jit caches on function identity; building
# the closures per call would recompile the batched DRC/rates programs on
# every sweep -- see parallel/batch.py).
@lru_cache(maxsize=128)
def _net_rates_program(spec):
    def net_rates(cond, y):
        fwd, rev = engine.reaction_rates_at(spec, cond, y)
        return fwd - rev
    return jax.jit(jax.vmap(net_rates))


@_precision.kernel_keyed
@lru_cache(maxsize=128)
def _drc_program(spec, tof_terms, drc_mode, eps, sopts,
                 kernel="xla"):
    """Batched DRC returning (xi [lanes, n_r], ok [lanes]): ok=False
    lanes had an unconverged (perturbed) solve and carry unreliable xi.
    ``kernel`` is a cache key only (precision.kernel_keyed): the
    perturbed steady solves bake the direction-kernel choice in at
    trace time."""
    if drc_mode == "fd":
        # opts deliberately not forwarded: drc_fd's default tightened
        # tolerances are required for a meaningful difference quotient.
        def drc_one(cond, x0):
            return engine.drc_fd(spec, cond, list(tof_terms), eps=eps,
                                 x0=x0, return_success=True)
    else:
        def drc_one(cond, x0):
            xi = engine.drc(spec, cond, list(tof_terms), x0=x0,
                            opts=sopts)
            return xi, jnp.asarray(True)
    return jax.jit(jax.vmap(drc_one))


def run(sim_system, steady_state_solve=False, plot_results=False,
        save_results=False, fig_path=None, csv_path=""):
    """Transient solve (+ optional steady state, plots, CSV export)
    (reference presets.py:16-28)."""
    sim_system.solve_odes()
    if plot_results:
        from .plotting import plot_transient
        plot_transient(sim_system, path=fig_path)
    if save_results:
        write_results(sim_system, path=csv_path)
    if steady_state_solve:
        sim_system.find_steady(store_steady=True)


def _sweep(sim_system, values, set_value, steady_state_solve, tof_terms,
           eps, drc_mode):
    """Shared machinery of run_temperatures / run_parameters: build one
    lane-batched Conditions, run transient + (optionally) steady + DRC as
    batched device programs."""
    spec = sim_system.spec
    conds = []
    for v in values:
        set_value(v)
        conds.append(sim_system.conditions())
    batched = stack_conditions(conds)

    times = sim_system.params["times"]
    grid = np.asarray(log_time_grid(times[0], times[-1],
                                    sim_system.params.get("n_out", 300)))
    ys, ok = batch_transient(spec, batched, grid, sim_system._ode_options())
    if not bool(np.all(np.asarray(ok))):
        idx = np.flatnonzero(~np.asarray(ok))
        bad = [values[i] for i in idx]
        print(f"Warning: transient integration incomplete for lanes "
              f"{idx.tolist()} (sweep values {bad}); downstream results "
              "for those lanes are unreliable", file=sys.stderr)
    finals = np.asarray(ys[:, -1, :])

    if steady_state_solve:
        x0 = ys[:, -1, :][:, spec.dynamic_indices]
        sopts = sim_system.solver_options()

        def run_steady(device=None):
            import contextlib
            ctx = (jax.default_device(device) if device is not None
                   else contextlib.nullcontext())
            with ctx:
                return batch_steady_state(spec, batched, x0=x0, opts=sopts)

        def reject_poisoned(res):
            bad = np.asarray(res.success) & ~np.all(
                np.isfinite(np.asarray(res.x)), axis=-1)
            return (f"{int(bad.sum())} converged lane(s) with non-finite "
                    "state" if bad.any() else None)

        # Degradation ladder (robustness/ladder.py): a steady solve
        # that dies on every rung degrades to the transient finals with
        # a structured event + warning instead of killing the sweep.
        res, _ = run_chunk_with_ladder(run_steady, label="preset:steady",
                                       validate=reject_poisoned)
        if res is None:
            from ..utils import profiling
            lanes = list(range(len(values)))
            detail = (f"steady solve failed on every degradation rung; "
                      f"lanes {lanes} (sweep values {list(values)}) "
                      f"degraded to transient finals")
            profiling.record_event("degradation", label="preset:steady",
                                   rung="transient-fallback",
                                   detail=detail, lanes=lanes)
            print(f"Warning: {detail} (see diagnostics events)",
                  file=sys.stderr)
        else:
            finals = np.asarray(res.x)
            if not bool(np.all(np.asarray(res.success))):
                idx = np.flatnonzero(~np.asarray(res.success))
                bad = [values[i] for i in idx]
                print(f"Warning: steady solve unconverged for lanes "
                      f"{idx.tolist()} (sweep values {bad})",
                      file=sys.stderr)

    rates = np.asarray(_net_rates_program(spec)(batched,
                                                jnp.asarray(finals)))

    drcs = {}
    if tof_terms is not None:
        x0s = jnp.asarray(finals[:, spec.dynamic_indices])
        sopts = sim_system.solver_options()
        xis, drc_ok = _drc_program(spec, tuple(tof_terms), drc_mode,
                                   float(eps), sopts)(batched, x0s)
        xis = np.asarray(xis)
        drc_ok = np.asarray(drc_ok)
        if not drc_ok.all():
            idx = np.flatnonzero(~drc_ok)
            bad = [values[i] for i in idx]
            print(f"Warning: DRC perturbed steady solves unconverged for "
                  f"lanes {idx.tolist()} (sweep values {bad}); xi for "
                  "those lanes is unreliable (prefer "
                  "drc_mode='implicit')", file=sys.stderr)
        for i, v in enumerate(values):
            drcs[v] = dict(zip(spec.rnames, xis[i]))
    return finals, rates, drcs


def run_temperatures(sim_system, temperatures, steady_state_solve=False,
                     tof_terms=None, eps=5.0e-2, plot_results=False,
                     save_results=False, fig_path=None, csv_path="",
                     drc_mode="implicit"):
    """Temperature sweep with optional steady solve and DRC (reference
    presets.py:31-167); the sweep runs as one batched device program."""
    T0 = sim_system.params["temperature"]

    def set_T(T):
        sim_system.params["temperature"] = T

    finals, rates, drcs = _sweep(sim_system, list(temperatures), set_T,
                                 steady_state_solve, tof_terms, eps,
                                 drc_mode)
    sim_system.params["temperature"] = T0

    if save_results:
        _save_sweep(sim_system, "temperature", "Temperature (K)",
                    list(temperatures), finals, rates, drcs, tof_terms,
                    csv_path)
    if plot_results:
        from .plotting import plot_sweep
        plot_sweep(sim_system, "temperature", list(temperatures), finals,
                   rates, drcs, tof_terms, fig_path)
    return finals, rates, drcs


def run_parameters(sim_system, parameters, params_name,
                   steady_state_solve=False, tof_terms=None, eps=5.0e-2,
                   plot_results=False, save_results=False, fig_path=None,
                   csv_path="", drc_mode="implicit"):
    """Sweep over any params key, including start_state_X / inflow_state_X
    entries (reference presets.py:170-305)."""

    def set_param(v):
        if "start_state" in params_name:
            key = params_name.split("start_state_")[1]
            sim_system.params["start_state"][key] = v
        elif "inflow_state" in params_name:
            key = params_name.split("inflow_state_")[1]
            sim_system.params["inflow_state"][key] = v
        else:
            sim_system.params[params_name] = v

    finals, rates, drcs = _sweep(sim_system, list(parameters), set_param,
                                 steady_state_solve, tof_terms, eps,
                                 drc_mode)
    if save_results:
        _save_sweep(sim_system, params_name, params_name, list(parameters),
                    finals, rates, drcs, tof_terms, csv_path)
    if plot_results:
        from .plotting import plot_sweep
        plot_sweep(sim_system, params_name, list(parameters), finals, rates,
                   drcs, tof_terms, fig_path)
    return finals, rates, drcs


def _save_sweep(sim_system, tag, header0, values, finals, rates, drcs,
                tof_terms, csv_path):
    _ensure_dir(csv_path)
    spec = sim_system.spec
    vcol = np.reshape(values, (len(values), 1))

    rheader = [header0] + list(spec.rnames)
    df = pd.DataFrame(np.concatenate((vcol, rates), axis=1), columns=rheader)
    df.to_csv(os.path.join(csv_path, f"rates_vs_{tag}.csv"), index=False)

    ads = spec.adsorbate_indices
    cheader = [header0] + [spec.snames[i] for i in ads]
    df = pd.DataFrame(np.concatenate((vcol, finals[:, ads]), axis=1),
                      columns=cheader)
    df.to_csv(os.path.join(csv_path, f"coverages_vs_{tag}.csv"), index=False)

    gas = spec.gas_indices
    pheader = [header0] + [f"p{spec.snames[i]} (bar)" for i in gas]
    df = pd.DataFrame(np.concatenate((vcol, finals[:, gas]), axis=1),
                      columns=pheader)
    df.to_csv(os.path.join(csv_path, f"pressures_vs_{tag}.csv"), index=False)

    if tof_terms is not None:
        dheader = [header0] + list(spec.rnames)
        vals = np.zeros((len(values), spec.n_reactions + 1))
        vals[:, 0] = values
        for i, v in enumerate(values):
            vals[i, 1:] = np.array(list(drcs[v].values()))
        df = pd.DataFrame(vals, columns=dheader)
        df.to_csv(os.path.join(csv_path, f"drcs_vs_{tag}.csv"), index=False)


def run_energy_span_temperatures(sim_system, temperatures, etype="free",
                                 save_results=False, csv_path=""):
    """Energy-span model over a temperature range (reference
    presets.py:343-375); writes energy_span_summary_<k>.csv plus
    xTDTS/xTDI tables."""
    _ensure_dir(csv_path)
    out = {}
    for k, landscape in sim_system.energy_landscapes.items():
        esm = {}
        for T in temperatures:
            esm[T] = landscape.evaluate_energy_span_model(
                T=T, p=sim_system.params["pressure"],
                verbose=sim_system.params["verbose"], etype=etype)
        out[k] = esm
        if save_results:
            df = pd.DataFrame(
                data=[[T] + list(esm[T][0:4]) for T in temperatures],
                columns=["Temperature (K)", "TOF (1/s)", "Espan (eV)",
                         "TDTS", "TDI"])
            df.to_csv(os.path.join(csv_path, f"energy_span_summary_{k}.csv"),
                      index=False)
            df = pd.DataFrame(
                data=[[T] + esm[T][4] for T in temperatures],
                columns=["Temperature (K)"] + esm[temperatures[0]][6])
            df.to_csv(os.path.join(csv_path, f"energy_span_xTDTS_{k}.csv"),
                      index=False)
            df = pd.DataFrame(
                data=[[T] + esm[T][5] for T in temperatures],
                columns=["Temperature (K)"] + esm[temperatures[0]][7])
            df.to_csv(os.path.join(csv_path, f"energy_span_xTDI_{k}.csv"),
                      index=False)
    return out


def save_energies(sim_system, csv_path=""):
    """Reaction energies/barriers at current (T, p) (reference
    presets.py:378-406)."""
    _ensure_dir(csv_path)
    T = sim_system.params["temperature"]
    p = sim_system.params["pressure"]
    re = sim_system.reaction_energy_table()
    spec = sim_system.spec
    df = pd.DataFrame(
        data=[[r, float(re.dErxn[j]), float(re.dGrxn[j]),
               float(re.dEa_fwd[j]), float(re.dGa_fwd[j])]
              for j, r in enumerate(spec.rnames)],
        columns=["Reaction", "dEr (J/mol)", "dGr (J/mol)", "dEa (J/mol)",
                 "dGa (J/mol)"])
    fname = f"reaction_energies_and_barriers_{T:.1f}K_{p / 1e5:.1f}bar.csv"
    df.to_csv(os.path.join(csv_path, fname), index=False)
    return df


def save_energies_temperatures(sim_system, temperatures, csv_path=""):
    """Per-reaction energy tables over T (reference presets.py:409-438)."""
    _ensure_dir(csv_path)
    spec = sim_system.spec
    rows = {r: [] for r in spec.rnames}
    for T in temperatures:
        re = sim_system.reaction_energy_table(T=T)
        for j, r in enumerate(spec.rnames):
            rows[r].append([T, float(re.dErxn[j]), float(re.dGrxn[j]),
                            float(re.dEa_fwd[j]), float(re.dGa_fwd[j])])
    for r in spec.rnames:
        df = pd.DataFrame(rows[r], columns=[
            "Temperature (K)", "dEr (J/mol)", "dGr (J/mol)", "dEa (J/mol)",
            "dGa (J/mol)"])
        df.to_csv(os.path.join(csv_path,
                               f"reaction_energies_and_barriers_{r}.csv"),
                  index=False)


def save_state_energies(sim_system, csv_path=""):
    """State energies at current (T, p) (reference presets.py:441-471).

    NOTE: column headers are labelled correctly here; the reference swaps
    'Translational' and 'Rotational' (its values under 'Rotational' are
    translational energies and vice versa, presets.py:459-469).
    """
    _ensure_dir(csv_path)
    T = sim_system.params["temperature"]
    p = sim_system.params["pressure"]
    fe = sim_system.free_energy_table()
    spec = sim_system.spec
    df = pd.DataFrame(
        data=[[s, float(fe.gfree[i]), float(fe.gelec[i]),
               float(fe.gvibr[i]), float(fe.gtran[i]), float(fe.grota[i])]
              for i, s in enumerate(spec.snames)],
        columns=["State", "Free (eV)", "Electronic (eV)",
                 "Vibrational (eV)", "Translational (eV)",
                 "Rotational (eV)"])
    fname = f"state_energies_{T:.1f}K_{p / 1e5:.1f}bar.csv"
    df.to_csv(os.path.join(csv_path, fname), index=False)
    return df


def save_pes_energies(sim_system, csv_path=""):
    """Relative landscape energies per energy landscape (reference
    presets.py:474-498)."""
    _ensure_dir(csv_path)
    T = sim_system.params["temperature"]
    p = sim_system.params["pressure"]
    for k, landscape in sim_system.energy_landscapes.items():
        landscape.construct_energy_landscape(T=T, p=p)
        n = len(landscape.minima)
        df = pd.DataFrame(
            data=[[landscape.labels[s],
                   landscape.energy_landscape["free"][s],
                   landscape.energy_landscape["electronic"][s]]
                  for s in range(n)],
            columns=["State", "Free (eV)", "Electronic (eV)"])
        fname = f"{k}_energy_landscape_{T:.1f}K_{p / 1e5:.1f}bar.csv"
        df.to_csv(os.path.join(csv_path, fname), index=False)


def write_results(sim_system, path=""):
    """Transient rates/coverages/pressures CSV export (reference
    old_system.py:531-568)."""
    _ensure_dir(path)
    spec = sim_system.spec
    T = sim_system.params["temperature"]
    p = sim_system.params["pressure"]
    tag = f"{T:.1f}K_{p / 1e5:.1f}bar"
    times = sim_system.times.reshape(-1, 1)

    cond = sim_system.conditions()
    kf, kr, _ = engine.rate_constants(spec, cond)

    def rates_at(y):
        fwd, rev = engine.reaction_rates_at(spec, cond, y, kf, kr)
        return jnp.stack([fwd, rev], axis=1)
    rmat = np.asarray(jax.jit(jax.vmap(rates_at))(
        jnp.asarray(sim_system.solution))).reshape(len(times), -1)
    rheader = ["Time (s)"] + [c for r in spec.rnames
                              for c in (f"{r}_fwd", f"{r}_rev")]
    pd.DataFrame(np.concatenate((times, rmat), axis=1),
                 columns=rheader).to_csv(
        os.path.join(path, f"rates_{tag}.csv"), index=False)

    ads = spec.adsorbate_indices
    cheader = ["Time (s)"] + [spec.snames[i] for i in ads]
    pd.DataFrame(np.concatenate((times, sim_system.solution[:, ads]),
                                axis=1), columns=cheader).to_csv(
        os.path.join(path, f"coverages_{tag}.csv"), index=False)

    gas = spec.gas_indices
    pheader = ["Time (s)"] + [spec.snames[i] for i in gas]
    pd.DataFrame(np.concatenate((times, sim_system.solution[:, gas]),
                                axis=1), columns=pheader).to_csv(
        os.path.join(path, f"pressures_{tag}.csv"), index=False)


def save_structures(sim_system, fig_path="", types_to_skip=("TS",),
                    render_png=True):
    """Export every state's structure as .pdb plus a headless .png
    render (the file-artifact side of the reference's draw_states
    preset, presets.py:308-320 + state.py:444-463 view_atoms image
    export; the interactive ASE viewer itself has no headless
    counterpart and is out of scope). Returns {name: pdb_path} for the
    states that had structure data; .png renders land next to the
    .pdb files."""
    written = {}
    for name, st in sim_system.states.items():
        if st.state_type in types_to_skip:
            continue
        fname = st.save_pdb(path=fig_path)
        if fname:
            written[name] = fname
            if render_png:
                st.save_png(path=fig_path)
    return written


def get_tof_for_given_reactions(sim_system, tof_terms):
    """Sum of net rates of the named steps at the last transient solution
    (reference presets.py:585-597)."""
    cond = sim_system.conditions()
    mask = engine.tof_mask_for(sim_system.spec, tof_terms)
    return float(engine.tof(sim_system.spec, cond,
                            sim_system.solution[-1], mask))
