"""System: the single user-facing facade over the compiled engine.

The reference ships two incompatible System APIs mid-refactor (legacy
old_system.py:13-647 with a params dict / solve_odes / DRC / activity, and
the patched system.py:33-639 with build()/get_dydt/find_steady). This
class exposes ONE coherent union of both capability sets (SURVEY.md §1.2),
implemented over the functional engine: host-side mutation of states,
reactions or params is re-compiled into a fresh :class:`Conditions` pytree
on each call, so the mutate-and-solve workflows of the reference examples
keep working while the math runs as jitted device code.
"""

from __future__ import annotations

import copy
from typing import Optional

import numpy as np

from .. import engine
from ..analysis.energy_span import Energy
from ..frontend.reactions import Reaction
from ..frontend.spec import Conditions, build_spec, default_conditions
from ..frontend.states import GAS, State
from ..models.reactor import Reactor
from ..solvers.newton import SolverOptions, SteadyStateResults
from ..solvers.ode import ODEOptions, log_time_grid


class System:

    def __init__(self, times=None, start_state=None, inflow_state=None,
                 T=293.15, p=101325.0, use_jacobian=True,
                 ode_solver="trbdf2", nsteps=1.0e4, rtol=1.0e-8,
                 atol=1.0e-10, xtol=1.0e-8, ftol=1.0e-8, verbose=False,
                 min_tol=1.0e-32, n_out=300,
                 desorption_model="detailed_balance"):
        # Desorption convention for non-activated ads/des steps:
        # 'detailed_balance' (upstream, golden-number compatible) or
        # 'collision' (the fork's statistical kdes rewrite, reference
        # reaction.py:134-162 + rate_constants.py:26-53). Schema: the
        # "system" section's "desorption_model" key.
        if desorption_model not in ("detailed_balance", "collision"):
            raise ValueError(
                f"desorption_model must be 'detailed_balance' or "
                f"'collision', got {desorption_model!r}")
        self.desorption_model = desorption_model
        # Legacy solver knobs are honored, not silently swallowed
        # (reference old_system.py:154-174):
        #   ode_solver -- two native L-stable families (mirroring the
        #     reference's two scipy families, old_system.py:350-376):
        #     'trbdf2' (2nd order, the default) and 'esdirk4' (4th
        #     order, the faster choice for accuracy-limited transients
        #     and the independent cross-check method). The reference
        #     schema values 'solve_ivp' and 'ode' are accepted as
        #     aliases of the default; anything else raises.
        #   nsteps -> ODEOptions.max_steps (per-save-interval budget).
        #   ftol/xtol -> SolverOptions.rate_tol: the reference passes
        #     both to least_squares (old_system.py:426-428), which stops
        #     when EITHER fires; convergence here is purely
        #     residual-based, so the tightest of the two becomes the
        #     absolute residual tolerance (reference inputs ship
        #     non-default xtol, e.g. COOxReactor's 1e-12).
        if ode_solver not in ("trbdf2", "esdirk4", "solve_ivp", "ode"):
            raise ValueError(
                f"ode_solver={ode_solver!r} is not supported: use "
                "'trbdf2' or 'esdirk4' (the native L-stable stiff "
                "integrators) or the reference-schema aliases "
                "'solve_ivp'/'ode', which map onto the default.")
        # Legacy-compatible parameter dict (reference old_system.py:154-174);
        # sweep drivers mutate these keys directly.
        self.params = {
            "times": copy.deepcopy(times),
            "start_state": copy.deepcopy(start_state) or {},
            "inflow_state": copy.deepcopy(inflow_state) or {},
            "temperature": T,
            "pressure": p,
            "rtol": rtol,
            "atol": atol,
            "xtol": xtol,
            "ftol": ftol,
            "jacobian": use_jacobian,
            "nsteps": int(nsteps),
            "ode_solver": ode_solver,
            "verbose": verbose,
            "n_out": int(n_out),
        }
        self.min_tol = min_tol
        self.states: dict[str, State] = {}
        self.reactions: dict[str, Reaction] = {}
        self.reactor: Optional[Reactor] = None
        self.energy_landscapes: dict[str, Energy] = {}

        self._spec = None
        self.times = None
        self.solution = None
        self.full_steady = None
        self.steady_result: Optional[SteadyStateResults] = None

    # -- new-API style scalar accessors --------------------------------
    @property
    def T(self):
        return self.params["temperature"]

    @T.setter
    def T(self, value):
        self.params["temperature"] = value

    @property
    def p(self):
        return self.params["pressure"]

    @p.setter
    def p(self, value):
        self.params["pressure"] = value

    @property
    def verbose(self):
        return self.params["verbose"]

    # ------------------------------------------------------------------
    # construction
    def add_state(self, state: State):
        assert isinstance(state, State), "state must be a pycatkin_tpu State"
        if state.name in self.states:
            raise ValueError(
                f"Found two copies of state {state.name}. "
                "State names must be unique!")
        if self.params["verbose"]:
            print(f"Adding state {state.name}")
        self.states[state.name] = state
        self._spec = None

    def add_reaction(self, reaction: Reaction):
        assert isinstance(reaction, Reaction), \
            "reaction must be a pycatkin_tpu Reaction"
        if self.params["verbose"]:
            print(f"Adding reaction {reaction.name}")
        self.reactions[reaction.name] = reaction
        self._spec = None

    def add_reactor(self, reactor: Reactor):
        assert isinstance(reactor, Reactor), \
            "reactor must be a pycatkin_tpu Reactor"
        self.reactor = reactor
        self._spec = None

    def add_energy_landscape(self, energy_landscape: Energy):
        assert isinstance(energy_landscape, Energy)
        energy_landscape._system = self
        self.energy_landscapes[energy_landscape.name] = energy_landscape

    # ------------------------------------------------------------------
    # compilation
    def build(self, force: bool = False, strict: bool | None = None):
        """Compile the mechanism into the immutable ModelSpec (reference
        system.py:167-186). Idempotent; re-run after structural changes.

        ``strict`` controls the input-validation gate
        (frontend/validate.py) run before compiling: True raises
        :class:`~pycatkin_tpu.frontend.validate.ValidationError` on any
        validation error, False skips the gate, None (default) follows
        the ``PYCATKIN_VALIDATE`` environment variable
        (strict|warn|off; default warn -- issues become
        ``UserWarning``s and the build proceeds)."""
        need_build = self._spec is None or force
        # An explicit ``strict`` runs the gate even on an already-built
        # system (revalidation without recompilation).
        if need_build or strict is not None:
            from ..frontend.validate import validate_system, validation_mode
            mode = (validation_mode() if strict is None
                    else ("strict" if strict else "off"))
            if mode != "off":
                validate_system(self).emit(mode)
        if need_build:
            rtype = self.reactor.reactor_type if self.reactor else None
            rparams = self.reactor.params() if self.reactor else None
            self._spec = build_spec(self.states, self.reactions,
                                    reactor=rtype, reactor_params=rparams,
                                    desorption_model=self.desorption_model)
        return self

    @property
    def spec(self):
        self.build()
        return self._spec

    @property
    def snames(self):
        return list(self.spec.snames)

    @property
    def adsorbate_indices(self):
        return list(self.spec.adsorbate_indices)

    @property
    def gas_indices(self):
        return list(self.spec.gas_indices)

    @property
    def dynamic_indices(self):
        return list(self.spec.dynamic_indices)

    @property
    def initial_system(self):
        return np.asarray(self.conditions().y0)

    def conditions(self, T=None, p=None, kscale=None,
                   eps_extra: dict | None = None) -> Conditions:
        """Snapshot current host-side model state into a Conditions pytree.

        Reads (possibly user-mutated) State.Gelec values, user reaction
        energies and energy modifiers -- the bridge from the reference's
        mutate-and-solve style to the functional engine.
        """
        spec = self.spec
        T = self.params["temperature"] if T is None else T
        p = self.params["pressure"] if p is None else p
        gelec_overrides = {name: st.Gelec for name, st in self.states.items()
                           if st.Gelec is not None}
        eps = {name: st.add_to_energy for name, st in self.states.items()
               if st.add_to_energy}
        if eps_extra:
            for name, val in eps_extra.items():
                eps[name] = eps.get(name, 0.0) + val
        return default_conditions(
            spec, self.reactions, T=T, p=p,
            start_state=self.params.get("start_state"),
            inflow_state=self.params.get("inflow_state"),
            gelec_overrides=gelec_overrides, eps=eps, kscale=kscale)

    # ------------------------------------------------------------------
    # point evaluations
    def free_energy_table(self, T=None, p=None) -> engine.FreeEnergies:
        """All species' electronic/free energies and contributions at
        (T, p); also writes them back onto the State objects, so
        reference-style attribute access (state.Gfree etc.) works."""
        fe = engine.free_energies(self.spec, self.conditions(T=T, p=p))
        for i, name in enumerate(self.spec.snames):
            # Foreign energy-only species (derived-reaction bases from a
            # donor system) have no entry in self.states.
            if name not in self.states:
                continue
            st = self.states[name]
            st.Gelec_computed = float(fe.gelec[i])
            if not st.is_scaling and st.Gelec is None:
                st.Gelec = float(fe.gelec[i])
            st.Gvibr_computed = float(fe.gvibr[i])
            st.Gtran_computed = float(fe.gtran[i])
            st.Grota_computed = float(fe.grota[i])
            st.Gfree_computed = float(fe.gfree[i])
        return fe

    def reaction_energy_table(self, T=None, p=None) -> engine.ReactionEnergies:
        return engine.reaction_energies(self.spec, self.conditions(T=T, p=p))

    def rate_constant_table(self, T=None, p=None):
        kf, kr, keq = engine.rate_constants(self.spec,
                                            self.conditions(T=T, p=p))
        return np.asarray(kf), np.asarray(kr), np.asarray(keq)

    def get_dydt(self, y, cond: Conditions | None = None):
        return np.asarray(engine.get_dydt(self.spec,
                                          cond or self.conditions(), y))

    # legacy alias (old_system.py:227)
    species_odes = get_dydt

    def get_jacobian(self, y, cond: Conditions | None = None):
        return np.asarray(engine.get_jacobian(self.spec,
                                              cond or self.conditions(), y))

    species_jacobian = get_jacobian

    def reaction_terms(self, y, cond: Conditions | None = None):
        """(n_r, 2) forward/reverse rates at y (reference
        old_system.py:202-225). Also stored on self.rates."""
        fwd, rev = engine.reaction_rates_at(self.spec,
                                            cond or self.conditions(), y)
        self.rates = np.stack([np.asarray(fwd), np.asarray(rev)], axis=1)
        return self.rates

    # ------------------------------------------------------------------
    # solvers
    def _ode_options(self) -> ODEOptions:
        opts = ODEOptions(rtol=self.params["rtol"],
                          atol=self.params["atol"])
        if self.params["ode_solver"] == "esdirk4":
            opts = opts._replace(method="esdirk4")
        # The legacy default (1e4) maps onto the native default budget;
        # an explicitly tuned nsteps becomes the per-interval step cap.
        if int(self.params["nsteps"]) != 10000:
            opts = opts._replace(max_steps=int(self.params["nsteps"]))
        return opts

    def solver_options(self, **overrides) -> SolverOptions:
        # ftol/xtol: tightest wins (see __init__ knob mapping notes).
        base = SolverOptions(floor=self.min_tol,
                             rate_tol=min(float(self.params["ftol"]),
                                          float(self.params["xtol"])))
        return base._replace(**overrides) if overrides else base

    def solve_odes(self, n_out=None, times=None):
        """Transient integration over the configured time span on a
        log-spaced output grid (reference old_system.py:315-383). Stores
        self.times / self.solution."""
        times = times if times is not None else self.params["times"]
        assert times is not None, "System times are not set"
        n_out = n_out or self.params.get("n_out", 300)
        grid = np.asarray(log_time_grid(times[0], times[-1], n_out))
        cond = self.conditions()
        ys, ok = engine.transient_chunked(self.spec, cond, grid,
                                          self._ode_options())
        self.times = grid
        self.solution = np.asarray(ys)
        if not bool(ok):
            print("Warning: transient integration did not complete cleanly")
        if self.params["verbose"]:
            print("Final state:", dict(zip(self.spec.snames,
                                           self.solution[-1])))
        return self.solution

    def find_steady(self, store_steady=False, y0=None,
                    use_transient_guess=True, key=None,
                    opts: SolverOptions | None = None,
                    check_stability=True,
                    pos_jac_tol=1e-2) -> SteadyStateResults:
        """Steady-state solve (union of reference old_system.py:385-468 and
        system.py:566-639). Initial guess priority: explicit y0, then the
        transient tail if available (legacy behavior), then the start
        state.

        check_stability: reject converged-but-unstable fixed points (all
        Jacobian eigenvalues must have real part <= pos_jac_tol, reference
        solver.py:102-106) and retry from random restarts; if no stable
        state is found the result reports success=False."""
        cond = self.conditions()
        solver_opts = opts or self.solver_options()
        x0 = None
        if y0 is not None:
            x0 = np.asarray(y0)[self.spec.dynamic_indices]
        elif use_transient_guess:
            # `is not None` + len: sweep drivers mutate params directly,
            # so "times" may arrive as a numpy array (whose truth value
            # is ambiguous) -- same latent pattern as solve_odes'
            # `times or ...`.
            times = self.params.get("times")
            if (self.solution is None and times is not None
                    and len(times) > 0):
                # Multistable networks (e.g. the CH4 oxidation mechanism)
                # carry several stable roots; the physically meaningful
                # one is the t->inf limit of the start state. The
                # reference ALWAYS seeds find_steady from the transient
                # tail (old_system.py:393-395, and every preset runs
                # solve_odes first) -- so when no transient is stored and
                # a time span is configured, integrate before solving.
                self.solve_odes()
            if self.solution is not None:
                x0 = self.solution[-1][self.spec.dynamic_indices]
        res = engine.steady_state(self.spec, cond, x0=x0, key=key,
                                  opts=solver_opts)
        if not bool(res.success):
            # Strategy fallback (reference solve_root -> solve_minimize
            # chain): re-solve with projected-LM descent from the best
            # PTC iterate.
            lm = engine.steady_state(
                self.spec, cond,
                x0=np.asarray(res.x)[self.spec.dynamic_indices],
                key=key, opts=solver_opts, strategy="lm")
            if bool(lm.success):
                res = lm
        if check_stability and bool(res.success):
            import jax
            k = key if key is not None else jax.random.PRNGKey(1)
            stable = engine.check_stability(self.spec, cond, res.x,
                                            pos_tol=pos_jac_tol)
            for _ in range(3):
                if stable:
                    break
                # Converged onto an unstable branch (e.g. the middle root
                # of a bistable mechanism): restart from a fresh random
                # guess, as the reference's verdict-and-retry loop does.
                k, sub = jax.random.split(k)
                retry = engine.steady_state(self.spec, cond, key=sub,
                                            opts=solver_opts)
                if bool(retry.success):
                    res = retry
                    stable = engine.check_stability(self.spec, cond, res.x,
                                                    pos_tol=pos_jac_tol)
            if not stable:
                res = res._replace(success=np.asarray(False))
        self.steady_result = res
        # Always stored (the legacy API gates this on store_steady, but
        # every downstream consumer here reads full_steady).
        self.full_steady = np.asarray(res.x)
        if self.params["verbose"]:
            print(f"Steady state: success={bool(res.success)} "
                  f"residual={float(res.residual):.3g} "
                  f"iters={int(res.iterations)}")
        return res

    # ------------------------------------------------------------------
    # derived analyses (reference old_system.py:470-529)
    def run_and_return_tof(self, tof_terms, ss_solve=False):
        if ss_solve:
            self.find_steady()
            y = self.full_steady
        else:
            self.solve_odes()
            y = self.solution[-1]
        cond = self.conditions()
        mask = engine.tof_mask_for(self.spec, tof_terms)
        self.reaction_terms(y, cond)
        return float(engine.tof(self.spec, cond, y, mask))

    def degree_of_rate_control(self, tof_terms, ss_solve=True, eps=1.0e-3,
                               mode="implicit"):
        """DRC per reaction. mode='implicit': one reverse-mode pass through
        the steady solve (TPU-native default); mode='fd': reference-parity
        batched central differences (old_system.py:490-515)."""
        cond = self.conditions()
        x0 = (self.solution[-1][self.spec.dynamic_indices]
              if self.solution is not None else None)
        if x0 is None:
            self.solve_odes()
            x0 = self.solution[-1][self.spec.dynamic_indices]
        if mode == "implicit":
            xi = engine.drc(self.spec, cond, tof_terms, x0=x0,
                            opts=self.solver_options())
        else:
            xi, ok = engine.drc_fd(self.spec, cond, tof_terms, eps=eps,
                                   x0=x0, return_success=True)
            if not bool(ok):
                import warnings
                warnings.warn(
                    "finite-difference DRC: not all perturbed steady "
                    "solves converged; values may be unreliable (prefer "
                    "mode='implicit')", stacklevel=2)
        return dict(zip(self.spec.rnames, np.asarray(xi)))

    def activity(self, tof_terms, ss_solve=False):
        tof_val = self.run_and_return_tof(tof_terms, ss_solve=ss_solve)
        if tof_val <= 0.0:
            import warnings
            warnings.warn(
                f"activity: net TOF of {tof_terms} is non-positive "
                f"({tof_val:.3e}); the selected steps run in reverse at "
                "the solution. Reporting the activity of |TOF| (the "
                "reference silently NaNs here, old_system.py:524-529).",
                stacklevel=2)
        return float(engine.activity_from_tof(tof_val,
                                              self.params["temperature"]))

    # ------------------------------------------------------------------
    def copy(self) -> "System":
        new = copy.deepcopy(self)
        new._spec = None
        return new
