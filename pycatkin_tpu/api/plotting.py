"""Plotting: transient traces, sweep figures, energy landscape drawings.

Capability parity with the reference's matplotlib output (transient plots
old_system.py:570-639, sweep figures presets.py:66-131, landscape drawing
with cubic-spline TS arcs energy.py:62-236, multi-system overlays
presets.py:501-556, generic plot presets.py:559-582).
"""

from __future__ import annotations

import os

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

from ..constants import eVtoJmol, eVtokJ, eVtokcal  # noqa: E402

FONT = {"family": "sans-serif", "weight": "normal", "size": 8}
plt.rc("font", **FONT)
matplotlib.rcParams["lines.markersize"] = 6
matplotlib.rcParams["lines.linewidth"] = 1.5

_UNIT_CONV = {"eV": 1.0, "kcal/mol": eVtokcal, "kJ/mol": eVtokJ,
              "J/mol": eVtoJmol}


def _ensure_dir(path):
    if path and not os.path.isdir(path):
        os.makedirs(path, exist_ok=True)


def plot_transient(sim_system, path=None):
    """Coverage / pressure / rate transients (reference
    old_system.py:570-639)."""
    _ensure_dir(path)
    spec = sim_system.spec
    T = sim_system.params["temperature"]
    p = sim_system.params["pressure"]
    tag = f"{T:.1f}K_{p / 1e5:.1f}bar"
    times = sim_system.times

    ads = spec.adsorbate_indices
    cmap = plt.get_cmap("tab20", max(len(ads), 1))
    fig, ax = plt.subplots(figsize=(3.2, 3.2))
    for k, i in enumerate(ads):
        if sim_system.solution[:, i].max() > 0.01:
            ax.plot(times / 3600, sim_system.solution[:, i],
                    label=spec.snames[i], color=cmap(k))
    ax.legend(loc="best", frameon=False)
    ax.set(xlabel="Time (hr)", xscale="log", ylabel="Coverage",
           ylim=(-0.1, 1.1), title=f"$T={T:.1f}$ K")
    fig.tight_layout()
    if path is not None:
        fig.savefig(os.path.join(path, f"coverages_{tag}.png"), dpi=300)

    gas = spec.gas_indices
    if len(gas):
        cmap = plt.get_cmap("tab20", len(gas))
        fig, ax = plt.subplots(figsize=(3.2, 3.2))
        for k, i in enumerate(gas):
            ax.plot(times / 3600, sim_system.solution[:, i],
                    label=spec.snames[i], color=cmap(k))
        ax.legend(loc="center right", frameon=False)
        ax.set(xlabel="Time (hr)", xscale="log", ylabel="Pressure (bar)",
               title=f"T = {T:.1f} K")
        fig.tight_layout()
        if path is not None:
            fig.savefig(os.path.join(path, f"pressures_{tag}.png"), dpi=300)
    plt.close("all")


def plot_sweep(sim_system, tag, values, finals, rates, drcs, tof_terms,
               fig_path=None):
    """Sweep result figures (reference presets.py:66-131): coverages,
    pressures, rates, DRCs and TOF vs the swept value."""
    _ensure_dir(fig_path)
    spec = sim_system.spec
    values = np.asarray(values)

    ads = spec.adsorbate_indices
    cmap = plt.get_cmap("tab20", max(len(ads), 1))
    fig, ax = plt.subplots(figsize=(3.2, 3.2))
    for k, i in enumerate(ads):
        if finals[:, i].max() > 0.01:
            ax.plot(values, finals[:, i], label=spec.snames[i], color=cmap(k))
    ax.legend(loc="best", frameon=False)
    ax.set(xlabel=tag, ylabel="Coverage", ylim=(-0.1, 1.1))
    fig.tight_layout()
    if fig_path is not None:
        fig.savefig(os.path.join(fig_path, f"coverages_vs_{tag}.png"),
                    dpi=300)

    gas = spec.gas_indices
    if len(gas):
        cmap = plt.get_cmap("tab20", len(gas))
        fig, ax = plt.subplots(figsize=(3.2, 3.2))
        for k, i in enumerate(gas):
            ax.plot(values, finals[:, i], label=spec.snames[i], color=cmap(k))
        ax.legend(loc="best", frameon=False)
        ax.set(xlabel=tag, ylabel="Pressure (bar)")
        fig.tight_layout()
        if fig_path is not None:
            fig.savefig(os.path.join(fig_path, f"pressures_vs_{tag}.png"),
                        dpi=300)

    cmap = plt.get_cmap("tab20", spec.n_reactions)
    fig, ax = plt.subplots(figsize=(3.2, 3.2))
    for j, r in enumerate(spec.rnames):
        ax.plot(values, rates[:, j], label=r, color=cmap(j))
    ax.legend(loc="best", frameon=False)
    yv = ax.get_ylim()
    ax.set(xlabel=tag, ylabel="Rate (1/s)", yscale="log",
           ylim=(max(1e-10, yv[0]), yv[1]))
    fig.tight_layout()
    if fig_path is not None:
        fig.savefig(os.path.join(fig_path, f"surfrates_vs_{tag}.png"),
                    dpi=300)

    if tof_terms is not None and drcs:
        fig, ax = plt.subplots(figsize=(3.2, 3.2))
        for j, r in enumerate(spec.rnames):
            drc = [drcs[v][r] for v in values]
            if max(abs(d) for d in drc) > 0.01:
                ax.plot(values, drc, label=r, color=cmap(j))
        ax.set(xlabel=tag, ylabel="Degree of rate control")
        ax.legend(loc="best", frameon=False)
        fig.tight_layout()
        if fig_path is not None:
            fig.savefig(os.path.join(fig_path, f"drc_vs_{tag}.png"), dpi=300)

        tof_idx = [spec.rindex(t) for t in tof_terms]
        fig, ax = plt.subplots(figsize=(3.2, 3.2))
        ax.plot(values, rates[:, tof_idx].sum(axis=1), color="k")
        ax.set(xlabel=tag, ylabel="TOF (1/s)", yscale="log")
        fig.tight_layout()
        if fig_path is not None:
            fig.savefig(os.path.join(fig_path, f"tof_vs_{tag}.png"), dpi=300)
    plt.close("all")


def _landscape_points(landscape, etype, conv):
    """Polyline through minima with cubic TS arcs (reference
    energy.py:95-121); clamped cubic Hermite between plateau edges."""
    energies = landscape.energy_landscape[etype]
    is_ts = landscape.energy_landscape["isTS"]
    n = len(energies)
    xs, ys = [], []

    def hermite(x0, y0, x1, y1, num=100):
        # clamped cubic: zero slope at both ends (CubicSpline bc 'clamped')
        t = np.linspace(0.0, 1.0, num)
        h = 3 * t**2 - 2 * t**3
        return x0 + (x1 - x0) * t, y0 + (y1 - y0) * h

    for i in range(n):
        d = 0.25
        if not is_ts[i]:
            xs += [i - d, i + d]
            ys += [energies[i] * conv, energies[i] * conv]
        else:
            x, y = hermite(i - 1 + d, energies[i - 1], i, energies[i])
            xs += list(x)
            ys += [v * conv for v in y]
            x, y = hermite(i, energies[i], i + 1 - d, energies[i + 1])
            xs += list(x)
            ys += [v * conv for v in y]
    return xs, ys


def draw_energy_landscape(landscape, T, p, etype="free", eunits="eV",
                          legend_location="upper right", path=None,
                          show_labels=False, figtitle=None, verbose=False):
    """Single-landscape drawing (reference energy.py:62-156)."""
    landscape._landscape_vector(T, p, etype, verbose)
    conv = _UNIT_CONV.get(eunits, 1.0)
    fig, ax = plt.subplots(figsize=(10, 4))
    xs, ys = _landscape_points(landscape, etype, conv)
    ax.plot(xs, ys, "-", color="black")
    energies = landscape.energy_landscape[etype]
    is_ts = landscape.energy_landscape["isTS"]
    seen_ts = seen_i = False
    for k in range(len(energies)):
        if is_ts[k]:
            ax.plot(k, energies[k] * conv, "s", color="tomato",
                    label=("Transition state" if not seen_ts else ""))
            seen_ts = True
        else:
            ax.plot(k, energies[k] * conv, "s", color="darkturquoise",
                    label=("Intermediate" if not seen_i else ""))
            seen_i = True
        ax.text(k, energies[k] * conv + 0.2 * conv,
                f"{energies[k] * conv:.3g}", ha="center")
        if show_labels:
            ax.text(k, energies[k] * conv - 0.2 * conv,
                    landscape.labels[k], ha="center", va="top")
    ax.legend(loc=legend_location)
    ax.set(xlabel="Reaction coordinate",
           ylabel=f"Relative {etype} energy ({eunits})")
    plt.tick_params(axis="x", which="both", bottom=False, top=False,
                    labelbottom=False)
    if figtitle:
        ax.set(title=figtitle)
    fig.tight_layout()
    if path is not None:
        _ensure_dir(path)
        fig.savefig(os.path.join(
            path, f"{etype}_energy_{landscape.name}.png"), dpi=300)
    return fig, ax


def draw_energy_landscapes(sim_system, etype="free", eunits="eV",
                           legend_location="upper right", show_labels=False,
                           fig_path=None):
    """All landscapes of a system (reference presets.py:323-340)."""
    for landscape in sim_system.energy_landscapes.values():
        draw_energy_landscape(landscape, T=sim_system.params["temperature"],
                              p=sim_system.params["pressure"], etype=etype,
                              eunits=eunits,
                              legend_location=legend_location,
                              path=fig_path, show_labels=show_labels)
    plt.close("all")


def compare_energy_landscapes(sim_systems, landscapes=None, etype="free",
                              eunits="eV", legend_location=None,
                              show_labels=False, fig_path=None, cmap=None):
    """Overlay landscapes from multiple systems (reference
    presets.py:501-556)."""
    fig, ax = plt.subplots(figsize=(10, 4))
    conv = _UNIT_CONV.get(eunits, 1.0)
    # Accept one system, a list of systems, or a name->system dict
    # (the reference examples use all three call styles).
    if isinstance(sim_systems, dict):
        sys_items = list(sim_systems.items())
    elif isinstance(sim_systems, (list, tuple)):
        sys_items = [(getattr(s, "name", f"system{i}"), s)
                     for i, s in enumerate(sim_systems)]
    else:
        sys_items = [(getattr(sim_systems, "name", "system"), sim_systems)]
    items = []
    for sname, sim in sys_items:
        for lname, landscape in sim.energy_landscapes.items():
            if landscapes is None or lname in landscapes:
                label = lname if len(sys_items) == 1 else f"{sname}:{lname}"
                items.append((label, sim, landscape))
    if cmap is None:
        cmap = plt.get_cmap("tab20", len(items))
    for idx, (label, sim, landscape) in enumerate(items):
        landscape._landscape_vector(sim.params["temperature"],
                                    sim.params["pressure"], etype)
        xs, ys = _landscape_points(landscape, etype, conv)
        ax.plot(xs, ys, "-", color=cmap(idx), label=label)
    if legend_location is not None:
        ax.legend(loc=legend_location)
    ax.set(xlabel="Reaction coordinate",
           ylabel=f"Relative {etype} energy ({eunits})")
    plt.tick_params(axis="x", which="both", bottom=False, top=False,
                    labelbottom=False)
    fig.tight_layout()
    if fig_path is not None:
        _ensure_dir(fig_path)
        fig.savefig(os.path.join(fig_path, f"{etype}_energy_landscapes.png"),
                    dpi=300)
    return fig, ax


def plot_data_simple(fig=None, ax=None, xdata=None, ydata=None, label=None,
                     linestyle="-", color="k", xlabel=None, ylabel=None,
                     title=None, addlegend=False, legendloc="best",
                     fig_path=None, fig_name="figure"):
    """Generic data plot helper (reference presets.py:559-582)."""
    if fig is None or ax is None:
        fig, ax = plt.subplots(figsize=(3.2, 3.2))
    ax.plot(xdata, ydata, linestyle, color=color, label=label)
    ax.set(xlabel=xlabel, ylabel=ylabel, title=title)
    if addlegend:
        ax.legend(loc=legendloc, frameon=False)
    fig.tight_layout()
    if fig_path is not None:
        _ensure_dir(fig_path)
        fig.savefig(os.path.join(fig_path, f"{fig_name}.png"), dpi=300)
    return fig, ax
