"""pycatkin_tpu: a TPU-native microkinetics framework.

A ground-up JAX/XLA re-design with the full capability set of PyCatKin
(DFT-landscape thermochemistry, TST kinetics, mean-field microkinetic
models, idealised reactors, energy-span model, degree-of-rate-control,
descriptor scans, uncertainty quantification) built as pure jitted
functions over an immutable compiled ModelSpec, so condition sweeps and
descriptor grids run as single batched device programs.

Float64 is enabled by default: rate constants span ~30 decades and
barriers sit in exponentials, so double precision is part of the numerical
contract (disable with PYCATKIN_TPU_X64=0 at your own risk).
"""

import os as _os

import jax as _jax

if _os.environ.get("PYCATKIN_TPU_X64", "1") != "0":
    _jax.config.update("jax_enable_x64", True)

from . import constants
from .engine import (FreeEnergies, ReactionEnergies, activity_from_tof, drc,
                     drc_fd, free_energies, get_dydt, get_jacobian,
                     make_rhs, make_steady_x, rate_constants,
                     reaction_energies, reaction_rates_at, steady_state,
                     tof, transient)
from .analysis.uncertainty import Uncertainty
from .frontend.loader import read_from_input_file
from .frontend.reactions import (Reaction, ReactionDerivedReaction,
                                 UserDefinedReaction)
from .frontend.spec import Conditions, ModelSpec, build_spec
from .frontend.states import ScalingState, State
from .solvers.newton import SolverOptions, SteadyStateResults
from .solvers.ode import ODEOptions, log_time_grid

__version__ = "0.1.0"
