"""Profiling helpers: device-aware timing, XLA traces, cProfile.

TPU-native counterpart of the reference's developer tooling
(/root/reference/pycatkin/functions/profiling.py: PyCallGraph rendering,
cProfile wrapper, wall-clock timer). Call-graph rendering is replaced by
``jax.profiler`` traces (viewable in TensorBoard/XProf), and the timing
harness blocks on device results so asynchronous dispatch does not fake
speedups.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import time
from contextlib import contextmanager


def run_timed(fn, *args, repeats: int = 1, warmup: bool = True, **kwargs):
    """Wall-clock a function with device synchronization (reference
    profiling.py:49-58, plus ``block_until_ready`` correctness for
    asynchronously-dispatched JAX computations).

    Returns (result, seconds): ``seconds`` is the best of ``repeats``
    synchronized runs, excluding the optional warmup (which absorbs
    compilation).
    """
    import jax

    if warmup:
        jax.block_until_ready(fn(*args, **kwargs))
    best = float("inf")
    result = None
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return result, best


@contextmanager
def profile_trace(log_dir: str):
    """XLA/TPU profiler trace around a block (replaces the reference's
    PyCallGraph call-graph PNG, profiling.py:5-34). Inspect with
    TensorBoard's profile plugin or xprof pointed at ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def run_cprofiler(fn, *args, sort: str = "cumulative", lines: int = 30,
                  **kwargs):
    """Host-side cProfile of a callable (reference profiling.py:37-46).
    Returns (result, report_text)."""
    prof = cProfile.Profile()
    prof.enable()
    result = fn(*args, **kwargs)
    prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats(sort).print_stats(lines)
    return result, buf.getvalue()
