"""Profiling helpers: device-aware timing, XLA traces, cProfile.

TPU-native counterpart of the reference's developer tooling
(/root/reference/pycatkin/functions/profiling.py: PyCallGraph rendering,
cProfile wrapper, wall-clock timer). Call-graph rendering is replaced by
``jax.profiler`` traces (viewable in TensorBoard/XProf), and the timing
harness fences on device results so asynchronous dispatch cannot fake
speedups.

Timing-fence design (round-4 measurement, docs/round4_notes.md): on the
tunneled axon TPU backend ``jax.block_until_ready`` does NOT synchronize
(0.6 ms reported "wall" for a 5.1 s computation), and each device->host
materialization call costs a full tunnel round trip. The only honest
fence is therefore a device-side checksum reduced to ONE scalar whose
value depends on every output, materialized once: the computation cannot
complete the scalar without executing the whole program chain, and only
~8 bytes cross the wire inside the timed window.
"""

from __future__ import annotations

import contextlib
import cProfile
import io
import pstats
import threading
import time
from contextlib import contextmanager

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _obs
from .. import san as _san


# ---------------------------------------------------------------------
# Structured diagnostics event log. The degradation ladder
# (robustness/ladder.py) and the dispatchers record every non-fatal
# failure-handling decision here (retry exhaustion, requeue, host
# fallback, salvage), so a run that limped home carries machine-
# readable evidence of HOW -- drivers fold drain_events() into their
# end-of-run reports instead of scraping stderr.
#
# Storage is run-scoped since the obs subsystem landed: events go to
# the AMBIENT RunTrace (pycatkin_tpu.obs.trace) -- the process root
# trace when no ``obs.run_trace()`` context is open, which is exactly
# the old process-global behavior, so no legacy call site changes.


def record_event(kind: str, **fields) -> dict:
    """Append one structured diagnostics event ({'kind': kind, 't':
    monotonic seconds, **fields}) to the ambient trace and return it."""
    return _obs.current_trace().record(kind, **fields)


def peek_events(kind: str | None = None) -> list:
    """The ambient trace's events recorded so far (optionally filtered
    by kind), without clearing them."""
    return _obs.current_trace().peek(kind)


def drain_events() -> list:
    """Return AND clear the ambient trace's events (end-of-run report
    hook)."""
    return _obs.current_trace().drain()


@contextmanager
def span(label: str, **fields):
    """Wall-clock one region of the sweep hot path into the event log::

        with span("rescue pass", strategy="ptc"):
            ...

    Records ONE ``{"kind": "span", "label": label, "dur": seconds}``
    event on exit (exceptions included -- a span that died still shows
    how long it ran), extended with span/parent ids so the obs
    exporters can rebuild the tree. Spans are the variance-forensics
    primitive: bench.py diffs per-trial span events to attribute
    slow-trial outliers to a named region (dispatch, rescue pass, tail
    sync, in-band compile) instead of guessing from total walls."""
    with _obs.trace_span(label, **fields):
        yield


# ---------------------------------------------------------------------
# Host-sync accounting. Every BLOCKING device->host materialization on
# the sweep hot path goes through :func:`host_sync` -- the one choke
# point -- so a process-wide counter can certify the sync budget: on the
# tunneled axon backend each materialization call costs ~0.8-1.2 s of
# round trip regardless of payload, which makes "how many times did the
# host block on the device" the primary latency metric of a sweep. The
# budget is CONTRACTUAL: a clean (zero-failure) sweep_steady_state may
# perform at most 2 counted syncs (tests/test_sync_budget.py; the fused
# one-dispatch tail spends 1 -- the packed bundle), and
# tools/lint_host_syncs.py statically flags raw np.asarray/int(jnp.
# materializations in the hot-path functions that bypass this counter.
_SYNC_LOCK = threading.Lock()
_SYNC_COUNT = 0
_SYNC_LABELS: list = []


def host_sync(value, label: str = ""):  # pclint: disable=PCL013 -- this IS the counted sync choke point the budget measures
    """Materialize ``value`` onto the host (the blocking sync point) and
    count it ONCE. ``value`` is usually a single array (returns the
    numpy array, the historical contract); a tuple/list/dict of arrays
    is transferred as ONE batched ``jax.device_get`` and returned with
    every leaf as numpy -- a pytree of masks costs one counted round
    trip, not one per leaf. ``label`` tags the site for debugging (see
    :func:`sync_labels`)."""
    global _SYNC_COUNT
    with _SYNC_LOCK:
        _SYNC_COUNT += 1
        _SYNC_LABELS.append(label)
    # Run-scoped attribution rides alongside the process-wide counter:
    # the ambient trace counts the sync for its own sync_budget and
    # records a "sync" instant event (label + enclosing span) so the
    # exported trace reproduces the budget labels.
    _obs.note_sync(label)
    _metrics.counter("pycatkin_host_syncs_total",
                     "counted blocking device->host syncs").inc()
    # Sanitizer seam (pcsan, PYCATKIN_SAN=1): inside a strict sync
    # region the budget check raises HERE -- the counted call site --
    # and the pulls below run flagged as counted so the patched
    # np.asarray/device_get seams wave them through.
    if _san.enabled():
        from ..san import syncs as _san_syncs
        _san_syncs.note_counted_sync(label)
        counted_cm = _san_syncs.counted()
    else:
        counted_cm = contextlib.nullcontext()
    # The materialization below is the actual blocking window: its
    # duration (not just its count) is what the tunnel bills, so it is
    # histogrammed per label -- sync COST is budgetable alongside sync
    # count (docs/observability.md).
    t0 = time.perf_counter()
    try:
        with counted_cm:
            if isinstance(value, (tuple, list, dict)):
                import jax
                return jax.tree_util.tree_map(np.asarray,
                                              jax.device_get(value))
            return np.asarray(value)
    finally:
        _metrics.histogram(
            "pycatkin_host_sync_seconds",
            "blocked wall of each counted device->host sync",
        ).observe(time.perf_counter() - t0, label=label)


def sync_count() -> int:
    """Process-wide number of counted host syncs since the last
    :func:`reset_sync_count`."""
    return _SYNC_COUNT


def sync_labels() -> list:
    """Labels of the counted syncs since the last reset (one entry per
    :func:`host_sync` call, in order)."""
    return list(_SYNC_LABELS)


def reset_sync_count() -> int:
    """Zero the host-sync counter; returns the count it held."""
    global _SYNC_COUNT
    with _SYNC_LOCK:
        prior = _SYNC_COUNT
        _SYNC_COUNT = 0
        _SYNC_LABELS.clear()
    return prior


@contextmanager
def sync_budget():
    """Context manager measuring host syncs inside a block::

        with sync_budget() as b:
            sweep_steady_state(...)
        assert b.count <= 3

    Measured against the AMBIENT trace's per-trace counters, so the
    budget is a real attribution: threads syncing under their own
    ``obs.run_trace()`` contexts no longer pollute a foreign budget
    (the concurrency bug the process-global counter had). Without an
    open trace this reads the process root trace, which in a
    single-threaded program is identical to the historical
    process-wide measurement."""
    class _Budget:
        count = 0
        labels: list = []
    b = _Budget()
    tr = _obs.current_trace()
    with tr.lock:
        start = tr.sync_count
        start_len = len(tr.sync_labels)
    try:
        yield b
    finally:
        with tr.lock:
            b.count = tr.sync_count - start
            b.labels = list(tr.sync_labels[start_len:])


def checksum_fence():
    """Build a jitted pytree -> scalar checksum for honest timing.

    The returned function reduces every array leaf of its argument to
    one float64 scalar (non-finite entries counted as 0 so a NaN lane
    cannot poison the fence, with their count folded in so they still
    influence the value). Materializing that single scalar forces the
    entire producing program chain to execute; nothing upstream can be
    skipped because the value depends on every element of every leaf.

    Non-array leaves (strings, None, arbitrary Python objects riding a
    result dict) are skipped -- only numeric leaves can carry deferred
    device work, and ``jax.jit`` would reject the rest.

    Compiled per (structure, shapes) by ``jax.jit``'s cache -- build it
    once and reuse it across repeats so the compile stays out of timed
    regions.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _fence_arrays(leaves):
        tot = jnp.zeros((), dtype=jnp.float64)
        for leaf in leaves:
            x = jnp.asarray(leaf)
            if jnp.issubdtype(x.dtype, jnp.floating):
                finite = jnp.isfinite(x)
                tot = tot + jnp.sum(jnp.where(finite, x, 0.0),
                                    dtype=jnp.float64)
                tot = tot + jnp.sum(~finite, dtype=jnp.float64)
            elif jnp.issubdtype(x.dtype, jnp.complexfloating):
                finite = jnp.isfinite(x)
                tot = tot + jnp.sum(
                    jnp.where(finite, x.real + x.imag, 0.0),
                    dtype=jnp.float64)
                tot = tot + jnp.sum(~finite, dtype=jnp.float64)
            else:
                tot = tot + jnp.sum(x, dtype=jnp.float64)
        return tot

    import numbers

    def fence(tree):
        leaves = [x for x in jax.tree_util.tree_leaves(tree)
                  if isinstance(x, (jax.Array, np.ndarray, np.generic,
                                    numbers.Number))]
        return _fence_arrays(leaves)

    return fence


def result_fence():
    """Sweep-result timing fence shared by bench.py and bench_suite.py
    (kept in the library so their fence guarantees cannot drift apart):
    the returned jitted function reduces y + finite activities + success
    flags to ONE scalar whose value depends on every output, so a
    single materialization (one tunnel round trip) forces the whole
    program chain to execute with nothing hidden."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fence(y, activity, success):
        act = jnp.where(jnp.isfinite(activity), activity, 0.0)
        return jnp.sum(y) + jnp.sum(act) + jnp.sum(success)

    return fence


def materialize(value) -> float:
    """Force ``value`` (the scalar from a fence) onto the host and
    return it as a Python float -- the actual synchronization point."""
    return float(np.asarray(value))


# One process-wide fence program: its jax.jit cache (keyed on result
# structure/shapes) then persists across run_timed calls, so repeated
# timings of same-shaped results never recompile the fence.
_RUN_TIMED_FENCE = None


def run_timed(fn, *args, repeats: int = 1, warmup: bool = True, **kwargs):
    """Wall-clock a function with an honest device fence (reference
    profiling.py:49-58, corrected for asynchronously-dispatched JAX
    computations on backends where ``block_until_ready`` is broken).

    Each timed call is fenced by a device-side checksum over the full
    result pytree, materialized as one scalar (see module docstring for
    why ``block_until_ready`` is not trusted). The optional warmup call
    absorbs compilation of both ``fn`` and the fence program. With
    ``warmup=False`` the fence is still compiled untimed when the
    result structure can be inferred (``jax.eval_shape`` on ``fn`` --
    tracing only, no execution); if ``fn`` is not traceable (host-side
    code), the first repeat absorbs the fence compile.

    Returns (result, seconds): ``seconds`` is the best of ``repeats``
    fenced runs, excluding the warmup.
    """
    global _RUN_TIMED_FENCE
    if _RUN_TIMED_FENCE is None:
        _RUN_TIMED_FENCE = checksum_fence()
    fence = _RUN_TIMED_FENCE

    if warmup:
        materialize(fence(fn(*args, **kwargs)))
    else:
        try:
            import jax
            import jax.numpy as jnp
            shapes = jax.eval_shape(fn, *args, **kwargs)
            dummy = jax.tree_util.tree_map(
                lambda s: (jnp.zeros(s.shape, s.dtype)
                           if hasattr(s, "shape") else s), shapes)
            materialize(fence(dummy))        # fence compile, untimed
        except Exception:
            pass                             # non-traceable fn
    best = float("inf")
    result = None
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        materialize(fence(result))
        best = min(best, time.perf_counter() - t0)
    return result, best


@contextmanager
def profile_trace(log_dir: str):
    """XLA/TPU profiler trace around a block (replaces the reference's
    PyCallGraph call-graph PNG, profiling.py:5-34). Inspect with
    TensorBoard's profile plugin or xprof pointed at ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def run_cprofiler(fn, *args, sort: str = "cumulative", lines: int = 30,
                  **kwargs):
    """Host-side cProfile of a callable (reference profiling.py:37-46).
    Returns (result, report_text)."""
    prof = cProfile.Profile()
    prof.enable()
    result = fn(*args, **kwargs)
    prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats(sort).print_stats(lines)
    return result, buf.getvalue()
