"""Bounded retry on transient backend/transport failures.

The deployed TPU runtime reaches the compiler over HTTP (the axon
tunnel's remote-compile service); one dropped connection mid-compile
surfaces as ``JaxRuntimeError: INTERNAL: ... remote_compile: read body:
response body closed`` and, without a retry, costs the whole run (the
round-4 driver bench died exactly this way inside a rescue-pass
compile). A transient infrastructure flake is not a program error:
re-dispatching the identical call either hits the now-written
persistent-cache entry or re-runs a pure function, so a bounded retry
is always safe for the jitted-program call sites here.

Only errors matching known-transient transport/compiler-service
signatures are retried; genuine program errors (shape mismatches,
NaN-checking, OOM with its own semantics) re-raise immediately.
"""

from __future__ import annotations

import sys
import time

# Substrings identifying transport-layer / compile-service flakes, as
# observed on the tunneled backend plus the standard gRPC transient
# status codes. Matched case-insensitively against the exception text.
TRANSIENT_MARKERS = (
    "remote_compile",
    "read body",
    "response body closed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "transport closed",
    "failed to connect",
)


def is_transient_backend_error(exc: BaseException) -> bool:
    """True when ``exc`` looks like a transport/compile-service flake
    rather than a program error."""
    try:
        import jax
        if not isinstance(exc, jax.errors.JaxRuntimeError):
            return False
    except ImportError:                      # pragma: no cover
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in TRANSIENT_MARKERS)


def call_with_backend_retry(fn, *args, attempts: int = 3,
                            base_delay_s: float = 2.0, label: str = "",
                            **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying up to ``attempts`` total
    tries on transient backend errors (exponential backoff, logged to
    stderr). Non-transient exceptions propagate immediately; the last
    transient failure propagates after the final attempt."""
    for i in range(attempts):
        try:
            return fn(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 -- filtered below
            if i + 1 >= attempts or not is_transient_backend_error(exc):
                raise
            delay = base_delay_s * (2.0 ** i)
            print(f"transient backend error{f' in {label}' if label else ''}"
                  f" (attempt {i + 1}/{attempts}, retrying in "
                  f"{delay:.0f} s): {str(exc).splitlines()[0][:200]}",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
    raise AssertionError("unreachable")      # pragma: no cover
