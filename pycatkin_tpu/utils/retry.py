"""Bounded retry on transient backend/transport failures.

The deployed TPU runtime reaches the compiler over HTTP (the axon
tunnel's remote-compile service); one dropped connection mid-compile
surfaces as ``JaxRuntimeError: INTERNAL: ... remote_compile: read body:
response body closed`` and, without a retry, costs the whole run (the
round-4 driver bench died exactly this way inside a rescue-pass
compile). A transient infrastructure flake is not a program error:
re-dispatching the identical call either hits the now-written
persistent-cache entry or re-runs a pure function, so a bounded retry
is always safe for the jitted-program call sites here.

Only errors matching known-transient transport/compiler-service
signatures are retried; genuine program errors (shape mismatches,
NaN-checking, OOM with its own semantics) re-raise immediately.
Transient classification covers both ``JaxRuntimeError`` text markers
and raw gRPC-style exceptions that expose a status ``code()`` (the
tunnel occasionally surfaces those undressed, before jax wraps them).

Backoff is full-jitter exponential (AWS architecture-blog recipe:
``sleep ~ U(0, min(cap, base * 2**i))``) -- synchronized lanes/workers
retrying a shared flaky service must not stampede it in lockstep -- and
an optional overall ``deadline_s`` bounds the total time spent inside
one retried unit (a sweep chunk must fail into the degradation ladder,
not sleep forever). Retry logging is capped per call so a long retry
storm cannot flood stderr.

Every attempt also passes through the fault-injection hooks
(robustness/faults.py) keyed by the call's ``label``, which is how the
test suite exercises each branch of this module deterministically.
"""

from __future__ import annotations

import asyncio
import random
import signal
import sys
import time
from dataclasses import dataclass

# Substrings identifying transport-layer / compile-service flakes, as
# observed on the tunneled backend plus the standard gRPC transient
# status codes. Matched case-insensitively against the exception text.
TRANSIENT_MARKERS = (
    "remote_compile",
    "read body",
    "response body closed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "transport closed",
    "failed to connect",
)

# gRPC status codes that are infrastructure-transient (retry-safe for
# pure re-dispatch). RESOURCE_EXHAUSTED is deliberately absent: on
# accelerators it usually means device OOM, which a retry cannot fix.
TRANSIENT_GRPC_CODES = frozenset(
    {"UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED"})

# Exception TYPES that are transient by construction: asyncio/socket
# transport failures at the serve tier. A reset/refused/half-read
# connection and a burned deadline are preemption-shaped -- the peer
# (or the route to it) went away, not the program -- so the front
# router's failover and the TCP clients' retries treat them exactly
# like a transient backend error (same-width sweeps are deterministic,
# so a duplicated dispatch is bit-identical and therefore safe).
# asyncio.TimeoutError is TimeoutError on 3.11+, but keep both spelled
# out for older interpreters; IncompleteReadError is the stream-reader
# face of a torn connection.
TRANSIENT_CONNECTION_TYPES = (
    ConnectionResetError,
    ConnectionRefusedError,
    ConnectionAbortedError,
    BrokenPipeError,
    asyncio.IncompleteReadError,
    asyncio.TimeoutError,
    TimeoutError,
)

# Stop printing per-retry lines after this many within one call; a
# single summary line marks the suppression.
_LOG_CAP = 3

# Process-wide jitter source (full-jitter backoff); call sites needing
# reproducible delays pass their own ``rng``.
_jitter_rng = random.Random()


def _grpc_status_name(exc: BaseException) -> str | None:
    """Status-code name of a gRPC-style exception (``exc.code()``
    returning an enum with ``.name``), or None."""
    code = getattr(exc, "code", None)
    if not callable(code):
        return None
    try:
        status = code()
    except Exception:                        # pragma: no cover
        return None
    name = getattr(status, "name", None)
    return name if isinstance(name, str) else None


def is_transient_backend_error(exc: BaseException) -> bool:
    """True when ``exc`` looks like a transport/compile-service flake
    rather than a program error.

    Three classes qualify: asyncio/socket transport failures by TYPE
    (:data:`TRANSIENT_CONNECTION_TYPES` -- the taxonomy the front
    router's failover and the serve clients share with this wrapper),
    ``jax.errors.JaxRuntimeError`` whose text carries a
    :data:`TRANSIENT_MARKERS` signature, and raw gRPC-style exceptions
    (``grpc.RpcError`` or anything exposing ``code()``) whose status
    is in :data:`TRANSIENT_GRPC_CODES`. Arbitrary Python exceptions
    that merely CONTAIN a marker string (e.g.
    ``ValueError("remote_compile")``) stay non-transient -- a program
    error must never be silently re-run."""
    if isinstance(exc, TRANSIENT_CONNECTION_TYPES):
        return True
    status = _grpc_status_name(exc)
    if status is not None:
        return status.upper() in TRANSIENT_GRPC_CODES
    try:
        import jax
        if not isinstance(exc, jax.errors.JaxRuntimeError):
            return False
    except ImportError:                      # pragma: no cover
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in TRANSIENT_MARKERS)


def backoff_delay(attempt: int, base_delay_s: float,
                  max_delay_s: float, jitter: bool = True,
                  rng=None) -> float:
    """Full-jitter exponential backoff delay before retry ``attempt``
    (0-based): ``U(0, min(max_delay_s, base_delay_s * 2**attempt)]``
    when ``jitter`` is on, the deterministic cap otherwise. The one
    backoff formula shared by the in-process retry wrapper below and
    the elastic scheduler's worker-restart loop
    (robustness/scheduler.py) -- synchronized workers recovering from
    a shared failure must not stampede back in lockstep."""
    delay = min(max_delay_s, base_delay_s * (2.0 ** attempt))
    if jitter:
        rng = rng if rng is not None else _jitter_rng
        delay = rng.uniform(0.0, delay)
    return delay


@dataclass(frozen=True)
class WorkerExit:
    """Classification of one worker subprocess exit into the retry
    taxonomy: ``transient`` deaths (signal-death, timeout) are
    preemption-shaped -- re-dispatching the same block is pure, so a
    requeue/restart is always safe, exactly like a transient backend
    error in :func:`is_transient_backend_error`; a ``nonzero-exit`` is
    a program error (a re-run of the identical input will likely die
    again) and only counts toward poison-chunk bisection."""
    kind: str          # "ok" | "signal-death" | "nonzero-exit" | "timeout"
    transient: bool
    detail: str


def classify_worker_exit(returncode: int | None,
                         timed_out: bool = False) -> WorkerExit:
    """Map a subprocess return code (``Popen.returncode`` semantics:
    negative = killed by that signal) onto the retry taxonomy."""
    if timed_out:
        return WorkerExit("timeout", True,
                          "worker exceeded its deadline (treated like "
                          "DEADLINE_EXCEEDED: requeue-safe)")
    if returncode is None:
        return WorkerExit("ok", False, "worker still running")
    if returncode == 0:
        return WorkerExit("ok", False, "clean exit")
    if returncode < 0:
        try:
            name = signal.Signals(-returncode).name
        except ValueError:
            name = f"signal {-returncode}"
        return WorkerExit("signal-death", True,
                          f"killed by {name} (preemption-shaped: "
                          f"requeue-safe)")
    return WorkerExit("nonzero-exit", False,
                      f"exit status {returncode} (program error: "
                      f"re-run of the identical block may die again)")


def call_with_backend_retry(fn, *args, attempts: int = 3,
                            base_delay_s: float = 2.0,
                            max_delay_s: float = 60.0,
                            deadline_s: float | None = None,
                            jitter: bool = True, rng=None,
                            label: str = "", **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying up to ``attempts`` total
    tries on transient backend errors.

    Backoff before attempt ``i+1`` is ``min(max_delay_s,
    base_delay_s * 2**i)``, drawn uniformly from ``(0, that]`` when
    ``jitter`` is on (full jitter -- desynchronizes fleets of workers
    hammering one recovering service; pass ``rng`` for deterministic
    tests). ``deadline_s`` bounds the TOTAL elapsed time across
    attempts and sleeps: when the next backoff would cross it, the
    current failure propagates instead (the caller's degradation
    ladder owns what happens next).

    Non-transient exceptions propagate immediately; the last transient
    failure propagates after the final attempt. Per-retry log lines are
    capped at ``_LOG_CAP`` per call."""
    from ..robustness import faults

    rng = rng if rng is not None else _jitter_rng
    start = time.monotonic()
    logged = 0
    for i in range(attempts):
        plan = faults.active_plan()
        try:
            if plan is not None:
                plan.on_call(label)
            out = fn(*args, **kwargs)
            if plan is not None:
                out = plan.on_result(label, out)
            return out
        except Exception as exc:  # noqa: BLE001 -- filtered below
            if i + 1 >= attempts or not is_transient_backend_error(exc):
                raise
            delay = backoff_delay(i, base_delay_s, max_delay_s,
                                  jitter=jitter, rng=rng)
            if deadline_s is not None and \
                    time.monotonic() - start + delay > deadline_s:
                raise
            # Absorbed flakes must still be visible in the structured
            # diagnostics, not only on stderr: a sweep that "worked"
            # after 40 retries is a degraded run.
            from . import profiling
            from ..obs import metrics as _metrics
            profiling.record_event(
                "retry", label=label, attempt=i + 1, attempts=attempts,
                delay_s=round(delay, 3),
                error=str(exc).splitlines()[0][:200])
            _metrics.counter("pycatkin_retry_attempts_total",
                             "transient backend errors absorbed by "
                             "the retry wrapper").inc()
            if logged < _LOG_CAP:
                print(f"transient backend error"
                      f"{f' in {label}' if label else ''}"
                      f" (attempt {i + 1}/{attempts}, retrying in "
                      f"{delay:.1f} s): "
                      f"{str(exc).splitlines()[0][:200]}",
                      file=sys.stderr, flush=True)
                logged += 1
                if logged == _LOG_CAP and attempts - (i + 1) > 1:
                    print(f"(suppressing further retry logs"
                          f"{f' for {label}' if label else ''})",
                          file=sys.stderr, flush=True)
            time.sleep(delay)
    raise AssertionError("unreachable")      # pragma: no cover
