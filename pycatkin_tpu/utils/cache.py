"""Persistent XLA compilation cache.

Every fresh process otherwise re-pays the full XLA compile of the big
batched programs (the 256x256 volcano program costs ~2 min to compile vs
~6 s to run). JAX ships a content-addressed persistent cache keyed on the
(HLO, compile options, backend) fingerprint; enabling it turns every
warm-start compile into a disk read.

This is deliberately opt-in-by-call (not import-time magic): library
imports must not write to disk, but every entry-point that owns a process
(bench.py, bench_suite.py, __graft_entry__.py, examples/*) calls
:func:`enable_persistent_cache` first thing.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Must run before the first compilation (any time before is fine — the
    flags are read per-compile). Thresholds are zeroed so even the small
    helper programs cache: the cost model ("only cache slow compiles")
    defaults to 1 s / 0 bytes minimums, which would skip exactly the
    many-small-programs pattern the sweep drivers produce.

    Returns the cache directory in use (None when disabled). Safe to
    call repeatedly.

    CPU backend: the cache is DISABLED. XLA:CPU persists AOT-compiled
    executables tagged with the compiling toolchain's CPU-feature set;
    reloading warns about feature mismatches (cpu_aot_loader) and can
    die executing them -- measured in this environment as a
    deterministic segfault inside compilation_cache
    .get_executable_and_time on a freshly written entry. CPU compiles
    are cheap relative to TPU's, so tests/virtual-mesh runs simply
    recompile; the cache stays on for TPU, where one volcano-scale
    compile costs minutes.
    """
    import jax

    if jax.default_backend() == "cpu":
        return None
    if cache_dir is None:
        cache_dir = os.environ.get("PYCATKIN_JAX_CACHE_DIR", _DEFAULT_DIR)
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # Read-only install (e.g. system site-packages): fall back to a
        # per-user cache rather than aborting the entry point. Prefer the
        # user's own cache dir over a world-shared temp path, and derive
        # the user id portably (os.getuid does not exist on Windows).
        if hasattr(os, "getuid"):
            uid = str(os.getuid())
        else:
            import getpass
            uid = getpass.getuser()
        base = os.environ.get("XDG_CACHE_HOME",
                              os.path.join(os.path.expanduser("~"),
                                           ".cache"))
        try:
            cache_dir = os.path.join(base, f"pycatkin_jax_cache_{uid}")
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            import tempfile
            cache_dir = os.path.join(tempfile.gettempdir(),
                                     f"pycatkin_jax_cache_{uid}")
            os.makedirs(cache_dir, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return cache_dir
