from .io import (load_results, save_results, save_state_energy,
                 save_state_vibrations, save_system_json, system_to_dict)
from .profiling import profile_trace, run_cprofiler, run_timed
