"""Serialization: model checkpoints and derived-data caches.

The reference persists models by pickling every class
(state.py:24-29/413-443, reaction.py:18-23, old_system.py:24-29) and
caches DFT-derived data as ``.dat`` files (state.py:213-245). Pickle is
replaced here by a *JSON round-trip*: :func:`system_to_dict` serializes a
System (with all resolved energies/frequencies inlined) back into the
reference input schema, so the checkpoint is human-readable, diffable and
loads through the ordinary :func:`read_from_input_file`. The ``.dat``
writers keep the reference's exact formats so cached files interoperate
with reference data trees. Sweep results checkpoint as ``.npz`` bundles.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..frontend.reactions import (ReactionDerivedReaction,
                                  UserDefinedReaction)
from ..frontend.states import GAS, ScalingState
from ..models.reactor import CSTReactor, InfiniteDilutionReactor


def save_state_energy(state, path: str):
    """Write ``<energy> eV`` (reference state.py:213-227 save_energy;
    readable by energy_source='datafile')."""
    state.load()
    assert state.Gelec is not None, f"state {state.name} has no energy"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(f"{state.Gelec:.15e} eV\n")
    os.replace(tmp, path)


def save_state_vibrations(state, path: str):
    """Write ``i f = <Hz> Hz`` / ``i f/i = <Hz> Hz`` lines (reference
    state.py:229-245 save_vibrations; readable by
    freq_source='datafile')."""
    state.load()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        k = 0
        for f in np.asarray(state.freq).ravel():
            fh.write(f"{k} f = {f:.15e} Hz\n")
            k += 1
        for f in np.asarray(state.i_freq if state.i_freq is not None
                            else []).ravel():
            fh.write(f"{k} f/i = {f:.15e} Hz\n")
            k += 1
    os.replace(tmp, path)


def _state_cfg(st, sname=None) -> dict:
    """Serialize one state. ``sname`` maps gasdata partner State ->
    checkpoint name (needed for inlined donor states that were renamed
    on collision)."""
    if sname is None:
        sname = lambda s: s.name  # noqa: E731
    st.load()
    cfg = {"state_type": st.state_type}
    if st.sigma is not None:
        cfg["sigma"] = st.sigma
    if st.mass is not None:
        cfg["mass"] = st.mass
    if st.inertia is not None:
        cfg["inertia"] = list(np.asarray(st.inertia, dtype=float).ravel())
    if st.freq is not None and np.asarray(st.freq).size:
        cfg["freq"] = list(np.asarray(st.freq, dtype=float).ravel())
        if st.i_freq is not None and np.asarray(st.i_freq).size:
            cfg["i_freq"] = list(np.asarray(st.i_freq, dtype=float).ravel())
    for key in ("Gelec", "Gzpe", "Gvibr", "Gtran", "Grota", "Gfree"):
        val = getattr(st, key)
        if val is not None:
            cfg[key] = val
    if st.add_to_energy:
        cfg["add_to_energy"] = st.add_to_energy
    if not st.truncate_freq:
        cfg["truncate_freq"] = False
    if st.gasdata is not None:
        cfg["gasdata"] = {
            "fraction": list(st.gasdata["fraction"]),
            "state": [sname(s) if hasattr(s, "name") else s
                      for s in st.gasdata["state"]],
        }
    if isinstance(st, ScalingState):
        cfg["scaling_coeffs"] = st.scaling_coeffs
        cfg["scaling_reactions"] = {
            key: {"reaction": (e["reaction"].name
                               if hasattr(e["reaction"], "name")
                               else e["reaction"]),
                  **({"multiplicity": e["multiplicity"]}
                     if "multiplicity" in e else {})}
            for key, e in st.scaling_reactions.items()}
        if st.dereference:
            cfg["dereference"] = True
        if st.use_descriptor_as_reactant:
            cfg["use_descriptor_as_reactant"] = True
    return cfg


def _reaction_cfg(rx, sname=None, base_names=None) -> dict:
    """Serialize one reaction. ``sname`` maps State -> checkpoint name
    (defaults to the state's own name); ``base_names`` maps id(base
    reaction) -> checkpoint name for foreign donor bases."""
    if sname is None:
        sname = lambda s: s.name  # noqa: E731
    cfg = {"reac_type": rx.reac_type,
           "area": rx.area,
           "reactants": [sname(s) for s in rx.reactants],
           "products": [sname(s) for s in rx.products],
           "TS": [sname(s) for s in rx.TS] if rx.TS else None}
    if not rx.reversible:
        cfg["reversible"] = False
    if rx.scaling != 1.0:
        cfg["scaling"] = rx.scaling
    if isinstance(rx, ReactionDerivedReaction):
        base = rx.base_reaction
        cfg["base_reaction"] = ((base_names or {}).get(id(base))
                                or base.name)
    if isinstance(rx, UserDefinedReaction):
        for key in ("dErxn_user", "dGrxn_user", "dEa_fwd_user",
                    "dGa_fwd_user", "dEa_rev_user", "dGa_rev_user"):
            val = getattr(rx, key)
            if val is not None:
                cfg[key] = val
    return cfg


def _collect_foreign_bases(sim):
    """Foreign donor base reactions/states of ReactionDerivedReactions
    (Butadiene-style MKM: bases live in a donor DFT system). Returns
    (base_states {ckpt name -> State}, base_rx {ckpt name -> Reaction},
    sname mapper, base_names {id(rx) -> ckpt name}) so the checkpoint can
    inline the donor energetics and reload WITHOUT re-supplying
    base_system."""
    base_states, base_rx = {}, {}
    state_names, base_names = {}, {}
    taken_states = set(sim.states)
    taken_rx = set(sim.reactions)

    def fresh(name, taken, extra):
        out, k = name, 1
        while out in taken or out in extra:
            out = f"{name}@base{k}"
            k += 1
        return out

    # Transitive worklist: a donor base may itself be derived from yet
    # another donor reaction.
    work = [rx.base_reaction for rx in sim.reactions.values()
            if isinstance(rx, ReactionDerivedReaction)]
    while work:
        b = work.pop()
        if sim.reactions.get(b.name) is b or id(b) in base_names:
            continue
        bname = fresh(b.name, taken_rx, base_rx)
        base_names[id(b)] = bname
        base_rx[bname] = b
        if isinstance(b, ReactionDerivedReaction):
            work.append(b.base_reaction)
        for s in list(b.reactants) + list(b.products) + list(b.TS or []):
            if id(s) in state_names or sim.states.get(s.name) is s:
                continue
            if s.is_scaling:
                raise NotImplementedError(
                    f"donor base state {s.name} is a ScalingState; "
                    "scaling relations must resolve within one system "
                    "(build_spec enforces the same)")
            nm = fresh(s.name, taken_states, base_states)
            state_names[id(s)] = nm
            base_states[nm] = s
            # Inline gasdata partners of donor states too, so the
            # checkpoint's gas-mixture corrections resolve on reload.
            for g in (s.gasdata or {}).get("state", []):
                if (hasattr(g, "name") and id(g) not in state_names
                        and sim.states.get(g.name) is not g):
                    gn = fresh(g.name, taken_states, base_states)
                    state_names[id(g)] = gn
                    base_states[gn] = g

    def sname(s):
        return state_names.get(id(s), s.name)

    return base_states, base_rx, sname, base_names


def system_to_dict(sim) -> dict:
    """Serialize a System into the reference input-file schema with all
    resolved data inlined -- the pickle-replacement checkpoint. Foreign
    donor base reactions (and their states) are inlined under the
    'base reactions' / 'base states' extension sections, which the loader
    reads back as energy-only donors."""
    p = sim.params["pressure"]
    states, scaling = {}, {}
    for name, st in sim.states.items():
        (scaling if isinstance(st, ScalingState) else states)[name] = \
            _state_cfg(st)

    base_states, base_rx, sname, base_names = _collect_foreign_bases(sim)

    plain, manual, derived = {}, {}, {}
    for name, rx in sim.reactions.items():
        cfg = _reaction_cfg(rx, base_names=base_names)
        if isinstance(rx, ReactionDerivedReaction):
            derived[name] = cfg
        elif isinstance(rx, UserDefinedReaction):
            manual[name] = cfg
        else:
            plain[name] = cfg

    def _unscale_gas(entries):
        # Stored in bar; the schema holds fractions of total pressure
        # (loader multiplies by p/1e5, reference load_input.py:50).
        out = {}
        for name, val in (entries or {}).items():
            if sim.states[name].state_type == GAS:
                out[name] = val / (p / 1.0e5)
            else:
                out[name] = val
        return out

    sys_cfg = {
        "times": list(sim.params["times"]) if sim.params["times"] else None,
        "T": sim.params["temperature"],
        "p": p,
        "start_state": _unscale_gas(sim.params.get("start_state")),
        "verbose": sim.params["verbose"],
        "use_jacobian": sim.params["jacobian"],
        "rtol": sim.params["rtol"],
        "atol": sim.params["atol"],
    }
    if sim.params.get("inflow_state"):
        sys_cfg["inflow_state"] = _unscale_gas(sim.params["inflow_state"])
    if getattr(sim, "desorption_model", "detailed_balance") != \
            "detailed_balance":
        sys_cfg["desorption_model"] = sim.desorption_model

    cfg = {"states": states}
    if scaling:
        cfg["scaling relation states"] = scaling
    if base_states:
        cfg["base states"] = {n: _state_cfg(s, sname=sname)
                              for n, s in base_states.items()}
    cfg["system"] = sys_cfg
    if plain:
        cfg["reactions"] = plain
    if manual:
        cfg["manual reactions"] = manual
    if base_rx:
        cfg["base reactions"] = {
            n: _reaction_cfg(r, sname=sname, base_names=base_names)
            for n, r in base_rx.items()}
    if derived:
        cfg["reaction derived reactions"] = derived
    if sim.reactor is not None:
        if isinstance(sim.reactor, CSTReactor):
            params = {k: v for k, v in sim.reactor.params().items()
                      if v is not None}
            cfg["reactor"] = {"CSTReactor": params}
        else:
            cfg["reactor"] = "InfiniteDilutionReactor"
    if sim.energy_landscapes:
        cfg["energy landscapes"] = {
            name: {"minima": [[s.name for s in entry]
                              for entry in lsc.minima],
                   "labels": list(lsc.labels)}
            for name, lsc in sim.energy_landscapes.items()}
    return cfg


def save_system_json(sim, path: str):
    """Checkpoint a System as a reference-schema JSON input file
    (tmp + ``os.replace``: a concurrent reader -- or a reload after a
    mid-write kill -- never parses a torn checkpoint)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(system_to_dict(sim), fh, indent=1)
    os.replace(tmp, path)


def save_results(path: str, **arrays):
    """Checkpoint sweep/grid result arrays as a compressed ``.npz``
    (replaces the reference's per-run pickle dumps for results)."""
    np.savez_compressed(path, **{k: np.asarray(v)
                                 for k, v in arrays.items()})


def load_results(path: str) -> dict:
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}


def _fsync_enabled() -> bool:
    """The paranoid-durability knob: ``PYCATKIN_JOURNAL_FSYNC=1`` adds
    payload-file and directory fsyncs to atomic result writes, closing
    the power-loss window where a rename is journaled but the renamed
    bytes never reached the platter. Off by default -- a process kill
    (the failure the elastic scheduler actually drills) is already
    covered by the write-then-rename order alone."""
    import os
    return os.environ.get("PYCATKIN_JOURNAL_FSYNC",
                          "").lower() in ("1", "on", "true", "yes")


def atomic_save_results(path: str, arrays: dict,
                        fsync: bool | None = None) -> None:
    """Atomically checkpoint result arrays as a compressed ``.npz``:
    the payload is written to a temp name in the same directory and
    ``os.replace``d into place, so a reader (journal replay, elastic
    merge, a lease thief) either sees the complete file or no file --
    never a torn one, even when the writer is SIGKILLed mid-write.

    ``fsync`` (default: the ``PYCATKIN_JOURNAL_FSYNC`` env knob) also
    fsyncs the payload before the rename and the directory after it,
    extending the guarantee from "kill-safe" to "power-loss-safe".
    Writing to an open file object (not a path) keeps ``np.savez``
    from appending its own ``.npz`` suffix and breaking the rename."""
    import os
    if fsync is None:
        fsync = _fsync_enabled()
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **{k: np.asarray(v)
                                   for k, v in arrays.items()})
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    os.replace(tmp, path)
    if fsync:
        dfd = os.open(os.path.dirname(os.path.abspath(path)),
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


def _truncate_torn_tail(path: str) -> None:
    """Repair a ``.jsonl`` file whose FINAL line was torn by a kill
    mid-append: if the file does not end in a newline, truncate back to
    the byte after the last ``\\n`` (or to empty when no newline
    exists). Complete records are never touched; this keeps a
    subsequent append from gluing a new record onto the torn fragment
    and producing a corrupt NON-final line that
    :func:`read_json_lines` refuses."""
    import os
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0:
        return
    with open(path, "rb+") as fh:
        fh.seek(-1, os.SEEK_END)
        if fh.read(1) == b"\n":
            return
        # Scan backwards in chunks for the last newline.
        pos = size
        chunk = 4096
        keep = 0
        while pos > 0:
            step = min(chunk, pos)
            fh.seek(pos - step)
            buf = fh.read(step)
            nl = buf.rfind(b"\n")
            if nl >= 0:
                keep = pos - step + nl + 1
                break
            pos -= step
        fh.truncate(keep)
        fh.flush()
        os.fsync(fh.fileno())


def append_json_line(path: str, record: dict) -> None:
    """Durably append one JSON object as a line to a ``.jsonl`` file
    (the sweep journal's manifest format, robustness/journal.py): the
    line is flushed AND fsynced before returning, so a record that
    this function reported written survives a process kill. A torn
    final line left by a previous kill is truncated away first, so
    appending after a crash never corrupts the file."""
    import os
    _truncate_torn_tail(path)
    line = json.dumps(record, sort_keys=True)
    with open(path, "a") as fh:
        fh.write(line + "\n")
        fh.flush()
        os.fsync(fh.fileno())


def read_json_lines(path: str, *, tolerate_torn_tail: bool = True) -> list:
    """Read a ``.jsonl`` file written by :func:`append_json_line`.

    With ``tolerate_torn_tail=True`` (the crash-replay mode used by
    both the chunk journal, robustness/journal.py, and the request
    journal, serve/durable.py) a truncated FINAL line is dropped: a
    kill mid-append leaves at most one partial record, which by the
    fsync discipline of :func:`append_json_line` was never acknowledged
    to anyone. With ``tolerate_torn_tail=False`` a torn tail raises
    like any other corruption -- use it when the file is expected to be
    complete (e.g. an atomically-published artifact). A corrupt
    NON-final line always raises -- that is damage, not a crash
    artifact."""
    records = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1 and tolerate_torn_tail:
                break
            raise
    return records
