"""JSON input loader: reference-compatible schema -> System facade.

Reads the exact input schema of the reference
(/root/reference/pycatkin/functions/load_input.py:9-168): top-level
sections ``states``, ``scaling relation states``, ``system``,
``reactions``, ``manual reactions``, ``reaction derived reactions``,
``reactor`` and ``energy landscapes``, including the unit fixup that
multiplies gas start/inflow entries by p/1e5 (bar) and the name->object
resolution passes for reaction members, gasdata and scaling reactions.
"""

from __future__ import annotations

import json
import os

from ..analysis.energy_span import Energy
from ..constants import bartoPa
from ..models.reactor import CSTReactor, InfiniteDilutionReactor
from .reactions import Reaction, ReactionDerivedReaction, UserDefinedReaction
from .states import ADSORBATE, GAS, SURFACE, ScalingState, State


def read_from_input_file(input_path="input.json", base_system=None,
                         base_path=None, verbose=False):
    """Build a System from a JSON input file.

    base_system: donor System for 'reaction derived reactions' whose
    base_reaction names resolve there (reference load_input.py:95-114).
    base_path: directory against which relative state paths are resolved
    (defaults to the input file's directory, which is what the reference
    tests emulate by rewriting paths, test_1.py:22-31).
    """
    from ..api.system import System

    if verbose:
        print(f"Loading input file: {input_path}.")
    with open(input_path) as fh:
        cfg = json.load(fh)

    if base_path is None:
        base_path = os.path.dirname(os.path.abspath(input_path))

    def _resolve_path(p):
        if p is None or os.path.isabs(p):
            return p
        return os.path.join(base_path, p)

    if "states" not in cfg:
        raise RuntimeError("Input file contains no states.")

    states: dict[str, State] = {}
    for name, scfg in cfg["states"].items():
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        states[name] = State(name=name, **scfg)

    for name, scfg in cfg.get("scaling relation states", {}).items():
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        states[name] = ScalingState(name=name, **scfg)

    # Checkpoint extension: energy-only donor states for 'base reactions'
    # (written by utils.io.system_to_dict for derived-reaction systems
    # whose bases live in a donor system). NOT added to the system -- they
    # only carry the borrowed energetics.
    base_states: dict[str, State] = {}
    for name, scfg in cfg.get("base states", {}).items():
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        base_states[name] = State(name=name, **scfg)

    if "system" not in cfg:
        raise RuntimeError("Input file contains no system details.")
    sys_params = dict(cfg["system"])
    p = sys_params["p"]
    # Gas start/inflow entries arrive as fractions of total pressure and
    # are stored in bar (reference load_input.py:47-60).
    startsites = 0.0
    for name, val in sys_params.get("start_state", {}).items():
        if states[name].state_type == GAS:
            sys_params["start_state"][name] = val * p / bartoPa
        elif states[name].state_type in (SURFACE, ADSORBATE):
            startsites += val
    if "start_state" in sys_params and startsites == 0.0:
        raise ValueError(
            "Initial surface coverage cannot be zero for all states!")
    for name, val in sys_params.get("inflow_state", {}).items():
        if states[name].state_type != GAS:
            raise TypeError("Only gas states can comprise the inflow!")
        sys_params["inflow_state"][name] = val * p / bartoPa

    sim = System(**sys_params)
    for name, st in states.items():
        if st.gasdata is not None:
            st.gasdata["state"] = [states[s] for s in st.gasdata["state"]]
        sim.add_state(st)
    for st in base_states.values():
        if st.gasdata is not None:
            st.gasdata["state"] = [base_states.get(s) or states[s]
                                   for s in st.gasdata["state"]]

    reactions: dict[str, Reaction] = {}

    def _wire(rx_cfg, pool=states):
        rx_cfg = dict(rx_cfg)
        rx_cfg["reactants"] = [pool[s] for s in rx_cfg["reactants"]]
        rx_cfg["products"] = [pool[s] for s in rx_cfg["products"]]
        if rx_cfg.get("TS") is not None:
            rx_cfg["TS"] = [pool[s] for s in rx_cfg["TS"]]
        return rx_cfg

    for name, rcfg in cfg.get("reactions", {}).items():
        reactions[name] = Reaction(name=name, **_wire(rcfg))
    for name, rcfg in cfg.get("manual reactions", {}).items():
        reactions[name] = UserDefinedReaction(name=name, **_wire(rcfg))

    # Checkpoint extension: donor reactions resolved against base states
    # first; kept out of the system's kinetics (energy donors only).
    # A donor may itself be user-defined (user-energy keys in its cfg) or
    # derived from another donor ('base_reaction' key; second pass).
    donor_reactions: dict[str, Reaction] = {}
    if cfg.get("base reactions"):
        pool = {**states, **base_states}
        deferred = {}
        for name, rcfg in cfg["base reactions"].items():
            if "base_reaction" in rcfg:
                deferred[name] = rcfg
            elif any(k.endswith("_user") for k in rcfg):
                donor_reactions[name] = UserDefinedReaction(
                    name=name, **_wire(rcfg, pool))
            else:
                donor_reactions[name] = Reaction(name=name,
                                                 **_wire(rcfg, pool))
        while deferred:
            # A donor may be derived from another donor OR from one of
            # the system's own reactions (both sections parsed above).
            donors = {**reactions, **donor_reactions}
            resolvable = [n for n, rc in deferred.items()
                          if rc["base_reaction"] in donors]
            if not resolvable:
                raise KeyError(
                    f"base reactions {sorted(deferred)} reference donors "
                    "absent from the checkpoint")
            for name in resolvable:
                rcfg = _wire(deferred.pop(name), pool)
                bname = rcfg.pop("base_reaction")
                donor_reactions[name] = ReactionDerivedReaction(
                    name=name, base_reaction=donors[bname], **rcfg)

    if "reaction derived reactions" in cfg:
        if base_system is not None:
            donor = base_system.reactions
        else:
            donor = {**reactions, **donor_reactions}
        for name, rcfg in cfg["reaction derived reactions"].items():
            rcfg = _wire(rcfg)
            base_name = rcfg.pop("base_reaction")
            if base_name not in donor:
                raise KeyError(
                    f"derived reaction {name}: base reaction {base_name!r} "
                    "not found -- supply base_system= or load a checkpoint "
                    "with inlined 'base reactions'")
            reactions[name] = ReactionDerivedReaction(
                name=name, base_reaction=donor[base_name], **rcfg)

    # Resolve scaling-reaction name references now that reactions exist
    # (reference load_input.py:116-128).
    for st in states.values():
        if isinstance(st, ScalingState):
            for key, entry in st.scaling_reactions.items():
                if isinstance(entry["reaction"], str):
                    entry["reaction"] = reactions[entry["reaction"]]

    for rx in reactions.values():
        sim.add_reaction(rx)

    if "reactor" in cfg:
        rcfg = cfg["reactor"]
        if not isinstance(rcfg, dict):
            if rcfg == "InfiniteDilutionReactor":
                sim.add_reactor(InfiniteDilutionReactor())
            else:
                raise TypeError(
                    "Only InfiniteDilutionReactor can be specified without "
                    "reactor parameters.")
        elif "InfiniteDilutionReactor" in rcfg:
            sim.add_reactor(InfiniteDilutionReactor())
        elif "CSTReactor" in rcfg:
            sim.add_reactor(CSTReactor(**rcfg["CSTReactor"]))
        else:
            raise TypeError("Unknown reactor option, please choose "
                            "InfiniteDilutionReactor or CSTReactor.")
    elif reactions:
        raise RuntimeError(
            "Cannot consider reactions without reactor. To use constant "
            "boundary conditions, please specify InfiniteDilutionReactor.")

    for pes, lcfg in cfg.get("energy landscapes", {}).items():
        minima = [[states[s] for s in entry] for entry in lcfg["minima"]]
        labels = lcfg.get("labels") or [e[0].name for e in minima]
        sim.add_energy_landscape(Energy(name=pes, minima=minima,
                                        labels=labels))

    return sim
