"""JSON input loader: reference-compatible schema -> System facade.

Reads the exact input schema of the reference
(/root/reference/pycatkin/functions/load_input.py:9-168): top-level
sections ``states``, ``scaling relation states``, ``system``,
``reactions``, ``manual reactions``, ``reaction derived reactions``,
``reactor`` and ``energy landscapes``, including the unit fixup that
multiplies gas start/inflow entries by p/1e5 (bar) and the name->object
resolution passes for reaction members, gasdata and scaling reactions.
"""

from __future__ import annotations

import json
import os

from ..analysis.energy_span import Energy
from ..constants import bartoPa
from ..models.reactor import CSTReactor, InfiniteDilutionReactor
from .reactions import Reaction, ReactionDerivedReaction, UserDefinedReaction
from .states import ADSORBATE, GAS, SURFACE, ScalingState, State


def read_from_input_file(input_path="input.json", base_system=None,
                         base_path=None, verbose=False):
    """Build a System from a JSON input file.

    base_system: donor System for 'reaction derived reactions' whose
    base_reaction names resolve there (reference load_input.py:95-114).
    base_path: directory against which relative state paths are resolved
    (defaults to the input file's directory, which is what the reference
    tests emulate by rewriting paths, test_1.py:22-31).

    Every schema error names the input file and the offending JSON key
    (JSON-pointer style, e.g. ``/reactions/CO_ox/reactants``). After
    wiring, the loaded system runs through the input-validation gate
    (frontend/validate.py) under the ``PYCATKIN_VALIDATE`` mode
    (strict|warn|off; default warn).
    """
    from ..api.system import System
    from .validate import validate_system, validation_mode

    if verbose:
        print(f"Loading input file: {input_path}.")
    with open(input_path) as fh:
        cfg = json.load(fh)

    if base_path is None:
        base_path = os.path.dirname(os.path.abspath(input_path))

    def _resolve_path(p):
        if p is None or os.path.isabs(p):
            return p
        return os.path.join(base_path, p)

    if "states" not in cfg:
        raise RuntimeError(
            f"{input_path}: /states: input file contains no states.")

    def _lookup(pool, sname, location, kind="state"):
        """Name -> object resolution with schema context on failure."""
        try:
            return pool[sname]
        except KeyError:
            raise KeyError(
                f"{input_path}: {location}: references unknown {kind} "
                f"{sname!r}") from None

    states: dict[str, State] = {}
    for name, scfg in cfg["states"].items():
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        states[name] = State(name=name, **scfg)

    for name, scfg in cfg.get("scaling relation states", {}).items():
        if name in states:
            raise ValueError(
                f"{input_path}: /scaling relation states/{name}: name "
                f"collides with an entry of /states")
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        states[name] = ScalingState(name=name, **scfg)

    # Checkpoint extension: energy-only donor states for 'base reactions'
    # (written by utils.io.system_to_dict for derived-reaction systems
    # whose bases live in a donor system). NOT added to the system -- they
    # only carry the borrowed energetics.
    base_states: dict[str, State] = {}
    for name, scfg in cfg.get("base states", {}).items():
        scfg = dict(scfg)
        for key in ("path", "vibs_path"):
            if key in scfg:
                scfg[key] = _resolve_path(scfg[key])
        base_states[name] = State(name=name, **scfg)

    if "system" not in cfg:
        raise RuntimeError(
            f"{input_path}: /system: input file contains no system "
            f"details.")
    sys_params = dict(cfg["system"])
    if "p" not in sys_params:
        raise KeyError(
            f"{input_path}: /system/p: total pressure is required to "
            f"convert gas fractions to partial pressures")
    p = sys_params["p"]
    # Gas start/inflow entries arrive as fractions of total pressure and
    # are stored in bar (reference load_input.py:47-60).
    startsites = 0.0
    for name, val in sys_params.get("start_state", {}).items():
        st = _lookup(states, name, "/system/start_state")
        if st.state_type == GAS:
            sys_params["start_state"][name] = val * p / bartoPa
        elif st.state_type in (SURFACE, ADSORBATE):
            startsites += val
    if "start_state" in sys_params and startsites == 0.0:
        raise ValueError(
            f"{input_path}: /system/start_state: initial surface "
            f"coverage cannot be zero for all states")
    for name, val in sys_params.get("inflow_state", {}).items():
        st = _lookup(states, name, "/system/inflow_state")
        if st.state_type != GAS:
            raise TypeError(
                f"{input_path}: /system/inflow_state/{name}: only gas "
                f"states can comprise the inflow (state {name!r} is "
                f"{st.state_type!r})")
        sys_params["inflow_state"][name] = val * p / bartoPa

    sim = System(**sys_params)
    for name, st in states.items():
        if st.gasdata is not None:
            st.gasdata["state"] = [states[s] for s in st.gasdata["state"]]
        sim.add_state(st)
    for st in base_states.values():
        if st.gasdata is not None:
            st.gasdata["state"] = [base_states.get(s) or states[s]
                                   for s in st.gasdata["state"]]

    reactions: dict[str, Reaction] = {}

    def _wire(rx_cfg, pool=states, where="/reactions/?"):
        rx_cfg = dict(rx_cfg)
        for member in ("reactants", "products"):
            if member not in rx_cfg:
                raise KeyError(
                    f"{input_path}: {where}: reaction is missing its "
                    f"{member!r} list") from None
            rx_cfg[member] = [_lookup(pool, s, f"{where}/{member}")
                              for s in rx_cfg[member]]
        if rx_cfg.get("TS") is not None:
            rx_cfg["TS"] = [_lookup(pool, s, f"{where}/TS")
                            for s in rx_cfg["TS"]]
        return rx_cfg

    for name, rcfg in cfg.get("reactions", {}).items():
        reactions[name] = Reaction(
            name=name, **_wire(rcfg, where=f"/reactions/{name}"))
    for name, rcfg in cfg.get("manual reactions", {}).items():
        reactions[name] = UserDefinedReaction(
            name=name, **_wire(rcfg, where=f"/manual reactions/{name}"))

    # Checkpoint extension: donor reactions resolved against base states
    # first; kept out of the system's kinetics (energy donors only).
    # A donor may itself be user-defined (user-energy keys in its cfg) or
    # derived from another donor ('base_reaction' key; second pass).
    donor_reactions: dict[str, Reaction] = {}
    if cfg.get("base reactions"):
        pool = {**states, **base_states}
        deferred = {}
        for name, rcfg in cfg["base reactions"].items():
            if "base_reaction" in rcfg:
                deferred[name] = rcfg
            elif any(k.endswith("_user") for k in rcfg):
                donor_reactions[name] = UserDefinedReaction(
                    name=name,
                    **_wire(rcfg, pool, f"/base reactions/{name}"))
            else:
                donor_reactions[name] = Reaction(
                    name=name,
                    **_wire(rcfg, pool, f"/base reactions/{name}"))
        while deferred:
            # A donor may be derived from another donor OR from one of
            # the system's own reactions (both sections parsed above).
            donors = {**reactions, **donor_reactions}
            resolvable = [n for n, rc in deferred.items()
                          if rc["base_reaction"] in donors]
            if not resolvable:
                raise KeyError(
                    f"{input_path}: /base reactions: entries "
                    f"{sorted(deferred)} reference base_reaction donors "
                    f"absent from the checkpoint")
            for name in resolvable:
                rcfg = _wire(deferred.pop(name), pool,
                             f"/base reactions/{name}")
                bname = rcfg.pop("base_reaction")
                donor_reactions[name] = ReactionDerivedReaction(
                    name=name, base_reaction=donors[bname], **rcfg)

    if "reaction derived reactions" in cfg:
        if base_system is not None:
            donor = base_system.reactions
        else:
            donor = {**reactions, **donor_reactions}
        for name, rcfg in cfg["reaction derived reactions"].items():
            rcfg = _wire(rcfg,
                         where=f"/reaction derived reactions/{name}")
            base_name = rcfg.pop("base_reaction")
            if base_name not in donor:
                raise KeyError(
                    f"{input_path}: /reaction derived reactions/{name}: "
                    f"base reaction {base_name!r} not found -- supply "
                    f"base_system= or load a checkpoint with inlined "
                    f"'base reactions'")
            reactions[name] = ReactionDerivedReaction(
                name=name, base_reaction=donor[base_name], **rcfg)

    # Resolve scaling-reaction name references now that reactions exist
    # (reference load_input.py:116-128).
    for st in states.values():
        if isinstance(st, ScalingState):
            for key, entry in st.scaling_reactions.items():
                if isinstance(entry["reaction"], str):
                    entry["reaction"] = _lookup(
                        reactions, entry["reaction"],
                        f"/scaling relation states/{st.name}"
                        f"/scaling_reactions/{key}", kind="reaction")

    for rx in reactions.values():
        sim.add_reaction(rx)

    if "reactor" in cfg:
        rcfg = cfg["reactor"]
        if not isinstance(rcfg, dict):
            if rcfg == "InfiniteDilutionReactor":
                sim.add_reactor(InfiniteDilutionReactor())
            else:
                raise TypeError(
                    f"{input_path}: /reactor: only "
                    f"InfiniteDilutionReactor can be specified without "
                    f"reactor parameters, got {rcfg!r}")
        elif "InfiniteDilutionReactor" in rcfg:
            sim.add_reactor(InfiniteDilutionReactor())
        elif "CSTReactor" in rcfg:
            sim.add_reactor(CSTReactor(**rcfg["CSTReactor"]))
        else:
            raise TypeError(
                f"{input_path}: /reactor: unknown reactor option(s) "
                f"{sorted(rcfg)}, please choose InfiniteDilutionReactor "
                f"or CSTReactor")
    elif reactions:
        raise RuntimeError(
            f"{input_path}: /reactor: cannot consider reactions without "
            f"a reactor. To use constant boundary conditions, specify "
            f"InfiniteDilutionReactor.")

    for pes, lcfg in cfg.get("energy landscapes", {}).items():
        minima = [[_lookup(states, s,
                           f"/energy landscapes/{pes}/minima/{i}")
                   for s in entry]
                  for i, entry in enumerate(lcfg["minima"])]
        labels = lcfg.get("labels") or [e[0].name for e in minima]
        sim.add_energy_landscape(Energy(name=pes, minima=minima,
                                        labels=labels))

    # Validation gate: run the host-side checks over the freshly wired
    # system. PYCATKIN_VALIDATE picks strict|warn|off (default warn).
    mode = validation_mode()
    if mode != "off":
        validate_system(sim, source=str(input_path)).emit(mode)

    return sim
