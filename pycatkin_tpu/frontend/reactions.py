"""Host-side elementary-step description.

Pure data holders; rate-constant math lives in
:mod:`pycatkin_tpu.ops.rates`. Capability parity with the reference
``Reaction``/``UserDefinedReaction``/``ReactionDerivedReaction``
(/root/reference/pycatkin/classes/reaction.py:6-360): reaction types
arrhenius / adsorption / desorption / ghost, reversibility, site area and
rate scaling, user-supplied energies (scalar or per-temperature dict) and
energy borrowing from a base reaction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .states import GAS, State

ARRHENIUS = "arrhenius"
ADSORPTION = "adsorption"
DESORPTION = "desorption"
GHOST = "ghost"

REAC_TYPES = (ARRHENIUS, ADSORPTION, DESORPTION, GHOST)


@dataclass
class Reaction:
    name: str = "reaction"
    reac_type: str = None
    reversible: bool = True
    reactants: list = field(default_factory=list)
    products: list = field(default_factory=list)
    TS: Optional[list] = None
    area: float = 1.0e-19
    scaling: float = 1.0

    def __post_init__(self):
        rt = str(self.reac_type).lower()
        if rt in ("scaling", "base"):
            # Reference-schema aliases ('scaling': COOxReactor
            # input_AuPd.json "CO_ox"; 'base': DMTM metals
            # input_*_sr.json r0-r10, the TS-mediated donor steps for
            # derived reactions). The reference has no explicit branch
            # for either -- they land in the activated path through the
            # ``or self.dGa_fwd`` condition (reaction.py:121), which is
            # exactly the arrhenius dispatch here.
            rt = ARRHENIUS
        if rt not in REAC_TYPES:
            raise ValueError(
                f"reaction {self.name}: reac_type must be one of "
                f"{REAC_TYPES}, got {self.reac_type!r}")
        self.reac_type = rt

    # ------------------------------------------------------------------
    @property
    def energy_states(self) -> "Reaction":
        """The reaction whose states define this reaction's energetics.

        ReactionDerivedReaction overrides this to its base reaction
        (reference reaction.py:312-334)."""
        return self

    def gas_species(self) -> Optional[State]:
        """The single gas species that adsorbs/desorbs, if applicable.

        Reference asserts exactly one (reaction.py:137,152)."""
        if self.reac_type == ADSORPTION:
            pool = self.reactants
        elif self.reac_type == DESORPTION:
            pool = self.products
        else:
            return None
        gas = [s for s in pool if s.state_type == GAS]
        assert len(gas) == 1, (
            f"reaction {self.name}: must have exactly one gas-phase species "
            "adsorbing or desorbing per elementary step")
        return gas[0]

    @property
    def is_user_defined(self) -> bool:
        return False

    @property
    def site_density(self) -> float:
        return 1.0 / self.area if self.area else 0.0


def _resolve_user_value(value, T: float):
    """User energies may be scalars or dicts keyed by temperature
    (reference reaction.py:228-260).

    The reference KeyErrors on any swept T absent from the dict; here
    intermediate temperatures are linearly interpolated (sweeps like
    run_temperatures otherwise cannot cross a per-T dict), while
    temperatures outside the tabulated range raise a clear error."""
    if value is None:
        return None
    if isinstance(value, dict):
        T = float(T)
        table = {float(k): float(v) for k, v in value.items()}
        if T in table:
            return table[T]
        keys = sorted(table)
        if T < keys[0] or T > keys[-1]:
            raise ValueError(
                f"user energy tabulated for T in [{keys[0]}, {keys[-1]}] K "
                f"only; cannot extrapolate to T={T} K")
        import bisect
        hi = bisect.bisect_left(keys, T)
        lo, hi = keys[hi - 1], keys[hi]
        w = (T - lo) / (hi - lo)
        return (1.0 - w) * table[lo] + w * table[hi]
    return float(value)


@dataclass
class UserDefinedReaction(Reaction):
    """Reaction with user-supplied energies in eV (reference
    reaction.py:202-274). The defaulting rules (dE<->dG mirror each other
    when one is absent; missing barriers mean a non-activated step) are
    applied in :meth:`resolved_user_energies`."""

    dErxn_user: Optional[object] = None
    dEa_fwd_user: Optional[object] = None
    dEa_rev_user: Optional[object] = None
    dGrxn_user: Optional[object] = None
    dGa_fwd_user: Optional[object] = None
    dGa_rev_user: Optional[object] = None

    @property
    def is_user_defined(self) -> bool:
        return True

    def resolved_user_energies(self, T: float) -> dict:
        """Resolve user energies at temperature T with reference defaulting:
        dErxn<->dGrxn fall back to each other; barrier pairs likewise; a
        reaction with neither barrier gets 0.0 (non-activated)."""
        dErxn = _resolve_user_value(self.dErxn_user, T)
        dGrxn = _resolve_user_value(self.dGrxn_user, T)
        if dErxn is None and dGrxn is not None:
            dErxn = dGrxn
        if dGrxn is None and dErxn is not None:
            dGrxn = dErxn
        dEa = _resolve_user_value(self.dEa_fwd_user, T)
        dGa = _resolve_user_value(self.dGa_fwd_user, T)
        if dEa is None and dGa is not None:
            dEa = dGa
        if dGa is None and dEa is not None:
            dGa = dEa
        has_barrier = dEa is not None
        return {
            "dErxn": dErxn,
            "dGrxn": dGrxn,
            "dEa_fwd": dEa if dEa is not None else 0.0,
            "dGa_fwd": dGa if dGa is not None else 0.0,
            "has_rxn_energy": dErxn is not None,
            "has_barrier": has_barrier,
        }


@dataclass
class ReactionDerivedReaction(Reaction):
    """Reaction that borrows its energetics from another reaction with
    different stoichiometry (reference reaction.py:298-334)."""

    base_reaction: Optional[Reaction] = None

    def __post_init__(self):
        super().__post_init__()
        assert self.base_reaction is not None, (
            f"reaction {self.name}: base_reaction is required")

    @property
    def energy_states(self) -> Reaction:
        return self.base_reaction
